"""Dump the observability layer's expositions to files.

Two sources (docs/OBSERVABILITY.md):

- ``--url http://host:port`` — scrape a live process's exposition
  server (``FLEETX_OBS_PORT``): writes ``metrics.prom`` (Prometheus
  text), ``snapshot.json`` (registry + events + health), and
  ``trace.json`` (Chrome-trace of the host span ring buffer — load in
  chrome://tracing or Perfetto, or merge next to a jax profiler trace).
- no ``--url`` — dump THIS process's in-memory registry/events/spans
  (the in-process path library code uses:
  ``from tools.obs_dump import dump_all``).

Usage::

    python tools/obs_dump.py --url http://127.0.0.1:9100 --out-dir obs/
    python tools/obs_dump.py --out-dir obs/   # current process

Exit is non-zero when the scrape fails — a cron'd dump must not rot
silently.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FILES = {
    # endpoint path -> (filename, is_json)
    "/metrics": ("metrics.prom", False),
    "/snapshot": ("snapshot.json", True),
    "/trace": ("trace.json", True),
}


def _fetch(url: str, timeout_s: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read()


def dump_url(base_url: str, out_dir: str, timeout_s: float = 10.0) -> dict:
    """Scrape ``base_url``'s three exposition endpoints into ``out_dir``;
    returns {endpoint: written path}."""
    os.makedirs(out_dir, exist_ok=True)
    written = {}
    for path, (fname, is_json) in _FILES.items():
        body = _fetch(base_url.rstrip("/") + path, timeout_s)
        if is_json:
            json.loads(body)  # fail loudly on a broken payload
        dst = os.path.join(out_dir, fname)
        with open(dst, "wb") as f:
            f.write(body)
        written[path] = dst
    return written


def dump_all(out_dir: str) -> dict:
    """Dump the CURRENT process's registry/events/spans into ``out_dir``
    (same three files as :func:`dump_url`); returns {endpoint: path}."""
    from fleetx_tpu.obs import get_recorder, get_registry
    from fleetx_tpu.obs.http import snapshot_payload

    os.makedirs(out_dir, exist_ok=True)
    payloads = {
        "/metrics": get_registry().prometheus_text().encode(),
        # the exact /snapshot endpoint payload — shared builder, no drift
        "/snapshot": json.dumps(snapshot_payload()).encode(),
        "/trace": json.dumps(get_recorder().chrome_trace()).encode(),
    }
    written = {}
    for path, body in payloads.items():
        dst = os.path.join(out_dir, _FILES[path][0])
        with open(dst, "wb") as f:
            f.write(body)
        written[path] = dst
    return written


def main(argv=None) -> int:
    """CLI entry (module docstring); 0 on success."""
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", default=None,
                    help="base URL of a live FLEETX_OBS_PORT server "
                         "(omit to dump this process's own state)")
    ap.add_argument("--out-dir", default="obs_dump",
                    help="directory for metrics.prom / snapshot.json / "
                         "trace.json")
    ap.add_argument("--timeout-s", type=float, default=10.0,
                    help="per-request scrape timeout")
    args = ap.parse_args(argv)
    try:
        written = (dump_url(args.url, args.out_dir, args.timeout_s)
                   if args.url else dump_all(args.out_dir))
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"obs_dump: FAILED: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    for path, dst in sorted(written.items()):
        print(f"obs_dump: {path} -> {dst}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
