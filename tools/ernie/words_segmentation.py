"""Chinese word segmentation for ERNIE whole-word-mask corpora.

Capability parity with the reference's segmentation stage
(/root/reference/ppfleetx/data/data_tools/ernie/preprocess/
words_segmentation.py:1-223): segment each jsonl document's text into words
joined by a split delimiter, so the downstream tokenizer can apply
whole-word masking. Segmenter backends: ``jieba``/``lac`` when importable
(not bundled in this image — zero-egress), else the ``space`` fallback for
pre-segmented or space-delimited corpora.

    python tools/ernie/words_segmentation.py --input-path zh.jsonl \
        --output-path zh_seg --seg-func jieba
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "../.."))

from fleetx_tpu.utils.log import logger

_seg = {}


def build_segmenter(name):
    if name == "jieba":
        try:
            import jieba
        except ImportError:
            raise SystemExit(
                "jieba is not installed in this image; use --seg-func space "
                "for pre-segmented corpora")
        return lambda line: list(jieba.cut(line))
    if name == "lac":
        try:
            from LAC import LAC
        except ImportError:
            raise SystemExit(
                "LAC is not installed in this image; use --seg-func space")
        lac = LAC(mode="seg")
        return lambda line: lac.run(line)
    if name == "space":
        return lambda line: line.split()
    raise SystemExit(f"unknown seg-func {name!r}")


def get_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--input-path", "--input_path", dest="input_path",
                   required=True)
    p.add_argument("--output-path", "--output_path", dest="output_path",
                   required=True)
    p.add_argument("--json-key", "--json_key", dest="json_key", default="text")
    p.add_argument("--seg-func", "--cn_seg_func", dest="seg_func",
                   default="space", choices=["jieba", "lac", "space"])
    p.add_argument("--split-dimer", "--cn_split_dimer", dest="split_dimer",
                   default=" ")
    p.add_argument("--workers", type=int, default=1)
    return p.parse_args(argv)


def _init(args):
    _seg["fn"] = build_segmenter(args.seg_func)
    _seg["args"] = args


def _process(line):
    args = _seg["args"]
    try:
        obj = json.loads(line)
        text = obj[args.json_key]
    except (json.JSONDecodeError, KeyError):
        return None
    if not isinstance(text, str):
        return None
    words = _seg["fn"](text)
    obj[args.json_key] = args.split_dimer.join(w for w in words if w.strip())
    return json.dumps(obj, ensure_ascii=False)


def run(args) -> dict:
    out_path = args.output_path + ".jsonl"
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    n = 0
    with open(args.input_path, encoding="utf-8") as f, \
            open(out_path, "w", encoding="utf-8") as out:
        if args.workers > 1:
            with mp.Pool(args.workers, initializer=_init, initargs=(args,)) as pool:
                for line in pool.imap(_process, f, 64):
                    if line is not None:
                        out.write(line + "\n")
                        n += 1
        else:
            _init(args)
            for raw in f:
                line = _process(raw)
                if line is not None:
                    out.write(line + "\n")
                    n += 1
    logger.info("segmented %d docs -> %s", n, out_path)
    return {"docs": n, "output": out_path}


def main(argv=None):
    run(get_args(argv))


if __name__ == "__main__":
    main()
