"""ERNIE raw-text -> jsonl stage (reference
/root/reference/ppfleetx/data/data_tools/ernie/preprocess/trans_to_json.py:
same job as the GPT stage, kept as a separate entry point for CLI parity).
Delegates to tools/raw_trans_to_json.py."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "../.."))

from tools.raw_trans_to_json import get_args, main, run  # noqa: F401

if __name__ == "__main__":
    main()
