"""ERNIE pretraining corpus builder: segmented jsonl -> mmap token dataset.

Capability parity with the reference
(/root/reference/ppfleetx/data/data_tools/ernie/preprocess/
create_pretraining_data.py:1-416): WordPiece-tokenize each document with
ErnieTokenizer, one index entry per document (matching the reference's doc-level
instance building — ErnieDataset halves one entry into the SOP segment
pair, so entries must span multiple sentences; pass ``--split-sentences``
only for corpora whose "documents" are already multi-sentence lines),
writing ``{prefix}_ids.npy`` + ``{prefix}_idx.npz``.
The masking itself is *dynamic* in this framework — ErnieDataset re-draws
span masks per (seed, epoch, index) at load time (fleetx_tpu/data/
ernie_dataset.py), so the offline stage stores plain token ids instead of
the reference's pre-baked masked instances; that is what makes multi-epoch
training see fresh masks for free.

    python tools/ernie/create_pretraining_data.py --input-path zh_seg.jsonl \
        --output-prefix out/ernie --vocab-dir vocabs/ernie
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "../.."))


def get_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--input-path", "--input_path", dest="input_path",
                   required=True)
    p.add_argument("--output-prefix", "--output_prefix", dest="output_prefix",
                   required=True)
    p.add_argument("--vocab-dir", "--model_name", dest="vocab_dir",
                   default=None, help="directory holding vocab.txt")
    p.add_argument("--json-key", "--json_key", dest="json_key", default="text")
    p.add_argument("--split-sentences", action="store_true",
                   help="one index entry per newline-split sentence instead "
                        "of per document (degrades SOP pairing; see module "
                        "docstring)")
    p.add_argument("--workers", type=int, default=1)
    return p.parse_args(argv)


def run(args) -> dict:
    from tools import preprocess_data as pp

    pp_args = pp.get_args([
        "--input", args.input_path,
        "--output-prefix", args.output_prefix,
        "--tokenizer-name", "ErnieTokenizer",
        "--json-key", args.json_key,
        "--workers", str(args.workers),
    ] + ([] if args.vocab_dir is None else ["--vocab-dir", args.vocab_dir])
      + (["--split-sentences"] if args.split_sentences else []))
    return pp.run(pp_args)


def main(argv=None):
    run(get_args(argv))


if __name__ == "__main__":
    main()
