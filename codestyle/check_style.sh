#!/usr/bin/env bash
# Style gate (reference codestyle/: pylint docstring plugin + clang-format +
# cpplint pre-commit hooks). Dependency-free equivalents; native linters run
# only when present on the machine.
set -u
cd "$(dirname "$0")/.."
fail=0

echo "== syntax (compileall) =="
python -m compileall -q fleetx_tpu tools tasks || fail=1

echo "== docstring coverage =="
python codestyle/docstring_checker.py fleetx_tpu || fail=1

echo "== whitespace =="
if grep -rn --include='*.py' -P ' +$' fleetx_tpu tools tasks | head -5 | grep .; then
    echo "trailing whitespace found"; fail=1
fi
if grep -rln --include='*.py' -P '\t' fleetx_tpu | head -5 | grep .; then
    echo "hard tabs found in python sources"; fail=1
fi

if command -v clang-format > /dev/null; then
    echo "== clang-format (C++ diff check) =="
    for f in $(find fleetx_tpu -name '*.cpp' -o -name '*.h'); do
        if ! diff -q <(clang-format "$f") "$f" > /dev/null; then
            echo "$f needs clang-format"; fail=1
        fi
    done
fi

[ "$fail" -eq 0 ] && echo "style OK"
exit $fail
