"""Docstring coverage checker (reference codestyle/docstring_checker.py is a
pylint plugin; this is a dependency-free AST walker so the hook runs on a
bare image).

Public modules, classes, and top-level functions (no leading underscore)
must carry a docstring. Methods are exempt unless --strict: module/class
docs describe the contract, and flax ``__call__`` bodies are annotated at
the class level.

    python codestyle/docstring_checker.py fleetx_tpu [--strict]
"""

import argparse
import ast
import os
import sys


def check_file(path: str, strict: bool) -> list:
    with open(path, encoding="utf-8") as f:
        try:
            tree = ast.parse(f.read(), filename=path)
        except SyntaxError as e:
            return [(path, e.lineno or 0, f"syntax error: {e.msg}")]
    missing = []
    if not ast.get_docstring(tree):
        missing.append((path, 1, "module docstring missing"))
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and not node.name.startswith("_"):
            if not ast.get_docstring(node):
                missing.append((path, node.lineno, f"class {node.name}: docstring missing"))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.startswith("_"):
                continue
            # methods only in --strict mode
            if node.col_offset > 0 and not strict:
                continue
            if not ast.get_docstring(node):
                missing.append((path, node.lineno, f"def {node.name}: docstring missing"))
    return missing


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("roots", nargs="+")
    ap.add_argument("--strict", action="store_true")
    args = ap.parse_args()

    problems = []
    for root in args.roots:
        if os.path.isfile(root):
            problems += check_file(root, args.strict)
            continue
        for dirpath, _, files in os.walk(root):
            for name in files:
                if name.endswith(".py"):
                    problems += check_file(os.path.join(dirpath, name), args.strict)
    for path, line, msg in problems:
        print(f"{path}:{line}: {msg}")
    print(f"{len(problems)} docstring problems")
    sys.exit(1 if problems else 0)


if __name__ == "__main__":
    main()
