"""Interactive generation driver over an export artifact (reference
/root/reference/tasks/gpt/generation.py:35-124: loads exported module,
reads prompts from stdin, prints completions).

    python tasks/gpt/generation.py --export-dir ./exported --vocab-dir ./vocab
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

from fleetx_tpu.core.inference_engine import InferenceEngine


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--export-dir", required=True)
    ap.add_argument("--vocab-dir", default="./vocab")
    ap.add_argument("--max-length", type=int, default=128)
    args = ap.parse_args()

    from fleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer

    tok = GPTTokenizer.from_pretrained(args.vocab_dir)
    engine = InferenceEngine(args.export_dir)
    print("prompt> ", end="", flush=True)
    for line in sys.stdin:
        prompt = line.strip()
        if not prompt:
            break
        ids = np.asarray([tok.encode(prompt)], np.int32)
        out = np.asarray(engine.generate(ids, max_length=args.max_length))
        gen = out[0][ids.shape[1]:]
        eos = np.nonzero(gen == engine.eos_token_id)[0]
        if eos.size:  # trim the post-EOS pad fill
            gen = gen[: eos[0]]
        print(tok.decode(gen))
        print("prompt> ", end="", flush=True)


if __name__ == "__main__":
    main()
