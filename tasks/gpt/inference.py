"""One-shot inference driver over an export artifact (reference
/root/reference/tasks/gpt/inference.py:35-62: builds the module in
mode='inference', encodes a prompt, runs engine.inference, decodes).

    python tasks/gpt/inference.py --export-dir ./exported --vocab-dir ./vocab \
        --prompt "Hi, GPT2. Tell me who Jack Ma is."
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

from fleetx_tpu.core.inference_engine import InferenceEngine


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--export-dir", required=True)
    ap.add_argument("--vocab-dir", default="./vocab")
    ap.add_argument("--prompt", default="Hi, GPT2. Tell me who Jack Ma is.")
    ap.add_argument("--max-length", type=int, default=128)
    args = ap.parse_args()

    from fleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer

    tok = GPTTokenizer.from_pretrained(args.vocab_dir)
    engine = InferenceEngine(args.export_dir)

    ids = np.asarray([tok.encode(args.prompt)], np.int32)
    out = np.asarray(engine.generate(ids, max_length=args.max_length))
    gen = out[0][ids.shape[1]:]
    eos = np.nonzero(gen == engine.eos_token_id)[0]
    if eos.size:  # trim EOS + the post-EOS pad fill
        gen = gen[: eos[0]]
    print("Prompt:", args.prompt)
    print("Generation:", args.prompt + tok.decode(gen))


if __name__ == "__main__":
    main()
