"""Deterministic fault injection for the resilience chaos suite.

One process-global :class:`FaultInjector` (``faults``) owns a
:class:`FaultPlan` parsed from ``FLEETX_FAULT_*`` env vars (or installed
programmatically via :meth:`FaultInjector.configure`). Production code
carries the injection points — the Trainer wraps its train-data iterator
in :meth:`wrap_train_data` and calls :meth:`on_checkpoint_save` before
every checkpoint write — but when no plan is active every hook is a
single ``is None`` check, so an unconfigured run is byte-identical to a
build without this module.

Injection points (all batch indices count *fetched* train batches across
the whole run, independent of whether the sentry later skipped the step —
that keeps the injection deterministic under skip/resume):

- ``FLEETX_FAULT_NAN_BATCH``: poison every floating-point leaf of the
  matching train batches with NaN (the classic bad-shard/corrupt-record
  failure that turns the loss and every grad NaN).
- ``FLEETX_FAULT_DATA_RAISE_BATCH``: the data iterator raises
  ``DataFault`` instead of yielding the matching batch (a dead shard /
  filesystem error mid-epoch).
- ``FLEETX_FAULT_DATA_SLOW_BATCH`` / ``FLEETX_FAULT_DATA_SLOW_S``:
  sleep before yielding the matching batch (input-pipeline stall).
- ``FLEETX_FAULT_CKPT_SAVE_STEP``: ``Trainer.save`` raises ``CkptFault``
  at the matching step numbers (full disk / flaky object store).

Batch/step selectors share one grammar: a comma-separated list of
entries, each either an int (``"3"``), or ``"N+"`` for every index >= N
(``"0+"`` = always). :func:`raising_on_token` builds the deterministic
raising streaming callback the serving chaos scenarios use.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Iterable, Optional

import numpy as np

__all__ = [
    "CkptFault",
    "DataFault",
    "FaultInjector",
    "FaultPlan",
    "faults",
    "raising_on_token",
]


class DataFault(RuntimeError):
    """Injected data-iterator failure (FLEETX_FAULT_DATA_RAISE_BATCH)."""


class CkptFault(IOError):
    """Injected checkpoint-write failure (FLEETX_FAULT_CKPT_SAVE_STEP)."""


class _Selector:
    """Index selector: ``"3"``, ``"1,4"``, ``"2+"`` (every index >= 2)."""

    def __init__(self, spec: str):
        self.exact = set()
        self.from_ = None  # smallest N of any "N+" entry
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            if part.endswith("+"):
                n = int(part[:-1])
                self.from_ = n if self.from_ is None else min(self.from_, n)
            else:
                self.exact.add(int(part))

    def __contains__(self, i: int) -> bool:
        return i in self.exact or (self.from_ is not None and i >= self.from_)

    def __bool__(self) -> bool:
        return bool(self.exact) or self.from_ is not None


@dataclasses.dataclass
class FaultPlan:
    """Parsed fault schedule (module docstring has the env grammar)."""

    nan_batch: Optional[str] = None
    data_raise_batch: Optional[str] = None
    data_slow_batch: Optional[str] = None
    data_slow_s: float = 0.05
    ckpt_save_step: Optional[str] = None

    @classmethod
    def from_env(cls, env=os.environ) -> Optional["FaultPlan"]:
        """Build a plan from ``FLEETX_FAULT_*`` (None when none are set).
        Malformed values raise a ValueError naming the offending var — a
        chaos run must fail loudly, never silently skip its faults."""
        slow_s = 0.05
        raw = env.get("FLEETX_FAULT_DATA_SLOW_S")
        if raw:
            try:
                slow_s = float(raw)
            except ValueError:
                raise ValueError(
                    f"FLEETX_FAULT_DATA_SLOW_S={raw!r} is not a float")
        plan = cls(
            nan_batch=env.get("FLEETX_FAULT_NAN_BATCH") or None,
            data_raise_batch=env.get("FLEETX_FAULT_DATA_RAISE_BATCH") or None,
            data_slow_batch=env.get("FLEETX_FAULT_DATA_SLOW_BATCH") or None,
            data_slow_s=slow_s,
            ckpt_save_step=env.get("FLEETX_FAULT_CKPT_SAVE_STEP") or None,
        )
        if not (plan.nan_batch or plan.data_raise_batch
                or plan.data_slow_batch or plan.ckpt_save_step):
            return None
        return plan


class FaultInjector:
    """Process-global injector: holds the active plan + fetch counters."""

    def __init__(self):
        self._plan: Optional[FaultPlan] = None
        self._nan_sel = self._raise_sel = self._slow_sel = self._ckpt_sel = None
        self._batch_counter = 0
        self.injected = {"nan": 0, "data_raise": 0, "data_slow": 0, "ckpt": 0}

    # ----------------------------------------------------------- configure
    def configure(self, plan: Optional[FaultPlan] = None, **kw) -> None:
        """Install ``plan`` (or build one from kwargs); resets counters."""
        if plan is None and kw:
            plan = FaultPlan(**{k: str(v) if v is not None
                                and k.endswith(("batch", "step")) else v
                                for k, v in kw.items()})
        def sel(field):
            spec = getattr(plan, field, None) if plan else None
            if not spec:
                return None
            try:
                return _Selector(spec)
            except ValueError:
                raise ValueError(
                    f"FLEETX_FAULT_{field.upper()}={spec!r}: selector "
                    "entries must be ints like '3', '1,4', or '2+'")

        self._plan = plan
        self._nan_sel = sel("nan_batch")
        self._raise_sel = sel("data_raise_batch")
        self._slow_sel = sel("data_slow_batch")
        self._ckpt_sel = sel("ckpt_save_step")
        self._batch_counter = 0
        self.injected = {"nan": 0, "data_raise": 0, "data_slow": 0, "ckpt": 0}

    def configure_from_env(self, env=os.environ) -> None:
        """Re-read ``FLEETX_FAULT_*`` into the active plan."""
        self.configure(FaultPlan.from_env(env))

    def reset(self) -> None:
        """Deactivate all faults and zero the counters."""
        self.configure(None)

    @property
    def active(self) -> bool:
        """True when any fault is scheduled."""
        return self._plan is not None

    # ------------------------------------------------------ injection points
    def wrap_train_data(self, data: Iterable) -> Iterable:
        """Route a train-data iterable through the data faults. Returns
        ``data`` unchanged when inert; the fetch counter is global across
        epochs (each wrap continues where the previous left off)."""
        if self._plan is None:
            return data

        def gen():
            for batch in data:
                i = self._batch_counter
                self._batch_counter += 1
                if self._raise_sel and i in self._raise_sel:
                    self.injected["data_raise"] += 1
                    raise DataFault(f"injected data failure at batch {i} "
                                    "(FLEETX_FAULT_DATA_RAISE_BATCH)")
                if self._slow_sel and i in self._slow_sel:
                    self.injected["data_slow"] += 1
                    time.sleep(self._plan.data_slow_s)
                if self._nan_sel and i in self._nan_sel:
                    batch = self._poison(batch, i)
                yield batch

        return gen()

    def _poison(self, batch, i: int):
        """NaN-fill every floating-point leaf of a dict batch (copy)."""
        out, hit = {}, False
        for k, v in batch.items():
            arr = np.asarray(v)
            if np.issubdtype(arr.dtype, np.floating):
                arr = np.full_like(arr, np.nan)
                hit = True
            out[k] = arr
        if not hit:
            raise ValueError(
                f"FLEETX_FAULT_NAN_BATCH: batch {i} has no floating-point "
                "leaf to poison (keys: " + ", ".join(batch) + ")")
        self.injected["nan"] += 1
        return out

    def on_checkpoint_save(self, step: int) -> None:
        """Raise :class:`CkptFault` when ``step`` matches the plan."""
        if self._ckpt_sel and step in self._ckpt_sel:
            self.injected["ckpt"] += 1
            raise CkptFault(f"injected checkpoint-write failure at step "
                            f"{step} (FLEETX_FAULT_CKPT_SAVE_STEP)")


def raising_on_token(after_tokens: int = 1, record: Optional[list] = None):
    """Streaming callback that raises once its request has received
    ``after_tokens`` tokens — the deterministic bad-user-callback fault
    for the serving chaos scenarios. Tokens seen before the raise are
    appended to ``record`` (as ``(request_id, token, finished)``)."""
    seen = {"n": 0}

    def cb(request_id: int, token: int, finished: bool) -> None:
        seen["n"] += 1
        if record is not None:
            record.append((request_id, token, finished))
        if seen["n"] >= after_tokens:
            raise RuntimeError(
                f"injected on_token failure (request {request_id}, "
                f"token #{seen['n']})")

    return cb


faults = FaultInjector()
faults.configure_from_env()
