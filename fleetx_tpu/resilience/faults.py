"""Deterministic fault injection for the resilience chaos suite.

One process-global :class:`FaultInjector` (``faults``) owns a
:class:`FaultPlan` parsed from ``FLEETX_FAULT_*`` env vars (or installed
programmatically via :meth:`FaultInjector.configure`). Production code
carries the injection points — the Trainer wraps its train-data iterator
in :meth:`wrap_train_data` and calls :meth:`on_checkpoint_save` before
every checkpoint write — but when no plan is active every hook is a
single ``is None`` check, so an unconfigured run is byte-identical to a
build without this module.

Injection points (all batch indices count *fetched* train batches across
the whole run, independent of whether the sentry later skipped the step —
that keeps the injection deterministic under skip/resume):

- ``FLEETX_FAULT_NAN_BATCH``: poison every floating-point leaf of the
  matching train batches with NaN (the classic bad-shard/corrupt-record
  failure that turns the loss and every grad NaN).
- ``FLEETX_FAULT_DATA_RAISE_BATCH``: the data iterator raises
  ``DataFault`` instead of yielding the matching batch (a dead shard /
  filesystem error mid-epoch).
- ``FLEETX_FAULT_DATA_SLOW_BATCH`` / ``FLEETX_FAULT_DATA_SLOW_S``:
  sleep before yielding the matching batch (input-pipeline stall).
- ``FLEETX_FAULT_CKPT_SAVE_STEP``: ``Trainer.save`` raises ``CkptFault``
  at the matching step numbers (full disk / flaky object store).
- ``FLEETX_FAULT_HOST_LOSS_STEP``: selector over *applied* train step
  indices (the step about to run, i.e. ``state.step``) — the Trainer's
  step path raises ``HostLossFault`` before the matching step executes,
  modeling a host dropping out of the job. Each matching step index
  fires at most once per configure: a lost host does not die twice, and
  the elastic supervisor's resumed run (which replays the same step
  index on a smaller mesh) must survive. docs/RESILIENCE.md "Elastic
  training" has the recovery contract.

Serving injection points (exercised by the crash-safe serving story,
docs/RESILIENCE.md; indices count *attempted* device calls, so a
retried-after-recovery tick consumes a fresh index and a one-shot
selector faults exactly once):

- ``FLEETX_FAULT_TICK_RAISE``: the matching decode ticks raise
  ``TickFault`` before the device step (an XLA/device error mid-tick).
- ``FLEETX_FAULT_PREFILL_RAISE``: the matching prefill attempts raise
  ``PrefillFault`` (a prompt whose prefill reliably dies).
- ``FLEETX_FAULT_TICK_HANG`` / ``FLEETX_FAULT_TICK_HANG_S``: sleep
  ``FLEETX_FAULT_TICK_HANG_S`` seconds inside the matching decode ticks
  (a wedged device step — what the engine watchdog's
  ``FLEETX_SERVING_TICK_TIMEOUT_S`` is for).
- ``FLEETX_FAULT_POISON_REQUEST``: selector over *request ids* — any
  decode tick whose active set contains a matching request raises
  ``PoisonFault`` (the deterministic poison request the engine's
  bisection quarantine isolates). Decode-only by design: a poison that
  dies in its own prefill is already isolated (the engine knows who it
  was admitting) and is covered by ``FLEETX_FAULT_PREFILL_RAISE``.
- ``FLEETX_FAULT_KV_SHIP_RAISE``: the matching KV export attempts
  (``ServingEngine.export_kv`` on a prefill-role replica, counted per
  attempted export) raise ``KVShipFault`` before any page is read — the
  prefill replica dying mid-handoff; the router falls back to replaying
  the request on a surviving replica.
- ``FLEETX_FAULT_KV_SHIP_CORRUPT``: flip one byte inside the matching
  exported page payloads AFTER serialization — the in-flight bit flip
  the wire format's crc32 trailer exists to catch; the decode replica's
  ``payload_from_bytes`` must reject the ship loudly and the router
  falls back to replay.

Replica-level injection points (the multi-replica router failure
domain, docs/RESILIENCE.md "Router failover"; the router calls both
hooks — a process that runs one engine never pays more than the flag
check):

- ``FLEETX_FAULT_REPLICA_KILL``: ``"replica:tick"`` entries (comma-
  separated) — the router's attempt to tick the matching replica at the
  matching ROUTER tick raises ``ReplicaKilled`` (the process/device
  behind that replica vanished mid-burst; each entry fires once). The
  router marks the replica dead and migrates its in-flight requests.
- ``FLEETX_FAULT_PROBE_FLAP``: ``"replica:times"`` entries — the
  matching replica's next ``times`` health probes LIE (``state:
  "dead"``) before telling the truth again, exercising the router's
  bounded-backoff re-probe loop (a flap shorter than
  ``FLEETX_ROUTER_PROBE_MAX`` failures must rotate the replica out and
  back, never mark it dead).

Cross-process RPC injection points (the serving front door's replica
transport, docs/SERVING.md "Deployment"; indices count *attempted* RPC
calls process-wide in the calling process, so a retried call consumes a
fresh index):

- ``FLEETX_FAULT_RPC_DROP``: the matching RPC attempts raise
  :class:`RPCFault` INSTEAD of touching the network (a dropped
  connection / dead replica process). The replica client maps the
  failure onto the router's existing fallbacks: a dropped health probe
  reads as a dead replica, a dropped step as ``ReplicaKilled`` →
  migration, a dropped submit as a refusal the router routes around.
- ``FLEETX_FAULT_RPC_DELAY`` / ``FLEETX_FAULT_RPC_DELAY_S``: sleep
  ``FLEETX_FAULT_RPC_DELAY_S`` seconds before the matching RPC attempts
  (congested network / slow replica — what RPC timeouts exist to
  bound).

Batch/step selectors share one grammar: a comma-separated list of
entries, each either an int (``"3"``), or ``"N+"`` for every index >= N
(``"0+"`` = always). :func:`raising_on_token` builds the deterministic
raising streaming callback the serving chaos scenarios use.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Iterable, Optional

import numpy as np

from fleetx_tpu.obs.events import emit as obs_emit

__all__ = [
    "CkptFault",
    "DataFault",
    "FaultInjector",
    "FaultPlan",
    "HostLossFault",
    "KVShipFault",
    "PoisonFault",
    "PrefillFault",
    "RPCFault",
    "ReplicaKilled",
    "TickFault",
    "faults",
    "raising_on_token",
]


class DataFault(RuntimeError):
    """Injected data-iterator failure (FLEETX_FAULT_DATA_RAISE_BATCH)."""


class CkptFault(IOError):
    """Injected checkpoint-write failure (FLEETX_FAULT_CKPT_SAVE_STEP)."""


class HostLossFault(RuntimeError):
    """Injected training host loss (FLEETX_FAULT_HOST_LOSS_STEP): a host
    dropped out of the job before the matching step ran — the device
    state for its shard is gone and the job cannot continue on the
    current mesh. The elastic supervisor (resilience/elastic.py) catches
    this, snapshots what it can, and resumes on a smaller mesh."""


class TickFault(RuntimeError):
    """Injected serving decode-tick failure (FLEETX_FAULT_TICK_RAISE)."""


class PrefillFault(RuntimeError):
    """Injected serving prefill failure (FLEETX_FAULT_PREFILL_RAISE)."""


class PoisonFault(RuntimeError):
    """Injected poison-request failure (FLEETX_FAULT_POISON_REQUEST): the
    decode batch contained a request whose presence reliably kills the
    device step."""


class ReplicaKilled(RuntimeError):
    """Injected replica death (FLEETX_FAULT_REPLICA_KILL): the process or
    device behind a router replica vanished — every further call into its
    engine would hang or fail, so the router must rotate it out and
    migrate its in-flight requests."""


class KVShipFault(RuntimeError):
    """Injected KV-export failure (FLEETX_FAULT_KV_SHIP_RAISE): the
    prefill-role replica died (or its transport did) mid-handoff — the
    router must fall back to replaying the request on a survivor."""


class RPCFault(ConnectionError):
    """Injected RPC transport failure (FLEETX_FAULT_RPC_DROP): the
    request never reached the replica process (dropped connection, dead
    peer). A ``ConnectionError`` subclass so the replica client's
    network-failure mapping treats injected and real drops through one
    code path."""


class _Selector:
    """Index selector: ``"3"``, ``"1,4"``, ``"2+"`` (every index >= 2)."""

    def __init__(self, spec: str):
        self.exact = set()
        self.from_ = None  # smallest N of any "N+" entry
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            if part.endswith("+"):
                n = int(part[:-1])
                self.from_ = n if self.from_ is None else min(self.from_, n)
            else:
                self.exact.add(int(part))

    def __contains__(self, i: int) -> bool:
        return i in self.exact or (self.from_ is not None and i >= self.from_)

    def __bool__(self) -> bool:
        return bool(self.exact) or self.from_ is not None


def _parse_pairs(spec: str, what: str):
    """Parse the replica-level ``"a:b"`` grammar — comma-separated
    ``replica:value`` int pairs — into an ordered ``[(a, b), ...]``.
    Malformed entries raise, naming the offending variable (a chaos run
    must fail loudly, never silently skip its faults)."""
    out = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        try:
            a, b = part.split(":")
            out.append((int(a), int(b)))
        except ValueError:
            raise ValueError(
                f"{what}={spec!r}: entries must be 'replica:N' int pairs "
                "like '1:3' or '0:2,1:3'")
    return out


@dataclasses.dataclass
class FaultPlan:
    """Parsed fault schedule (module docstring has the env grammar)."""

    nan_batch: Optional[str] = None
    data_raise_batch: Optional[str] = None
    data_slow_batch: Optional[str] = None
    data_slow_s: float = 0.05
    ckpt_save_step: Optional[str] = None
    host_loss_step: Optional[str] = None
    tick_raise: Optional[str] = None
    prefill_raise: Optional[str] = None
    tick_hang: Optional[str] = None
    tick_hang_s: float = 30.0
    poison_request: Optional[str] = None
    replica_kill: Optional[str] = None
    probe_flap: Optional[str] = None
    kv_ship_raise: Optional[str] = None
    kv_ship_corrupt: Optional[str] = None
    rpc_drop: Optional[str] = None
    rpc_delay: Optional[str] = None
    rpc_delay_s: float = 0.05

    @classmethod
    def from_env(cls, env=os.environ) -> Optional["FaultPlan"]:
        """Build a plan from ``FLEETX_FAULT_*`` (None when none are set).
        Malformed values raise a ValueError naming the offending var — a
        chaos run must fail loudly, never silently skip its faults."""
        def _float(name, default):
            raw = env.get(name)
            if not raw:
                return default
            try:
                return float(raw)
            except ValueError:
                raise ValueError(f"{name}={raw!r} is not a float")

        plan = cls(
            nan_batch=env.get("FLEETX_FAULT_NAN_BATCH") or None,
            data_raise_batch=env.get("FLEETX_FAULT_DATA_RAISE_BATCH") or None,
            data_slow_batch=env.get("FLEETX_FAULT_DATA_SLOW_BATCH") or None,
            data_slow_s=_float("FLEETX_FAULT_DATA_SLOW_S", 0.05),
            ckpt_save_step=env.get("FLEETX_FAULT_CKPT_SAVE_STEP") or None,
            host_loss_step=env.get("FLEETX_FAULT_HOST_LOSS_STEP") or None,
            tick_raise=env.get("FLEETX_FAULT_TICK_RAISE") or None,
            prefill_raise=env.get("FLEETX_FAULT_PREFILL_RAISE") or None,
            tick_hang=env.get("FLEETX_FAULT_TICK_HANG") or None,
            tick_hang_s=_float("FLEETX_FAULT_TICK_HANG_S", 30.0),
            poison_request=env.get("FLEETX_FAULT_POISON_REQUEST") or None,
            replica_kill=env.get("FLEETX_FAULT_REPLICA_KILL") or None,
            probe_flap=env.get("FLEETX_FAULT_PROBE_FLAP") or None,
            kv_ship_raise=env.get("FLEETX_FAULT_KV_SHIP_RAISE") or None,
            kv_ship_corrupt=env.get("FLEETX_FAULT_KV_SHIP_CORRUPT") or None,
            rpc_drop=env.get("FLEETX_FAULT_RPC_DROP") or None,
            rpc_delay=env.get("FLEETX_FAULT_RPC_DELAY") or None,
            rpc_delay_s=_float("FLEETX_FAULT_RPC_DELAY_S", 0.05),
        )
        if not (plan.nan_batch or plan.data_raise_batch
                or plan.data_slow_batch or plan.ckpt_save_step
                or plan.host_loss_step
                or plan.tick_raise or plan.prefill_raise or plan.tick_hang
                or plan.poison_request or plan.replica_kill
                or plan.probe_flap or plan.kv_ship_raise
                or plan.kv_ship_corrupt or plan.rpc_drop or plan.rpc_delay):
            return None
        return plan


class FaultInjector:
    """Process-global injector: holds the active plan + fetch counters."""

    _ZERO = {"nan": 0, "data_raise": 0, "data_slow": 0, "ckpt": 0,
             "host_loss": 0,
             "tick_raise": 0, "prefill_raise": 0, "tick_hang": 0,
             "poison": 0, "replica_kill": 0, "probe_flap": 0,
             "kv_ship_raise": 0, "kv_ship_corrupt": 0,
             "rpc_drop": 0, "rpc_delay": 0}

    def __init__(self):
        self._plan: Optional[FaultPlan] = None
        self._nan_sel = self._raise_sel = self._slow_sel = self._ckpt_sel = None
        self._host_loss_sel = None
        self._host_loss_fired = set()  # step indices already killed once
        self._tick_sel = self._prefill_sel = self._hang_sel = None
        self._poison_sel = None
        self._ship_raise_sel = self._ship_corrupt_sel = None
        self._rpc_drop_sel = self._rpc_delay_sel = None
        self._kill_pending = set()   # {(replica, router_tick)} unfired
        self._flap_remaining = {}    # replica -> lying probes left
        self._batch_counter = 0
        self._rpc_counter = 0
        self.injected = dict(self._ZERO)

    # ----------------------------------------------------------- configure
    def configure(self, plan: Optional[FaultPlan] = None, **kw) -> None:
        """Install ``plan`` (or build one from kwargs); resets counters."""
        if plan is None and kw:
            plan = FaultPlan(**{k: str(v) if v is not None
                                and k.endswith(("batch", "step", "raise",
                                                "hang", "request", "kill",
                                                "flap", "corrupt", "drop",
                                                "delay")) else v
                                for k, v in kw.items()})
        def sel(field):
            spec = getattr(plan, field, None) if plan else None
            if not spec:
                return None
            try:
                return _Selector(spec)
            except ValueError:
                raise ValueError(
                    f"FLEETX_FAULT_{field.upper()}={spec!r}: selector "
                    "entries must be ints like '3', '1,4', or '2+'")

        self._plan = plan
        self._nan_sel = sel("nan_batch")
        self._raise_sel = sel("data_raise_batch")
        self._slow_sel = sel("data_slow_batch")
        self._ckpt_sel = sel("ckpt_save_step")
        self._host_loss_sel = sel("host_loss_step")
        self._host_loss_fired = set()
        self._tick_sel = sel("tick_raise")
        self._prefill_sel = sel("prefill_raise")
        self._hang_sel = sel("tick_hang")
        self._poison_sel = sel("poison_request")
        self._ship_raise_sel = sel("kv_ship_raise")
        self._ship_corrupt_sel = sel("kv_ship_corrupt")
        self._rpc_drop_sel = sel("rpc_drop")
        self._rpc_delay_sel = sel("rpc_delay")
        kill = getattr(plan, "replica_kill", None) if plan else None
        flap = getattr(plan, "probe_flap", None) if plan else None
        self._kill_pending = set(
            _parse_pairs(kill, "FLEETX_FAULT_REPLICA_KILL") if kill else ())
        self._flap_remaining = dict(
            _parse_pairs(flap, "FLEETX_FAULT_PROBE_FLAP") if flap else ())
        self._batch_counter = 0
        self._rpc_counter = 0
        self.injected = dict(self._ZERO)

    def configure_from_env(self, env=os.environ) -> None:
        """Re-read ``FLEETX_FAULT_*`` into the active plan."""
        self.configure(FaultPlan.from_env(env))

    def reset(self) -> None:
        """Deactivate all faults and zero the counters."""
        self.configure(None)

    @property
    def active(self) -> bool:
        """True when any fault is scheduled."""
        return self._plan is not None

    # ------------------------------------------------------ injection points
    def wrap_train_data(self, data: Iterable) -> Iterable:
        """Route a train-data iterable through the data faults. Returns
        ``data`` unchanged when inert; the fetch counter is global across
        epochs (each wrap continues where the previous left off)."""
        if self._plan is None:
            return data

        def gen():
            for batch in data:
                i = self._batch_counter
                self._batch_counter += 1
                if self._raise_sel and i in self._raise_sel:
                    self.injected["data_raise"] += 1
                    obs_emit("fault_injected", fault="data_raise", batch=i)
                    raise DataFault(f"injected data failure at batch {i} "
                                    "(FLEETX_FAULT_DATA_RAISE_BATCH)")
                if self._slow_sel and i in self._slow_sel:
                    self.injected["data_slow"] += 1
                    obs_emit("fault_injected", fault="data_slow", batch=i)
                    time.sleep(self._plan.data_slow_s)
                if self._nan_sel and i in self._nan_sel:
                    batch = self._poison(batch, i)
                yield batch

        return gen()

    def _poison(self, batch, i: int):
        """NaN-fill every floating-point leaf of a dict batch (copy)."""
        out, hit = {}, False
        for k, v in batch.items():
            arr = np.asarray(v)
            if np.issubdtype(arr.dtype, np.floating):
                arr = np.full_like(arr, np.nan)
                hit = True
            out[k] = arr
        if not hit:
            raise ValueError(
                f"FLEETX_FAULT_NAN_BATCH: batch {i} has no floating-point "
                "leaf to poison (keys: " + ", ".join(batch) + ")")
        self.injected["nan"] += 1
        obs_emit("fault_injected", fault="nan", batch=i)
        return out

    def on_checkpoint_save(self, step: int) -> None:
        """Raise :class:`CkptFault` when ``step`` matches the plan."""
        if self._ckpt_sel and step in self._ckpt_sel:
            self.injected["ckpt"] += 1
            obs_emit("fault_injected", fault="ckpt", step=step)
            raise CkptFault(f"injected checkpoint-write failure at step "
                            f"{step} (FLEETX_FAULT_CKPT_SAVE_STEP)")

    def on_train_step(self, step: int) -> None:
        """Raise :class:`HostLossFault` before applied step ``step`` runs
        when it matches the plan. Each matching step index fires at most
        once per :meth:`configure` — a lost host does not die twice, so
        the elastic supervisor's resumed run replays the same step index
        on the shrunken mesh without re-triggering the fault."""
        if (self._host_loss_sel and step in self._host_loss_sel
                and step not in self._host_loss_fired):
            self._host_loss_fired.add(step)
            self.injected["host_loss"] += 1
            obs_emit("fault_injected", fault="host_loss", step=step)
            raise HostLossFault(
                f"injected host loss before step {step} "
                "(FLEETX_FAULT_HOST_LOSS_STEP)")

    def on_serving_tick(self, tick: int) -> None:
        """Counter-indexed decode-tick faults: hang (sleep) and/or raise
        when attempt index ``tick`` matches. Called INSIDE the engine's
        watchdog-guarded device call, so an injected hang is what the
        ``FLEETX_SERVING_TICK_TIMEOUT_S`` monitor sees."""
        if self._plan is None:
            return
        if self._hang_sel and tick in self._hang_sel:
            self.injected["tick_hang"] += 1
            obs_emit("fault_injected", fault="tick_hang", tick=tick)
            time.sleep(self._plan.tick_hang_s)
        if self._tick_sel and tick in self._tick_sel:
            self.injected["tick_raise"] += 1
            obs_emit("fault_injected", fault="tick_raise", tick=tick)
            raise TickFault(f"injected decode-tick failure at tick {tick} "
                            "(FLEETX_FAULT_TICK_RAISE)")

    def on_serving_prefill(self, attempt: int, request_id: int) -> None:
        """Raise :class:`PrefillFault` when prefill-attempt ``attempt``
        matches (attempts count every prefill device call, replays
        included)."""
        if self._prefill_sel and attempt in self._prefill_sel:
            self.injected["prefill_raise"] += 1
            obs_emit("fault_injected", fault="prefill_raise",
                     attempt=attempt, request=request_id)
            raise PrefillFault(
                f"injected prefill failure at attempt {attempt} "
                f"(request {request_id}, FLEETX_FAULT_PREFILL_RAISE)")

    def on_serving_batch(self, request_ids) -> None:
        """Raise :class:`PoisonFault` when any id in ``request_ids`` is a
        configured poison request. The engine calls this for real decode
        ticks AND for bisection probe subsets — exactly the semantics of a
        request whose presence kills any batch containing it."""
        if self._poison_sel is None:
            return
        hits = [int(r) for r in request_ids if int(r) in self._poison_sel]
        if hits:
            self.injected["poison"] += 1
            obs_emit("fault_injected", fault="poison", requests=str(hits))
            raise PoisonFault(
                f"injected poison-request failure (requests {hits} in the "
                "decode batch, FLEETX_FAULT_POISON_REQUEST)")


    def on_kv_ship(self, attempt: int, request_id: int) -> None:
        """Raise :class:`KVShipFault` when KV-export attempt ``attempt``
        matches (attempts count every ``export_kv`` call on the replica,
        so the index is deterministic across retries)."""
        if self._ship_raise_sel and attempt in self._ship_raise_sel:
            self.injected["kv_ship_raise"] += 1
            obs_emit("fault_injected", fault="kv_ship_raise",
                     attempt=attempt, request=request_id)
            raise KVShipFault(
                f"injected KV-export failure at ship attempt {attempt} "
                f"(request {request_id}, FLEETX_FAULT_KV_SHIP_RAISE)")

    def on_kv_ship_corrupt(self, attempt: int) -> bool:
        """True when export attempt ``attempt`` should corrupt its
        serialized payload (the engine flips one byte past the header so
        the crc32 check on the receiving side fails loudly)."""
        if self._ship_corrupt_sel and attempt in self._ship_corrupt_sel:
            self.injected["kv_ship_corrupt"] += 1
            obs_emit("fault_injected", fault="kv_ship_corrupt",
                     attempt=attempt)
            return True
        return False

    def on_rpc(self, method: str) -> None:
        """Cross-process RPC fault seam, called by the replica client
        before every HTTP call it issues. Indices count attempted RPCs
        process-wide (``method`` only labels the event). Sleeps
        ``rpc_delay_s`` when the delay selector matches, then raises
        :class:`RPCFault` when the drop selector matches — delay-then-
        drop models a connection that stalls before dying."""
        if self._plan is None:
            return
        if self._rpc_drop_sel is None and self._rpc_delay_sel is None:
            return
        i = self._rpc_counter
        self._rpc_counter += 1
        if self._rpc_delay_sel and i in self._rpc_delay_sel:
            self.injected["rpc_delay"] += 1
            obs_emit("fault_injected", fault="rpc_delay", attempt=i,
                     method=method)
            time.sleep(self._plan.rpc_delay_s)
        if self._rpc_drop_sel and i in self._rpc_drop_sel:
            self.injected["rpc_drop"] += 1
            obs_emit("fault_injected", fault="rpc_drop", attempt=i,
                     method=method)
            raise RPCFault(
                f"injected RPC drop at attempt {i} (method {method!r}, "
                "FLEETX_FAULT_RPC_DROP)")

    def on_router_tick(self, replica: int, tick: int) -> None:
        """Raise :class:`ReplicaKilled` when the router is about to tick
        ``replica`` at router tick ``tick`` and an unfired
        ``FLEETX_FAULT_REPLICA_KILL`` entry matches (each entry fires
        exactly once — a killed process does not die twice)."""
        if not self._kill_pending:
            return
        key = (int(replica), int(tick))
        if key in self._kill_pending:
            self._kill_pending.discard(key)
            self.injected["replica_kill"] += 1
            obs_emit("fault_injected", fault="replica_kill",
                     replica=key[0], tick=key[1])
            raise ReplicaKilled(
                f"injected replica death: replica {key[0]} at router tick "
                f"{key[1]} (FLEETX_FAULT_REPLICA_KILL)")

    def on_router_probe(self, replica: int) -> Optional[dict]:
        """A LYING health report for ``replica`` while its
        ``FLEETX_FAULT_PROBE_FLAP`` budget lasts (None = probe honestly).
        The lie is a ``state: "dead"`` healthz body — the worst rotate-out
        reason — so the router's backoff/escalation path is the one under
        test, not the report parser."""
        remaining = self._flap_remaining.get(int(replica), 0)
        if remaining <= 0:
            return None
        self._flap_remaining[int(replica)] = remaining - 1
        self.injected["probe_flap"] += 1
        obs_emit("fault_injected", fault="probe_flap", replica=int(replica),
                 remaining=remaining - 1)
        return {"state": "dead", "queue_depth": 0, "active": 0,
                "injected": True}


def raising_on_token(after_tokens: int = 1, record: Optional[list] = None):
    """Streaming callback that raises once its request has received
    ``after_tokens`` tokens — the deterministic bad-user-callback fault
    for the serving chaos scenarios. Tokens seen before the raise are
    appended to ``record`` (as ``(request_id, token, finished)``)."""
    seen = {"n": 0}

    def cb(request_id: int, token: int, finished: bool) -> None:
        seen["n"] += 1
        if record is not None:
            record.append((request_id, token, finished))
        if seen["n"] >= after_tokens:
            raise RuntimeError(
                f"injected on_token failure (request {request_id}, "
                f"token #{seen['n']})")

    return cb


faults = FaultInjector()
faults.configure_from_env()
