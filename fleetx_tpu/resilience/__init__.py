"""Resilience layer: deterministic fault injection for chaos testing.

The training sentry (core/engine.py), checkpoint fallback (Trainer.load),
and serving admission control (serving/engine.py) are the *production*
halves of the resilience story; this package holds the test half — a
deterministic, env/config-driven fault injector (``faults.py``) whose
injection points are compiled into the hot paths but cost one global
flag check when inert. docs/RESILIENCE.md has the full tour.
"""

from fleetx_tpu.resilience.faults import (
    FaultPlan,
    PoisonFault,
    PrefillFault,
    ReplicaKilled,
    TickFault,
    faults,
)

__all__ = ["FaultPlan", "PoisonFault", "PrefillFault", "ReplicaKilled",
           "TickFault", "faults"]
