"""Elastic training: survive host loss by shrinking the mesh and
resuming through reshard-on-load.

The serving layer already survives replica death (docs/RESILIENCE.md
"Router failover"); this module is the training-side counterpart. The
pieces:

- :func:`validate_restore_mesh` — the reshard-on-load contract.
  ``Trainer.load()`` restores a checkpoint written under one
  dp×fsdp×mp mesh onto a *different* mesh: orbax's abstract-shape
  ``StandardRestore`` reshards into the new trainer's
  ``_state_shardings`` (ZeRO update layouts re-derived, not assumed)
  because array *global* shapes do not depend on dp/fsdp extents. They
  DO depend on mp/pp/cp — vocab padding is sized by the mp degree, and
  layer stacking by pp — so those extents must match and this function
  refuses the restore with :class:`ElasticMeshMismatch` (a config
  error, never quarantined as corruption) when they do not.
- :func:`plan_shrunken_mesh` — which axis to give up when hosts are
  lost: dp first (pure replication, cheapest capacity to lose), then
  fsdp. mp/pp/cp never shrink — the checkpoint contract above.
- :func:`run_elastic` — the supervisor seam ``tools/train.py`` runs
  under: catch :class:`~fleetx_tpu.resilience.faults.HostLossFault`
  from ``Trainer.fit``, take an emergency snapshot if the device state
  is still reachable, rebuild a smaller mesh, resume via
  reshard-on-load, and continue — every batch consumed exactly once
  across the shrink (``consumed_samples`` → sampler continuity).

Chaos coverage: ``tools/chaos_check.py train_elastic`` asserts
loss-trajectory parity across a mid-run dp4→dp2 shrink against an
uninterrupted dp2 run over the same batches.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from fleetx_tpu.obs.events import emit as obs_emit
from fleetx_tpu.parallel.mesh import MeshConfig
from fleetx_tpu.resilience.faults import HostLossFault
from fleetx_tpu.utils.log import logger

__all__ = [
    "ElasticMeshMismatch",
    "apply_mesh_to_config",
    "plan_shrunken_mesh",
    "run_elastic",
    "validate_restore_mesh",
]

# axes whose extent is baked into array global shapes (vocab padding ~ mp,
# layer placement ~ pp, sequence split ~ cp): a checkpoint cannot move
# across a change in any of these, only across dp/fsdp.
_FIXED_AXES = ("mp", "pp", "cp")


class ElasticMeshMismatch(RuntimeError):
    """A checkpoint cannot be restored onto this mesh (or the mesh cannot
    shrink): an axis whose extent is baked into array shapes differs.
    This is a *configuration* error, not checkpoint corruption —
    ``Trainer.load`` re-raises it instead of quarantining the (healthy)
    checkpoint."""


def validate_restore_mesh(saved: dict, mesh_cfg: MeshConfig,
                          step: Optional[int] = None) -> None:
    """Check a checkpoint's recorded mesh against the restoring mesh.

    ``saved`` is the ``meta["mesh"]`` dict the Trainer records at save
    time (``{"dp": ..., "fsdp": ..., "mp": ..., "pp": ..., "cp": ...}``).
    mp/pp/cp extents must agree (raises :class:`ElasticMeshMismatch`
    otherwise); a dp/fsdp change is the supported elastic reshard and
    just logs + emits an ``elastic_reshard`` event.
    """
    bad = {}
    for ax in _FIXED_AXES:
        was, now = int(saved.get(ax) or 1), int(getattr(mesh_cfg, ax))
        if was != now:
            bad[ax] = (was, now)
    if bad:
        detail = ", ".join(f"{ax} {was}->{now}" for ax, (was, now) in bad.items())
        raise ElasticMeshMismatch(
            f"checkpoint{'' if step is None else f' step {step}'} was written "
            f"under an incompatible mesh: {detail} (mp/pp/cp extents are "
            "baked into array shapes; only dp/fsdp may change on restore)")
    was_dp = int(saved.get("dp") or 1)
    was_fsdp = int(saved.get("fsdp") or 1)
    if (was_dp, was_fsdp) != (mesh_cfg.dp, mesh_cfg.fsdp):
        logger.info(
            "elastic reshard-on-load: checkpoint mesh dp%d x fsdp%d -> "
            "dp%d x fsdp%d (ZeRO update layouts re-derived for the new mesh)",
            was_dp, was_fsdp, mesh_cfg.dp, mesh_cfg.fsdp)
        obs_emit("elastic_reshard", step=step,
                 saved_dp=was_dp, saved_fsdp=was_fsdp,
                 dp=mesh_cfg.dp, fsdp=mesh_cfg.fsdp)


def plan_shrunken_mesh(mesh_cfg: MeshConfig, factor: int = 2) -> MeshConfig:
    """The mesh to resume on after losing ``1 - 1/factor`` of the hosts.

    Gives up dp capacity first (pure replication — shrinking it costs
    throughput, nothing else), then fsdp. mp/pp/cp never change: their
    extents are baked into the checkpoint (see :func:`validate_restore_mesh`),
    so a job that loses part of a model-parallel group cannot shrink and
    this raises :class:`ElasticMeshMismatch`.
    """
    if mesh_cfg.dp > 1 and mesh_cfg.dp % factor == 0:
        return dataclasses.replace(mesh_cfg, dp=mesh_cfg.dp // factor)
    if mesh_cfg.fsdp > 1 and mesh_cfg.fsdp % factor == 0:
        return dataclasses.replace(mesh_cfg, fsdp=mesh_cfg.fsdp // factor)
    raise ElasticMeshMismatch(
        f"mesh dp{mesh_cfg.dp} x fsdp{mesh_cfg.fsdp} x mp{mesh_cfg.mp} x "
        f"pp{mesh_cfg.pp} x cp{mesh_cfg.cp} has no data-parallel capacity "
        f"to give up (cannot shrink by {factor}; mp/pp/cp extents are fixed "
        "by the checkpoint contract)")


def apply_mesh_to_config(cfg, new_mesh: MeshConfig) -> None:
    """Rewrite ``cfg`` in place for a shrunken mesh, holding the
    optimization trajectory fixed.

    ``Global.global_batch_size`` (and the gradient-accumulation factor
    ``local/micro``) are preserved by scaling ``local_batch_size`` and
    ``micro_batch_size`` up by the lost data-parallel capacity — the
    resumed run applies the *same* global batches in the same order,
    just spread over fewer replicas. Raises :class:`ElasticMeshMismatch`
    when the global batch does not divide over the new mesh.
    """
    dist = cfg.Distributed
    old_world = (dist.dp_degree or 1) * ((dist.sharding or {}).get("sharding_degree") or 1)
    new_world = new_mesh.dp * new_mesh.fsdp
    glb = cfg.Global
    gbs = glb.global_batch_size
    if gbs % new_world:
        raise ElasticMeshMismatch(
            f"global_batch_size {gbs} does not divide over the shrunken "
            f"data-parallel world {new_world} (dp{new_mesh.dp} x fsdp{new_mesh.fsdp})")
    accum = glb.local_batch_size // glb.micro_batch_size
    dist.dp_degree = new_mesh.dp
    dist.sharding.sharding_degree = new_mesh.fsdp
    glb.local_batch_size = gbs // new_world
    if glb.local_batch_size % accum:
        raise ElasticMeshMismatch(
            f"local_batch_size {glb.local_batch_size} on the shrunken mesh "
            f"does not preserve the gradient-accumulation factor {accum}")
    glb.micro_batch_size = glb.local_batch_size // accum
    logger.info(
        "elastic config rewrite: dp world %d -> %d, local_batch %d, "
        "micro_batch %d (global_batch %d held fixed)",
        old_world, new_world, glb.local_batch_size, glb.micro_batch_size, gbs)


def run_elastic(cfg, trainer, train_data, valid_data=None, *,
                build_trainer: Optional[Callable] = None,
                make_loader: Optional[Callable] = None,
                max_shrinks: int = 4):
    """Run ``trainer.fit`` under the elastic supervisor.

    On :class:`HostLossFault` (the injected stand-in for a host dropping
    out): take an emergency snapshot if the device state is still
    reachable (``_guarded_save`` absorbs a failure — resume then falls
    back to the last periodic checkpoint, re-feeding its batches exactly
    once), plan a smaller mesh, rewrite ``cfg``, rebuild the trainer,
    and resume through reshard-on-load. Returns the (possibly rebuilt)
    trainer after ``fit`` completes.

    ``build_trainer(cfg)`` overrides trainer construction (default:
    ``Trainer(cfg, build_module(cfg))``); ``make_loader(cfg, consumed)``
    rebuilds the train iterable for the new mesh given the samples
    already consumed — without it ``train_data`` is reused as-is, and
    data-order continuity rides the batch sampler's
    ``consumed_samples`` when one is attached.
    """
    shrinks = 0
    while True:
        try:
            trainer.fit(train_data, valid_data)
            return trainer
        except HostLossFault as e:
            shrinks += 1
            step = int(trainer.state.step) if trainer.state is not None else -1
            if shrinks > max_shrinks:
                logger.error("host loss at step %d but shrink budget "
                             "(%d) exhausted; giving up", step, max_shrinks)
                raise
            logger.warning("host loss at step %d (%s); attempting elastic "
                           "shrink %d/%d", step, e, shrinks, max_shrinks)
            # emergency snapshot: in a real host loss the device state may
            # already be unreachable — _guarded_save counts the failure and
            # resume falls back to the last periodic checkpoint
            epoch = getattr(trainer, "_cur_epoch", trainer.start_epoch)
            trainer._guarded_save(epoch)
            trainer.wait_for_checkpoints()
            new_mesh = plan_shrunken_mesh(trainer.mesh_cfg)
            obs_emit("elastic_shrink", step=step,
                     dp=trainer.mesh_cfg.dp, fsdp=trainer.mesh_cfg.fsdp,
                     new_dp=new_mesh.dp, new_fsdp=new_mesh.fsdp)
            apply_mesh_to_config(cfg, new_mesh)
            if build_trainer is not None:
                trainer = build_trainer(cfg)
            else:
                from fleetx_tpu.core.engine import Trainer
                from fleetx_tpu.models import build_module
                trainer = Trainer(cfg, build_module(cfg))
            # init_state's resumable branch restores the snapshot through
            # reshard-on-load (abstract restore into the new mesh's shardings)
            first = next(iter(train_data))
            trainer.init_state(first)
            if make_loader is not None:
                train_data = make_loader(cfg, trainer.consumed_samples)
            sampler = getattr(train_data, "batch_sampler", None)
            if sampler is not None and hasattr(sampler, "consumed_samples"):
                sampler.consumed_samples = trainer.consumed_samples
