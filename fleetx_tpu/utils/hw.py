"""Hardware peak-FLOPs lookup shared by MFU accounting everywhere.

One table (public spec sheets, dense bf16) so ``bench.py``'s BENCH_*
records, the Trainer's live ``mfu`` gauge/log-line, and any future
report all divide by the SAME peak — MFU numbers stay comparable across
surfaces. Unknown accelerators assume v5e-class so the ratio is at
least stable; CPU gets a placeholder that keeps smoke runs finite.
"""

from __future__ import annotations

__all__ = ["PEAK_FLOPS", "peak_flops_per_chip"]

# Peak dense bf16 FLOP/s per chip by device kind (public spec sheets).
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v4 lite": 138e12,   # v4i
    "TPU v3": 123e12,
    "TPU v6 lite": 918e12,   # Trillium
    "TPU v6e": 918e12,
    "cpu": 1e12,             # placeholder so CPU smoke runs don't div0
}


def peak_flops_per_chip(device) -> float:
    """Peak dense bf16 FLOP/s for ``device`` (a jax Device or anything
    with ``device_kind``). Longest-prefix match so 'TPU v4 lite'
    resolves before 'TPU v4'; unknown kinds assume v5e-class."""
    kind = getattr(device, "device_kind", "cpu")
    for name in sorted(PEAK_FLOPS, key=len, reverse=True):
        if kind.startswith(name):
            return PEAK_FLOPS[name]
    return 197e12  # unknown accelerator: assume v5e-class
