"""Profiler summary views (reference EagerEngine._print_summary,
/root/reference/ppfleetx/core/engine/eager_engine.py:761-820: prints
overview/model/kernel/op/mem summaries from the paddle profiler, view set
configurable via ``Profiler.summary`` with a ``detailed`` override).

TPU equivalents, assembled from what XLA/JAX actually exposes:
- overview: wall-time stats of the profiled steps (collected by the Trainer)
- model:    param/opt-state footprint + XLA cost analysis of the compiled
            train step (flops / bytes accessed per step)
- kernel:   top ops by total self-duration, parsed from the Chrome-trace
            .trace.json.gz the jax profiler writes under
            ``{log_dir}/plugins/profile/<run>/``
- mem:      per-device live/peak HBM from device.memory_stats()
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from collections import defaultdict
from typing import Dict, List, Optional

import numpy as np

from fleetx_tpu.utils.log import logger

__all__ = ["print_summary"]

_DEFAULT_VIEWS = ("overview", "model", "kernel", "mem")
_ALL_VIEWS = ("overview", "model", "kernel", "mem")


def _selected_views(profiler_cfg: Dict) -> List[str]:
    if profiler_cfg.get("detailed"):
        return list(_ALL_VIEWS)
    chosen = profiler_cfg.get("summary") or {}
    views = [v for v in _ALL_VIEWS
             if chosen.get(v, v in _DEFAULT_VIEWS)]
    return views


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0:
            return f"{n:.2f} {unit}"
        n /= 1024.0
    return f"{n:.2f} PiB"


def _rule(title: str) -> str:
    pad = max(4, 72 - len(title) - 2)
    return f"--- {title} {'-' * pad}"


def _overview(step_times: List[float]):
    logger.info(_rule("profiler overview"))
    if not step_times:
        logger.info("no step timings collected in the profiled window")
        return
    t = np.asarray(step_times)
    logger.info(
        "steps profiled: %d | step time mean %.2f ms, min %.2f ms, "
        "max %.2f ms, p50 %.2f ms",
        t.size, t.mean() * 1e3, t.min() * 1e3, t.max() * 1e3,
        float(np.percentile(t, 50)) * 1e3,
    )


def _model(trainer):
    import jax

    logger.info(_rule("model view"))
    try:
        params = trainer.state.params
        n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        p_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
        o_bytes = sum(
            x.size * x.dtype.itemsize
            for x in jax.tree.leaves(trainer.state.opt_state)
            if hasattr(x, "dtype")
        )
        logger.info(
            "params: %.1fM (%s) | opt state: %s",
            n_params / 1e6, _fmt_bytes(p_bytes), _fmt_bytes(o_bytes),
        )
    except Exception as e:  # state not initialized — still print cost info
        logger.info("param stats unavailable: %s", e)
    cost = None
    try:
        # AOT path: jit wrappers expose no cost_analysis, only the Compiled
        # object does — trainer.cost_analysis() re-lowers with the recorded
        # avals (a compilation-cache hit) and asks the executable
        cost = trainer.cost_analysis("train")
    except Exception:
        cost = None
    if cost:
        flops = cost.get("flops", 0.0)
        logger.info(
            "xla cost analysis (per step): %.2f GFLOP, %s accessed",
            flops / 1e9, _fmt_bytes(cost.get("bytes accessed", 0.0)),
        )


def _kernel(log_dir: str, top_k: int = 15):
    logger.info(_rule("kernel view (top ops by self time)"))
    traces = sorted(
        glob.glob(os.path.join(log_dir, "plugins", "profile", "*",
                               "*.trace.json.gz")),
        key=os.path.getmtime,
    )
    if not traces:
        logger.info("no trace found under %s", log_dir)
        return
    try:
        with gzip.open(traces[-1], "rt") as f:
            trace = json.load(f)
    except Exception as e:
        logger.info("trace unreadable (%s): %s", traces[-1], e)
        return
    events = trace.get("traceEvents", [])
    # pid->process name so we can keep device (TPU/XLA) tracks and drop the
    # python host threads, which would otherwise double-count everything
    proc_names = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            proc_names[ev.get("pid")] = (ev.get("args") or {}).get("name", "")
    device_pids = {
        pid for pid, name in proc_names.items()
        if any(s in name for s in ("TPU", "GPU", "/device:", "XLA Op"))
    }
    # the 'XLA Ops' line holds the LEAF per-op events; module/step lines
    # ('XLA Modules', 'Steps', jit_* wrappers) span entire steps and would
    # double-count everything beneath them
    thread_names = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            thread_names[(ev.get("pid"), ev.get("tid"))] = (
                (ev.get("args") or {}).get("name", "")
            )
    op_tracks = {
        key for key, name in thread_names.items() if name == "XLA Ops"
    }
    # no 'XLA Ops' line in this trace flavor: still drop the known
    # step/module wrapper lines, whose events span whole steps and would
    # bury the leaf ops in the self-time table
    wrapper_tracks = {
        key for key, name in thread_names.items()
        if name in ("Steps", "XLA Modules", "Framework Ops")
    }
    # SELF time per op: complete events on one track nest (jit_train_step >
    # while > fusion), so naive dur sums double-count every level. Per
    # (pid, tid), sweep events in start order with an enclosing-interval
    # stack; each event's self time is its duration minus its direct
    # children's spans.
    per_track = defaultdict(list)
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        if device_pids and ev.get("pid") not in device_pids:
            continue
        key = (ev.get("pid"), ev.get("tid"))
        if op_tracks:
            if key not in op_tracks:
                continue
        elif key in wrapper_tracks:
            continue
        per_track[key].append(ev)
    totals = defaultdict(float)
    counts = defaultdict(int)
    for track in per_track.values():
        track.sort(key=lambda e: (e["ts"], -e["dur"]))
        open_nodes = []  # [name, end_ts, child_total, dur]

        def _close(node):
            totals[node[0]] += max(0.0, node[3] - node[2])
            counts[node[0]] += 1

        for ev in track:
            ts, dur = ev["ts"], ev["dur"]
            while open_nodes and ts >= open_nodes[-1][1] - 1e-9:
                _close(open_nodes.pop())
            if open_nodes:
                open_nodes[-1][2] += dur  # child span off the parent's self
            open_nodes.append([ev["name"], ts + dur, 0.0, dur])
        while open_nodes:
            _close(open_nodes.pop())
    if not totals:
        logger.info("trace had no complete device events")
        return
    grand = sum(totals.values())
    logger.info("%-48s %10s %8s %7s", "op", "total(us)", "calls", "%")
    for name, dur in sorted(totals.items(), key=lambda kv: -kv[1])[:top_k]:
        shown = name if len(name) <= 48 else name[:45] + "..."
        logger.info(
            "%-48s %10.0f %8d %6.1f%%",
            shown, dur, counts[name], 100.0 * dur / grand,
        )


def _mem():
    import jax

    logger.info(_rule("memory view"))
    any_stats = False
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        any_stats = True
        logger.info(
            "%s: in use %s | peak %s | limit %s",
            d, _fmt_bytes(stats.get("bytes_in_use", 0)),
            _fmt_bytes(stats.get("peak_bytes_in_use", 0)),
            _fmt_bytes(stats.get("bytes_limit", 0)),
        )
    if not any_stats:
        logger.info("device memory stats not exposed on this platform")


def print_summary(
    trainer,
    profiler_cfg: Dict,
    log_dir: str,
    step_times: Optional[List[float]] = None,
):
    """Print the configured summary views after a profiling window closes."""
    views = _selected_views(profiler_cfg)
    if "overview" in views:
        _overview(step_times or [])
    if "model" in views:
        _model(trainer)
    if "kernel" in views:
        _kernel(log_dir)
    if "mem" in views:
        _mem()
    logger.info(
        "full timeline: tensorboard --logdir %s (or xprof)", log_dir
    )
