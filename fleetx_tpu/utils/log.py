"""Leveled, colored logger with extra TRAIN/EVAL levels.

Parity with the reference logger (/root/reference/ppfleetx/utils/log.py:33-151)
which CI depends on for its ``ips:`` keyword lines; process-0 gating uses
``jax.process_index()`` lazily instead of an MPI/NCCL rank.
"""

from __future__ import annotations

import functools
import logging
import os
import sys
import time

__all__ = ["logger", "get_timestamp", "advertise", "only_primary"]

TRAIN = 21
EVAL = 22
logging.addLevelName(TRAIN, "TRAIN")
logging.addLevelName(EVAL, "EVAL")

_COLORS = {
    "DEBUG": "\033[36m",
    "INFO": "\033[32m",
    "TRAIN": "\033[35m",
    "EVAL": "\033[34m",
    "WARNING": "\033[33m",
    "ERROR": "\033[31m",
}
_RESET = "\033[0m"


class _Formatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if sys.stdout.isatty():
            color = _COLORS.get(record.levelname, "")
            if color:
                ts, _, rest = msg.partition(record.levelname)
                return f"{ts}{color}{record.levelname}{_RESET}{rest}"
        return msg


class _Logger(logging.Logger):
    def train(self, msg, *args, **kwargs):
        if self.isEnabledFor(TRAIN):
            self._log(TRAIN, msg, args, **kwargs)

    def eval(self, msg, *args, **kwargs):
        if self.isEnabledFor(EVAL):
            self._log(EVAL, msg, args, **kwargs)


logging.setLoggerClass(_Logger)
logger: _Logger = logging.getLogger("fleetx_tpu")  # type: ignore[assignment]
logging.setLoggerClass(logging.Logger)

_handler = logging.StreamHandler(sys.stdout)
_handler.setFormatter(_Formatter("[%(asctime)s] [%(levelname)8s] %(message)s", "%Y-%m-%d %H:%M:%S"))
logger.addHandler(_handler)
logger.setLevel(os.environ.get("FLEETX_LOG_LEVEL", "INFO"))
logger.propagate = False


def _is_primary() -> bool:
    # Deliberately uncached and side-effect-free w.r.t. backend init: calling
    # jax.process_index() before jax.distributed.initialize would both break
    # the later init and wrongly pin process 0 on every host. Until the
    # distributed service is up, every host counts as primary.
    try:
        import jax

        if not jax.distributed.is_initialized():
            return int(os.environ.get("FLEETX_PROCESS_ID", "0")) == 0
        return jax.process_index() == 0
    except Exception:
        return True


def only_primary(fn):
    """Decorator: run fn only on process 0 of a multi-host job."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if _is_primary():
            return fn(*args, **kwargs)

    return wrapper


def get_timestamp() -> str:
    """Filesystem-safe timestamp string (reference log.py:181)."""
    return time.strftime("%Y%m%d_%H%M%S", time.localtime())


def advertise() -> None:
    """Startup banner (reference log.py:153)."""
    logger.info("=" * 64)
    logger.info("fleetx-tpu — TPU-native large-model toolkit (JAX/XLA/Pallas)")
    logger.info("=" * 64)
