"""Config system, logger, export, profiler summaries (reference ppfleetx/utils)."""
