"""Model export (reference /root/reference/ppfleetx/utils/export.py:44 —
``paddle.jit.to_static`` + prune + save, consumed by InferenceEngine).

TPU-native artifact, one directory:

    export_dir/
      config.yaml         # Model/Generation config to rebuild the module
      params/             # orbax checkpoint of inference params
      forward.stablehlo   # jit-lowered StableHLO of the forward fn
      input_spec.json     # shapes/dtypes the export was traced with

StableHLO is the portable compiled-graph format (what ``to_static``'s
program is to paddle.inference); any XLA runtime — and jax2tf / IREE
pipelines — can consume it. Serving-side, InferenceEngine
(fleetx_tpu/core/inference_engine.py) AOT-compiles from config+params;
TensorRT has no TPU analogue (XLA is the optimizing backend).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np
import yaml

from fleetx_tpu.utils.log import logger

__all__ = ["export_inference_model", "load_exported", "serving_contract"]


def serving_contract(module, input_spec):
    """(forward_fn(params, feed), served_keys) — THE single place the
    serving batch contract is derived; export pruning and
    InferenceEngine.predict both consume it.

    Resolution order: a module-provided ``serving_forward(input_spec)``
    hook, then the language-model token contract (tokens/input_ids +
    optional seq_lens for classification pooling). Anything else must
    export with an explicit ``forward_fn`` (served keys = whole spec).
    """
    hook = getattr(module, "serving_forward", None)
    if hook is not None:
        return hook(input_spec)
    token_key = next((k for k in ("tokens", "input_ids") if k in input_spec), None)
    if token_key is None:
        return None, None
    if "seq_lens" in input_spec:
        def forward_fn(p, batch):
            return module.nets.apply(
                {"params": p}, batch[token_key], None, None, batch["seq_lens"]
            )
        return forward_fn, [token_key, "seq_lens"]

    def forward_fn(p, batch):
        return module.nets.apply({"params": p}, batch[token_key])

    return forward_fn, [token_key]


def _spec_to_json(spec_tree) -> Dict[str, Any]:
    return {
        k: {"shape": list(v.shape), "dtype": str(np.dtype(v.dtype))}
        for k, v in (spec_tree or {}).items()
    }


def export_inference_model(
    module,
    params,
    output_dir: str,
    forward_fn=None,
    input_spec: Optional[Dict[str, jax.ShapeDtypeStruct]] = None,
    quantize: Optional[str] = None,
) -> str:
    """Write the export artifact for ``module`` with ``params``.

    ``quantize="int8"`` stores weight-only per-channel int8 params (the
    reference's quantized export, eager_engine.py:734-745 + paddleslim);
    load_exported dequantizes transparently, so serving code is unchanged
    while the artifact holds int8 weights + fp32 scales."""
    import orbax.checkpoint as ocp

    os.makedirs(output_dir, exist_ok=True)
    input_spec = input_spec or module.input_spec()
    if input_spec is None:
        raise ValueError("module.input_spec() required for export")

    # 1. config: everything needed to rebuild the module at load time
    cfg = module.cfg
    keep = {
        k: dict(v) if hasattr(v, "keys") else v
        for k, v in dict(cfg).items()
        # Engine carries mix_precision: without it the module would rebuild
        # at inference in fp32 while the export traced bf16
        if k in ("Model", "Generation", "Global", "Data", "Engine")
    }
    if quantize:
        if quantize != "int8":
            raise ValueError(f"unsupported quantize={quantize!r} (only 'int8')")
        keep["Quantization"] = {"export": "int8_weight_only"}
    with open(os.path.join(output_dir, "config.yaml"), "w") as f:
        yaml.safe_dump(json.loads(json.dumps(keep)), f)

    # 2. params (unboxed; inference has no sharding metadata needs)
    from fleetx_tpu.core.engine import _unbox

    save_params = _unbox(params)
    if quantize:
        from fleetx_tpu.ops.quant import quantize_tree_int8

        save_params = jax.device_get(quantize_tree_int8(save_params))
    ckpter = ocp.StandardCheckpointer()
    ckpter.save(
        os.path.abspath(os.path.join(output_dir, "params")),
        save_params,
        force=True,
    )
    ckpter.wait_until_finished()

    # 3. StableHLO of the forward fn, traced at the exported shapes
    if forward_fn is None:
        forward_fn, served = serving_contract(module, input_spec)
        if forward_fn is None:
            raise ValueError(
                f"{type(module).__name__} has no default serving contract "
                "(batch carries none of tokens/input_ids and the module "
                "defines no serving_forward) — pass forward_fn= explicitly"
            )
    else:
        served = list(input_spec)  # caller-supplied forward: serve the full spec

    abstract_params = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), _unbox(params)
    )  # traced at full precision; int8 artifacts dequantize at load
    # input_spec.json records exactly the served keys (a finetune module's
    # training spec also lists labels, which serving never reads). A
    # serving_forward hook may return a full spec dict with extra inputs
    # (e.g. the diffusion timestep).
    serve_spec = served if isinstance(served, dict) else {
        k: input_spec[k] for k in served
    }
    lowered = jax.jit(forward_fn).lower(abstract_params, serve_spec)
    with open(os.path.join(output_dir, "forward.stablehlo"), "w") as f:
        f.write(lowered.as_text())

    with open(os.path.join(output_dir, "input_spec.json"), "w") as f:
        json.dump(_spec_to_json(serve_spec), f, indent=2)

    logger.info("exported inference model to %s", output_dir)
    return output_dir


def load_exported(export_dir: str):
    """(cfg_dict, params, input_spec) from an export artifact."""
    import orbax.checkpoint as ocp

    with open(os.path.join(export_dir, "config.yaml")) as f:
        cfg = yaml.safe_load(f)
    with open(os.path.join(export_dir, "input_spec.json")) as f:
        spec = {
            k: jax.ShapeDtypeStruct(tuple(v["shape"]), np.dtype(v["dtype"]))
            for k, v in json.load(f).items()
        }
    ckpter = ocp.StandardCheckpointer()
    params = ckpter.restore(os.path.abspath(os.path.join(export_dir, "params")))
    if (cfg.get("Quantization") or {}).get("export") == "int8_weight_only":
        from fleetx_tpu.ops.quant import dequantize_tree_int8

        params = dequantize_tree_int8(params, dtype=np.float32)
    return cfg, params, spec
