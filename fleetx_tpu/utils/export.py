"""Model export (reference /root/reference/ppfleetx/utils/export.py:44 —
``paddle.jit.to_static`` + prune + save, consumed by InferenceEngine).

TPU-native artifact, one directory:

    export_dir/
      config.yaml         # Model/Generation config to rebuild the module
      params/             # orbax checkpoint of inference params
      forward.stablehlo   # jit-lowered StableHLO of the forward fn
      input_spec.json     # shapes/dtypes the export was traced with

StableHLO is the portable compiled-graph format (what ``to_static``'s
program is to paddle.inference); any XLA runtime — and jax2tf / IREE
pipelines — can consume it. Serving-side, InferenceEngine
(fleetx_tpu/core/inference_engine.py) AOT-compiles from config+params;
TensorRT has no TPU analogue (XLA is the optimizing backend).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np
import yaml

from fleetx_tpu.utils.log import logger

__all__ = ["export_inference_model", "load_exported", "default_forward_fn"]


def default_forward_fn(module, input_spec):
    """Forward closure matching the module's batch contract: passes
    seq_lens when the spec carries it (classification pooling needs the
    true lengths, not the padded end)."""
    token_key = "tokens" if "tokens" in input_spec else "input_ids"
    if "seq_lens" in input_spec:
        def forward_fn(p, batch):
            return module.nets.apply(
                {"params": p}, batch[token_key], None, None, batch["seq_lens"]
            )
    else:
        def forward_fn(p, batch):
            return module.nets.apply({"params": p}, batch[token_key])
    return forward_fn


def _spec_to_json(spec_tree) -> Dict[str, Any]:
    return {
        k: {"shape": list(v.shape), "dtype": str(np.dtype(v.dtype))}
        for k, v in (spec_tree or {}).items()
    }


def export_inference_model(
    module,
    params,
    output_dir: str,
    forward_fn=None,
    input_spec: Optional[Dict[str, jax.ShapeDtypeStruct]] = None,
) -> str:
    """Write the export artifact for ``module`` with ``params``."""
    import orbax.checkpoint as ocp

    os.makedirs(output_dir, exist_ok=True)
    input_spec = input_spec or module.input_spec()
    if input_spec is None:
        raise ValueError("module.input_spec() required for export")

    # 1. config: everything needed to rebuild the module at load time
    cfg = module.cfg
    keep = {
        k: dict(v) if hasattr(v, "keys") else v
        for k, v in dict(cfg).items()
        # Engine carries mix_precision: without it the module would rebuild
        # at inference in fp32 while the export traced bf16
        if k in ("Model", "Generation", "Global", "Data", "Engine")
    }
    with open(os.path.join(output_dir, "config.yaml"), "w") as f:
        yaml.safe_dump(json.loads(json.dumps(keep)), f)

    # 2. params (unboxed; inference has no sharding metadata needs)
    from fleetx_tpu.core.engine import _unbox

    ckpter = ocp.StandardCheckpointer()
    ckpter.save(
        os.path.abspath(os.path.join(output_dir, "params")),
        _unbox(params),
        force=True,
    )
    ckpter.wait_until_finished()

    # 3. StableHLO of the forward fn, traced at the exported shapes
    if forward_fn is None:
        forward_fn = default_forward_fn(module, input_spec)

    abstract_params = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), _unbox(params)
    )
    # prune the serving contract to the inputs the forward actually reads
    # (a finetune module's training spec also lists labels)
    token_key = "tokens" if "tokens" in input_spec else "input_ids"
    served = [token_key] + (["seq_lens"] if "seq_lens" in input_spec else [])
    serve_spec = {k: input_spec[k] for k in served}
    lowered = jax.jit(forward_fn).lower(abstract_params, serve_spec)
    with open(os.path.join(output_dir, "forward.stablehlo"), "w") as f:
        f.write(lowered.as_text())

    with open(os.path.join(output_dir, "input_spec.json"), "w") as f:
        json.dump(_spec_to_json(serve_spec), f, indent=2)

    logger.info("exported inference model to %s", output_dir)
    return output_dir


def load_exported(export_dir: str):
    """(cfg_dict, params, input_spec) from an export artifact."""
    import orbax.checkpoint as ocp

    with open(os.path.join(export_dir, "config.yaml")) as f:
        cfg = yaml.safe_load(f)
    with open(os.path.join(export_dir, "input_spec.json")) as f:
        spec = {
            k: jax.ShapeDtypeStruct(tuple(v["shape"]), np.dtype(v["dtype"]))
            for k, v in json.load(f).items()
        }
    ckpter = ocp.StandardCheckpointer()
    params = ckpter.restore(os.path.abspath(os.path.join(export_dir, "params")))
    return cfg, params, spec
