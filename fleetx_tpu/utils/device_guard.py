"""Watchdog around first jax backend init.

A wedged TPU tunnel hangs device acquisition forever inside C++
(uninterruptible by signals the Python layer can catch), which would block
any harness driving this repo. Better a loud nonzero exit than a silent
hang: a daemon thread os._exit(3)s the process if acquisition exceeds the
timeout. ``acquired`` is set in a finally so a *fast raise* (e.g. unknown
backend) never triggers the delayed exit — the watchdog fires only on a
genuine hang.
"""

from __future__ import annotations

import os
import sys

__all__ = ["acquire_devices_or_die", "honor_platform_env"]


def honor_platform_env() -> None:
    """Re-apply a JAX_PLATFORMS request through jax.config.

    The env var is only read at first backend init, and a sitecustomize (the
    sandbox pins the axon/TPU backend) may re-pin the platform AFTER env
    vars are read — so subprocesses that must stay off the TPU (converters,
    CPU test drives) call this before their first device use. The single
    shared implementation of the pin used by parallel/env.init_dist_env and
    the CLI tools."""
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def acquire_devices_or_die(timeout_s: int = 300, label: str = "fleetx",
                           platform_override: str | None = None):
    """Return ``jax.devices()``, aborting the process (exit 3) on a hang.

    ``platform_override`` pins ``jax_platforms`` via jax.config before the
    first device query — the sandbox sitecustomize re-pins JAX_PLATFORMS
    after env vars are read, so the config update is the only reliable knob
    (same trick as tests/conftest.py).
    """
    import threading

    acquired = threading.Event()

    def watchdog():
        if not acquired.wait(timeout_s):
            sys.stderr.write(
                f"{label}: jax device acquisition exceeded {timeout_s}s "
                "(TPU tunnel wedged?); aborting\n"
            )
            sys.stderr.flush()
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()
    import jax

    if platform_override:
        jax.config.update("jax_platforms", platform_override)
    try:
        devices = jax.devices()
    finally:
        acquired.set()
    return devices
