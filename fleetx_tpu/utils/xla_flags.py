"""XLA flag presets for comms/compute overlap (ROADMAP item 3a).

The ZeRO weight-update sharding in `core/engine.py` expresses the train
step as reduce-scatter(grads) -> shard-local update -> all-gather(params).
XLA only *overlaps* those collectives with the surrounding compute when its
latency-hiding scheduler and async-collective passes are on — without them
the all-gather sits synchronously at the step tail and the sharding saves
memory but no time. This module owns the flag set and the env-gated,
idempotent application to ``XLA_FLAGS`` (flags are read once, at backend
initialization, so `apply_overlap_flags` must run before the first jax
device touch — the Trainer constructor and the CLI entry points call it).

Gating (``FLEETX_XLA_OVERLAP``):

- ``1``  — always append the flag set,
- ``0``  — never,
- unset — append only when a TPU backend is expected (JAX_PLATFORMS
  mentions tpu/axon, or a TPU device file / TPU_NAME is present): the
  flags are ``--xla_tpu_*``-heavy, and the CPU backend rejects unknown
  flags loudly at init.
"""

from __future__ import annotations

import os
from typing import List, MutableMapping, Optional

__all__ = ["OVERLAP_FLAGS", "apply_overlap_flags", "overlap_flags_state",
           "strip_overlap_flags"]

# The MaxText/JAX-LLM lineage flag set: latency-hiding scheduler + async
# collectives (all-gather / collective-permute / fusion), so the ZeRO
# param all-gather and the pipeline's stage permutes float into adjacent
# compute instead of serializing the step tail.
OVERLAP_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
)


def _tpu_expected(env: MutableMapping[str, str]) -> bool:
    """Best-effort 'will this process init a TPU backend?' without
    importing jax (which would pin the backend before flags apply)."""
    platforms = env.get("JAX_PLATFORMS", "").lower()
    if "cpu" in platforms and "tpu" not in platforms:
        return False
    if "tpu" in platforms or "axon" in platforms:
        return True
    if env.get("TPU_NAME") or env.get("TPU_WORKER_ID"):
        return True
    try:
        # /dev/accel0 is TPU-VM-specific; deliberately NOT /dev/vfio etc.
        # (a CPU-only jaxlib aborts on unknown --xla_tpu_* flags, so a
        # false positive here would be fatal, a false negative just slow)
        return os.path.exists("/dev/accel0")
    except OSError:  # pragma: no cover - exotic fs
        return False


def _backend_already_initialized() -> bool:
    """True iff a jax backend has been created in this process (best
    effort, never initializes one; private-API probes are guarded)."""
    mods = __import__("sys").modules
    jax = mods.get("jax")
    if jax is None:
        return False
    try:
        xb = jax._src.xla_bridge  # noqa: SLF001 - no public probe exists
        if hasattr(xb, "backends_are_initialized"):
            return bool(xb.backends_are_initialized())
        return bool(getattr(xb, "_backends", None))
    except Exception:  # pragma: no cover - jax internals moved
        return False


def apply_overlap_flags(
    env: Optional[MutableMapping[str, str]] = None,
) -> List[str]:
    """Append the overlap flag set to ``env['XLA_FLAGS']`` (idempotent:
    flags already present — under any value — are left alone so an
    operator override wins). Returns the flags newly appended ([] when
    gated off or nothing was missing)."""
    env = os.environ if env is None else env
    gate = env.get("FLEETX_XLA_OVERLAP", "")
    if gate == "0":
        return []
    if gate != "1" and not _tpu_expected(env):
        return []
    if env is os.environ and _backend_already_initialized():
        # XLA read XLA_FLAGS at backend init; appending now would be a
        # silent no-op that overlap_flags_state() would then misreport
        # as active. Leave the env alone so the report stays honest.
        return []
    current = env.get("XLA_FLAGS", "")
    present = {f.split("=", 1)[0] for f in current.split() if f}
    added = [f for f in OVERLAP_FLAGS if f.split("=", 1)[0] not in present]
    if added:
        env["XLA_FLAGS"] = (current + " " + " ".join(added)).strip()
    return added


def strip_overlap_flags(
    env: Optional[MutableMapping[str, str]] = None,
) -> List[str]:
    """Remove every overlap-set flag (by name, any value) from
    ``env['XLA_FLAGS']``. For flows that appended the TPU flag set and
    then fell back to a CPU backend in the SAME process (bench.py's
    wedged-tunnel fallback): a CPU-only jaxlib can abort on unknown
    ``--xla_tpu_*`` flags, so they must be gone before that backend
    initializes. Returns the removed flags."""
    env = os.environ if env is None else env
    names = {f.split("=", 1)[0] for f in OVERLAP_FLAGS}
    kept, removed = [], []
    for f in env.get("XLA_FLAGS", "").split():
        (removed if f.split("=", 1)[0] in names else kept).append(f)
    if removed:
        env["XLA_FLAGS"] = " ".join(kept)
    return removed


def overlap_flags_state(
    env: Optional[MutableMapping[str, str]] = None,
) -> dict:
    """Observability snapshot for bench records: gate value + which of the
    overlap flags are live in XLA_FLAGS right now."""
    env = os.environ if env is None else env
    present = {f.split("=", 1)[0]
               for f in env.get("XLA_FLAGS", "").split() if f}
    return {
        "gate": env.get("FLEETX_XLA_OVERLAP", "") or "auto",
        "active": [f for f in OVERLAP_FLAGS
                   if f.split("=", 1)[0] in present],
    }
