"""Configuration system: YAML with ``_base_`` inheritance, dot-path overrides,
and batch-size/degree algebra.

Capability parity with the reference config stack
(/root/reference/ppfleetx/utils/config.py:31-374 — ``parse_config`` `_base_`
chains, ``override_config`` ``-o a.b.c=v``, ``process_dist_config`` degree
math, ``process_global_configs`` batch algebra, ``process_engine_config``
accumulate_steps) re-designed for a JAX/TPU runtime: degrees validate against
``jax.device_count()`` instead of NCCL world size, and the output feeds a
`jax.sharding.Mesh` builder rather than a fleet HybridCommunicateGroup.
"""

from __future__ import annotations

import argparse
import codecs
import copy
import os
from typing import Any, List, Optional, Sequence

import yaml

from fleetx_tpu.utils.log import logger

__all__ = [
    "AttrDict",
    "parse_config",
    "parse_args",
    "override_config",
    "process_dist_config",
    "process_global_configs",
    "process_engine_config",
    "process_configs",
    "get_config",
]


class AttrDict(dict):
    """Dict with attribute-style access. Missing keys read as ``None`` so
    optional config sections can be probed without try/except."""

    def __getattr__(self, key: str) -> Any:
        try:
            return self[key]
        except KeyError:
            return None

    def __setattr__(self, key: str, value: Any) -> None:
        self[key] = value

    def __deepcopy__(self, memo):
        return AttrDict({copy.deepcopy(k, memo): copy.deepcopy(v, memo) for k, v in self.items()})

    def setdefault_section(self, key: str) -> "AttrDict":
        """Return cfg[key], creating an empty AttrDict section if absent."""
        if self.get(key) is None:
            self[key] = AttrDict()
        return self[key]


def create_attr_dict(d: dict) -> AttrDict:
    """Recursively convert nested dicts into AttrDicts in place."""
    out = AttrDict()
    for k, v in d.items():
        if k == "_inherited_":  # inheritance marker, never part of the config
            continue
        out[k] = create_attr_dict(v) if isinstance(v, dict) else v
    return out


def _merge_dict(base: dict, update: dict) -> dict:
    """Recursively merge ``update`` into ``base`` (update wins). A sub-dict in
    ``update`` carrying ``_inherited_: False`` replaces the base sub-dict
    wholesale instead of merging."""
    for k, v in update.items():
        if isinstance(v, dict):
            inherit = v.pop("_inherited_", True)
            if isinstance(base.get(k), dict) and inherit is not False:
                _merge_dict(base[k], v)
            else:
                base[k] = v
        else:
            base[k] = v
    return base


def parse_config(fpath: str) -> AttrDict:
    """Load a YAML config, resolving ``_base_`` inheritance chains
    (child values override parents; relative ``_base_`` paths resolve against
    the child file's directory)."""
    with codecs.open(fpath, "r", "utf-8") as f:
        raw = yaml.safe_load(f) or {}
    base_path = raw.pop("_base_", None)
    if base_path is not None:
        if not os.path.isabs(base_path):
            base_path = os.path.join(os.path.dirname(fpath), base_path)
        base = dict(parse_config(base_path))
        raw = _merge_dict(base, raw)
    return create_attr_dict(raw)


def _parse_scalar(text: str) -> Any:
    """Parse a CLI override value with YAML scalar semantics
    ('True'→bool, '1e-4'→float, '[1,2]'→list, bare words→str)."""
    try:
        value = yaml.safe_load(text)
    except yaml.YAMLError:
        return text
    if isinstance(value, str):
        # YAML 1.1 misses '1e-4'-style floats (no dot before the exponent).
        try:
            return float(value)
        except ValueError:
            return value
    return value


def override_config(cfg: AttrDict, options: Optional[Sequence[str]] = None) -> AttrDict:
    """Apply ``-o Key.Sub.Leaf=value`` dot-path overrides in order."""
    if not options:
        return cfg
    for opt in options:
        opt = opt.strip()
        if "=" not in opt:
            raise ValueError(f"override option must look like a.b.c=value, got {opt!r}")
        path, value = opt.split("=", 1)
        keys = path.split(".")
        node = cfg
        for k in keys[:-1]:
            if not isinstance(node.get(k), dict):
                node[k] = AttrDict()
            node = node[k]
        node[keys[-1]] = _parse_scalar(value)
    return cfg


def _device_count() -> int:
    """Total accelerator count. Import of jax is deferred so pure config-time
    tooling (data preprocessing CLIs) stays jax-free."""
    env = os.environ.get("FLEETX_FAKE_DEVICE_COUNT")
    if env:
        return int(env)
    import jax

    return jax.device_count()


def process_dist_config(cfg: AttrDict, nranks: Optional[int] = None) -> AttrDict:
    """Normalize the ``Distributed`` section: fill defaults, derive
    ``dp_degree = nranks // (mp * pp * sharding)``, and validate the product.

    Degree semantics match the reference (config.py:31-93); the degrees here
    parameterize mesh axes ('dp','fsdp','mp','pp') instead of NCCL groups.
    """
    dist = cfg.setdefault_section("Distributed")
    if nranks is None:
        nranks = _device_count()
    mp = dist.mp_degree or 1
    pp = dist.pp_degree or 1
    cp = dist.cp_degree or 1
    dist.mp_degree = mp
    dist.pp_degree = pp
    dist.cp_degree = cp

    sharding = dist.setdefault_section("sharding")
    sharding.sharding_degree = sharding.sharding_degree or 1
    sharding.sharding_stage = sharding.sharding_stage or 1
    sharding.sharding_offload = bool(sharding.sharding_offload)
    if sharding.sharding_stage not in (1, 2, 3):
        raise ValueError(f"sharding_stage must be 1/2/3, got {sharding.sharding_stage}")
    sd = sharding.sharding_degree

    other = mp * pp * sd * cp
    if nranks % other != 0:
        raise ValueError(
            f"device count {nranks} not divisible by mp*pp*sharding*cp = {mp}*{pp}*{sd}*{cp}"
        )
    derived_dp = nranks // other
    if dist.dp_degree in (None, ""):
        dist.dp_degree = derived_dp
    dp = dist.dp_degree
    if dp * other != nranks:
        raise ValueError(
            f"dp({dp}) * mp({mp}) * pp({pp}) * sharding({sd}) * cp({cp}) = {dp * other} "
            f"!= device count {nranks}"
        )
    # Sequence parallel rides the mp axis (Megatron-style); flag lives in Model.
    model = cfg.get("Model") or {}
    if model.get("sequence_parallel") and mp <= 1:
        logger.warning("sequence_parallel=True with mp_degree<=1 has no effect; disabling")
        model["sequence_parallel"] = False
    # (r5) attention dropout under cp_degree>1 runs inside the ring's
    # per-hop flash kernels with position-keyed bits, so the realized mask
    # equals the cp=1 kernel's (parallel/context_parallel.py). The old
    # forcing-to-0 guard survives ONLY for configurations the flash ring
    # cannot serve (explicit FLEETX_CP_FLASH=0, or a local block below the
    # 8-row tile) — there the jnp ring path has no dropout support and
    # would raise deep inside shard_map tracing.
    if cp > 1 and (model.get("attention_probs_dropout_prob") or 0) > 0:
        seq = ((cfg.get("Data") or {}).get("Train") or {}).get(
            "dataset", {}).get("max_seq_len")
        # mirror context_parallel._cp_flash_enabled: any value but "1"
        # disables the flash ring
        flash_off = os.environ.get("FLEETX_CP_FLASH", "1") != "1"
        untileable = seq is not None and (seq // (2 * cp)) % 8 != 0
        if flash_off or untileable:
            logger.warning(
                "cp_degree>1 with attention dropout needs the flash ring "
                "path (%s); forcing attention_probs_dropout_prob=0",
                "FLEETX_CP_FLASH=0 set" if flash_off
                else f"seq {seq} / (2*cp={2 * cp}) is not 8-row tileable",
            )
            model["attention_probs_dropout_prob"] = 0.0
    return cfg


def process_global_configs(cfg: AttrDict) -> AttrDict:
    """Batch-size algebra: ``global = local * dp * sharding`` where the
    data-parallel world is dp_degree × sharding_degree. Any one of
    global/local may be omitted and is derived; both present are validated."""
    glb = cfg.setdefault_section("Global")
    dist = cfg.Distributed or AttrDict()
    dp_world = (dist.dp_degree or 1) * ((dist.sharding or AttrDict()).sharding_degree or 1)

    gbs, lbs, mbs = glb.global_batch_size, glb.local_batch_size, glb.micro_batch_size
    if gbs in (None, "") and lbs in (None, ""):
        raise ValueError("one of Global.global_batch_size / Global.local_batch_size required")
    if gbs in (None, ""):
        glb.global_batch_size = lbs * dp_world
    elif lbs in (None, ""):
        if gbs % dp_world != 0:
            raise ValueError(f"global_batch_size {gbs} not divisible by dp world {dp_world}")
        glb.local_batch_size = gbs // dp_world
    else:
        if gbs != lbs * dp_world:
            raise ValueError(
                f"global_batch_size {gbs} != local_batch_size {lbs} * dp world {dp_world}"
            )
    if mbs in (None, ""):
        glb.micro_batch_size = glb.local_batch_size
    if glb.local_batch_size % glb.micro_batch_size != 0:
        raise ValueError(
            f"local_batch_size {glb.local_batch_size} not divisible by "
            f"micro_batch_size {glb.micro_batch_size}"
        )
    if glb.seed in (None, ""):  # explicit 0 is a valid seed
        glb.seed = 1024
    return cfg


def process_engine_config(cfg: AttrDict) -> AttrDict:
    """Fill Engine defaults; ``accumulate_steps = local / micro`` unless set."""
    eng = cfg.setdefault_section("Engine")
    glb = cfg.Global or AttrDict()
    if eng.accumulate_steps in (None, ""):
        local = glb.local_batch_size or 1
        micro = glb.micro_batch_size or local
        eng.accumulate_steps = max(1, local // micro)
    eng.max_steps = eng.max_steps or 500000
    eng.num_train_epochs = eng.num_train_epochs or 1
    eng.logging_freq = eng.logging_freq or 10
    eng.eval_freq = eng.eval_freq if eng.eval_freq else 0
    eng.eval_iters = eng.eval_iters or 10

    mp_cfg = eng.setdefault_section("mix_precision")
    if mp_cfg.use_pure_fp16 is None:
        mp_cfg.use_pure_fp16 = False
    # TPU-native default: bf16 needs no loss scaling; fp16 paths keep it.
    mp_cfg.scale_loss = mp_cfg.scale_loss or 32768.0
    if mp_cfg.dtype is None:
        mp_cfg.dtype = "bfloat16" if mp_cfg.use_pure_fp16 else "float32"

    sl = eng.setdefault_section("save_load")
    sl.save_steps = sl.save_steps or 1000
    sl.output_dir = sl.output_dir or "./output"
    return cfg


def process_configs(cfg: AttrDict, nranks: Optional[int] = None) -> AttrDict:
    """Run all normalization passes (dist degrees, batch algebra, engine
    defaults) on a parsed config."""
    process_dist_config(cfg, nranks=nranks)
    process_global_configs(cfg)
    process_engine_config(cfg)
    return cfg


def get_config(
    fpath: str,
    overrides: Optional[Sequence[str]] = None,
    show: bool = False,
    nranks: Optional[int] = None,
) -> AttrDict:
    """Load + override + normalize a training config."""
    cfg = parse_config(fpath)
    override_config(cfg, overrides)
    process_configs(cfg, nranks=nranks)
    if show:
        print_config(cfg)
    return cfg


def print_config(cfg: dict, indent: int = 0) -> None:
    """Pretty-print the config tree via the logger."""
    for k, v in cfg.items():
        if isinstance(v, dict):
            logger.info("%s%s:", "  " * indent, k)
            print_config(v, indent + 1)
        else:
            logger.info("%s%s: %s", "  " * indent, k, v)


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    """Standard CLI surface: -c/--config plus repeatable -o dot-path overrides
    (reference utils/config.py parse_args)."""
    parser = argparse.ArgumentParser("fleetx-tpu runner")
    parser.add_argument("-c", "--config", required=True, help="config YAML path")
    parser.add_argument(
        "-o",
        "--override",
        action="append",
        default=[],
        help="override option Key.Sub=value (repeatable)",
    )
    return parser.parse_args(argv)
