"""ctypes loader for the native index-map helpers, with a pure-numpy
fallback when no C++ toolchain is available.

Build contract mirrors the reference (gpt_dataset.py:56-69 + data_tools/cpp/
compile.py): first process to need it compiles the .so next to the source;
other processes wait on the file.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import time

import numpy as np

from fleetx_tpu.utils.log import logger

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libindex_helpers.so")
_LIB = None


def _ensure_built(timeout_s: float = 120.0):
    global _LIB
    if _LIB is not None:
        return _LIB
    if not os.path.isfile(_SO):
        lock = _SO + ".building"
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
            try:
                logger.info("compiling native index helpers...")
                subprocess.run(["make", "-C", _HERE], check=True, capture_output=True)
            finally:
                os.unlink(lock)
        except FileExistsError:
            deadline = time.time() + timeout_s
            while not os.path.isfile(_SO):
                if time.time() > deadline:
                    raise TimeoutError("timed out waiting for index helper build")
                time.sleep(0.5)
    lib = ctypes.CDLL(_SO)
    lib.build_sample_idx.argtypes = [
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.c_int64,
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
    ]
    lib.build_blending_indices.argtypes = [
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        ctypes.c_int32,
        ctypes.c_int64,
    ]
    _LIB = lib
    return lib


def build_sample_idx(sizes, doc_idx, seq_length, num_epochs, tokens_per_epoch):
    """[num_samples+1, 2] int64 (doc_idx position, token offset) pairs."""
    num_samples = (num_epochs * tokens_per_epoch - 1) // seq_length
    out = np.empty((num_samples + 1, 2), dtype=np.int64)
    try:
        lib = _ensure_built()
    except Exception as e:  # no toolchain: numpy fallback
        logger.warning("native index helper unavailable (%s); using numpy", e)
        return _build_sample_idx_np(
            sizes, doc_idx, seq_length, num_epochs, tokens_per_epoch, num_samples
        )
    lib.build_sample_idx(
        np.ascontiguousarray(sizes, np.int32),
        np.ascontiguousarray(doc_idx, np.int32),
        seq_length,
        num_epochs,
        tokens_per_epoch,
        num_samples,
        out.reshape(-1),
    )
    return out


def _build_sample_idx_np(sizes, doc_idx, seq_length, num_epochs,
                         tokens_per_epoch, num_samples):
    out = np.empty((num_samples + 1, 2), dtype=np.int64)
    di, off = 0, 0
    out[0] = (di, off)
    for s in range(1, num_samples + 1):
        remaining = seq_length + 1
        while remaining != 0:
            doc_len = sizes[doc_idx[di]] - off
            remaining -= doc_len
            if remaining <= 0:
                off += remaining + doc_len - 1
                remaining = 0
            else:
                di += 1
                off = 0
        out[s] = (di, off)
    return out


def build_blending_indices(weights, size):
    """(dataset_index uint8[size], dataset_sample_index int64[size])."""
    weights = np.ascontiguousarray(weights, np.float64)
    ds_index = np.empty(size, np.uint8)
    ds_sample = np.empty(size, np.int64)
    try:
        lib = _ensure_built()
        lib.build_blending_indices(ds_index, ds_sample, weights, len(weights), size)
        return ds_index, ds_sample
    except Exception:
        current = np.zeros(len(weights), np.int64)
        for i in range(size):
            denom = max(float(i), 1.0)
            errors = weights * denom - current
            pick = int(np.argmax(errors))
            ds_index[i] = pick
            ds_sample[i] = current[pick]
            current[pick] += 1
        return ds_index, ds_sample
