// Native index-map builders for the Megatron-style mmap token dataset.
//
// TPU-native reimplementation of the reference's pybind11 extension
// (/root/reference/ppfleetx/data/data_tools/cpp/fast_index_map_helpers.cpp:
// build_sample_idx two-pointer construction, build_blending_indices
// error-minimizing dataset interleave). Exposed through a plain C ABI and
// loaded with ctypes (no pybind11 in this image); compiled on first use by
// process 0 (reference compile-on-rank-0 contract, gpt_dataset.py:58-69).
//
// All buffers are caller-allocated numpy arrays; int32 doc ids / int64
// offsets match the .npy cache format the Python side writes.

#include <algorithm>
#include <cstdint>

extern "C" {

// sample_idx out: [(num_samples+1) * 2] int64 pairs (doc_idx index, offset).
// Walks the flattened doc stream epoch by epoch, emitting one entry per
// seq_length tokens consumed (+1 shared boundary token per sample).
void build_sample_idx(const int32_t *sizes, const int32_t *doc_idx,
                      int32_t seq_length, int32_t num_epochs,
                      int64_t tokens_per_epoch, int64_t num_samples,
                      int64_t *sample_idx_out) {
  int64_t sample_index = 0;
  int64_t doc_idx_index = 0;
  int32_t doc_offset = 0;

  sample_idx_out[0] = doc_idx_index;
  sample_idx_out[1] = doc_offset;
  ++sample_index;

  while (sample_index <= num_samples) {
    int32_t remaining_seq_length = seq_length + 1;
    while (remaining_seq_length != 0) {
      const int32_t doc_id = doc_idx[doc_idx_index];
      const int32_t doc_length = sizes[doc_id] - doc_offset;
      remaining_seq_length -= doc_length;
      if (remaining_seq_length <= 0) {
        // sample ends inside this doc; next sample re-reads the boundary
        // token (the -1), matching the reference construction
        doc_offset += remaining_seq_length + doc_length - 1;
        remaining_seq_length = 0;
      } else {
        ++doc_idx_index;
        doc_offset = 0;
      }
    }
    sample_idx_out[2 * sample_index] = doc_idx_index;
    sample_idx_out[2 * sample_index + 1] = doc_offset;
    ++sample_index;
  }
}

// Blend multiple datasets to target weights by always taking the dataset
// with the largest sampling deficit.
void build_blending_indices(uint8_t *dataset_index_out,
                            int64_t *dataset_sample_index_out,
                            const double *weights, int32_t num_datasets,
                            int64_t size) {
  int64_t *current = new int64_t[num_datasets]();
  for (int64_t i = 0; i < size; ++i) {
    const double denom = std::max(static_cast<double>(i), 1.0);
    int32_t pick = 0;
    double max_error = weights[0] * denom - static_cast<double>(current[0]);
    for (int32_t d = 1; d < num_datasets; ++d) {
      const double err = weights[d] * denom - static_cast<double>(current[d]);
      if (err > max_error) {
        max_error = err;
        pick = d;
      }
    }
    dataset_index_out[i] = static_cast<uint8_t>(pick);
    dataset_sample_index_out[i] = current[pick];
    ++current[pick];
  }
  delete[] current;
}

}  // extern "C"
