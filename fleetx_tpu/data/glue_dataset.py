"""GLUE finetuning datasets (reference /root/reference/ppfleetx/data/
dataset/glue_dataset.py, 841 LoC of per-task TSV readers + tokenization).

Tasks carry (columns, num_classes, regression, metric) — the TSV layouts of
the standard GLUE release. Text is BPE-tokenized (GPTTokenizer) and packed
to ``max_seq_len`` with the actual length kept so the classification head
pools the last real token. ``synthetic: True`` generates label-correlated
token streams for CI (zero-egress: no GLUE download here)."""

from __future__ import annotations

import csv
import os
from typing import Optional

import numpy as np

from fleetx_tpu.utils.log import logger

__all__ = ["GlueDataset", "GLUE_TASKS"]

# task -> sentence columns (train/dev), label column, classes, metric; the
# standard GLUE TSV layouts. test.tsv ships (index, sentence...) WITHOUT
# labels -> test_cols; dev_file covers MNLI's dev_matched/dev_mismatched.
GLUE_TASKS = {
    "sst2": dict(cols=(0,), label=1, num_classes=2, regression=False,
                 metric="Accuracy", test_cols=(1,), has_header=True),
    "cola": dict(cols=(3,), label=1, num_classes=2, regression=False,
                 metric="Mcc", test_cols=(1,), has_header=False,
                 test_has_header=True),
    "mrpc": dict(cols=(3, 4), label=0, num_classes=2, regression=False,
                 metric="AccuracyAndF1", test_cols=(3, 4), has_header=True),
    "qqp": dict(cols=(3, 4), label=5, num_classes=2, regression=False,
                metric="AccuracyAndF1", test_cols=(1, 2), has_header=True),
    "stsb": dict(cols=(7, 8), label=9, num_classes=1, regression=True,
                 metric="PearsonAndSpearman", test_cols=(7, 8), has_header=True),
    # MNLI dev has 16 columns: label1-5 at 10-14, gold_label at 15 (train's
    # gold_label sits at 11) -> per-split label column
    "mnli": dict(cols=(8, 9), label=11, eval_label=15, num_classes=3,
                 regression=False, metric="Accuracy", test_cols=(8, 9),
                 has_header=True, dev_file="dev_matched.tsv",
                 test_file="test_matched.tsv",
                 label_map={"contradiction": 0, "entailment": 1, "neutral": 2}),
    "qnli": dict(cols=(1, 2), label=3, num_classes=2, regression=False,
                 metric="Accuracy", test_cols=(1, 2), has_header=True,
                 label_map={"entailment": 0, "not_entailment": 1}),
    "rte": dict(cols=(1, 2), label=3, num_classes=2, regression=False,
                metric="Accuracy", test_cols=(1, 2), has_header=True,
                label_map={"entailment": 0, "not_entailment": 1}),
    "wnli": dict(cols=(1, 2), label=3, num_classes=2, regression=False,
                 metric="Accuracy", test_cols=(1, 2), has_header=True),
}


class GlueDataset:
    """GLUE task dataset: TSV parsing per task spec with synthetic fallback
    (reference glue_dataset.py)."""
    def __init__(
        self,
        task: str,
        input_dir: Optional[str] = None,
        max_seq_len: int = 128,
        mode: str = "Train",
        seed: int = 1234,
        vocab_dir: Optional[str] = None,
        synthetic: bool = False,
        num_samples: Optional[int] = None,
        vocab_size: int = 50304,
        pad_id: int = 0,
        **_unused,
    ):
        task = task.lower().replace("-", "")
        if task not in GLUE_TASKS:
            raise ValueError(f"unknown GLUE task {task!r}; have {sorted(GLUE_TASKS)}")
        self.task = task
        self.spec = GLUE_TASKS[task]
        self.max_seq_len = max_seq_len
        self.pad_id = pad_id
        self.mode = mode
        self.seed = seed

        if synthetic or input_dir is None:
            self._init_synthetic(num_samples or 256, vocab_size)
            return

        spec = self.spec
        fname = {
            "Train": "train.tsv",
            "Eval": spec.get("dev_file", "dev.tsv"),
            "Test": spec.get("test_file", "test.tsv"),
        }[mode]
        path = os.path.join(input_dir, fname)
        from fleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer

        tok = GPTTokenizer.from_pretrained(vocab_dir or os.path.join(input_dir, "vocab"))
        self.samples = []
        label_map = spec.get("label_map")
        is_test = mode == "Test"  # no labels in GLUE test splits
        cols = spec["test_cols"] if is_test else spec["cols"]
        label_col = (
            spec.get("eval_label", spec["label"]) if mode == "Eval" else spec["label"]
        )
        has_header = spec.get("test_has_header", True) if is_test else spec["has_header"]
        with open(path, encoding="utf-8") as f:
            reader = csv.reader(f, delimiter="\t", quotechar=None)
            for i, row in enumerate(reader):
                if i == 0 and has_header:
                    continue
                try:
                    texts = [row[c] for c in cols]
                    raw = None if is_test else row[label_col]
                except IndexError:
                    continue  # malformed line
                if is_test:
                    label = -1
                elif spec["regression"]:
                    label = float(raw)
                elif label_map:
                    label = label_map[raw]
                else:
                    label = int(raw)
                ids = tok.encode(" ".join(texts))[: max_seq_len]
                self.samples.append((np.asarray(ids, np.int64), label))
        self._num_samples = num_samples or len(self.samples)
        logger.info("GlueDataset[%s/%s]: %d examples", task, mode, len(self.samples))

    def _init_synthetic(self, n, vocab_size):
        """Label-correlated synthetic data: class k drawn from a k-shifted
        token range, so a real model can actually fit it (CI sanity)."""
        rng = np.random.RandomState(self.seed)
        self.samples = []
        ncls = self.spec["num_classes"]
        # disjoint token bands per class (band width scales with vocab)
        band = max((vocab_size - 1) // max(ncls, 2), 2)
        for _ in range(n):
            if self.spec["regression"]:
                label = float(rng.rand() * 5)
                lo = 1 + int(label / 5.0 * (vocab_size - band - 1))
            else:
                label = int(rng.randint(ncls))
                lo = 1 + label * band
            length = rng.randint(8, self.max_seq_len)
            ids = rng.randint(lo, min(lo + band, vocab_size), size=length)
            self.samples.append((ids.astype(np.int64), label))
        self._num_samples = n

    def __len__(self):
        return self._num_samples

    def __getitem__(self, index):
        ids, label = self.samples[index % len(self.samples)]
        n = min(len(ids), self.max_seq_len)
        tokens = np.full(self.max_seq_len, self.pad_id, np.int64)
        tokens[:n] = ids[:n]
        return {
            "tokens": tokens,
            "seq_lens": np.int64(n),
            "labels": (
                np.float32(label) if self.spec["regression"] else np.int64(label)
            ),
        }
