"""Data builders from config (reference /root/reference/ppfleetx/data/
__init__.py:28-107): ``build_dataset(cfg_section, mode)`` and
``build_dataloader(cfg, mode)`` resolve dataset/sampler/loader classes by
name from the YAML schema."""

from __future__ import annotations

_DATASETS = {}
_BUILTINS_LOADED = False


def register_dataset(name):
    """Class decorator adding a dataset to the build_dataset registry."""
    def deco(cls):
        _DATASETS[name] = cls
        return cls

    return deco


def _dataset_registry():
    global _BUILTINS_LOADED
    if _BUILTINS_LOADED:
        return _DATASETS
    from fleetx_tpu.data.gpt_dataset import GPTDataset, LMEvalDataset, LambadaEvalDataset
    from fleetx_tpu.data.ernie_dataset import ErnieDataset
    from fleetx_tpu.data.vision_dataset import (
        ContrastiveViewsDataset,
        GeneralClsDataset,
        SyntheticClsDataset,
    )

    _DATASETS.setdefault("GeneralClsDataset", GeneralClsDataset)
    _DATASETS.setdefault("SyntheticClsDataset", SyntheticClsDataset)
    _DATASETS.setdefault("ContrastiveViewsDataset", ContrastiveViewsDataset)
    from fleetx_tpu.data.glue_dataset import GlueDataset
    from fleetx_tpu.data.multimodal_dataset import TextImageDataset

    _DATASETS.setdefault("GlueDataset", GlueDataset)
    _DATASETS.setdefault("TextImageDataset", TextImageDataset)
    _DATASETS.setdefault("ErnieDataset", ErnieDataset)
    _DATASETS.setdefault("GPTDataset", GPTDataset)
    _DATASETS.setdefault("LM_Eval_Dataset", LMEvalDataset)
    _DATASETS.setdefault("LMEvalDataset", LMEvalDataset)
    _DATASETS.setdefault("Lambada_Eval_Dataset", LambadaEvalDataset)
    _DATASETS.setdefault("LambadaEvalDataset", LambadaEvalDataset)
    _BUILTINS_LOADED = True
    return _DATASETS


def build_dataset(ds_cfg, mode: str = "Train", **extra):
    """Instantiate the dataset named by the Data.<mode>.dataset config node."""
    registry = _dataset_registry()
    kwargs = dict(ds_cfg)
    name = kwargs.pop("name")
    if name not in registry:
        raise ValueError(f"unknown dataset {name!r}; have {sorted(registry)}")
    kwargs.update(extra)
    return registry[name](mode=mode, **kwargs)


def build_dataloader(cfg, mode: str = "Train", consumed_samples: int = 0):
    """Full loader from the config's Data.{Train,Eval,Test} section. Yields
    GLOBAL batches (engine shards them onto the mesh)."""
    from fleetx_tpu.data.dataloader import DataLoader, default_collate_fn
    from fleetx_tpu.data.sampler import GPTBatchSampler

    section = cfg.Data[mode]
    dataset = build_dataset(section.dataset, mode=mode, seed=cfg.Global.seed)

    sampler_cfg = dict(section.get("sampler") or {})
    sampler_cfg.pop("name", None)
    try:
        import jax

        pidx, pcount = jax.process_index(), jax.process_count()
    except Exception:
        pidx, pcount = 0, 1
    sampler = GPTBatchSampler(
        dataset_len=len(dataset),
        batch_size=cfg.Global.global_batch_size,
        consumed_samples=consumed_samples,
        seed=cfg.Global.seed,
        process_index=pidx,
        process_count=pcount,
        **sampler_cfg,
    )
    loader_cfg = dict(section.get("loader") or {})
    return DataLoader(
        dataset,
        sampler,
        collate_fn=default_collate_fn,
        num_workers=loader_cfg.get("num_workers", 0),
    )
