"""Batch samplers (reference GPTBatchSampler, /root/reference/ppfleetx/data/
sampler/batch_sampler.py:31-188).

TPU twist: the engine consumes GLOBAL batches (it shards them onto the mesh
itself), so the sampler yields global-batch index lists. On multi-host runs
each process takes its contiguous slice of every global batch
(process_index/process_count), which lines up with
`jax.make_array_from_process_local_data`. ``consumed_samples`` resume
reproduces the reference's data-order recovery.
"""

from __future__ import annotations

from typing import Iterator, List

import numpy as np

__all__ = ["GPTBatchSampler", "DistributedBatchSampler"]


class GPTBatchSampler:
    """Distributed batch sampler with consumed_samples resume (reference
    batch_sampler.py:31)."""
    def __init__(
        self,
        dataset_len: int,
        batch_size: int,
        shuffle: bool = False,
        drop_last: bool = True,
        consumed_samples: int = 0,
        seed: int = 1024,
        process_index: int = 0,
        process_count: int = 1,
        **_,
    ):
        if batch_size % process_count != 0:
            raise ValueError(
                f"global batch {batch_size} not divisible by {process_count} processes"
            )
        self.dataset_len = dataset_len
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.consumed_samples = consumed_samples
        self.seed = seed
        self.process_index = process_index
        self.process_count = process_count
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def _order(self) -> np.ndarray:
        order = np.arange(self.dataset_len)
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            rng.shuffle(order)
        return order

    def __iter__(self) -> Iterator[List[int]]:
        order = self._order()
        start = self.consumed_samples % self.dataset_len
        per_proc = self.batch_size // self.process_count
        batch_start = start
        while batch_start + self.batch_size <= self.dataset_len:
            batch = order[batch_start : batch_start + self.batch_size]
            lo = self.process_index * per_proc
            yield batch[lo : lo + per_proc].tolist()
            batch_start += self.batch_size
        if not self.drop_last and batch_start < self.dataset_len:
            batch = order[batch_start:]
            per = max(len(batch) // self.process_count, 1)
            lo = min(self.process_index * per, len(batch))
            yield batch[lo : lo + per].tolist()

    def __len__(self) -> int:
        n = self.dataset_len - (self.consumed_samples % self.dataset_len)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


DistributedBatchSampler = GPTBatchSampler
