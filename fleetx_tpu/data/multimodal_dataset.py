"""Text-image pair datasets for Imagen (reference
/root/reference/ppfleetx/data/dataset/multimodal_dataset.py, 180 LoC).

Storage: ``{prefix}_images.npy`` [N,H,W,3] uint8 (mmap),
``{prefix}_embeds.npy`` [N,L,D] float16/32 (mmap, precomputed T5/encoder
embeddings), ``{prefix}_mask.npy`` [N,L]. ``synthetic: True`` generates
noise pairs for benchmarking."""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from fleetx_tpu.utils.log import logger

__all__ = ["TextImageDataset"]


class TextImageDataset:
    """Imagen text-image pairs: mmap images + precomputed text embeddings (see
    module docstring for the on-disk layout)."""
    def __init__(self, input_dir=None, image_size: int = 64, mode="Train",
                 seed: int = 1234, num_samples: Optional[int] = None,
                 synthetic: bool = False, max_text_len: int = 64,
                 cond_dim: int = 512, **_unused):
        self.image_size = image_size
        self.seed = seed
        self.max_text_len = max_text_len
        self.cond_dim = cond_dim
        self.synthetic = synthetic or input_dir is None
        if self.synthetic:
            self._num_samples = num_samples or 1280
            self.images = self.embeds = self.mask = None
            return
        prefix = input_dir
        if os.path.isdir(prefix):
            prefix = os.path.join(prefix, mode.lower())
        self.images = np.load(prefix + "_images.npy", mmap_mode="r")
        self.embeds = np.load(prefix + "_embeds.npy", mmap_mode="r")
        self.mask = np.load(prefix + "_mask.npy", mmap_mode="r")
        self._num_samples = num_samples or len(self.images)
        logger.info("TextImageDataset[%s]: %d pairs", mode, self._num_samples)

    def __len__(self):
        return self._num_samples

    def __getitem__(self, index):
        if self.synthetic:
            rng = np.random.RandomState((self.seed + index) % (2**31))
            s = self.image_size
            return {
                "images": rng.uniform(-1, 1, (s, s, 3)).astype(np.float32),
                "text_embeds": rng.randn(self.max_text_len, self.cond_dim)
                .astype(np.float32),
                "text_mask": (np.arange(self.max_text_len)
                              < rng.randint(4, self.max_text_len))
                .astype(np.float32),
            }
        i = index % len(self.images)
        img = np.asarray(self.images[i]).astype(np.float32) / 127.5 - 1.0
        return {
            "images": img,
            "text_embeds": np.asarray(self.embeds[i], np.float32),
            "text_mask": np.asarray(self.mask[i], np.float32),
        }
