"""Lightweight threaded data loader + collate functions.

Replaces paddle.io.DataLoader + the reference collate stack
(/root/reference/ppfleetx/data/utils/batch_collate_fn.py:94, sampler/
collate.py:27-248): batches are dicts of numpy arrays (the engine device-puts
them onto the mesh). Worker threads prefetch; numpy stacking is the
collation.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Iterator, Optional

import numpy as np

__all__ = ["DataLoader", "default_collate_fn", "gpt_collate_fn"]


def default_collate_fn(samples):
    """Stack a list of dict samples into a dict of [batch, ...] arrays."""
    if isinstance(samples[0], dict):
        return {k: np.stack([s[k] for s in samples]) for k in samples[0]}
    if isinstance(samples[0], (tuple, list)):
        return tuple(np.stack([s[i] for s in samples]) for i in range(len(samples[0])))
    return np.stack(samples)


gpt_collate_fn = default_collate_fn  # GPT samples are already dicts


class DataLoader:
    """Iterates a dataset by sampler-provided index batches, with optional
    background prefetch. ``num_workers`` threads pipeline __getitem__ +
    collate; order is preserved."""

    def __init__(
        self,
        dataset,
        batch_sampler,
        collate_fn: Optional[Callable] = None,
        num_workers: int = 0,
        prefetch: int = 2,
    ):
        self.dataset = dataset
        self.batch_sampler = batch_sampler
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch = max(prefetch, 1)

    def _load(self, indices):
        return self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        if self.num_workers <= 0:
            for indices in self.batch_sampler:
                yield self._load(indices)
            return
        yield from self._threaded_iter()

    def _threaded_iter(self):
        work: "queue.Queue" = queue.Queue(maxsize=self.prefetch * self.num_workers)
        done: Dict[int, object] = {}
        done_lock = threading.Lock()
        done_cv = threading.Condition(done_lock)
        STOP = object()

        def worker():
            while True:
                item = work.get()
                if item is STOP:
                    return
                i, indices = item
                try:
                    batch = self._load(indices)
                except Exception as e:  # surface in consumer
                    batch = e
                with done_cv:
                    done[i] = batch
                    done_cv.notify_all()

        threads = [
            threading.Thread(target=worker, daemon=True) for _ in range(self.num_workers)
        ]
        for t in threads:
            t.start()

        def feeder():
            for i, indices in enumerate(self.batch_sampler):
                work.put((i, indices))
            for _ in threads:
                work.put(STOP)

        feed_thread = threading.Thread(target=feeder, daemon=True)
        feed_thread.start()

        n = len(self.batch_sampler)
        for i in range(n):
            with done_cv:
                while i not in done:
                    done_cv.wait()
                batch = done.pop(i)
            if isinstance(batch, Exception):
                raise batch
            yield batch
        feed_thread.join()
        for t in threads:
            t.join()

    def __len__(self):
        return len(self.batch_sampler)
