"""Image-classification dataset + numpy transform pipeline.

Capability parity with the reference's GeneralClsDataset + transforms
(/root/reference/ppfleetx/data/dataset/vision_dataset.py,
data/transforms/preprocess.py): train-time random-resized-crop + horizontal
flip + normalize, eval-time center crop, label list files.

Storage: ``{prefix}_images.npy`` [N,H,W,C] uint8 + ``{prefix}_labels.npy``
[N] int64, opened with ``mmap_mode='r'`` so a 250GB ImageNet array never
loads into host RAM (ImageNet-folder scanning has no place in a TPU data
hall — convert once with tools/preprocess_images.py). A small ``.npz``
(which numpy cannot mmap) is accepted for tests/tiny sets and loads
eagerly. ``SyntheticClsDataset`` serves benchmarking (reference test_tipc
uses fake data the same way).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from fleetx_tpu.utils.log import logger

__all__ = ["GeneralClsDataset", "SyntheticClsDataset", "ContrastiveViewsDataset"]

_IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
_IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def _random_resized_crop(rng, img, out_size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
    h, w = img.shape[:2]
    area = h * w
    for _ in range(10):
        target = rng.uniform(*scale) * area
        ar = np.exp(rng.uniform(np.log(ratio[0]), np.log(ratio[1])))
        cw = int(round(np.sqrt(target * ar)))
        ch = int(round(np.sqrt(target / ar)))
        if cw <= w and ch <= h:
            y = rng.randint(0, h - ch + 1)
            x = rng.randint(0, w - cw + 1)
            crop = img[y : y + ch, x : x + cw]
            return _resize(crop, out_size)
    return _center_crop(img, out_size)


def _resize(img, out_size):
    """Nearest-neighbour resize (no cv2/PIL dependency)."""
    h, w = img.shape[:2]
    ys = (np.arange(out_size) * h // out_size).clip(0, h - 1)
    xs = (np.arange(out_size) * w // out_size).clip(0, w - 1)
    return img[ys][:, xs]


def _center_crop(img, out_size):
    h, w = img.shape[:2]
    short = min(h, w)
    scaled = _resize(
        img[(h - short) // 2 : (h + short) // 2, (w - short) // 2 : (w + short) // 2],
        out_size,
    )
    return scaled


# ---------------------------------------------------------------- MoCo augs
# The contrastive recipe the reference builds from PIL/paddle.vision ops
# (/root/reference/ppfleetx/data/transforms/preprocess.py:294-401:
# ColorJitter, RandomGrayscale, GaussianBlur, RandomErasing), re-implemented
# as pure-numpy deterministic transforms: every draw comes from the caller's
# per-(seed, epoch, index) RandomState, so views are reproducible with no
# PIL dependency. Images are float32 [H, W, 3] in [0, 1] throughout.

_GRAY_W = np.array([0.299, 0.587, 0.114], np.float32)  # ITU-R 601 (PIL 'L')


def _rgb_to_hsv(img):
    r, g, b = img[..., 0], img[..., 1], img[..., 2]
    maxc = img.max(-1)
    minc = img.min(-1)
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(delta, 1e-12)
    rc, gc, bc = (maxc - r) / dz, (maxc - g) / dz, (maxc - b) / dz
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(delta > 0, (h / 6.0) % 1.0, 0.0)
    return h, s, maxc


def _hsv_to_rgb(h, s, v):
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    conds = [i == k for k in range(6)]
    r = np.select(conds, [v, q, p, p, t, v])
    g = np.select(conds, [t, v, v, q, p, p])
    b = np.select(conds, [p, p, t, v, v, q])
    return np.stack([r, g, b], axis=-1).astype(np.float32)


def _blend(a, b, factor):
    return np.clip(factor * a + (1.0 - factor) * b, 0.0, 1.0).astype(np.float32)


def _grayscale(img):
    g = img @ _GRAY_W
    return np.repeat(g[..., None], 3, axis=-1)


def _color_jitter(rng, img, brightness, contrast, saturation, hue):
    """torchvision-semantics jitter: factors uniform around 1 (hue additive
    in cycles), the four adjustments applied in a random order."""
    ops = []
    # NB: factors are captured as default args — a bare closure over the
    # loop variable would late-bind every op to the LAST drawn factor
    if brightness > 0:
        f = rng.uniform(max(0.0, 1 - brightness), 1 + brightness)
        ops.append(lambda im, f=f: _blend(im, np.zeros_like(im), f))
    if contrast > 0:
        f = rng.uniform(max(0.0, 1 - contrast), 1 + contrast)
        ops.append(lambda im, f=f: _blend(im, _grayscale(im).mean(), f))
    if saturation > 0:
        f = rng.uniform(max(0.0, 1 - saturation), 1 + saturation)
        ops.append(lambda im, f=f: _blend(im, _grayscale(im), f))
    if hue > 0:
        shift = rng.uniform(-hue, hue)

        def hue_op(im, shift=shift):
            h, s, v = _rgb_to_hsv(im)
            return _hsv_to_rgb((h + shift) % 1.0, s, v)

        ops.append(hue_op)
    for idx in rng.permutation(len(ops)):
        img = ops[idx](img)
    return img


def _gaussian_blur(img, sigma):
    """Separable gaussian, reflect padding (SimCLR-style blur; PIL radius
    == sigma)."""
    radius = max(1, int(round(3.0 * sigma)))
    x = np.arange(-radius, radius + 1, dtype=np.float32)
    kern = np.exp(-0.5 * (x / sigma) ** 2)
    kern /= kern.sum()
    for axis in (0, 1):
        pad = [(0, 0)] * img.ndim
        pad[axis] = (radius, radius)
        padded = np.pad(img, pad, mode="reflect")
        out = np.zeros_like(img)
        for t, w in enumerate(kern):  # ~2*3σ+1 taps; vectorized over H*W*3
            sl = [slice(None)] * img.ndim
            sl[axis] = slice(t, t + img.shape[axis])
            out += w * padded[tuple(sl)]
        img = out
    return img


def _random_erasing(rng, img, p=0.5, sl=0.02, sh=0.4, r1=0.3, value=0.0,
                    attempts=100):
    """Zero (or fill) a random rectangle (reference RandomErasing,
    preprocess.py:350, 'const' mode). Mutates and returns ``img``."""
    if rng.rand() > p:
        return img
    h, w = img.shape[:2]
    area = h * w
    for _ in range(attempts):
        target = rng.uniform(sl, sh) * area
        ar = rng.uniform(r1, 1.0 / r1)
        eh = int(round(np.sqrt(target * ar)))
        ew = int(round(np.sqrt(target / ar)))
        if eh < h and ew < w:
            y = rng.randint(0, h - eh + 1)
            x = rng.randint(0, w - ew + 1)
            img[y : y + eh, x : x + ew] = value
            return img
    return img


# (jitter args, jitter p, grayscale p, blur p, jitter-before-grayscale,
#  norm mean/std) per reference config:
# mocov2_pt_in1k_1n8c.yaml:87-95 — jitter(.4,.4,.4,.1)@p.8 -> gray@.2 ->
#   blur[.1,2]@.5, imagenet norm;
# mocov1_pt_in1k_1n8c.yaml:79-81 — gray@.2 -> jitter(.4,.4,.4,.4)@1.0,
#   no blur, 0.5/0.5 norm.
_MOCO_RECIPES = {
    "mocov2": ((0.4, 0.4, 0.4, 0.1), 0.8, 0.2, 0.5, True,
               _IMAGENET_MEAN, _IMAGENET_STD),
    "mocov1": ((0.4, 0.4, 0.4, 0.4), 1.0, 0.2, 0.0, False,
               np.full(3, 0.5, np.float32), np.full(3, 0.5, np.float32)),
}


class GeneralClsDataset:
    """Classification dataset over mmap .npz images with numpy augmentations
    (reference vision_dataset.py)."""
    def __init__(
        self,
        input_dir: str,
        image_size: int = 224,
        mode: str = "Train",
        seed: int = 1234,
        num_samples: Optional[int] = None,
        normalize: bool = True,
        random_erasing: float = 0.0,
        **_unused,
    ):
        prefix = input_dir
        if os.path.isdir(prefix):
            prefix = os.path.join(prefix, mode.lower())
        if os.path.isfile(prefix + "_images.npy"):
            # the scalable path: true mmap, O(1) resident memory
            self.images = np.load(prefix + "_images.npy", mmap_mode="r")
            self.labels = np.load(prefix + "_labels.npy", mmap_mode="r")
            path = prefix + "_images.npy"
        elif os.path.isfile(prefix + ".npz"):
            # .npz members cannot be mmapped — eager load, small sets only
            data = np.load(prefix + ".npz")
            self.images = data["images"]
            self.labels = data["labels"]
            path = prefix + ".npz"
            if self.images.nbytes > 1 << 30:
                logger.warning(
                    ".npz loads eagerly (%.1f GB in RAM); convert to the "
                    "_images.npy/_labels.npy pair for mmap", self.images.nbytes / 1e9,
                )
        else:
            raise FileNotFoundError(prefix + "_images.npy")
        self.image_size = image_size
        self.mode = mode
        self.seed = seed
        self.epoch = 0
        self.normalize = normalize
        self.random_erasing = random_erasing
        self._num_samples = num_samples or len(self.labels)
        logger.info(
            "GeneralClsDataset[%s]: %d images (%s), size %d",
            mode, self._num_samples, path, image_size,
        )

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self):
        return self._num_samples

    def __getitem__(self, index):
        i = index % len(self.labels)
        img = np.asarray(self.images[i]).astype(np.float32) / 255.0
        if self.mode == "Train":
            rng = np.random.RandomState(
                (self.seed * 2654435761 + self.epoch * 97003 + index) % (2**31)
            )
            img = _random_resized_crop(rng, img, self.image_size)
            if rng.rand() < 0.5:
                img = img[:, ::-1]
        else:
            img = _center_crop(img, self.image_size)
        if self.normalize:
            img = (img - _IMAGENET_MEAN) / _IMAGENET_STD
        if self.mode == "Train" and self.random_erasing > 0:
            # post-normalize const erase (timm convention; reference
            # RandomErasing 'const' mode)
            img = _random_erasing(rng, np.ascontiguousarray(img),
                                  p=self.random_erasing)
        return {
            "images": np.ascontiguousarray(img, np.float32),
            "labels": np.int64(self.labels[i]),
        }


class ContrastiveViewsDataset:
    """Two independently-augmented views per image for MoCo-style training.

    The augmentation stack is the reference's contrastive recipe
    (/root/reference/ppfleetx/configs/vis/moco/mocov2_pt_in1k_1n8c.yaml:
    87-95): random-resized-crop (scale 0.2-1.0) -> ColorJitter ->
    RandomGrayscale -> GaussianBlur -> horizontal flip -> normalize, with
    ``recipe: mocov1`` switching to the v1 ordering/strengths (grayscale
    before full-strength jitter, no blur, 0.5/0.5 normalization). Every
    knob is individually overridable from YAML. Wraps the same storage as
    GeneralClsDataset; ``synthetic: True`` generates noise images for
    benchmarking."""

    def __init__(self, input_dir=None, image_size=224, mode="Train", seed=1234,
                 num_samples=None, synthetic=False, num_synthetic=1280,
                 recipe="mocov2", crop_scale=(0.2, 1.0), color_jitter=None,
                 color_jitter_p=None, grayscale_p=None, blur_p=None,
                 blur_sigma=(0.1, 2.0), **_unused):
        self.image_size = image_size
        self.seed = seed
        self.epoch = 0
        self.mode = mode
        if recipe not in _MOCO_RECIPES:
            raise ValueError(
                f"unknown contrastive recipe {recipe!r}; "
                f"have {sorted(_MOCO_RECIPES)}"
            )
        (jit, jit_p, gray_p, blp, jit_first, mean, std) = _MOCO_RECIPES[recipe]
        self.color_jitter = tuple(color_jitter) if color_jitter is not None else jit
        self.color_jitter_p = color_jitter_p if color_jitter_p is not None else jit_p
        self.grayscale_p = grayscale_p if grayscale_p is not None else gray_p
        self.blur_p = blur_p if blur_p is not None else blp
        self.jitter_before_grayscale = jit_first
        self.norm_mean, self.norm_std = mean, std
        self.crop_scale = tuple(crop_scale)
        self.blur_sigma = tuple(blur_sigma)
        self.synthetic = synthetic or input_dir is None
        if self.synthetic:
            self._num_samples = num_samples or num_synthetic
            self.images = None
        else:
            base = GeneralClsDataset(
                input_dir, image_size=image_size, mode=mode, seed=seed,
                normalize=False,
            )
            self.images = base.images
            self._num_samples = num_samples or len(base.labels)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self):
        return self._num_samples

    def _view(self, rng, img):
        out = _random_resized_crop(rng, img, self.image_size,
                                   scale=self.crop_scale)

        def jitter(im):
            if any(self.color_jitter) and rng.rand() < self.color_jitter_p:
                im = _color_jitter(rng, im, *self.color_jitter)
            return im

        def gray(im):
            if rng.rand() < self.grayscale_p:
                im = _grayscale(im)
            return im

        out = gray(jitter(out)) if self.jitter_before_grayscale \
            else jitter(gray(out))
        if self.blur_p > 0 and rng.rand() < self.blur_p:
            out = _gaussian_blur(out, rng.uniform(*self.blur_sigma))
        if rng.rand() < 0.5:
            out = out[:, ::-1]
        return ((out - self.norm_mean) / self.norm_std).astype(np.float32)

    def __getitem__(self, index):
        # eval mode: epoch-independent rng so view pairs (and hence the
        # contrastive loss) are reproducible across runs
        epoch = self.epoch if self.mode == "Train" else 0
        rng = np.random.RandomState(
            (self.seed * 2654435761 + epoch * 97003 + index) % (2**31)
        )
        if self.synthetic:
            img = rng.rand(self.image_size + 16, self.image_size + 16, 3).astype(
                np.float32
            )
        else:
            img = np.asarray(self.images[index % len(self.images)]).astype(np.float32) / 255.0
        return {
            "query": np.ascontiguousarray(self._view(rng, img)),
            "key": np.ascontiguousarray(self._view(rng, img)),
        }


class SyntheticClsDataset:
    """Fake data for benchmarking (reference test_tipc fake-data path)."""

    def __init__(self, image_size=224, num_classes=1000, num_samples=1280,
                 mode="Train", seed=1234, **_unused):
        self.image_size = image_size
        self.num_classes = num_classes
        self._num_samples = num_samples
        self.seed = seed

    def __len__(self):
        return self._num_samples

    def __getitem__(self, index):
        rng = np.random.RandomState((self.seed + index) % (2**31))
        return {
            "images": rng.randn(self.image_size, self.image_size, 3).astype(np.float32),
            "labels": np.int64(rng.randint(0, self.num_classes)),
        }
