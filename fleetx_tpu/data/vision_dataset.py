"""Image-classification dataset + numpy transform pipeline.

Capability parity with the reference's GeneralClsDataset + transforms
(/root/reference/ppfleetx/data/dataset/vision_dataset.py,
data/transforms/preprocess.py): train-time random-resized-crop + horizontal
flip + normalize, eval-time center crop, label list files.

Storage: ``{prefix}_images.npy`` [N,H,W,C] uint8 + ``{prefix}_labels.npy``
[N] int64, opened with ``mmap_mode='r'`` so a 250GB ImageNet array never
loads into host RAM (ImageNet-folder scanning has no place in a TPU data
hall — convert once with tools/preprocess_images.py). A small ``.npz``
(which numpy cannot mmap) is accepted for tests/tiny sets and loads
eagerly. ``SyntheticClsDataset`` serves benchmarking (reference test_tipc
uses fake data the same way).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from fleetx_tpu.utils.log import logger

__all__ = ["GeneralClsDataset", "SyntheticClsDataset", "ContrastiveViewsDataset"]

_IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
_IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def _random_resized_crop(rng, img, out_size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
    h, w = img.shape[:2]
    area = h * w
    for _ in range(10):
        target = rng.uniform(*scale) * area
        ar = np.exp(rng.uniform(np.log(ratio[0]), np.log(ratio[1])))
        cw = int(round(np.sqrt(target * ar)))
        ch = int(round(np.sqrt(target / ar)))
        if cw <= w and ch <= h:
            y = rng.randint(0, h - ch + 1)
            x = rng.randint(0, w - cw + 1)
            crop = img[y : y + ch, x : x + cw]
            return _resize(crop, out_size)
    return _center_crop(img, out_size)


def _resize(img, out_size):
    """Nearest-neighbour resize (no cv2/PIL dependency)."""
    h, w = img.shape[:2]
    ys = (np.arange(out_size) * h // out_size).clip(0, h - 1)
    xs = (np.arange(out_size) * w // out_size).clip(0, w - 1)
    return img[ys][:, xs]


def _center_crop(img, out_size):
    h, w = img.shape[:2]
    short = min(h, w)
    scaled = _resize(
        img[(h - short) // 2 : (h + short) // 2, (w - short) // 2 : (w + short) // 2],
        out_size,
    )
    return scaled


class GeneralClsDataset:
    """Classification dataset over mmap .npz images with numpy augmentations
    (reference vision_dataset.py)."""
    def __init__(
        self,
        input_dir: str,
        image_size: int = 224,
        mode: str = "Train",
        seed: int = 1234,
        num_samples: Optional[int] = None,
        normalize: bool = True,
        **_unused,
    ):
        prefix = input_dir
        if os.path.isdir(prefix):
            prefix = os.path.join(prefix, mode.lower())
        if os.path.isfile(prefix + "_images.npy"):
            # the scalable path: true mmap, O(1) resident memory
            self.images = np.load(prefix + "_images.npy", mmap_mode="r")
            self.labels = np.load(prefix + "_labels.npy", mmap_mode="r")
            path = prefix + "_images.npy"
        elif os.path.isfile(prefix + ".npz"):
            # .npz members cannot be mmapped — eager load, small sets only
            data = np.load(prefix + ".npz")
            self.images = data["images"]
            self.labels = data["labels"]
            path = prefix + ".npz"
            if self.images.nbytes > 1 << 30:
                logger.warning(
                    ".npz loads eagerly (%.1f GB in RAM); convert to the "
                    "_images.npy/_labels.npy pair for mmap", self.images.nbytes / 1e9,
                )
        else:
            raise FileNotFoundError(prefix + "_images.npy")
        self.image_size = image_size
        self.mode = mode
        self.seed = seed
        self.epoch = 0
        self.normalize = normalize
        self._num_samples = num_samples or len(self.labels)
        logger.info(
            "GeneralClsDataset[%s]: %d images (%s), size %d",
            mode, self._num_samples, path, image_size,
        )

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self):
        return self._num_samples

    def __getitem__(self, index):
        i = index % len(self.labels)
        img = np.asarray(self.images[i]).astype(np.float32) / 255.0
        if self.mode == "Train":
            rng = np.random.RandomState(
                (self.seed * 2654435761 + self.epoch * 97003 + index) % (2**31)
            )
            img = _random_resized_crop(rng, img, self.image_size)
            if rng.rand() < 0.5:
                img = img[:, ::-1]
        else:
            img = _center_crop(img, self.image_size)
        if self.normalize:
            img = (img - _IMAGENET_MEAN) / _IMAGENET_STD
        return {
            "images": np.ascontiguousarray(img, np.float32),
            "labels": np.int64(self.labels[i]),
        }


class ContrastiveViewsDataset:
    """Two independently-augmented views per image for MoCo-style training
    (reference moco dataset transforms: two random crops + flips). Wraps the
    same storage as GeneralClsDataset; ``synthetic: True`` generates noise
    images for benchmarking."""

    def __init__(self, input_dir=None, image_size=224, mode="Train", seed=1234,
                 num_samples=None, synthetic=False, num_synthetic=1280, **_unused):
        self.image_size = image_size
        self.seed = seed
        self.epoch = 0
        self.mode = mode
        self.synthetic = synthetic or input_dir is None
        if self.synthetic:
            self._num_samples = num_samples or num_synthetic
            self.images = None
        else:
            base = GeneralClsDataset(
                input_dir, image_size=image_size, mode=mode, seed=seed,
                normalize=False,
            )
            self.images = base.images
            self._num_samples = num_samples or len(base.labels)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self):
        return self._num_samples

    def _view(self, rng, img):
        out = _random_resized_crop(rng, img, self.image_size)
        if rng.rand() < 0.5:
            out = out[:, ::-1]
        return ((out - _IMAGENET_MEAN) / _IMAGENET_STD).astype(np.float32)

    def __getitem__(self, index):
        # eval mode: epoch-independent rng so view pairs (and hence the
        # contrastive loss) are reproducible across runs
        epoch = self.epoch if self.mode == "Train" else 0
        rng = np.random.RandomState(
            (self.seed * 2654435761 + epoch * 97003 + index) % (2**31)
        )
        if self.synthetic:
            img = rng.rand(self.image_size + 16, self.image_size + 16, 3).astype(
                np.float32
            )
        else:
            img = np.asarray(self.images[index % len(self.images)]).astype(np.float32) / 255.0
        return {
            "query": np.ascontiguousarray(self._view(rng, img)),
            "key": np.ascontiguousarray(self._view(rng, img)),
        }


class SyntheticClsDataset:
    """Fake data for benchmarking (reference test_tipc fake-data path)."""

    def __init__(self, image_size=224, num_classes=1000, num_samples=1280,
                 mode="Train", seed=1234, **_unused):
        self.image_size = image_size
        self.num_classes = num_classes
        self._num_samples = num_samples
        self.seed = seed

    def __len__(self):
        return self._num_samples

    def __getitem__(self, index):
        rng = np.random.RandomState((self.seed + index) % (2**31))
        return {
            "images": rng.randn(self.image_size, self.image_size, 3).astype(np.float32),
            "labels": np.int64(rng.randint(0, self.num_classes)),
        }
