"""Megatron-style mmap GPT pretraining dataset + offline eval datasets.

Parity with the reference (/root/reference/ppfleetx/data/dataset/
gpt_dataset.py:42-645), same on-disk formats so preprocessed corpora are
interchangeable:

- ``{prefix}_ids.npy``  — all documents' token ids, one flat 1-D array
- ``{prefix}_idx.npz``  — key ``lens``: per-document token counts
- cached index maps ``{prefix}_{name}_indexmap_{ns}ns_{sl}sl_{doc,sample,
  shuffle}_idx.npy`` built once by process 0 (others spin-wait), sample
  construction in native code (fleetx_tpu/data/native).

Samples cross document boundaries; each is seq_len+1 tokens split into
(tokens, labels) with eos positions masked out of the loss.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Sequence

import numpy as np

from fleetx_tpu.data.native import build_sample_idx
from fleetx_tpu.utils.log import logger

__all__ = ["GPTDataset", "LMEvalDataset", "LambadaEvalDataset"]


def _train_valid_test_split(split: Sequence[float], n_docs: int) -> List[int]:
    """Cumulative doc boundaries from ratio triple (reference
    get_train_valid_test_split_, gpt_dataset.py:241-263)."""
    splits = list(split) + [0.0] * (3 - len(split))
    total = sum(splits)
    if total <= 0:
        raise ValueError(f"split ratios must sum > 0, got {split}")
    bounds = [0]
    for s in splits:
        bounds.append(bounds[-1] + int(round(s / total * n_docs)))
    bounds[-1] = n_docs
    diff = bounds[-1] - bounds[-2]
    if diff < 0:
        raise ValueError(f"bad split {split}")
    return bounds


def _build_doc_idx(documents, num_epochs, rng, separate_last_epoch):
    if not separate_last_epoch or num_epochs == 1:
        doc_idx = np.tile(documents, num_epochs).astype(np.int32)
        rng.shuffle(doc_idx)
        return doc_idx
    first = _build_doc_idx(documents, num_epochs - 1, rng, False)
    last = _build_doc_idx(documents, 1, rng, False)
    return np.concatenate((first, last))


def _build_shuffle_idx(num_samples, total_size, rng):
    """Shuffle the first num_samples densely, the tail separately
    (Megatron separate-last-epoch trick)."""
    dtype = np.int64 if total_size >= (np.iinfo(np.uint32).max - 1) else np.uint32
    first = np.arange(num_samples, dtype=dtype)
    rng.shuffle(first)
    if num_samples == total_size:
        return first
    last = np.arange(num_samples, total_size, dtype=dtype)
    rng.shuffle(last)
    return np.concatenate((first, last))


class GPTDataset:
    """mode: 'Train' | 'Eval' | 'Test'."""

    def __init__(
        self,
        input_dir,
        split=(949, 50, 1),
        max_seq_len: int = 1024,
        mode: str = "Train",
        seed: int = 1024,
        num_samples: Optional[int] = None,
        eos_id: int = 50256,
        build_data_file: Optional[bool] = None,
        **_,
    ):
        if isinstance(input_dir, str):
            prefix = input_dir
        else:
            assert len(input_dir) == 1, "GPT supports one dataset prefix"
            prefix = input_dir[0]
        for suffix in ("_ids.npy", "_idx.npz"):
            if not os.path.isfile(prefix + suffix):
                raise FileNotFoundError(prefix + suffix)

        self.sample_ids = np.load(prefix + "_ids.npy", mmap_mode="r", allow_pickle=True)
        lens = np.load(prefix + "_idx.npz")["lens"].astype(np.int32)
        self.max_seq_len = max_seq_len
        self.mode = mode
        self.name = "gpt_" + mode
        self.eos_id = eos_id

        bounds = _train_valid_test_split(split, len(lens))
        index = {"Train": 0, "Eval": 1, "Test": 2}[mode]
        documents = np.arange(bounds[index], bounds[index + 1], dtype=np.int32)
        if len(documents) == 0:
            raise ValueError(f"split {split} leaves no documents for mode {mode}")
        if num_samples is None:
            num_samples = max(1, int(lens[documents].sum()) // (max_seq_len + 1))

        if build_data_file is None:
            try:
                import jax

                build_data_file = jax.process_index() == 0
            except Exception:
                build_data_file = True

        self.doc_idx, self.sample_idx, self.shuffle_idx = self._indices(
            prefix, documents, lens, num_samples, max_seq_len, seed, build_data_file
        )
        self.start_pos = np.concatenate(([0], np.cumsum(lens))).astype(np.int64)

    # ------------------------------------------------------------------ index
    def _indices(self, prefix, documents, lens, num_samples, seq_len, seed, build):
        tokens_per_epoch = int(lens[documents].sum())
        num_epochs = 1
        while num_epochs * tokens_per_epoch < (num_samples * seq_len + 1):
            num_epochs += 1
        base = f"{prefix}_{self.name}_indexmap_{num_samples}ns_{seq_len}sl"
        files = {k: f"{base}_{k}_idx.npy" for k in ("doc", "sample", "shuffle")}

        if build and not all(os.path.isfile(f) for f in files.values()):
            rng = np.random.RandomState(seed)
            if num_epochs == 1:
                separate_last = False
            else:
                from_prev = ((num_epochs - 1) * tokens_per_epoch - 1) // seq_len
                last_count = num_samples - from_prev
                per_epoch = (tokens_per_epoch - 1) // seq_len
                separate_last = last_count < int(0.8 * per_epoch)
            t0 = time.time()
            doc_idx = _build_doc_idx(documents, num_epochs, rng, separate_last)
            sample_idx = build_sample_idx(
                lens, doc_idx, seq_len, num_epochs, tokens_per_epoch
            )
            n_shuffle = (
                ((num_epochs - 1) * tokens_per_epoch - 1) // seq_len
                if separate_last
                else sample_idx.shape[0] - 1
            )
            shuffle_idx = _build_shuffle_idx(n_shuffle, sample_idx.shape[0] - 1, rng)
            np.save(files["doc"], doc_idx, allow_pickle=True)
            np.save(files["sample"], sample_idx, allow_pickle=True)
            np.save(files["shuffle"], shuffle_idx, allow_pickle=True)
            logger.info(
                "built %s index maps (%d samples) in %.2fs",
                self.name,
                sample_idx.shape[0] - 1,
                time.time() - t0,
            )
        else:
            deadline = time.time() + 300
            while not all(os.path.isfile(f) for f in files.values()):
                if time.time() > deadline:
                    raise TimeoutError(f"waiting for index maps {base}")
                time.sleep(1.0)
        return tuple(
            np.load(files[k], allow_pickle=True, mmap_mode="r")
            for k in ("doc", "sample", "shuffle")
        )

    # ----------------------------------------------------------------- access
    def _tokens_for(self, idx: int) -> np.ndarray:
        doc_f, off_f = self.sample_idx[idx]
        doc_l, off_l = self.sample_idx[idx + 1]
        if doc_f == doc_l:
            start = self.start_pos[self.doc_idx[doc_f]]
            return np.asarray(self.sample_ids[start + off_f : start + off_l + 1])
        parts = []
        start = self.start_pos[self.doc_idx[doc_f]]
        end = self.start_pos[self.doc_idx[doc_f] + 1]
        parts.append(self.sample_ids[start + off_f : end])
        for i in range(doc_f + 1, doc_l):
            d = self.doc_idx[i]
            parts.append(self.sample_ids[self.start_pos[d] : self.start_pos[d + 1]])
        last = self.start_pos[self.doc_idx[doc_l]]
        parts.append(self.sample_ids[last : last + off_l + 1])
        return np.concatenate(parts)

    def __getitem__(self, index):
        seq = self._tokens_for(int(self.shuffle_idx[index])).astype(np.int64)
        tokens, labels = seq[:-1], seq[1:]
        loss_mask = (tokens != self.eos_id).astype(np.float32)
        position_ids = np.arange(len(tokens), dtype=np.int64)
        if self.mode == "Test":
            return {"tokens": tokens, "position_ids": position_ids}
        return {
            "tokens": tokens,
            "position_ids": position_ids,
            "labels": labels,
            "loss_mask": loss_mask,
        }

    def __len__(self):
        return self.sample_idx.shape[0] - 1


class LMEvalDataset:
    """Overlapping-window perplexity eval (reference LM_Eval_Dataset,
    gpt_dataset.py:474-576): slide over the token stream with
    ``overlapping_eval`` stride, masking out the overlap from the loss."""

    def __init__(self, tokens, seq_len: int, pad_id: int,
                 overlapping_eval: Optional[int] = None, **_):
        self.tokens = np.asarray(tokens, np.int64)
        self.seq_len = seq_len
        self.pad_id = pad_id
        self.overlapping_eval = overlapping_eval or seq_len
        total = len(self.tokens)
        self.total_targets = total - 1
        targets = max(self.total_targets - self.overlapping_eval, 0)
        self.total_sequences = max(
            targets // self.overlapping_eval + (1 if targets % self.overlapping_eval else 0),
            0,
        ) + 1

    def __len__(self):
        return self.total_sequences

    def __getitem__(self, idx):
        start = idx * self.overlapping_eval
        end = start + self.seq_len
        seq = self.tokens[start : end + 1].tolist()
        num_tokens = len(seq)
        pad_mask = [1] * num_tokens
        if num_tokens < self.seq_len + 1:
            seq += [self.pad_id] * (self.seq_len + 1 - num_tokens)
            pad_mask += [0] * (self.seq_len + 1 - num_tokens)
        pad_mask = np.asarray(pad_mask[1:], np.float32)
        if idx > 0 and self.overlapping_eval != self.seq_len:
            pad_mask[: self.seq_len - self.overlapping_eval] = 0
        seq = np.asarray(seq, np.int64)
        return {
            "tokens": seq[:-1],
            "position_ids": np.arange(self.seq_len, dtype=np.int64),
            "labels": seq[1:],
            "loss_mask": pad_mask,
        }


class LambadaEvalDataset:
    """LAMBADA last-word cloze accuracy (reference Lambada_Eval_Dataset,
    gpt_dataset.py:579-645): loss_mask covers only the target-word tokens."""

    def __init__(self, contexts, targets, seq_len: int, pad_id: int, **_):
        self.contexts = contexts  # list of token-id lists
        self.targets = targets  # list of token-id lists (the last word)
        self.seq_len = seq_len
        self.pad_id = pad_id

    def __len__(self):
        return len(self.contexts)

    def __getitem__(self, idx):
        ctx, tgt = list(self.contexts[idx]), list(self.targets[idx])
        seq = ctx + tgt
        seq = seq[-(self.seq_len + 1):]
        num = len(seq)
        pad = [self.pad_id] * (self.seq_len + 1 - num)
        loss_mask = np.zeros(self.seq_len, np.float32)
        loss_mask[num - len(tgt) - 1 : num - 1] = 1.0
        arr = np.asarray(seq + pad, np.int64)
        return {
            "tokens": arr[:-1],
            "position_ids": np.arange(self.seq_len, dtype=np.int64),
            "labels": arr[1:],
            "loss_mask": loss_mask,
        }
