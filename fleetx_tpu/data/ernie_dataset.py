"""ERNIE masked-LM + SOP pretraining dataset.

Capability parity with the reference's ERNIE data pipeline
(/root/reference/ppfleetx/data/dataset/ernie/ernie_dataset.py +
dataset_utils.py: span/ngram masking, 80/10/10 mask-random-keep policy,
sentence-order-prediction pairs) over the same mmap token format as
GPTDataset (``{prefix}_ids.npy`` + ``{prefix}_idx.npz``).

TPU-first: every sample has STATIC shapes — [max_seq_len] inputs and
[max_predictions_per_seq] masked slots with a weights vector — so the whole
training step is one XLA program (the reference pads dynamically per batch).
Sampling is deterministic per (seed, epoch, index) — the engine calls
``set_epoch`` each epoch so masks are re-drawn, and resume is safe without
checkpointing RNG state.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from fleetx_tpu.utils.log import logger

__all__ = ["ErnieDataset"]


class ErnieDataset:
    """Each sample: [CLS] segA [SEP] segB [SEP] with ngram masking.

    vocab layout follows the reference ERNIE tokenizers: ids for the special
    tokens are configurable; random-replacement draws uniformly from
    [special_tokens_ceiling, vocab_size).
    """

    def __init__(
        self,
        input_dir,
        max_seq_len: int = 512,
        mode: str = "Train",
        seed: int = 1234,
        num_samples: Optional[int] = None,
        masked_lm_prob: float = 0.15,
        max_predictions_per_seq: Optional[int] = None,
        max_ngram: int = 3,
        vocab_size: int = 40000,
        cls_id: int = 1,
        sep_id: int = 2,
        mask_id: int = 3,
        pad_id: int = 0,
        binary_head: bool = True,
        split=None,  # accepted for config parity; doc split not needed
        **_unused,
    ):
        if isinstance(input_dir, (list, tuple)):
            assert len(input_dir) == 1, "ERNIE supports one dataset prefix"
            input_dir = input_dir[0]
        prefix = input_dir
        for suffix in ("_ids.npy", "_idx.npz"):
            if not os.path.isfile(prefix + suffix):
                raise FileNotFoundError(prefix + suffix)
        self.ids = np.load(prefix + "_ids.npy", mmap_mode="r", allow_pickle=True)
        lens = np.load(prefix + "_idx.npz")["lens"].astype(np.int64)
        self.start = np.concatenate([[0], np.cumsum(lens)])
        self.lens = lens
        self.mode = mode
        self.epoch = 0
        self.seed = seed + {"Train": 0, "Eval": 1, "Test": 2}.get(mode, 0)
        self.max_seq_len = max_seq_len
        self.masked_lm_prob = masked_lm_prob
        self.max_predictions = max_predictions_per_seq or max(
            1, int(masked_lm_prob * max_seq_len * 3 // 2)
        )
        self.max_ngram = max_ngram
        self.vocab_size = vocab_size
        self.cls_id, self.sep_id = cls_id, sep_id
        self.mask_id, self.pad_id = mask_id, pad_id
        self.binary_head = binary_head
        # usable docs: long enough to split into two non-empty segments
        self.docs = np.nonzero(lens >= 4)[0]
        if len(self.docs) == 0:
            raise ValueError("no document long enough for ERNIE pairs")
        self._num_samples = num_samples or len(self.docs)
        logger.info(
            "ErnieDataset[%s]: %d docs, %d samples, seq %d, %d preds/seq",
            mode, len(self.docs), self._num_samples, max_seq_len, self.max_predictions,
        )

    def __len__(self):
        return self._num_samples

    def set_epoch(self, epoch: int) -> None:
        """Re-mask per epoch: the engine calls this each epoch so every pass
        draws fresh crops/swaps/masks (reference pipeline re-masks per epoch)."""
        self.epoch = epoch

    def _doc_tokens(self, doc: int) -> np.ndarray:
        return np.asarray(self.ids[self.start[doc] : self.start[doc + 1]])

    def __getitem__(self, index):
        epoch = getattr(self, "epoch", 0)
        rng = np.random.RandomState(
            (self.seed * 2654435761 + epoch * 97003 + index) % (2**31)
        )
        doc = self.docs[index % len(self.docs)]
        tokens = self._doc_tokens(int(doc)).astype(np.int64)

        # two consecutive segments; budget leaves room for [CLS] + 2x[SEP]
        budget = self.max_seq_len - 3
        if len(tokens) > budget:
            off = rng.randint(0, len(tokens) - budget + 1)
            tokens = tokens[off : off + budget]
        cut = len(tokens) // 2
        a, b = tokens[:cut], tokens[cut:]
        sop_label = 1
        if self.binary_head and rng.rand() < 0.5:
            a, b = b, a
            sop_label = 0

        ids = np.concatenate([[self.cls_id], a, [self.sep_id], b, [self.sep_id]])
        token_type = np.concatenate(
            [np.zeros(len(a) + 2, np.int64), np.ones(len(b) + 1, np.int64)]
        )
        n = len(ids)

        # ngram span masking over non-special positions
        maskable = np.nonzero(
            (ids != self.cls_id) & (ids != self.sep_id)
        )[0]
        rng.shuffle(maskable)
        target = max(1, min(self.max_predictions, int(round(n * self.masked_lm_prob))))
        # favour short ngrams: p(n) ∝ 1/n (reference dataset_utils ngram policy)
        ngram_p = np.array([1.0 / g for g in range(1, self.max_ngram + 1)])
        ngram_p /= ngram_p.sum()

        covered = np.zeros(n, bool)
        positions = []
        for start_pos in maskable:
            if len(positions) >= target:
                break
            g = rng.choice(np.arange(1, self.max_ngram + 1), p=ngram_p)
            span = range(start_pos, min(start_pos + g, n))
            if any(covered[list(span)]) or any(
                ids[p] in (self.cls_id, self.sep_id) for p in span
            ):
                continue
            for p in span:
                if len(positions) >= target:
                    break
                covered[p] = True
                positions.append(p)
        positions = np.sort(np.array(positions[: self.max_predictions], np.int64))

        masked_ids = ids.copy()
        labels = ids[positions].copy()
        for i, p in enumerate(positions):
            r = rng.rand()
            if r < 0.8:
                masked_ids[p] = self.mask_id
            elif r < 0.9:
                masked_ids[p] = rng.randint(
                    max(self.mask_id, self.sep_id, self.cls_id, self.pad_id) + 1,
                    self.vocab_size,
                )
            # else keep original

        # pad everything to static shapes
        s, P = self.max_seq_len, self.max_predictions
        out_ids = np.full(s, self.pad_id, np.int64)
        out_ids[:n] = masked_ids
        out_type = np.zeros(s, np.int64)
        out_type[:n] = token_type
        mp_out = np.zeros(P, np.int64)
        ml_out = np.zeros(P, np.int64)
        mw_out = np.zeros(P, np.float32)
        k = len(positions)
        mp_out[:k] = positions
        ml_out[:k] = labels
        mw_out[:k] = 1.0
        return {
            "input_ids": out_ids,
            "token_type_ids": out_type,
            "masked_positions": mp_out,
            "masked_labels": ml_out,
            "masked_weights": mw_out,
            "sop_labels": np.int64(sop_label),
        }
