"""Chinese GPT (CPM) tokenizer: sentencepiece-unigram in pure Python.

Reference: GPTChineseTokenizer ("gpt-cpm-large-cn"), selected by the GPT-cn
model class (/root/reference/ppfleetx/data/dataset/gpt_dataset.py:35-39).
The reference depends on the `sentencepiece` C++ wheel + `jieba`; neither
ships in this image, and the CPM .model file itself cannot be fetched under
zero egress. TPU-first replacement: the sentencepiece **model protobuf** is
parsed with the pb2 schema transformers already bundles, and unigram
segmentation is a plain Viterbi pass over the piece scores — so any
user-supplied `.model` file works with zero native dependencies.

CPM pre-segments text with jieba before sentencepiece (word-granularity
hints); jieba is pure Python and present in this image, so that path runs
by default. If jieba is ever absent, text goes straight to the unigram
model — different segmentation granularity, same vocabulary and decode
mapping.
CPM's whitespace conventions are kept: ' ' -> '▂', '\n' -> '▃' before
encoding, inverted after decoding, and the '▁' word-boundary markers the
space-joined segmentation introduces are dropped on decode.
"""

from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["SentencePieceUnigram", "GPTChineseTokenizer"]

_SPACE = "▂"    # ▂  CPM space placeholder
_NEWLINE = "▃"  # ▃  CPM newline placeholder
_WORD_SEP = "▁"  # ▁  sentencepiece word-boundary marker


class SentencePieceUnigram:
    """Unigram-LM sentencepiece encoder over a parsed ModelProto.

    Viterbi over piece log-probs: best[i] = max_j best[j] + score(text[j:i]).
    Characters no piece covers fall back to the model's unk id (score from
    trainer_spec, default well below any real piece so unk never beats a
    genuine segmentation).
    """

    def __init__(self, pieces: Dict[str, float], ids: Dict[str, int],
                 unk_id: int = 0, unk_piece: str = "<unk>",
                 escape_whitespaces: bool = True,
                 byte_ids: Optional[Dict[int, int]] = None):
        self.scores = pieces
        self.ids = ids
        self.id_to_piece = {i: p for p, i in ids.items()}
        self.unk_id = unk_id
        self.unk_piece = unk_piece
        # sentencepiece normalization: spaces become the ▁ meta symbol
        # BEFORE segmentation (normalizer_spec.escape_whitespaces)
        self.escape_whitespaces = escape_whitespaces
        self.max_piece_len = max((len(p) for p in pieces), default=1)
        # unk must stay strictly worse than any real single piece
        self.unk_score = min(pieces.values(), default=0.0) - 10.0
        self.eos_id: Optional[int] = None  # set by from_file when present
        # byte value -> BYTE(6) piece id; true byte-fallback alphabet. Real
        # sentencepiece keeps byte pieces OUT of the lattice (literal text
        # "<0x41>" segments as plain characters) and uses them only to
        # encode characters no piece covers — same here: the unk branch
        # emits the char's UTF-8 bytes when the alphabet is present.
        self.byte_ids = byte_ids or {}
        self._byte_vals = {pid: b for b, pid in self.byte_ids.items()}

    @classmethod
    def from_file(cls, model_file: str) -> "SentencePieceUnigram":
        from transformers.utils import sentencepiece_model_pb2_new as pb2

        proto = pb2.ModelProto()
        with open(model_file, "rb") as f:
            proto.ParseFromString(f.read())
        pieces: Dict[str, float] = {}
        ids: Dict[str, int] = {}
        byte_ids: Dict[int, int] = {}
        unk_id, unk_piece = 0, "<unk>"
        eos_id: Optional[int] = None
        for i, p in enumerate(proto.pieces):
            ids[p.piece] = i
            if p.piece in ("</s>", "<eod>") and eos_id is None:
                eos_id = i  # CPM's end-of-document control piece
            if p.type == 2:  # UNKNOWN
                unk_id, unk_piece = i, p.piece
                continue
            if p.type in (3, 5):  # CONTROL/UNUSED: id only, never
                continue          # segmented from raw text
            if p.type == 6:  # BYTE "<0xNN>": fallback alphabet, NOT a
                byte_ids[int(p.piece[3:5], 16)] = i  # surface candidate
                continue
            # NORMAL(1) keeps its trained log-prob; USER_DEFINED(4) is
            # segmented with its stored score (0.0, maximally preferred)
            pieces[p.piece] = p.score
        escape = True
        if proto.HasField("normalizer_spec") and proto.normalizer_spec.HasField(
                "escape_whitespaces"):
            escape = proto.normalizer_spec.escape_whitespaces
        sp = cls(pieces, ids, unk_id, unk_piece, escape, byte_ids)
        sp.eos_id = eos_id
        return sp

    def encode(self, text: str) -> List[int]:
        if self.escape_whitespaces:
            text = text.replace(" ", _WORD_SEP)
        n = len(text)
        if not n:
            return []
        neg = float("-inf")
        best = [neg] * (n + 1)
        best[0] = 0.0
        back: List[Optional[tuple]] = [None] * (n + 1)
        for i in range(n):
            if best[i] == neg:
                continue
            top = min(self.max_piece_len, n - i)
            for length in range(1, top + 1):
                sub = text[i:i + length]
                sc = self.scores.get(sub)
                if sc is not None and best[i] + sc > best[i + length]:
                    best[i + length] = best[i] + sc
                    back[i + length] = (i, self.ids[sub])
            if best[i] + self.unk_score > best[i + 1]:
                best[i + 1] = best[i] + self.unk_score
                # true byte-fallback: a char no piece covers becomes its
                # UTF-8 bytes via the <0xNN> alphabet (same lattice score
                # as unk, so segmentation is unchanged); <unk> only when
                # the model ships no byte pieces
                ch = text[i].encode("utf-8")
                if self.byte_ids and all(b in self.byte_ids for b in ch):
                    back[i + 1] = (i, tuple(self.byte_ids[b] for b in ch))
                else:
                    back[i + 1] = (i, self.unk_id)
        out: List[int] = []
        pos = n
        while pos > 0:
            prev, piece_id = back[pos]
            if isinstance(piece_id, tuple):
                out.extend(reversed(piece_id))
            else:
                out.append(piece_id)
            pos = prev
        out.reverse()
        return out

    def decode(self, ids) -> str:
        # runs of byte pieces decode as UTF-8 byte strings
        parts: List[str] = []
        pending: List[int] = []

        def flush():
            if pending:
                parts.append(bytes(pending).decode("utf-8", errors="replace"))
                pending.clear()

        for i in ids:
            i = int(i)
            if i in self._byte_vals:
                pending.append(self._byte_vals[i])
                continue
            flush()
            parts.append(self.id_to_piece.get(i, self.unk_piece))
        flush()
        return "".join(parts)


class GPTChineseTokenizer:
    """CPM conventions on top of the unigram core (same interface as
    GPTTokenizer: from_pretrained/encode/decode/vocab_size/__call__)."""

    def __init__(self, model_file: str):
        self.sp = SentencePieceUnigram.from_file(model_file)
        try:  # reference parity (jieba ships in-image); fallback documented
            import jieba

            self._cut = lambda text: jieba.cut(text, cut_all=False)
        except ImportError:
            self._cut = lambda text: [text]

    @classmethod
    def from_pretrained(cls, path: str) -> "GPTChineseTokenizer":
        import os

        if os.path.isdir(path):
            path = os.path.join(path, "sentencepiece.model")
        return cls(path)

    @property
    def vocab_size(self) -> int:
        return len(self.sp.ids)

    @property
    def eos_token_id(self) -> int:
        """End-of-document id (CPM '</s>'/'<eod>'), used by --append-eos."""
        eos = self.sp.eos_id
        if eos is None:
            raise ValueError(
                "this sentencepiece model defines no </s>/<eod> piece; "
                "re-run without --append-eos or add the control piece")
        return eos

    def encode(self, text: str) -> List[int]:
        words = [w.replace(" ", _SPACE).replace("\n", _NEWLINE)
                 for w in self._cut(text)]
        return self.sp.encode(" ".join(words))

    def decode(self, ids) -> str:
        text = self.sp.decode(ids)
        return (text.replace(" ", "").replace(_WORD_SEP, "")
                .replace(_SPACE, " ").replace(_NEWLINE, "\n"))

    def __call__(self, text: str) -> Dict[str, List[int]]:
        return {"input_ids": self.encode(text)}
