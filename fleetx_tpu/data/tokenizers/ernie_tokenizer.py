"""ERNIE/BERT-style WordPiece tokenizer.

Capability parity with the tokenizer the reference's ERNIE preprocessing
drives (/root/reference/ppfleetx/data/data_tools/ernie/preprocess/
create_pretraining_data.py uses paddlenlp's ErnieTokenizer): standard
basic-tokenization (lowercase, punctuation/CJK splitting) + greedy
longest-match WordPiece over a ``vocab.txt``. Pure Python, zero-egress:
``from_pretrained`` reads a local vocab file.
"""

from __future__ import annotations

import os
import unicodedata
from typing import Dict, List, Optional

__all__ = ["ErnieTokenizer"]


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (
        0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
        or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
        or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F
    )


class ErnieTokenizer:
    """WordPiece tokenizer over an ERNIE vocab.txt (reference paddlenlp
    ErnieTokenizer surface)."""
    cls_token = "[CLS]"
    sep_token = "[SEP]"
    mask_token = "[MASK]"
    pad_token = "[PAD]"
    unk_token = "[UNK]"

    def __init__(self, vocab_file: str, do_lower_case: bool = True,
                 max_chars_per_word: int = 100):
        self.vocab: Dict[str, int] = {}
        with open(vocab_file, encoding="utf-8") as f:
            for i, line in enumerate(f):
                tok = line.rstrip("\n")
                if tok:
                    self.vocab.setdefault(tok, i)
        self.inv_vocab = {v: k for k, v in self.vocab.items()}
        self.do_lower_case = do_lower_case
        self.max_chars_per_word = max_chars_per_word
        self.cls_token_id = self.vocab.get(self.cls_token, 1)
        self.sep_token_id = self.vocab.get(self.sep_token, 2)
        self.mask_token_id = self.vocab.get(self.mask_token, 3)
        self.pad_token_id = self.vocab.get(self.pad_token, 0)
        self.unk_token_id = self.vocab.get(self.unk_token, 0)

    @classmethod
    def from_pretrained(cls, path: Optional[str] = None) -> "ErnieTokenizer":
        path = path or os.environ.get("FLEETX_VOCAB_DIR", ".")
        vocab = path if path.endswith(".txt") else os.path.join(path, "vocab.txt")
        return cls(vocab)

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    # -------------------------------------------------------------- basic
    def _basic_tokenize(self, text: str) -> List[str]:
        if self.do_lower_case:
            text = text.lower()
        text = unicodedata.normalize("NFC", text)
        out: List[str] = []
        word: List[str] = []

        def flush():
            if word:
                out.append("".join(word))
                word.clear()

        for ch in text:
            cp = ord(ch)
            if ch.isspace():
                flush()
            elif _is_cjk(cp) or _is_punctuation(ch):
                flush()
                out.append(ch)
            elif unicodedata.category(ch) in ("Mn", "Cf") or cp == 0:
                continue  # strip accents-in-progress / control chars
            else:
                word.append(ch)
        flush()
        return out

    # ---------------------------------------------------------- wordpiece
    def _wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_chars_per_word:
            return [self.unk_token]
        pieces: List[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return [self.unk_token]
            pieces.append(piece)
            start = end
        return pieces

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        for word in self._basic_tokenize(text):
            out.extend(self._wordpiece(word))
        return out

    def convert_tokens_to_ids(self, tokens: List[str]) -> List[int]:
        return [self.vocab.get(t, self.unk_token_id) for t in tokens]

    def encode(self, text: str) -> List[int]:
        return self.convert_tokens_to_ids(self.tokenize(text))

    def decode(self, ids) -> str:
        toks = [self.inv_vocab.get(int(i), self.unk_token) for i in ids]
        text = " ".join(toks).replace(" ##", "")
        return text
