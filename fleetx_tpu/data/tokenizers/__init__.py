"""Tokenizers: GPT byte-level BPE, ERNIE WordPiece (reference data/tokenizers)."""
