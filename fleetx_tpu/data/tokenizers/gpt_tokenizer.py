"""GPT-2 byte-level BPE tokenizer (reference /root/reference/ppfleetx/data/
tokenizers/gpt_tokenizer.py:91 — same algorithm family as every GPT-2
implementation; this one is written against the published BPE scheme).

Loads local ``vocab.json`` + ``merges.txt`` (zero-egress environment: no
download path; pass explicit file paths or set FLEETX_VOCAB_DIR).
"""

from __future__ import annotations

import functools
import json
import os
from typing import Dict, List, Optional, Tuple

__all__ = ["GPTTokenizer"]

try:
    import regex as _re
except ImportError:  # pragma: no cover
    import re as _re

# GPT-2's split pattern: contractions, letter runs, number runs, other, spaces
_PAT = _re.compile(
    r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
    if _re.__name__ == "regex"
    else r"""'s|'t|'re|'ve|'m|'ll|'d| ?[A-Za-z]+| ?[0-9]+| ?[^\sA-Za-z0-9]+|\s+(?!\S)|\s+"""
)


@functools.lru_cache(None)
def _bytes_to_unicode() -> Dict[int, str]:
    """GPT-2's reversible byte→printable-unicode map."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


class GPTTokenizer:
    """Byte-level BPE tokenizer (GPT-2 vocab/merges files, reference
    gpt_tokenizer.py:91)."""
    eos_token = "<|endoftext|>"

    def __init__(self, vocab_file: str, merges_file: str, errors: str = "replace"):
        with open(vocab_file, encoding="utf-8") as f:
            self.encoder: Dict[str, int] = json.load(f)
        self.decoder = {v: k for k, v in self.encoder.items()}
        with open(merges_file, encoding="utf-8") as f:
            lines = f.read().split("\n")
        merges = [tuple(l.split()) for l in lines if l and not l.startswith("#version")]
        self.bpe_ranks = dict(zip(merges, range(len(merges))))
        self.byte_encoder = _bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self.errors = errors
        self.cache: Dict[str, str] = {}
        self.eos_token_id = self.encoder.get(self.eos_token, len(self.encoder) - 1)
        self.eod_token_id = self.eos_token_id  # Megatron naming
        self.pad_token_id = self.eos_token_id

    @classmethod
    def from_pretrained(cls, path: Optional[str] = None) -> "GPTTokenizer":
        path = path or os.environ.get("FLEETX_VOCAB_DIR", ".")
        return cls(
            os.path.join(path, "vocab.json"), os.path.join(path, "merges.txt")
        )

    @property
    def vocab_size(self) -> int:
        return len(self.encoder)

    def _bpe(self, token: str) -> str:
        if token in self.cache:
            return self.cache[token]
        word: Tuple[str, ...] = tuple(token)
        if len(word) == 1:
            return token
        while True:
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
            best = min(pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if best not in self.bpe_ranks:
                break
            a, b = best
            merged: List[str] = []
            i = 0
            while i < len(word):
                if i < len(word) - 1 and word[i] == a and word[i + 1] == b:
                    merged.append(a + b)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = tuple(merged)
            if len(word) == 1:
                break
        out = " ".join(word)
        self.cache[token] = out
        return out

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        for token in _PAT.findall(text):
            token = "".join(self.byte_encoder[b] for b in token.encode("utf-8"))
            ids.extend(self.encoder[t] for t in self._bpe(token).split(" "))
        return ids

    def decode(self, ids) -> str:
        # ids outside the vocab (e.g. a model whose padded vocab_size exceeds
        # len(vocab.json)) decode to nothing rather than crash serving
        text = "".join(self.decoder.get(int(i), "") for i in ids)
        return bytearray(self.byte_decoder[c] for c in text).decode(
            "utf-8", errors=self.errors
        )

    def __call__(self, text: str) -> Dict[str, List[int]]:
        return {"input_ids": self.encode(text)}
