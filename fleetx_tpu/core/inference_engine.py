"""Inference engine over an export artifact (reference
/root/reference/ppfleetx/core/engine/inference_engine.py:104-243:
paddle.inference predictor per rank + NCCL comm CSV + TensorRT config).

TPU-native: rebuild the flax module from the exported config, restore
params, AOT-compile the forward (and the generation loop when a
``Generation`` section was exported) with jax.jit over an optional mesh —
GSPMD replaces the reference's per-rank model dirs + comm-init CSV, and XLA
is the optimizing backend where the reference plugs TensorRT.

``FLEETX_SERVING_WEIGHT_DTYPE=int8`` serves this artifact weight-only-PTQ
(docs/QUANTIZATION.md): params are quantized once at load
(``ops/quant.quantize_tree_int8``, idempotent for quant-exported
artifacts) and live in HBM as int8 + per-channel scales; ``predict()``
dequantizes INSIDE its jit so XLA fuses the scale multiply into each
matmul consumer, and the continuous-batching delegate engine reads the
same env var and shares the same seam."""

from __future__ import annotations

from typing import Dict

import jax
import numpy as np

from fleetx_tpu.utils.export import load_exported
from fleetx_tpu.utils.log import logger

__all__ = ["InferenceEngine"]


class InferenceEngine:
    """Serves an export artifact: rebuilds the module, restores params,
    jit-compiles forward/generate (see module docstring)."""
    def __init__(self, export_dir: str, mesh=None):
        self.cfg, self.params, self.input_spec = load_exported(export_dir)
        model_cfg = self.cfg.get("Model") or {}
        module_name = model_cfg.get("module", "GPTModule")

        from fleetx_tpu.models import build_module
        from fleetx_tpu.utils.config import AttrDict

        cfg = AttrDict()
        for k, v in self.cfg.items():
            cfg[k] = AttrDict(v) if isinstance(v, dict) else v
        # inference always runs deterministic
        cfg.Model = AttrDict(model_cfg)
        cfg.Model.hidden_dropout_prob = 0.0
        cfg.Model.attention_probs_dropout_prob = 0.0
        self.module = build_module(cfg)
        self.mesh = mesh
        self._forward = None
        self._serving = None
        self._gen_calls = 0  # folded into sampling keys: repeat calls differ
        gen = self.cfg.get("Generation") or {}
        self.eos_token_id = int(gen.get("eos_token_id") or 50256)
        from fleetx_tpu.ops.quant import (
            resolve_serving_dtype,
            serving_weight_params,
        )

        # weight-only PTQ at load (no-op at bf16): HBM holds int8 +
        # scales from here on; consumers dequantize at their jit boundary
        # (module docstring)
        self.weight_dtype = resolve_serving_dtype(
            None, "FLEETX_SERVING_WEIGHT_DTYPE")
        self.params = serving_weight_params(self.params, self.weight_dtype)
        logger.info("inference engine: %s from %s", module_name, export_dir)

    def _float_params(self):
        """Float view of the served params for non-jitted consumers (the
        one-shot generate loop); a no-op at bf16. Dequantizes to the
        module's compute dtype — not fp32 — so the temporary tree is no
        larger than the unquantized original."""
        if self.weight_dtype != "int8":
            return self.params
        from fleetx_tpu.ops.quant import dequantize_tree_int8

        return dequantize_tree_int8(self.params,
                                    dtype=self.module.nets.cfg.dtype)

    def _compile(self):
        if self._forward is not None:
            return self._forward
        from fleetx_tpu.utils.export import serving_contract

        fwd, _ = serving_contract(self.module, self.input_spec)
        if fwd is None:
            raise ValueError(
                "export has no default serving contract; use the module API "
                "directly (predict() supports token-contract exports only)"
            )
        if self.weight_dtype == "int8":
            # dequant INSIDE the jit: the scale multiply fuses into each
            # matmul consumer, HBM keeps the int8 tree
            from fleetx_tpu.ops.quant import dequantize_tree_int8

            base_fwd = fwd

            def fwd(params, batch):
                return base_fwd(dequantize_tree_int8(params), batch)

        if self.mesh is not None:
            # replicated params + dp-sharded batch over the provided mesh;
            # activation constraints inside the model resolve via the rules
            from flax import linen as nn

            from fleetx_tpu.parallel.mesh import use_mesh
            from fleetx_tpu.parallel.sharding import make_rules

            mesh, rules = self.mesh, make_rules()
            jitted = jax.jit(fwd)  # one jit: retains its compile cache

            def sharded(params, batch):
                with use_mesh(mesh), nn.logical_axis_rules(rules):
                    return jitted(params, batch)

            self._forward = sharded
        else:
            self._forward = jax.jit(fwd)
        return self._forward

    def predict(self, batch: Dict[str, np.ndarray]):
        """Raw forward logits for a token batch (pass seq_lens for padded
        classification batches — the export's input_spec says if needed)."""
        fn = self._compile()
        # the export's input_spec holds exactly the served keys
        required = list(self.input_spec)
        missing = [k for k in required if k not in batch]
        if missing:
            raise ValueError(f"batch missing {missing} (export input_spec)")
        feed = {k: np.asarray(batch[k]) for k in required}
        # multi-output contracts (e.g. ERNIE's (mlm, sop)) stay pytrees
        return jax.tree.map(np.asarray, fn(self.params, feed))

    def generate(self, input_ids: np.ndarray, **overrides):
        """Sampling/greedy decode via the exported Generation config
        (requires the module to be a GPTGenerationModule export).

        Servable requests (greedy/sampling, no repetition penalty / forced
        EOS) delegate to the continuous-batching
        :class:`~fleetx_tpu.serving.ServingEngine` — same [b, prompt+max]
        token buffer, but rows retire independently and the engine is
        shared with any concurrent ``serving_engine()`` traffic pattern;
        ``FLEETX_SERVING_DELEGATE=0`` forces the legacy one-shot loop.
        A ``mesh`` rides into the delegate engine (mesh-native serving,
        docs/SERVING.md "Mesh-sharded serving") when delegating wins —
        an (fsdp, mp) mesh with the heads dividing over mp; dp>1 meshes
        (whose batch the one-shot path genuinely shards), pp/cp meshes,
        beam search, and penalty requests run one-shot, sharded over
        ``self.mesh`` exactly like ``predict()``.

        Each call folds a call counter into the sampling key, so repeated
        sampling requests draw fresh tokens; pass an explicit ``seed``
        override to pin a reproducible stream instead."""
        import os

        from fleetx_tpu.models.gpt.generation import GenerationConfig, generate

        gen_cfg = dict(self.cfg.get("Generation") or {})
        if "max_length" in overrides:
            gen_cfg.pop("max_dec_len", None)  # explicit override wins
        gen_cfg.update(overrides)
        gcfg = GenerationConfig.from_config(gen_cfg)
        base = jax.random.PRNGKey(int(gen_cfg.get("seed") or 0))
        # an explicit per-call seed means "give me this exact stream";
        # otherwise each call advances (the seed-reuse fix). seed=None is
        # NOT a pin — forwarded optionals must still advance.
        rng = (base if overrides.get("seed") is not None
               else jax.random.fold_in(base, self._gen_calls))
        self._gen_calls += 1
        ids = np.asarray(input_ids)
        # the serving cache must FIT the request — a too-small
        # FLEETX_SERVING_CACHE_LEN must fall back to the one-shot loop,
        # never silently truncate the delegated output
        max_pos = self.module.nets.cfg.max_position_embeddings
        serving_cap = min(
            int(os.environ.get("FLEETX_SERVING_CACHE_LEN", 0) or max_pos),
            max_pos)
        if (os.environ.get("FLEETX_SERVING_DELEGATE", "1") != "0"
                and self._servable(gcfg)
                and self._serving_mesh_ok()
                and ids.shape[-1] + gcfg.max_length <= serving_cap):
            return self._serving_engine(gcfg).generate_batch(
                ids, gcfg, rng=rng)
        run = lambda: generate(  # noqa: E731
            self.module.nets,
            {"params": self._float_params()},
            np.asarray(input_ids),
            gcfg,
            rng=rng,
        )
        if self.mesh is not None:
            # same contract as predict(): replicated params, dp-sharded
            # batch, logical-axis rules resolving the model's constraints
            from flax import linen as nn

            from fleetx_tpu.parallel.mesh import use_mesh
            from fleetx_tpu.parallel.sharding import make_rules

            with use_mesh(self.mesh), nn.logical_axis_rules(make_rules()):
                return run()
        return run()

    @staticmethod
    def _servable(gcfg) -> bool:
        """True when the continuous-batching engine covers this request
        shape (see ServingEngine docstring for the exclusions)."""
        return (gcfg.decode_strategy in ("greedy", "sampling")
                and gcfg.repetition_penalty == 1.0
                and gcfg.forced_eos_token_id is None
                and gcfg.num_return_sequences == 1)

    def _serving_mesh_ok(self) -> bool:
        """True when delegating ``self.mesh`` to the mesh-native
        ServingEngine is both covered AND a win: none at all, or an
        (fsdp, mp) mesh whose mp extent divides the attention heads (the
        engine's cache-sharding contract). pp/cp meshes and non-dividing
        heads would raise at engine construction; a dp>1 mesh is covered
        but a LOSS — the serving tick replicates over dp while the
        one-shot path genuinely batch-shards it — so both keep the
        one-shot path."""
        if self.mesh is None:
            return True
        shape = dict(self.mesh.shape)
        cfg = getattr(getattr(self.module, "nets", None), "cfg", None)
        heads = getattr(cfg, "num_attention_heads", None)
        return (shape.get("pp", 1) == 1 and shape.get("cp", 1) == 1
                and shape.get("dp", 1) == 1
                and heads is not None
                and heads % shape.get("mp", 1) == 0)

    def _serving_engine(self, gcfg):
        # built with the first servable call's config (engine-level
        # defaults only — generate_batch passes per-call configs anyway)
        if self._serving is None:
            self._serving = self.serving_engine(gen_cfg=gcfg)
        return self._serving

    def serving_engine(self, **kwargs):
        """Build a continuous-batching :class:`ServingEngine` over this
        artifact's module + params (kwargs forward: slots, cache_len,
        gen_cfg, ...). ``self.mesh`` rides along by default (the engine
        shards params + kv caches over it — docs/SERVING.md
        "Mesh-sharded serving"); pass ``mesh=None`` to opt a meshed
        InferenceEngine's serving side out. The engine handed back owns
        its own cache; call it directly for submit/step/drain streaming
        serving."""
        from fleetx_tpu.models.gpt.generation import GenerationConfig
        from fleetx_tpu.serving import ServingEngine

        if "gen_cfg" not in kwargs:
            kwargs["gen_cfg"] = GenerationConfig.from_config(
                dict(self.cfg.get("Generation") or {}))
        kwargs.setdefault("mesh", self.mesh)
        return ServingEngine(self.module.nets, {"params": self.params},
                             **kwargs)
