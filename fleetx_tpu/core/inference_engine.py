"""Inference engine over an export artifact (reference
/root/reference/ppfleetx/core/engine/inference_engine.py:104-243:
paddle.inference predictor per rank + NCCL comm CSV + TensorRT config).

TPU-native: rebuild the flax module from the exported config, restore
params, AOT-compile the forward (and the generation loop when a
``Generation`` section was exported) with jax.jit over an optional mesh —
GSPMD replaces the reference's per-rank model dirs + comm-init CSV, and XLA
is the optimizing backend where the reference plugs TensorRT."""

from __future__ import annotations

from typing import Dict

import jax
import numpy as np

from fleetx_tpu.utils.export import load_exported
from fleetx_tpu.utils.log import logger

__all__ = ["InferenceEngine"]


class InferenceEngine:
    """Serves an export artifact: rebuilds the module, restores params,
    jit-compiles forward/generate (see module docstring)."""
    def __init__(self, export_dir: str, mesh=None):
        self.cfg, self.params, self.input_spec = load_exported(export_dir)
        model_cfg = self.cfg.get("Model") or {}
        module_name = model_cfg.get("module", "GPTModule")

        from fleetx_tpu.models import build_module
        from fleetx_tpu.utils.config import AttrDict

        cfg = AttrDict()
        for k, v in self.cfg.items():
            cfg[k] = AttrDict(v) if isinstance(v, dict) else v
        # inference always runs deterministic
        cfg.Model = AttrDict(model_cfg)
        cfg.Model.hidden_dropout_prob = 0.0
        cfg.Model.attention_probs_dropout_prob = 0.0
        self.module = build_module(cfg)
        self.mesh = mesh
        self._forward = None
        gen = self.cfg.get("Generation") or {}
        self.eos_token_id = int(gen.get("eos_token_id") or 50256)
        logger.info("inference engine: %s from %s", module_name, export_dir)

    def _compile(self):
        if self._forward is not None:
            return self._forward
        from fleetx_tpu.utils.export import serving_contract

        fwd, _ = serving_contract(self.module, self.input_spec)
        if fwd is None:
            raise ValueError(
                "export has no default serving contract; use the module API "
                "directly (predict() supports token-contract exports only)"
            )
        if self.mesh is not None:
            # replicated params + dp-sharded batch over the provided mesh;
            # activation constraints inside the model resolve via the rules
            from flax import linen as nn

            from fleetx_tpu.parallel.mesh import use_mesh
            from fleetx_tpu.parallel.sharding import make_rules

            mesh, rules = self.mesh, make_rules()
            jitted = jax.jit(fwd)  # one jit: retains its compile cache

            def sharded(params, batch):
                with use_mesh(mesh), nn.logical_axis_rules(rules):
                    return jitted(params, batch)

            self._forward = sharded
        else:
            self._forward = jax.jit(fwd)
        return self._forward

    def predict(self, batch: Dict[str, np.ndarray]):
        """Raw forward logits for a token batch (pass seq_lens for padded
        classification batches — the export's input_spec says if needed)."""
        fn = self._compile()
        # the export's input_spec holds exactly the served keys
        required = list(self.input_spec)
        missing = [k for k in required if k not in batch]
        if missing:
            raise ValueError(f"batch missing {missing} (export input_spec)")
        feed = {k: np.asarray(batch[k]) for k in required}
        # multi-output contracts (e.g. ERNIE's (mlm, sop)) stay pytrees
        return jax.tree.map(np.asarray, fn(self.params, feed))

    def generate(self, input_ids: np.ndarray, **overrides):
        """Sampling/greedy decode via the exported Generation config
        (requires the module to be a GPTGenerationModule export)."""
        from fleetx_tpu.models.gpt.generation import GenerationConfig, generate

        gen_cfg = dict(self.cfg.get("Generation") or {})
        if "max_length" in overrides:
            gen_cfg.pop("max_dec_len", None)  # explicit override wins
        gen_cfg.update(overrides)
        gcfg = GenerationConfig.from_config(gen_cfg)
        return generate(
            self.module.nets,
            {"params": self.params},
            np.asarray(input_ids),
            gcfg,
            rng=jax.random.PRNGKey(int(gen_cfg.get("seed") or 0)),
        )
