"""Core runtime: Trainer engine, InferenceEngine (reference ppfleetx/core)."""
