"""Trainer — the TPU-native engine (reference EagerEngine,
/root/reference/ppfleetx/core/engine/eager_engine.py:41-820).

Where the reference wraps models in fleet.distributed_model and hand-drives
micro-batch loops, AMP scalers, and sharding wrappers, this engine compiles
ONE jitted train step: grad accumulation is a `lax.scan` inside it, parameter/
optimizer sharding is declared via NamedShardings derived from logical-axis
rules (ZeRO stage 1/2 = fsdp-sharded optimizer state, stage 3 = fsdp-sharded
params too), and every collective is inserted by GSPMD. Pipeline-parallel
configs route the forward through the stage axis (fleetx_tpu/parallel/
pipeline.py). Checkpointing is Orbax (async-capable, preemption-safe) with
step/epoch/consumed-samples resume parity (eager_engine.py:634-725).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from fleetx_tpu.models.module import BasicModule
from fleetx_tpu.obs import http as obs_http
from fleetx_tpu.obs.events import emit as obs_emit
from fleetx_tpu.obs.registry import get_registry
from fleetx_tpu.obs.tracing import span
from fleetx_tpu.optims.lr_scheduler import build_lr_scheduler
from fleetx_tpu.optims.optimizer import build_optimizer
from fleetx_tpu.parallel import env as dist_env
from fleetx_tpu.parallel.mesh import DATA_AXES, MeshConfig, build_mesh, use_mesh
from fleetx_tpu.parallel.sharding import (
    make_rules, param_shardings, zero_update_spec,
)
from fleetx_tpu.resilience.elastic import ElasticMeshMismatch, validate_restore_mesh
from fleetx_tpu.resilience.faults import faults
from fleetx_tpu.utils.hw import peak_flops_per_chip
from fleetx_tpu.utils.log import logger
from fleetx_tpu.utils.xla_flags import apply_overlap_flags

__all__ = ["CheckpointUnrestorable", "SentryAbort", "Trainer", "TrainState"]


class CheckpointUnrestorable(RuntimeError):
    """Checkpoints existed but every candidate failed verified restore
    (all quarantined). Distinct from the no-checkpoint-yet case — which
    ``load()`` reports as ``False`` so a first launch can start fresh —
    because resuming a real run from scratch must fail loudly."""


class SentryAbort(RuntimeError):
    """FLEETX_SENTRY_MAX_SKIPS consecutive train steps were skipped by the
    step sentry — the data stream (or the optimization itself) is
    producing nothing but anomalies, so the run stops cleanly instead of
    spinning. Params/opt_state are still the last healthy step's (skipped
    steps never touch them) and a checkpoint is written before raising."""


class TrainState(struct.PyTreeNode):
    """step + params + optimizer state (+ module extra state), the pytree
    threaded through the jitted train step."""
    step: jax.Array
    params: Any
    opt_state: Any
    # module-owned non-parameter training state (e.g. MoCo's momentum
    # encoder + negative queue); None for ordinary modules
    extra: Any = None


def make_grad_fn(module: "BasicModule", accum: int):
    """(params, batch, rng) -> (mean loss, mean grads).

    With accum > 1 the batch's leading axis is [accum, micro, ...] and a
    lax.scan accumulates microbatch grads — the in-jit replacement for the
    reference's host-side micro-batch loop (eager_engine.py:442-483)."""

    def loss_for_micro(params, micro, rng):
        # central QAT hooks: STE weight fake-quant INSIDE the grad
        # computation, and (when configured) activation fake-quant on every
        # Dense input via the module's interceptor context — so every module
        # family quantizes identically (no per-module wiring)
        with module.act_quant_ctx():
            loss, metrics = module.loss_fn(
                module.maybe_fake_quant(params), micro, rng, train=True
            )
        return loss, metrics

    grad_fn = jax.value_and_grad(loss_for_micro, has_aux=True)

    def compute(params, batch, rng):
        if accum == 1:
            (loss, _), grads = grad_fn(params, batch, rng)
            return loss, grads

        def micro_step(carry, micro):
            acc_grads, acc_loss, i = carry
            mrng = jax.random.fold_in(rng, i)
            (loss, _), grads = grad_fn(params, micro, mrng)
            acc_grads = jax.tree.map(jnp.add, acc_grads, grads)
            return (acc_grads, acc_loss + loss, i + 1), None

        zero = _rebox_like(
            jax.tree.map(lambda p: jnp.zeros(p.shape, p.dtype), _unbox(params)),
            params,
        )
        (grads, loss_sum, _), _ = jax.lax.scan(micro_step, (zero, 0.0, 0), batch)
        grads = jax.tree.map(lambda g: g / accum, grads)
        return loss_sum / accum, grads

    return compute


def make_grad_fn_extra(module: "BasicModule", accum: int):
    """(params, extra, batch, rng) -> (loss, grads, aux, new_extra) for
    modules carrying extra train state (MoCo momentum encoder/queue).
    Extra state updates are inherently sequential, so microbatch grad
    accumulation is not supported on this path."""
    if accum != 1:
        raise NotImplementedError(
            "accumulate_steps > 1 is not supported for modules with extra "
            "state (the queue/EMA update order would be ambiguous)"
        )

    def loss_for(params, extra, batch, rng):
        with module.act_quant_ctx():
            loss, aux, new_extra = module.loss_fn_extra(
                module.maybe_fake_quant(params), extra, batch, rng, train=True
            )
        return loss, (aux, new_extra)

    grad_fn = jax.value_and_grad(loss_for, has_aux=True)

    def compute(params, extra, batch, rng):
        (loss, (aux, new_extra)), grads = grad_fn(params, extra, batch, rng)
        return loss, grads, aux, new_extra

    return compute


from flax.core import meta as flax_meta


def _is_box(x):
    return isinstance(x, flax_meta.AxisMetadata)


def _unbox(tree):
    """Strip flax axis-metadata boxes (Partitioned / LogicallyPartitioned),
    keeping raw arrays."""
    return jax.tree.map(
        lambda x: x.unbox() if _is_box(x) else x, tree, is_leaf=_is_box
    )


def _rebox_like(raw_tree, boxed_tree):
    """Re-wrap raw arrays with the metadata boxes of a reference tree."""
    # prefix-tree map: raw leaves pair with the boxed tree's metadata nodes
    return jax.tree.map(
        lambda new, old: old.replace_boxed(new) if _is_box(old) else new,
        raw_tree,
        boxed_tree,
    )


class Trainer:
    """The engine: builds mesh/shardings/optimizer, compiles the
    train/eval/predict steps, owns fit/evaluate/save/load (see module
    docstring)."""
    def __init__(self, cfg, module: BasicModule, mode: str = "train"):
        self.cfg = cfg
        self.module = module
        self.mode = mode

        eng = cfg.Engine
        glb = cfg.Global
        self.max_steps = eng.max_steps
        self.num_train_epochs = eng.num_train_epochs
        self.accumulate_steps = eng.accumulate_steps or 1
        dist_pp = ((cfg.Distributed or {}).get("pp_degree")) or 1
        if dist_pp > 1:
            # the pipelined model consumes the full local batch and streams
            # microbatches itself; no outer accumulation scan
            self.accumulate_steps = 1
        self.logging_freq = eng.logging_freq
        self.eval_freq = eng.eval_freq
        self.eval_iters = eng.eval_iters
        self.save_steps = (eng.save_load or {}).get("save_steps", 1000)
        self.output_dir = (eng.save_load or {}).get("output_dir", "./output")

        dist = cfg.Distributed or {}
        self.mesh_cfg = MeshConfig.from_dist_config(dist)
        # comms/compute overlap flags must land in XLA_FLAGS before the
        # backend initializes (build_mesh below touches devices); env-gated
        # and TPU-only by default — see utils/xla_flags.py
        apply_overlap_flags()
        self.mesh = build_mesh(self.mesh_cfg)
        # ZeRO weight-update sharding (docs/PERFORMANCE.md "Training
        # overlap", arxiv 2004.13336): reduce-scatter grads over the
        # data-parallel axes, run optax + apply_updates + the sentry gnorm
        # on the 1/N shard, all-gather updated params. On by default
        # whenever a data-parallel axis exists; the optimizer state then
        # LIVES sharded between steps (out_shardings), cutting its HBM by
        # the dp*fsdp factor even at sharding stage 1/2.
        self._zero_update = (
            os.environ.get("FLEETX_ZERO_UPDATE", "1") == "1"
            and self.mesh_cfg.dp * self.mesh_cfg.fsdp > 1
        )
        self._zero_param_shardings = None
        from fleetx_tpu.parallel.dap import dap_rules

        self.rules = make_rules(
            sharding_stage=self.mesh_cfg.sharding_stage,
            sequence_parallel=bool((cfg.Model or {}).get("sequence_parallel")),
            context_parallel=self.mesh_cfg.cp > 1,
        ) + dap_rules()  # folding-trunk axial layout rides the cp axis

        self.root_key = dist_env.set_seed(glb.seed)
        self.lr_schedule = build_lr_scheduler((cfg.Optimizer or {}).get("lr", 1e-4))
        self.tx = build_optimizer(
            cfg.Optimizer or {}, self.lr_schedule,
            weight_decay_mask=module.weight_decay_mask(),
        )

        self._compiled = {}
        self._compiled_raw = {}
        self._abstract_args = {}  # name -> (args, kwargs) avals of first call
        self._restored_step = None
        self._preempted = False
        self._prev_sigterm = None
        self.state: Optional[TrainState] = None
        self.start_epoch = 0
        self._cur_epoch = 0  # epoch the fit loop is currently inside
        self.consumed_samples = 0
        self._ckpt_mgr = None
        # step-shadow snapshot checkpointing (FLEETX_CKPT_ASYNC_SNAPSHOT):
        # save() copies state device->host in the step path and hands the
        # host tree to a background uploader thread, so the step only stalls
        # for the D2H copy. Single-process only: multi-host orbax saves are
        # collective, and a per-host thread would skew the barrier.
        self._ckpt_async = (
            os.environ.get("FLEETX_CKPT_ASYNC_SNAPSHOT", "0") == "1"
            and jax.process_count() == 1)
        self._upload_thread = None  # in-flight snapshot uploader

        # step sentry (docs/RESILIENCE.md): finite/spike check folded into
        # the jitted train step; anomalous steps are skipped, not applied.
        # All thresholds are static at trace time (env read here, once).
        self._sentry_enabled = os.environ.get("FLEETX_SENTRY", "1") == "1"
        self._sentry_loss_max = float(os.environ.get("FLEETX_SENTRY_LOSS_MAX", 0) or 0)
        self._sentry_gnorm_max = float(os.environ.get("FLEETX_SENTRY_GNORM_MAX", 0) or 0)
        self._sentry_max_skips = int(os.environ.get("FLEETX_SENTRY_MAX_SKIPS", 25) or 25)
        self.sentry_skips = 0  # total skipped steps this run
        self._sentry_consecutive = 0
        self.save_failures = 0  # periodic saves that failed (run survived)
        self._last_saved_meta = None  # (step, epoch, consumed_samples)

        # observability (docs/OBSERVABILITY.md): live training gauges on
        # the process registry (FLEETX_OBS_PORT exposes them). Gauges are
        # process-wide last-writer-wins — one Trainer per process is the
        # production shape; counters accumulate across Trainer instances
        # (per-run numbers stay on self.sentry_skips/self.save_failures).
        obs_http.maybe_start_from_env()
        reg = get_registry()
        self._obs_steps = reg.counter(
            "fleetx_train_steps_total", "Optimizer steps applied")
        self._obs_sentry_skips = reg.counter(
            "fleetx_train_sentry_skips_total",
            "Train steps skipped by the anomaly sentry")
        self._obs_save_failures = reg.counter(
            "fleetx_train_save_failures_total",
            "Checkpoint saves that failed (run survived)")
        self._obs_quarantines = reg.counter(
            "fleetx_train_checkpoint_quarantines_total",
            "Corrupt checkpoint steps quarantined during restore")
        self._obs_loss = reg.gauge(
            "fleetx_train_loss", "Loss averaged over the last logging window")
        self._obs_lr = reg.gauge(
            "fleetx_train_learning_rate", "Current learning rate")
        self._obs_step_time = reg.histogram(
            "fleetx_train_step_seconds",
            "Per-step wall clock (logging-window mean samples)")
        self._obs_tokens_per_s = reg.gauge(
            "fleetx_train_tokens_per_second",
            "Training throughput over the last logging window")
        self._obs_mfu = reg.gauge(
            "fleetx_train_mfu",
            "Model-FLOPs utilization: cost_analysis flops / step time / "
            "peak chip FLOPs")
        self._obs_hbm_bytes = reg.gauge(
            "fleetx_train_step_hbm_bytes",
            "Compiled train step per-device HBM bytes accessed "
            "(cost_analysis static estimate)")
        self._obs_opt_bytes = reg.gauge(
            "fleetx_train_opt_state_bytes",
            "Optimizer-state bytes resident per device (ZeRO update "
            "sharding shrinks this by the dp*fsdp factor)")
        self._obs_ckpt_seconds = reg.histogram(
            "fleetx_ckpt_save_seconds",
            "Checkpoint save duration; phase=blocking is the step-path "
            "stall (D2H snapshot under FLEETX_CKPT_ASYNC_SNAPSHOT, the "
            "whole write otherwise), phase=total includes the async upload",
            labelnames=("phase",))
        self._obs_ckpt_bytes = reg.gauge(
            "fleetx_ckpt_bytes",
            "Bytes of train state in the last checkpoint snapshot")
        # expose every instrument at zero immediately (matching the
        # serving metrics, whose children exist from __init__): a healthy
        # run must scrape as 0, not as absent-looking-like-broken
        for fam in (self._obs_steps, self._obs_sentry_skips,
                    self._obs_save_failures, self._obs_quarantines,
                    self._obs_loss, self._obs_lr, self._obs_step_time,
                    self._obs_tokens_per_s, self._obs_mfu,
                    self._obs_hbm_bytes, self._obs_opt_bytes,
                    self._obs_ckpt_bytes):
            fam.labels()
        for phase in ("blocking", "total"):
            self._obs_ckpt_seconds.labels(phase=phase)
        self._flops_per_step = None  # lazy; False = cost analysis failed
        self._hbm_bytes_per_step = None  # same contract as _flops_per_step
        self._cost_cache = {}  # name -> (abstract-args spec, cost dict)

    # ------------------------------------------------------------------ init
    def init_state(self, sample_batch: Dict[str, np.ndarray]) -> TrainState:
        """Create sharded params + optimizer state directly on the mesh
        (never materializing an unsharded copy on one device)."""
        micro = self._microbatch(sample_batch)

        def _init(rng):
            variables = self.module.init_params(rng, micro)
            params = variables["params"] if "params" in variables else variables
            opt_state = self.tx.init(_unbox(params))
            extra = self.module.init_extra_state(_unbox(params), micro)
            return TrainState(
                step=jnp.zeros((), jnp.int32), params=params,
                opt_state=opt_state, extra=extra,
            )

        import flax.linen as nn

        with use_mesh(self.mesh), nn.logical_axis_rules(list(self.rules)):
            abstract = jax.eval_shape(_init, self.root_key)
        shardings = self._state_shardings(abstract)
        with use_mesh(self.mesh), nn.logical_axis_rules(list(self.rules)):
            init_fn = jax.jit(_init, out_shardings=shardings)
            self.state = init_fn(self.root_key)
        self._state_sharding_tree = shardings
        n_params = sum(
            int(np.prod(x.shape)) for x in jax.tree.leaves(_unbox(self.state.params))
        )
        logger.info(
            "initialized model: %.1fM params on mesh %s",
            n_params / 1e6,
            dict(self.mesh.shape),
        )
        self.n_params = n_params
        self._obs_opt_bytes.set(float(self.opt_state_device_bytes()))
        resumable = False
        if os.path.isdir(os.path.join(self.output_dir, "checkpoints")):
            resumable = self._ckpt_manager().latest_step() is not None
        if resumable:
            # restore the run's own checkpoint right here (don't just skip
            # the pretrained load: callers only invoke load() when ckpt_dir
            # is set, and a preempted run must not resume from random init).
            # If every checkpoint fails verified restore, load() raises
            # CheckpointUnrestorable (resuming from scratch must be loud);
            # the False branch only covers a checkpoint dir that emptied
            # between the latest_step() probe and the restore.
            loaded = None
            if not self.load():
                loaded = self.module.load_pretrained(_unbox(self.state.params))
        else:
            loaded = self.module.load_pretrained(_unbox(self.state.params))
        if loaded is not None:
            boxed = _rebox_like(loaded, self.state.params)
            boxed = jax.device_put(boxed, self._state_sharding_tree.params)
            self.state = self.state.replace(params=boxed)
        return self.state

    @staticmethod
    def _path_keys(path) -> tuple:
        """Normalize a jax key path to a tuple of strings."""
        out = []
        for k in path:
            for attr in ("key", "idx", "name"):
                if hasattr(k, attr):
                    out.append(str(getattr(k, attr)))
                    break
            else:
                out.append(str(k))
        return tuple(out)

    def _state_shardings(self, abstract: TrainState):
        ps = param_shardings(abstract.params, self.mesh, self.rules)

        if self._zero_update:
            # weight-update shard layout of every param: the in-jit
            # sharding constraints of the train step and (below) the
            # resident layout of the optimizer state
            flat_unboxed, treedef = jax.tree_util.tree_flatten(
                _unbox(abstract.params))
            zero_flat = [
                NamedSharding(
                    self.mesh,
                    zero_update_spec(sh.spec, leaf.shape, self.mesh))
                for leaf, sh in zip(flat_unboxed, jax.tree.leaves(ps))
            ]
            self._zero_param_shardings = jax.tree_util.tree_unflatten(
                treedef, zero_flat)

        # Index param specs by their *tree path*, and match optimizer-state
        # leaves by path suffix: optax moment trees (mu/nu, ...) mirror the
        # param tree under transform-specific prefixes, so the param path is
        # always a suffix of the moment path. Matching by path (not by
        # (shape, dtype)) keeps two same-shaped params with different
        # shardings from colliding.
        flat_params = jax.tree_util.tree_flatten_with_path(_unbox(abstract.params))[0]
        flat_specs = [s.spec for s in jax.tree.leaves(ps)]
        spec_by_path = {}
        for (path, leaf), spec in zip(flat_params, flat_specs):
            spec_by_path[self._path_keys(path)] = (leaf.shape, spec)

        # `sharding_offload` (reference sharding.py CPU offload) = optimizer
        # moments live in host memory; XLA streams them across PCIe at the
        # update. Only TPU backends lower the placement annotation.
        offload = bool(getattr(self.mesh_cfg, "sharding_offload", False))
        if offload and jax.default_backend() not in ("tpu", "axon"):
            raise NotImplementedError(
                "Distributed.sharding.sharding_offload=True needs a TPU "
                "backend (host memory placement is not lowered on "
                f"{jax.default_backend()!r})"
            )
        def shard_like_param(path, leaf, kind):
            """Moment tensors mirror the matching param sharding; ZeRO-1/2
            additionally shards moments over fsdp (stage 3 already shards the
            params themselves). Scalars and unmatched leaves replicate."""
            if not hasattr(leaf, "shape") or leaf.ndim == 0:
                return NamedSharding(self.mesh, P(), **kind)
            keys = self._path_keys(path)
            spec = None
            for start in range(len(keys)):
                hit = spec_by_path.get(keys[start:])
                if hit is not None and hit[0] == leaf.shape:
                    spec = hit[1]
                    break
            if spec is None:
                return NamedSharding(self.mesh, P(), **kind)
            if self._zero_update:
                # moments live on the weight-update shard (dp AND fsdp
                # folded in) — strictly more sharded than the stage-1/2
                # fsdp-only layout below
                spec = zero_update_spec(spec, leaf.shape, self.mesh)
            elif self.mesh_cfg.sharding_stage in (1, 2) and self.mesh_cfg.fsdp > 1:
                spec = self._add_fsdp(spec, leaf.shape)
            return NamedSharding(self.mesh, spec, **kind)

        opt_kind = {"memory_kind": "pinned_host"} if offload else {}
        opt_sh = jax.tree_util.tree_map_with_path(
            lambda p, l: shard_like_param(p, l, opt_kind), abstract.opt_state
        )
        # extra state (momentum encoders, queues): same path-matching rule —
        # param-shaped leaves under a mirrored path get the param sharding,
        # everything else replicates. Always on device: extra state feeds the
        # forward pass, so host offload would stall every step.
        extra_sh = (
            None if abstract.extra is None
            else jax.tree_util.tree_map_with_path(
                lambda p, l: shard_like_param(p, l, {}), abstract.extra
            )
        )
        return TrainState(
            step=NamedSharding(self.mesh, P()), params=ps, opt_state=opt_sh,
            extra=extra_sh,
        )

    def _add_fsdp(self, spec: P, shape) -> P:
        if any("fsdp" in (ax if isinstance(ax, tuple) else (ax,)) for ax in spec if ax):
            return spec
        fsdp = self.mesh.shape["fsdp"]
        parts = list(spec) + [None] * (len(shape) - len(spec))
        for i, (ax, dim) in enumerate(zip(parts, shape)):
            if ax is None and dim % fsdp == 0:
                parts[i] = "fsdp"
                return P(*parts)
        return spec

    # ------------------------------------------------------------- train step
    def _build_train_step(self):
        tx = self.tx
        if self.state is not None and self.state.extra is not None:
            grads_fn = make_grad_fn_extra(self.module, self.accumulate_steps)
        else:
            grads_fn = make_grad_fn(self.module, self.accumulate_steps)

        module = self.module
        sentry = self._sentry_enabled
        loss_max = self._sentry_loss_max
        gnorm_max = self._sentry_gnorm_max
        zero_sh = self._zero_param_shardings if self._zero_update else None

        def train_step(state: TrainState, batch, rng):
            params = state.params
            if state.extra is not None:
                loss, grads, aux, new_extra = grads_fn(params, state.extra, batch, rng)
            else:
                loss, grads = grads_fn(params, batch, rng)
                aux, new_extra = {}, None
            raw_grads = _unbox(grads)
            raw_params = _unbox(params)
            if zero_sh is not None:
                # ZeRO update sharding: constraining grads to the update-
                # shard layout turns the dp/fsdp grad all-reduce into a
                # reduce-scatter; params slice to the same shard (layout
                # only, no comms), the whole optax chain + apply_updates
                # then runs on 1/N elements per device, and the jit's
                # replicated param out_shardings insert the all-gather —
                # async under the latency-hiding scheduler (xla_flags.py),
                # so it floats into the next step's forward.
                raw_grads = jax.lax.with_sharding_constraint(
                    raw_grads, zero_sh)
                raw_params = jax.lax.with_sharding_constraint(
                    raw_params, zero_sh)
            updates, new_opt = tx.update(
                raw_grads, state.opt_state, raw_params
            )
            new_params_raw = optax.apply_updates(raw_params, updates)
            if zero_sh is not None:
                # keep the post-update tree (and the sentry select below)
                # on the shard; the gather happens once, at the jit edge
                new_params_raw = jax.lax.with_sharding_constraint(
                    new_params_raw, zero_sh)
            new_params = _rebox_like(new_params_raw, params)
            if new_extra is not None:
                new_extra = module.post_update_extra(new_params_raw, new_extra)
            gnorm = optax.global_norm(raw_grads)
            new_state = TrainState(
                step=state.step + 1, params=new_params, opt_state=new_opt,
                extra=new_extra,
            )
            metrics = {"loss": loss, "grad_norm": gnorm, **aux}
            if sentry:
                # step sentry: a non-finite or spike-over-threshold step is
                # SKIPPED — every state leaf (params, opt_state incl. the
                # optax count, extra) rolls back to the incoming state, so
                # a NaN batch can never poison a later checkpoint. The
                # jnp.where select is the identity when ok, so an anomaly-
                # free run is byte-identical with the sentry on or off.
                ok = jnp.isfinite(loss) & jnp.isfinite(gnorm)
                if loss_max > 0:
                    ok &= loss <= loss_max
                if gnorm_max > 0:
                    ok &= gnorm <= gnorm_max
                new_state = jax.tree.map(
                    lambda n, o: jnp.where(ok, n, o), new_state, state
                )
                metrics["sentry_ok"] = ok
            return new_state, metrics

        sh = self._state_sharding_tree
        batch_spec = (
            P(None, DATA_AXES) if self.accumulate_steps > 1 else P(DATA_AXES)
        )
        batch_sh = NamedSharding(self.mesh, batch_spec)
        # no mesh context needed here: jax.jit only traces on first call,
        # which _get() routes through _in_context()'s use_mesh wrapper
        return jax.jit(
            train_step,
            in_shardings=(sh, batch_sh, NamedSharding(self.mesh, P())),
            out_shardings=(sh, NamedSharding(self.mesh, P())),
            donate_argnums=(0,),
        )

    def _build_eval_step(self):
        module = self.module

        def eval_step(state: TrainState, batch):
            params = module.maybe_fake_quant(state.params)
            with module.act_quant_ctx():
                if state.extra is not None:
                    loss, metrics, _ = module.loss_fn_extra(
                        params, state.extra, batch, None, train=False
                    )
                else:
                    loss, metrics = module.loss_fn(params, batch, None,
                                                   train=False)
            return {"loss": loss, **metrics}

        sh = self._state_sharding_tree
        batch_sh = NamedSharding(self.mesh, P(DATA_AXES))
        return jax.jit(
            eval_step,
            in_shardings=(sh, batch_sh),
            out_shardings=NamedSharding(self.mesh, P()),
        )

    def _get(self, name, builder):
        if name not in self._compiled:
            raw = builder()
            self._compiled_raw[name] = raw  # jitted fn, for cost_analysis
            self._compiled[name] = self._in_context(raw, name=name)
        return self._compiled[name]

    def cost_analysis(self, name="train"):
        """XLA static cost model of a compiled step (flops / bytes accessed).

        jax.jit wrappers expose no cost_analysis; only the AOT Compiled object
        does. We recorded the abstract avals of the first real call, so
        lower().compile() here is a compilation-cache hit, not a recompile —
        but even a cache-hit relower costs milliseconds, so the result is
        memoized per compiled-step signature (the recorded avals): the
        per-step mfu/hbm gauges query the lowering exactly once."""
        import jax

        import flax.linen as nn

        fn = self._compiled_raw.get(name)
        spec = self._abstract_args.get(name)
        if fn is None or spec is None:
            return None
        cached = self._cost_cache.get(name)
        if cached is not None and cached[0] is spec:
            return cached[1]
        args, kwargs = spec
        # same contexts as _in_context: without the logical axis rules,
        # with_logical_constraint silently no-ops and we'd trace (and
        # fully recompile) a differently-sharded program
        with use_mesh(self.mesh), nn.logical_axis_rules(list(self.rules)):
            cost = fn.lower(*args, **kwargs).compile().cost_analysis()
        # jax-version skew: Compiled.cost_analysis() is one dict on newer
        # jax but a [dict]-per-computation list on older releases
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        self._cost_cache[name] = (spec, cost)
        return cost

    def _step_mfu(self, step_time_s: float) -> Optional[float]:
        """Live MFU for the TRAIN log line and the ``fleetx_train_mfu``
        gauge: the compiled train step's XLA flops (``cost_analysis``,
        so remat recompute is included — a hardware utilization number,
        the BENCH records' model-flops MFU stays the cross-config one)
        over ``step_time_s`` and the peak FLOP/s. ``cost_analysis`` runs
        on the SPMD-partitioned PER-DEVICE module, so its flops divide
        by one chip's peak, not the fleet's — the ratio is then mesh-
        size-independent. None when XLA exposes no flops for this step
        (tried once, then cached)."""
        if self._flops_per_step is None:
            try:
                cost = self.cost_analysis("train")
                flops = float((cost or {}).get("flops", 0.0) or 0.0)
                self._flops_per_step = flops if flops > 0 else False
            except Exception:  # noqa: BLE001 — observability never aborts
                self._flops_per_step = False
        if not self._flops_per_step:
            return None
        peak = peak_flops_per_chip(jax.devices()[0])
        return self._flops_per_step / max(step_time_s, 1e-9) / peak

    def _step_hbm_bytes(self) -> Optional[float]:
        """Compiled train step's per-device HBM bytes accessed (static
        cost_analysis estimate) for the ``fleetx_train_step_hbm_bytes``
        gauge — tried once, then cached, same contract as the flops."""
        if self._hbm_bytes_per_step is None:
            try:
                cost = self.cost_analysis("train")
                b = float((cost or {}).get("bytes accessed", 0.0) or 0.0)
                self._hbm_bytes_per_step = b if b > 0 else False
            except Exception:  # noqa: BLE001 — observability never aborts
                self._hbm_bytes_per_step = False
        return self._hbm_bytes_per_step or None

    def opt_state_device_bytes(self) -> int:
        """Optimizer-state bytes RESIDENT per device: per-leaf shard shape
        x itemsize — the number the ZeRO update sharding shrinks by the
        dp*fsdp factor (replicated leaves count full size)."""
        total = 0
        for leaf in jax.tree.leaves(self.state.opt_state):
            if not (hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
                continue
            sh = getattr(leaf, "sharding", None)
            if sh is not None and hasattr(sh, "shard_shape"):
                shape = sh.shard_shape(leaf.shape)
            else:
                shape = leaf.shape
            total += int(np.prod(shape, dtype=np.int64)) * leaf.dtype.itemsize
        return total

    def _in_context(self, fn, name=None):
        """Run calls (and hence first-call tracing) inside the mesh + logical
        axis-rules contexts so nn.with_logical_constraint resolves."""
        import flax.linen as nn
        import jax

        def _aval(x):
            if not (hasattr(x, "shape") and hasattr(x, "dtype")):
                return x
            # keep NamedShardings: cost_analysis re-lowers from these avals,
            # and shardingless avals would be a cache MISS (full recompile)
            # of a differently-GSPMD-partitioned program
            sh = getattr(x, "sharding", None)
            if isinstance(sh, jax.sharding.NamedSharding):
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

        def call(*args, **kwargs):
            if name is not None and name not in self._abstract_args:
                self._abstract_args[name] = jax.tree.map(_aval, (args, kwargs))
            with use_mesh(self.mesh), nn.logical_axis_rules(list(self.rules)):
                return fn(*args, **kwargs)

        return call

    # -------------------------------------------------------------- data prep
    def _microbatch(self, batch):
        """First microbatch slice, host-side, for shape inference. Pipelined
        models consume the full batch (they micro-split internally)."""
        if self.mesh_cfg.pp > 1:
            return {k: np.asarray(v) for k, v in batch.items()}
        micro_total = self._micro_total()
        return {k: np.asarray(v)[:micro_total] for k, v in batch.items()}

    def _micro_total(self):
        glb = self.cfg.Global
        dp_world = self.mesh_cfg.dp * self.mesh_cfg.fsdp
        return glb.micro_batch_size * dp_world

    def _shard_batch(self, batch, for_train=True):
        """Host batch -> device arrays. With grad accum the leading axis
        becomes [accum, micro_total] and the in-jit scan runs over it.

        Single-host feeds the full global batch; multi-host processes each
        feed their contiguous slice (the sampler already sliced it) and the
        global array is assembled per-shard."""
        accum = self.accumulate_steps if for_train else 1
        micro_total = self._micro_total()
        n_proc = jax.process_count()
        out = {}
        for k, v in batch.items():
            arr = np.asarray(v)
            if accum > 1:
                # local rows = micro_total/n_proc per microbatch on this host
                arr = arr.reshape((accum, arr.shape[0] // accum) + arr.shape[1:])
                spec = P(None, DATA_AXES)
            else:
                spec = P(DATA_AXES)
            sharding = NamedSharding(self.mesh, spec)
            if n_proc > 1:
                out[k] = jax.make_array_from_process_local_data(sharding, arr)
            else:
                out[k] = jax.device_put(arr, sharding)
        return out

    # -------------------------------------------------------------------- fit
    def fit(self, train_data: Iterable, valid_data: Optional[Iterable] = None,
            epochs: Optional[int] = None):
        epochs = epochs or self.num_train_epochs
        if self.state is None:
            first = self.module.pretreating_batch(next(iter(train_data)))
            self.init_state(first)
        train_step = self._get("train", self._build_train_step)

        step = int(self.state.step)
        tokens_per_batch = None
        self._profiler_maybe_start(step)
        self._preempted = False  # a fresh fit() must train, not insta-save
        self._install_preemption_handler()
        try:
            self._fit_epochs(train_data, valid_data, epochs, step,
                             tokens_per_batch, train_step)
        finally:
            self._restore_preemption_handler()

    def _fit_epochs(self, train_data, valid_data, epochs, step,
                    tokens_per_batch, train_step):
        for epoch in range(self.start_epoch, epochs):
            self._cur_epoch = epoch  # for emergency saves by outer supervisors
            sampler = getattr(train_data, "batch_sampler", None)
            if sampler is not None and hasattr(sampler, "set_epoch"):
                sampler.set_epoch(epoch)
            dataset = getattr(train_data, "dataset", None)
            if dataset is not None and hasattr(dataset, "set_epoch"):
                dataset.set_epoch(epoch)  # per-epoch re-masking (ERNIE)
            t_last = time.time()
            loss_window = []
            batches = iter(faults.wrap_train_data(train_data))
            while True:
                try:
                    # host data phase: visible in profiler traces next to
                    # the step program (an input-bound run shows up as fat
                    # train.data spans, not mystery gaps)
                    with span("train.data", step=step):
                        batch = next(batches)
                except StopIteration:
                    break
                except Exception:
                    # a dead shard / raising loader mid-epoch: bank the
                    # healthy progress before surfacing the failure, so a
                    # restart resumes here instead of the last periodic save
                    logger.exception(
                        "train data stream raised at step %d; writing an "
                        "emergency checkpoint before re-raising", step,
                    )
                    self._profiler_maybe_stop(summary=False)
                    self._guarded_save(epoch)
                    self.wait_for_checkpoints()
                    raise
                if step >= self.max_steps:
                    break
                if self._preempted:
                    logger.warning(
                        "preemption signal received: checkpointing at step %d "
                        "and exiting fit()", step,
                    )
                    # close the trace first (summary deferred: the grace
                    # window belongs to the checkpoint, not trace parsing)
                    self._profiler_maybe_stop(summary=False)
                    self.save(epoch=epoch)
                    self.wait_for_checkpoints()
                    return
                # elastic failure domain: a matching FLEETX_FAULT_HOST_LOSS
                # plan raises HostLossFault here, BEFORE the step runs — the
                # aborted step's batch was fetched but not applied, so the
                # supervisor's resumed run re-feeds it exactly once
                # (resilience/elastic.py has the recovery loop)
                faults.on_train_step(step)
                batch = self.module.pretreating_batch(batch)
                if tokens_per_batch is None:
                    # ips accounting: LM batches carry "tokens", encoder/
                    # vision batches "input_ids"/first array respectively
                    arr = batch.get("tokens")
                    if arr is None:
                        arr = batch.get("input_ids")
                    if arr is None:
                        arr = next(iter(batch.values()))
                    tokens_per_batch = int(np.prod(np.asarray(arr).shape))
                device_batch = self._shard_batch(batch)
                rng = dist_env.data_rank_key(step)
                with span("train.step", step=step):
                    self.state, metrics = train_step(self.state, device_batch,
                                                     rng)
                if self._sentry_enabled and not bool(metrics["sentry_ok"]):
                    # skipped step: the batch was consumed from the stream
                    # (consumed_samples advances -> resume won't re-feed it)
                    # but no update was applied, so neither the step counter
                    # nor the per-step rng/lr sequence moves — the applied-
                    # update trajectory stays identical to a run that never
                    # saw this batch.
                    self.consumed_samples += self.cfg.Global.global_batch_size
                    self.sentry_skips += 1
                    self._sentry_consecutive += 1
                    self._obs_sentry_skips.inc()
                    obs_emit("sentry_skip", step=step,
                             loss=float(metrics["loss"]),
                             grad_norm=float(metrics["grad_norm"]),
                             consecutive=self._sentry_consecutive)
                    logger.warning(
                        "sentry: skipped anomalous step %d (loss=%s "
                        "grad_norm=%s; %d skipped total, %d consecutive)",
                        step, float(metrics["loss"]),
                        float(metrics["grad_norm"]), self.sentry_skips,
                        self._sentry_consecutive,
                    )
                    if self._sentry_consecutive >= self._sentry_max_skips:
                        self._profiler_maybe_stop(summary=False)
                        self._guarded_save(epoch)
                        self.wait_for_checkpoints()
                        obs_emit("sentry_abort", step=step,
                                 consecutive=self._sentry_consecutive)
                        raise SentryAbort(
                            f"{self._sentry_consecutive} consecutive train "
                            f"steps skipped by the sentry at step {step} "
                            "(FLEETX_SENTRY_MAX_SKIPS); last healthy state "
                            "checkpointed")
                    continue
                self._sentry_consecutive = 0
                step += 1
                self._obs_steps.inc()
                # tick before the logging/eval/save hooks so the profiled
                # step-time window measures the train step, not a periodic
                # evaluation pass or checkpoint write
                self._profiler_step(step)
                self.consumed_samples += self.cfg.Global.global_batch_size
                loss_window.append(metrics["loss"])

                with span("train.callback", step=step):
                    if step % self.logging_freq == 0:
                        losses = np.mean([float(l) for l in loss_window])
                        loss_window = []
                        dt = (time.time() - t_last) / self.logging_freq
                        t_last = time.time()
                        ips_total = tokens_per_batch / dt
                        lr = float(self.lr_schedule(step))
                        mfu = self._step_mfu(dt)
                        hbm = self._step_hbm_bytes()
                        self._obs_loss.set(float(losses))
                        self._obs_lr.set(lr)
                        self._obs_step_time.observe(dt)
                        self._obs_tokens_per_s.set(ips_total)
                        if mfu is not None:
                            self._obs_mfu.set(mfu)
                        if hbm is not None:
                            self._obs_hbm_bytes.set(hbm)
                        self.module.training_step_end(
                            {
                                "epoch": epoch,
                                "batch": step,
                                "loss": losses,
                                "batch_cost": dt,
                                "ips_total": ips_total,
                                "ips": ips_total / max(jax.process_count(), 1),
                                "lr": lr,
                                "mfu": mfu,
                            }
                        )
                    if (self.eval_freq and valid_data is not None
                            and step % self.eval_freq == 0):
                        self.evaluate(valid_data, epoch=epoch)
                    if self.save_steps and step % self.save_steps == 0:
                        self._guarded_save(epoch)
            if step >= self.max_steps:
                break
        self._profiler_maybe_stop()
        self.wait_for_checkpoints()

    # ------------------------------------------------------------------- eval
    def evaluate(self, valid_data: Iterable, epoch: int = 0):
        batches = iter(valid_data)
        if self.state is None:
            try:
                first = next(batches)
            except StopIteration:
                return None
            self.init_state(self.module.pretreating_batch(first))
            batches = itertools.chain([first], batches)  # don't drop batch 0
        eval_step = self._get("eval", self._build_eval_step)
        losses = []
        t0 = time.time()
        for i, batch in enumerate(batches):
            if i >= self.eval_iters:
                break
            batch = self.module.pretreating_batch(batch)
            device_batch = self._shard_batch(batch, for_train=False)
            metrics = eval_step(self.state, device_batch)
            losses.append(float(metrics["loss"]))
        if losses:
            self.module.validation_step_end(
                {
                    "epoch": epoch,
                    "batch": int(self.state.step),
                    "loss": float(np.mean(losses)),
                    "batch_cost": (time.time() - t0) / len(losses),
                }
            )
        return float(np.mean(losses)) if losses else None

    def predict(self, data: Iterable):
        """Forward the module over ``data`` batches, returning host outputs
        per batch (reference predict loop, eager_engine.py:502-632;
        serving-grade inference over an export artifact stays in
        InferenceEngine). Uses the module's serving contract so the fed keys
        match what export/inference would serve."""
        from fleetx_tpu.utils.export import serving_contract

        spec = self.module.input_spec() or {}
        fwd, keys = serving_contract(self.module, spec)
        if fwd is None:
            raise NotImplementedError(
                "module has no serving contract; use GenerationModule / "
                "InferenceEngine or override serving_forward()"
            )
        batches = iter(data)
        if self.state is None:
            try:
                first = next(batches)
            except StopIteration:
                return []
            self.init_state(self.module.pretreating_batch(first))
            batches = itertools.chain([first], batches)  # don't drop batch 0

        def _build_predict_step():
            module = self.module

            def predict_step(state: TrainState, feed):
                with module.act_quant_ctx():
                    return fwd(module.maybe_fake_quant(state.params), feed)

            batch_sh = NamedSharding(self.mesh, P(DATA_AXES))
            return jax.jit(
                predict_step,
                in_shardings=(self._state_sharding_tree, batch_sh),
            )

        predict_step = self._get("predict", _build_predict_step)
        outputs = []
        for batch in batches:
            batch = self.module.pretreating_batch(batch)
            feed = {k: batch[k] for k in keys}
            feed = self._shard_batch(feed, for_train=False)
            out = jax.device_get(predict_step(self.state, feed))
            # multi-output contracts (e.g. ERNIE's (mlm, sop)) stay pytrees
            outputs.append(jax.tree.map(np.asarray, out))
        return outputs

    # ------------------------------------------------------------- checkpoint
    def _ckpt_manager(self):
        import orbax.checkpoint as ocp

        if self._ckpt_mgr is None:
            import atexit

            path = os.path.abspath(os.path.join(self.output_dir, "checkpoints"))
            os.makedirs(path, exist_ok=True)
            self._ckpt_mgr = ocp.CheckpointManager(
                path,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=3, create=True, enable_async_checkpointing=True
                ),
            )
            # async saves must finalize before interpreter teardown or the
            # checkpoint stays a *.orbax-checkpoint-tmp and is unloadable.
            # weakref so atexit doesn't pin the Trainer (and its device
            # arrays) alive for the process lifetime.
            import weakref

            ref = weakref.ref(self)
            atexit.register(lambda: ref() and ref().wait_for_checkpoints())
        return self._ckpt_mgr

    def wait_for_checkpoints(self):
        self._join_uploader()
        if self._ckpt_mgr is not None:
            self._ckpt_mgr.wait_until_finished()

    def _join_uploader(self):
        """Block until the in-flight snapshot upload (if any) finishes."""
        t = self._upload_thread
        if t is not None:
            t.join()
            self._upload_thread = None

    def _record_save_failure(self, step: int) -> None:
        """Count + emit one failed checkpoint save (the run survives)."""
        self.save_failures += 1
        self._obs_save_failures.inc()
        obs_emit("save_failure", step=step, failures=self.save_failures)

    def _guarded_save(self, epoch: int = 0):
        """Periodic/emergency save that survives a failed write: a full
        disk or flaky object store must not kill a healthy training run —
        the failure is logged and counted, and the next cadence retries."""
        try:
            self.save(epoch=epoch)
        except Exception:
            self._record_save_failure(int(self.state.step))
            logger.exception(
                "checkpoint save failed at step %d (%d failures so far); "
                "training continues, next save in %d steps",
                int(self.state.step), self.save_failures, self.save_steps,
            )

    def _save_meta(self, epoch: int) -> dict:
        """The JSON side of a checkpoint (resume + compatibility record)."""
        return {
            "epoch": epoch, "consumed_samples": self.consumed_samples,
            # the dropout noise stream is defined by these two switches
            # (ops/dropout.py HashDropout vs nn.Dropout; flash kernel hash
            # vs hardware PRNG) — record them so a resume under flipped
            # flags is detectable instead of silently changing the masks
            "dropout_impl": self._dropout_impl(),
            # the mesh this state was written under: dp/fsdp may change on
            # restore (elastic reshard-on-load), mp/pp/cp must not — their
            # extents are baked into array shapes (resilience/elastic.py)
            "mesh": {"dp": self.mesh_cfg.dp, "fsdp": self.mesh_cfg.fsdp,
                     "mp": self.mesh_cfg.mp, "pp": self.mesh_cfg.pp,
                     "cp": self.mesh_cfg.cp},
        }

    def save(self, epoch: int = 0):
        """Sharded save of {params, opt_state, step} + meta (epoch,
        consumed_samples) — reference meta_state.pdopt semantics
        (eager_engine.py:655-665).

        Under ``FLEETX_CKPT_ASYNC_SNAPSHOT`` (step-shadow snapshot
        checkpointing) the step path blocks only for the device→host copy;
        a background uploader thread feeds the host tree to the orbax
        manager, and an upload failure rides the same counter/event path
        as a synchronous one (``_guarded_save``). A meta-advanced rewrite
        of an existing step detaches the old directory first and reattaches
        it if the replacement save fails — a crash or injected fault in
        the rewrite window can never destroy the only copy of a step."""
        import orbax.checkpoint as ocp

        self._join_uploader()  # serialize with an in-flight snapshot upload
        mgr = self._ckpt_manager()
        step = int(self.state.step)
        meta_sig = (step, epoch, self.consumed_samples)
        t0 = time.perf_counter()
        backup = None
        if step in (mgr.all_steps() or []):
            if meta_sig == self._last_saved_meta:
                # e.g. a preemption save landing right on a periodic-save
                # step: orbax refuses duplicate steps, and that exact state
                # (params AND meta) is already safe
                logger.info("checkpoint for step %d already exists; "
                            "skipping duplicate save", step)
                return
            # same step but the meta moved on — sentry skips advance
            # consumed_samples with the step counter frozen, and stale meta
            # would re-feed the skipped batches on resume. Rewrite it.
            logger.info("checkpoint for step %d exists but meta advanced "
                        "(consumed_samples %s); rewriting", step,
                        self.consumed_samples)
            mgr.wait_until_finished()
            backup = self._detach_step(step)
            mgr = self._ckpt_manager()  # detach may have rebuilt the manager
        try:
            faults.on_checkpoint_save(step)  # chaos injection (inert: no-op)
            meta = self._save_meta(epoch)
            if self._ckpt_async and backup is None:
                # step-shadow snapshot: the D2H copy is the only blocking
                # work; the uploader owns durability from here. (Rewrites
                # stay synchronous — rare, and the reattach guarantee below
                # wants the save outcome known before the backup is dropped.)
                host_state = jax.device_get(_unbox(self.state))
                nbytes = sum(getattr(l, "nbytes", 0)
                             for l in jax.tree.leaves(host_state))
                blocking = time.perf_counter() - t0
                self._obs_ckpt_bytes.set(float(nbytes))
                self._obs_ckpt_seconds.labels(phase="blocking").observe(blocking)
                self._upload_thread = threading.Thread(
                    target=self._upload_snapshot,
                    args=(mgr, step, host_state, meta, meta_sig,
                          t0, blocking, nbytes),
                    name="fleetx-ckpt-upload", daemon=True)
                self._upload_thread.start()
                logger.info(
                    "snapshot of step %d handed to uploader "
                    "(D2H blocked %.3fs, %.1f MB)",
                    step, blocking, nbytes / 1e6)
                return
            mgr.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(_unbox(self.state)),
                    meta=ocp.args.JsonSave(meta),
                ),
            )
            if backup is not None:
                # rewrite: the replacement must be durably finalized before
                # the old copy stops being the fallback
                mgr.wait_until_finished()
        except BaseException:
            if backup is not None:
                self._reattach_step(backup, step)
            raise
        if backup is not None:
            import shutil
            shutil.rmtree(backup, ignore_errors=True)
        dt = time.perf_counter() - t0
        nbytes = sum(getattr(l, "nbytes", 0)
                     for l in jax.tree.leaves(_unbox(self.state)))
        self._obs_ckpt_bytes.set(float(nbytes))
        self._obs_ckpt_seconds.labels(phase="blocking").observe(dt)
        self._obs_ckpt_seconds.labels(phase="total").observe(dt)
        obs_emit("checkpoint_saved", step=step, mode="sync",
                 blocking_s=round(dt, 4), total_s=round(dt, 4), bytes=nbytes)
        self._last_saved_meta = meta_sig
        logger.info("saved checkpoint at step %d -> %s", step, self.output_dir)

    def _upload_snapshot(self, mgr, step, host_state, meta, meta_sig,
                         t0, blocking, nbytes):
        """Uploader-thread body: feed a host snapshot to the orbax manager.
        ``_last_saved_meta`` commits only once the write is durably
        finalized; a failure rides the ``_guarded_save`` counter/event
        path so chaos assertions see async and sync failures identically."""
        import orbax.checkpoint as ocp

        try:
            mgr.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(host_state),
                    meta=ocp.args.JsonSave(meta),
                ),
            )
            mgr.wait_until_finished()
            self._last_saved_meta = meta_sig
            total = time.perf_counter() - t0
            self._obs_ckpt_seconds.labels(phase="total").observe(total)
            obs_emit("checkpoint_saved", step=step, mode="async_snapshot",
                     blocking_s=round(blocking, 4),
                     total_s=round(total, 4), bytes=nbytes)
            logger.info(
                "saved checkpoint at step %d -> %s (async snapshot: "
                "%.3fs blocking / %.3fs total)",
                step, self.output_dir, blocking, total)
        except Exception:
            self._record_save_failure(step)
            logger.exception(
                "async snapshot upload failed at step %d (%d failures so "
                "far); training continues, next save retries", step,
                self.save_failures)

    def _detach_step(self, step: int):
        """Move an existing step directory aside (to
        ``<output_dir>/rewrite/<step>``) before a meta-advanced rewrite:
        the detached copy — still a complete, restorable checkpoint —
        survives any crash or injected fault in the replacement save,
        and :meth:`_reattach_step` puts it back on failure. One-filesystem
        renames, so both moves are O(1). Returns the backup path (None
        when the manager lists the step but no directory exists)."""
        import shutil

        root = os.path.abspath(os.path.join(self.output_dir, "checkpoints"))
        src = os.path.join(root, str(step))
        if not os.path.isdir(src):
            return None
        hold = os.path.join(self.output_dir, "rewrite")
        os.makedirs(hold, exist_ok=True)
        dst = os.path.join(hold, str(step))
        if os.path.exists(dst):
            shutil.rmtree(dst)  # stale leftover from an older crash
        shutil.move(src, dst)
        self._mgr_refresh()
        return dst

    def _reattach_step(self, backup, step: int) -> None:
        """Restore a detached step directory after a failed rewrite save
        (drops any partial replacement first — the backup is the good
        copy)."""
        import shutil

        if backup is None:
            return
        root = os.path.abspath(os.path.join(self.output_dir, "checkpoints"))
        dst = os.path.join(root, str(step))
        if os.path.exists(dst):
            shutil.rmtree(dst)
        for name in os.listdir(root):
            if name.startswith(f"{step}.") and "orbax-checkpoint-tmp" in name:
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)
        shutil.move(backup, dst)
        self._mgr_refresh()
        logger.warning(
            "rewrite of checkpoint step %d failed; original copy restored",
            step)

    def _mgr_refresh(self) -> None:
        """Refresh the manager's cached step list after the directory
        changed underneath it (quarantine/detach/reattach); falls back to
        a lazy rebuild on orbax versions without ``reload()``."""
        mgr = self._ckpt_mgr
        if mgr is None:
            return
        try:
            mgr.reload()
        except Exception:  # older orbax: rebuild the manager lazily
            try:
                mgr.close()
            except Exception:
                pass
            self._ckpt_mgr = None

    def _dropout_impl(self) -> dict:
        from fleetx_tpu.ops.pallas.flash_attention import HW_RNG

        model_cfg = (getattr(self.cfg, "Model", None) or {})
        return {
            "flash_hw_rng": bool(HW_RNG),
            # HashDropout vs nn.Dropout for the hidden dropouts
            "fast_dropout": bool(model_cfg.get("fast_dropout", True)),
        }

    def load(self, step: Optional[int] = None):
        """Restore; resumes step count, epoch, and data order
        (consumed_samples -> sampler, eager_engine.py:286-288).

        On auto-restore (``step=None``) a corrupt/truncated checkpoint —
        e.g. a kill that landed between an async save and its finalize —
        does not end the run: the bad step directory is quarantined to
        ``<output_dir>/quarantine/`` and the next-older step is tried,
        walking back until one restores (docs/RESILIENCE.md). An explicit
        ``step`` still raises on failure: the caller asked for exactly
        that state, silently substituting another would be worse."""
        self._join_uploader()  # a pending snapshot upload is a candidate too
        mgr = self._ckpt_manager()
        mgr.wait_until_finished()  # never race our own in-flight async save
        candidates = [step] if step is not None else sorted(
            mgr.all_steps(), reverse=True)
        if not candidates:
            logger.warning("no checkpoint found under %s", self.output_dir)
            return False
        newest = candidates[0]
        for cand in candidates:
            if (
                cand == self._restored_step
                and self.state is not None
                and int(self.state.step) == cand
            ):
                # init_state already restored this step (its resumable
                # branch); don't pay the multi-GB orbax restore twice on
                # CLI resume paths
                return True
            if self.state is None:
                raise RuntimeError(
                    "call init_state (or fit) before load, to build shardings")
            try:
                restored = self._restore_step(cand)
            except ElasticMeshMismatch:
                # a checkpoint written under an incompatible mp/pp/cp
                # extent is a CONFIG error, not corruption: re-raise
                # instead of quarantining a healthy checkpoint
                raise
            except Exception as e:
                if step is not None:
                    raise
                logger.error(
                    "checkpoint step %d failed verified restore (%s: %s); "
                    "quarantining it and falling back to the next-older step",
                    cand, type(e).__name__, e,
                )
                self._quarantine_step(cand)
                continue
            self._apply_restored(cand, restored)
            if cand != newest:
                logger.warning(
                    "restored FALLBACK checkpoint step %d — newer step(s) %s "
                    "were corrupt and quarantined; %d step(s) of progress "
                    "lost", cand,
                    [s for s in candidates if s > cand], newest - cand,
                )
            return True
        raise CheckpointUnrestorable(
            f"no restorable checkpoint under {self.output_dir}: every "
            f"candidate step {sorted(candidates, reverse=True)} failed "
            "verified restore and was quarantined")

    def _restore_step(self, step: int):
        """Restore + verify one checkpoint step (raises on any mismatch).

        The meta JSON is read FIRST and its recorded mesh validated
        against this trainer's: a dp/fsdp change is the supported elastic
        reshard (the abstract restore below reshards into THIS mesh's
        shardings — ZeRO update layouts were re-derived by
        ``_state_shardings``, never assumed from the writer), while a
        changed mp/pp/cp extent raises :class:`ElasticMeshMismatch`
        before the state restore can fail in a way that looks like
        corruption (``load()`` re-raises it instead of quarantining)."""
        import orbax.checkpoint as ocp

        mgr = self._ckpt_manager()
        head = mgr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))
        saved_mesh = (head["meta"] or {}).get("mesh")
        if saved_mesh:
            validate_restore_mesh(saved_mesh, self.mesh_cfg, step=step)
        abstract = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            _unbox(self.state),
            self._state_sharding_tree,
        )
        restored = mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract),
                meta=ocp.args.JsonRestore(),
            ),
        )
        got = int(restored["state"].step)
        if got != step:
            raise ValueError(
                f"checkpoint dir {step} restored step counter {got}")
        return restored

    def _apply_restored(self, step: int, restored) -> None:
        """Install a verified restore into trainer state + resume meta."""
        flat = restored["state"]
        self.state = TrainState(
            step=flat.step,
            params=_rebox_like(flat.params, self.state.params),
            opt_state=flat.opt_state,
            extra=flat.extra,
        )
        meta = restored["meta"]
        self.start_epoch = meta.get("epoch", 0)
        self.consumed_samples = meta.get("consumed_samples", 0)
        # seed the duplicate-save signature: a save() at this same step with
        # unchanged meta must SKIP, not take the delete-then-rewrite path
        # (which would momentarily leave no restorable copy of this step)
        self._last_saved_meta = (step, self.start_epoch, self.consumed_samples)
        saved_impl = meta.get("dropout_impl")
        if saved_impl is not None and saved_impl != self._dropout_impl():
            logger.warning(
                "checkpoint was trained with dropout_impl=%s but this run "
                "uses %s — the dropout noise stream will differ from an "
                "uninterrupted run (set FLEETX_FLASH_HW_RNG to match)",
                saved_impl, self._dropout_impl(),
            )
        self._restored_step = step
        self._obs_opt_bytes.set(float(self.opt_state_device_bytes()))
        logger.info("restored checkpoint step %d (epoch %d)", step, self.start_epoch)

    def _quarantine_step(self, step: int) -> None:
        """Move a corrupt step directory out of the checkpoint root (to
        ``<output_dir>/quarantine/<step>``) so the manager never offers it
        again, and refresh the manager's cached step list."""
        import shutil

        root = os.path.abspath(os.path.join(self.output_dir, "checkpoints"))
        names = [n for n in os.listdir(root)
                 if n.isdigit() and int(n) == step]
        if not names:
            logger.warning("quarantine: no directory for step %d under %s",
                           step, root)
            return
        qdir = os.path.join(self.output_dir, "quarantine")
        os.makedirs(qdir, exist_ok=True)
        for name in names:
            dst = os.path.join(qdir, name)
            n = 0
            while os.path.exists(dst):
                n += 1
                dst = os.path.join(qdir, f"{name}.{n}")
            shutil.move(os.path.join(root, name), dst)
            self._obs_quarantines.inc()
            obs_emit("checkpoint_quarantine", step=step, moved_to=dst)
            logger.warning("quarantined corrupt checkpoint %s -> %s",
                           os.path.join(root, name), dst)
        self._mgr_refresh()

    # ------------------------------------------------------------ preemption
    def _install_preemption_handler(self):
        """SIGTERM -> finish the in-flight step, checkpoint, exit cleanly.

        TPU-fleet preemptions deliver SIGTERM with a grace window; the
        reference has no preemption handling (SURVEY §5: recovery is
        checkpoint-resume only), so a preempted run there loses everything
        since the last periodic save. Only the main thread may set signal
        handlers — worker-thread callers just skip this."""
        import signal
        import threading

        if threading.current_thread() is not threading.main_thread():
            return

        def on_sigterm(signum, frame):
            self._preempted = True  # the fit loop checkpoints + returns

        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, on_sigterm)
        except (ValueError, OSError):  # non-main interpreter contexts
            self._prev_sigterm = None

    def _restore_preemption_handler(self):
        """Put back whatever SIGTERM handler fit() displaced."""
        import signal
        import threading

        if (
            self._prev_sigterm is None
            or threading.current_thread() is not threading.main_thread()
        ):
            return
        try:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
        except (ValueError, OSError):
            pass
        self._prev_sigterm = None

    # -------------------------------------------------------------- profiler
    def _profiler_maybe_start(self, step):
        prof = self.cfg.get("Profiler") or {}
        self._prof_enabled = bool(prof.get("enable"))
        if not self._prof_enabled:
            return
        sched = prof.get("scheduler") or [1, 5]
        self._prof_window = tuple(sched)
        self._prof_dir = prof.get("profiler_log", "profiler_log")
        self._prof_running = False

    def _profiler_step(self, step):
        if not getattr(self, "_prof_enabled", False):
            return
        lo, hi = self._prof_window
        if not self._prof_running and step >= lo:
            jax.profiler.start_trace(self._prof_dir)
            self._prof_running = True
            self._prof_ticks = [time.perf_counter()]
        elif self._prof_running:
            self._prof_ticks.append(time.perf_counter())
        if self._prof_running and step >= hi:
            jax.block_until_ready(self.state.params)  # close the async tail
            self._prof_ticks.append(time.perf_counter())
            jax.profiler.stop_trace()
            self._prof_running = False
            self._prof_enabled = False
            logger.info("profiler trace written to %s", self._prof_dir)
            self._print_summary()

    def _print_summary(self):
        """Reference _print_summary (eager_engine.py:761-820): configurable
        overview/model/kernel/mem views after the profiling window."""
        from fleetx_tpu.utils.profiler_summary import print_summary

        ticks = getattr(self, "_prof_ticks", [])
        step_times = [b - a for a, b in zip(ticks, ticks[1:])]
        print_summary(
            self, dict(self.cfg.get("Profiler") or {}), self._prof_dir,
            step_times,
        )

    def _profiler_maybe_stop(self, summary: bool = True):
        """Close an open trace window. ``summary=False`` finalizes the trace
        only — the preemption path uses it so the SIGTERM grace window is
        spent checkpointing, not parsing trace JSON."""
        if getattr(self, "_prof_running", False):
            jax.profiler.stop_trace()
            self._prof_running = False
            if summary:
                self._print_summary()
