"""LR schedules (reference /root/reference/ppfleetx/optims/lr_scheduler.py:
31-160) as optax schedule functions."""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import optax

__all__ = [
    "CosineAnnealingWithWarmupDecay",
    "LinearDecayWithWarmup",
    "ViTLRScheduler",
    "MultiStepDecay",
    "CosineDecay",
    "build_lr_scheduler",
]


def CosineAnnealingWithWarmupDecay(
    max_lr: float,
    min_lr: float = 0.0,
    warmup_rate: float = 0.01,
    decay_steps: int = 360000,
    warmup_steps: Optional[int] = None,
    **_,
) -> optax.Schedule:
    """Megatron schedule: linear warmup to max_lr over warmup_rate*decay_steps,
    cosine decay to min_lr at decay_steps, constant min_lr after."""
    if warmup_steps is None:
        warmup_steps = int(warmup_rate * decay_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = max_lr * step / jnp.maximum(warmup_steps, 1)
        frac = jnp.clip(
            (step - warmup_steps) / jnp.maximum(decay_steps - warmup_steps, 1), 0.0, 1.0
        )
        cos = min_lr + 0.5 * (max_lr - min_lr) * (1.0 + jnp.cos(jnp.pi * frac))
        return jnp.where(step < warmup_steps, warm, cos)

    return schedule


def LinearDecayWithWarmup(
    learning_rate: float = None,
    max_lr: float = None,
    total_steps: int = None,
    warmup: float = 0.1,
    **_,
) -> optax.Schedule:
    """Linear warmup (fraction ``warmup`` of total) then linear decay to 0."""
    lr = max_lr if learning_rate is None else learning_rate
    if total_steps is None:
        raise ValueError(
            "LinearDecayWithWarmup needs Optimizer.lr.total_steps "
            "(reference GLUE configs set it to epochs * steps_per_epoch)"
        )
    warmup_steps = int(warmup * total_steps) if warmup < 1 else int(warmup)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = lr * step / jnp.maximum(warmup_steps, 1)
        decay = lr * jnp.clip(
            (total_steps - step) / jnp.maximum(total_steps - warmup_steps, 1), 0.0, 1.0
        )
        return jnp.where(step < warmup_steps, warm, decay)

    return schedule


def ViTLRScheduler(
    learning_rate: float,
    epochs: int,
    step_each_epoch: int,
    warmup_epochs: int = 0,
    decay_type: str = "cosine",
    **_,
) -> optax.Schedule:
    """Linear-warmup + cosine decay used by the ViT configs (reference
    optims/lr_scheduler.py:88)."""
    total = epochs * step_each_epoch
    warmup_steps = warmup_epochs * step_each_epoch

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = learning_rate * step / jnp.maximum(warmup_steps, 1)
        frac = jnp.clip((step - warmup_steps) / jnp.maximum(total - warmup_steps, 1), 0.0, 1.0)
        if decay_type == "cosine":
            dec = 0.5 * learning_rate * (1.0 + jnp.cos(jnp.pi * frac))
        else:
            dec = learning_rate * (1.0 - frac)
        return jnp.where(step < warmup_steps, warm, dec)

    return schedule


def MultiStepDecay(
    learning_rate: float,
    milestones: Sequence[int],
    gamma: float = 0.1,
    **_,
) -> optax.Schedule:
    """Piecewise-constant decay at milestone steps (reference
    lr_scheduler.py:129)."""
    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        exponent = jnp.sum(
            jnp.asarray([step >= m for m in milestones], jnp.float32)
        )
        return learning_rate * gamma**exponent

    return schedule


def CosineDecay(
    learning_rate: float,
    decay_steps: int,
    alpha: float = 0.0,
    **_,
) -> optax.Schedule:
    """Plain cosine decay to zero over decay_steps (reference
    lr_scheduler.py:147)."""
    def schedule(step):
        frac = jnp.clip(jnp.asarray(step, jnp.float32) / decay_steps, 0.0, 1.0)
        coeff = 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        return learning_rate * ((1 - alpha) * coeff + alpha)

    return schedule


_SCHEDULES = {
    "CosineAnnealingWithWarmupDecay": CosineAnnealingWithWarmupDecay,
    "LinearDecayWithWarmup": LinearDecayWithWarmup,
    "ViTLRScheduler": ViTLRScheduler,
    "MultiStepDecay": MultiStepDecay,
    "CosineDecay": CosineDecay,
}


def build_lr_scheduler(lr_cfg) -> optax.Schedule:
    """Build from config (reference optims/__init__.py:29-42). A bare float
    'lr' config becomes a constant schedule."""
    if isinstance(lr_cfg, (int, float)):
        return optax.constant_schedule(float(lr_cfg))
    cfg = dict(lr_cfg)
    name = cfg.pop("name", "CosineAnnealingWithWarmupDecay")
    if name not in _SCHEDULES:
        raise ValueError(f"unknown lr scheduler {name!r}; have {sorted(_SCHEDULES)}")
    return _SCHEDULES[name](**cfg)
