"""Optimizer + LR-schedule + grad-clip builders (reference ppfleetx/optims)."""
