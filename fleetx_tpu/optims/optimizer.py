"""Optimizer + grad-clip builders (reference /root/reference/ppfleetx/optims/
__init__.py:29-68, optimizer.py:31-56, grad_clip.py:27-156) on optax.

The reference's FusedAdamW tensor-fusion trick (flattening params into fused
storages for fused NCCL allreduce, tensor_fusion_helper.py:36-126) has no TPU
analogue — XLA already fuses grad collectives — so ``tensor_fusion`` is
accepted and ignored. MoE-aware global-norm clipping
(ClipGradForMOEByGlobalNorm) is expressed as a partitioned global norm over
expert/non-expert param groups.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from fleetx_tpu.optims.lr_scheduler import build_lr_scheduler
from fleetx_tpu.utils.log import logger

__all__ = ["build_optimizer", "build_grad_clip"]


def build_grad_clip(clip_cfg) -> Optional[optax.GradientTransformation]:
    """ClipGradByGlobalNorm / ClipGradByNorm / ClipGradByValue by name."""
    if not clip_cfg or not clip_cfg.get("name"):
        return None
    name = clip_cfg["name"]
    if name in ("ClipGradByGlobalNorm", "ClipGradForMOEByGlobalNorm"):
        # ClipGradForMOEByGlobalNorm (reference grad_clip.py:27-156) exists
        # because expert grads live on a different process group than dense
        # grads; under GSPMD the grads arrive sharded on one mesh and
        # optax.global_norm reduces over every shard, so one clip serves both.
        return optax.clip_by_global_norm(clip_cfg.get("clip_norm", 1.0))
    if name == "ClipGradByNorm":
        return optax.clip_by_block_rms(clip_cfg.get("clip_norm", 1.0))
    if name == "ClipGradByValue":
        return optax.clip(clip_cfg.get("clip_value", 1.0))
    raise ValueError(f"unknown grad clip {name!r}")


def build_optimizer(
    opt_cfg,
    lr_schedule: Optional[optax.Schedule] = None,
    weight_decay_mask: Optional[Callable] = None,
) -> optax.GradientTransformation:
    """AdamW family from the Optimizer config section. Weight decay excludes
    LayerNorm scales/biases by default (standard GPT recipe; the reference
    applies decay to all params — configurable via apply_decay_param_fun)."""
    cfg = dict(opt_cfg or {})
    name = cfg.get("name", "AdamW")
    if lr_schedule is None:
        lr_schedule = build_lr_scheduler(cfg.get("lr", 1e-4))
    if name not in ("AdamW", "FusedAdamW", "Adam", "Momentum", "SGD"):
        raise ValueError(f"unknown optimizer {name!r}")
    if cfg.get("tensor_fusion"):
        logger.info("tensor_fusion requested; XLA fuses collectives natively — ignored")

    wd = cfg.get("weight_decay", 0.01) if name != "Adam" else 0.0
    if weight_decay_mask is None:
        def weight_decay_mask(params):
            def decay_ok(path, leaf):
                names = {str(getattr(k, "key", k)) for k in path}
                return not ({"norm1", "norm2", "final_norm", "bias"} & names)

            return jax.tree_util.tree_map_with_path(decay_ok, params)

    if name in ("Momentum", "SGD"):
        # SGD(+momentum) with coupled L2 decay: wd*param joins the gradient
        # BEFORE the momentum buffer and lr scaling — matching the reference
        # paddle.optimizer.Momentum(weight_decay=L2Decay) the vision/MoCo
        # recipes use, not AdamW-style decoupled decay.
        parts = []
        if wd:
            parts.append(optax.add_decayed_weights(wd, mask=weight_decay_mask))
        parts.append(
            optax.sgd(
                learning_rate=lr_schedule,
                momentum=cfg.get("momentum", 0.9) if name == "Momentum" else None,
                nesterov=bool(cfg.get("use_nesterov")),
            )
        )
        tx = optax.chain(*parts)
    else:
        # moment_dtype: bfloat16 halves the first-moment buffer (~1.4 GiB at
        # 345M) — HBM headroom for remat save-sets / bigger batches. The
        # second moment stays f32 (bf16's 8-bit mantissa distorts v, and
        # optax only exposes mu_dtype for exactly this reason).
        mu_dtype = cfg.get("moment_dtype")
        tx = optax.adamw(
            learning_rate=lr_schedule,
            b1=cfg.get("beta1", 0.9),
            b2=cfg.get("beta2", 0.999),
            eps=cfg.get("epsilon", 1e-8),
            mu_dtype=jnp.dtype(mu_dtype) if mu_dtype else None,
            weight_decay=wd,
            mask=weight_decay_mask if wd else None,
        )
    clip = build_grad_clip(cfg.get("grad_clip"))
    if clip is not None:
        tx = optax.chain(clip, tx)
    multi_precision = cfg.get("multi_precision", True)
    del multi_precision  # params are fp32 masters by construction
    return tx
