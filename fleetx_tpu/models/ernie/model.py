"""ERNIE encoder LM, TPU-native flax implementation.

Capability parity with the reference's ErnieModel / ErnieForPretraining
(/root/reference/ppfleetx/models/language_model/ernie/dygraph/
single_model.py:127-700 and the TP variant dygraph/hybrid_model.py /
layers/distributed_transformer.py): word+position+token-type embeddings,
bidirectional pre/post-LN encoder, pooler, tied-embedding masked-LM head and
sentence-order-prediction (SOP) head.

TPU-first departures from the reference:
- TP is logical-axis sharding annotations (GSPMD inserts the collectives the
  reference writes as ColumnParallelLinear/RowParallelLinear,
  distributed_transformer.py:115-790).
- The masked-LM head scores a *fixed-size* set of masked positions
  [batch, max_predictions] gathered with take_along_axis — static shapes
  keep the whole step one XLA program (the reference gathers a dynamic
  count, single_model.py:438-444, which would retrace under jit).
- Attention dispatches to the same fused path as GPT (ops/attention.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from fleetx_tpu.models.gpt.model import (
    _constrain_act,
    _dense,
    _layer_norm,
    attn_out_dense,
)
from fleetx_tpu.ops.attention import causal_attention
from fleetx_tpu.ops.dropout import dropout_layer

Dtype = Any

__all__ = [
    "ErnieConfig",
    "ErnieModel",
    "ErnieForPretraining",
    "ErnieForSequenceClassification",
    "ernie_pretraining_loss",
]


@dataclasses.dataclass(frozen=True)
class ErnieConfig:
    """ERNIE encoder hyperparameters (reference ernie single_model.py
    construction args)."""
    vocab_size: int = 40000
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    ffn_hidden_size: Optional[int] = None
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 4
    initializer_range: float = 0.02
    pad_token_id: int = 0
    # 'gelu_tanh' (reference paddle default) or 'gelu' (erf; HF BERT)
    hidden_act: str = "gelu_tanh"
    # When True, auto-derived pad masks are expressed as per-example key
    # lengths so right-padded batches ride the flash kernel. Only enable
    # when inputs are guaranteed right-padded (the shipped ERNIE datasets
    # are); the default keeps the exact positional mask semantics.
    right_padded_inputs: bool = False
    # hash-based hidden dropout (ops/dropout.py); False restores nn.Dropout
    fast_dropout: bool = True
    use_recompute: bool = False
    scan_layers: bool = True
    dtype: Dtype = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def ffn_size(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @classmethod
    def from_model_config(cls, model_cfg) -> "ErnieConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in dict(model_cfg).items() if k in known and v is not None}
        if isinstance(kw.get("dtype"), str):
            kw["dtype"] = jnp.dtype(kw["dtype"]).type
        return cls(**kw)


class ErnieSelfAttention(nn.Module):
    """Bidirectional self-attention; q/k/v column-parallel over heads, out
    row-parallel (reference distributed_transformer.py:115-477)."""

    cfg: ErnieConfig

    @nn.compact
    def __call__(self, x, attn_mask, *, deterministic=True):
        cfg = self.cfg
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        qkv = _dense((nh, 3 * hd), ("embed", "heads", "kv"), "qkv_proj", dtype=cfg.dtype)(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        dropout_rng = None
        if cfg.attention_probs_dropout_prob > 0.0 and not deterministic:
            dropout_rng = self.make_rng("dropout")
        # (dense [b,1,1,s] mask, per-example kv lengths) — exactly one set.
        # kv_lens rides the non-causal flash kernel; a dense mask falls back
        # to the XLA path (fleetx_tpu/ops/attention.py dispatch).
        mask4, kv_lens = attn_mask
        out = causal_attention(
            q,
            k,
            v,
            causal=False,
            attn_mask=mask4,
            kv_lens=kv_lens,
            dropout_rate=cfg.attention_probs_dropout_prob,
            dropout_rng=dropout_rng,
            deterministic=deterministic,
        )
        return attn_out_dense(cfg.hidden_size, cfg.dtype)(out)


class ErnieEncoderLayer(nn.Module):
    """Post-LN encoder layer (reference layers/transformer.py's
    TransformerEncoderLayer with normalize_before=False default)."""

    cfg: ErnieConfig

    @nn.compact
    def __call__(self, x, attn_mask, deterministic=True):
        cfg = self.cfg
        x = _constrain_act(x, cfg)
        y = ErnieSelfAttention(cfg, name="attn")(x, attn_mask, deterministic=deterministic)
        y = dropout_layer(cfg.hidden_dropout_prob, "attn_dropout", cfg.fast_dropout)(
            y, deterministic=deterministic
        )
        x = _layer_norm(cfg, "norm1")(x + y)
        y = _dense(cfg.ffn_size, ("embed", "mlp"), "linear1", dtype=cfg.dtype)(x)
        y = nn.gelu(y, approximate=cfg.hidden_act != "gelu")
        y = _dense(cfg.hidden_size, ("mlp", "embed"), "linear2", dtype=cfg.dtype)(y)
        y = dropout_layer(cfg.hidden_dropout_prob, "ffn_dropout", cfg.fast_dropout)(
            y, deterministic=deterministic
        )
        x = _layer_norm(cfg, "norm2")(x + y)
        return _constrain_act(x, cfg)


class _ScanEncoderLayer(nn.Module):
    cfg: ErnieConfig

    @nn.compact
    def __call__(self, x, attn_mask, deterministic):
        x = ErnieEncoderLayer(self.cfg, name="layer")(x, attn_mask, deterministic)
        return x, None


class ErnieModel(nn.Module):
    """Embeddings + encoder + pooler. Returns (sequence_output [b,s,h],
    pooled_output [b,h])."""

    cfg: ErnieConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, position_ids=None,
                 attention_mask=None, *, deterministic=True):
        cfg = self.cfg
        if attention_mask is None:
            attention_mask = (input_ids != cfg.pad_token_id).astype(jnp.int32)
            if cfg.right_padded_inputs:
                # caller guarantees right padding: the mask is a prefix the
                # flash kernel expresses as per-example key lengths
                masks = (None, jnp.sum(attention_mask, axis=-1).astype(jnp.int32))
            else:
                # exact positional mask (safe for any padding layout)
                masks = (attention_mask[:, None, None, :], None)
        else:
            # arbitrary user mask -> broadcastable [b, 1, 1, s] dense form
            masks = (attention_mask[:, None, None, :], None)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if position_ids is None:
            position_ids = jnp.broadcast_to(
                jnp.arange(input_ids.shape[1])[None, :], input_ids.shape
            )

        emb_init = nn.initializers.normal(cfg.initializer_range)
        word_emb = self.param(
            "word_embeddings",
            nn.with_logical_partitioning(emb_init, ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size),
            jnp.float32,
        )
        pos_emb = self.param(
            "position_embeddings",
            nn.with_logical_partitioning(emb_init, (None, "embed")),
            (cfg.max_position_embeddings, cfg.hidden_size),
            jnp.float32,
        )
        type_emb = self.param(
            "token_type_embeddings",
            nn.with_logical_partitioning(emb_init, (None, "embed")),
            (cfg.type_vocab_size, cfg.hidden_size),
            jnp.float32,
        )
        x = word_emb[input_ids] + pos_emb[position_ids] + type_emb[token_type_ids]
        x = _layer_norm(cfg, "embed_norm")(x.astype(cfg.dtype))
        x = dropout_layer(cfg.hidden_dropout_prob, "embed_dropout", cfg.fast_dropout)(
            x, deterministic=deterministic
        )
        x = _constrain_act(x, cfg)

        layer_cls = _ScanEncoderLayer
        if cfg.use_recompute:
            layer_cls = nn.remat(
                _ScanEncoderLayer,
                policy=jax.checkpoint_policies.nothing_saveable,
                prevent_cse=False,
                static_argnums=(3,),
            )
        if cfg.scan_layers:
            stack = nn.scan(
                layer_cls,
                variable_axes={"params": 0, "intermediates": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast, nn.broadcast),
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            x, _ = stack(cfg, name="layers")(x, masks, deterministic)
        else:
            for i in range(cfg.num_layers):
                x, _ = layer_cls(cfg, name=f"layers_{i}")(x, masks, deterministic)

        pooled = _dense(cfg.hidden_size, ("embed", None), "pooler", dtype=cfg.dtype)(
            x[:, 0]
        )
        pooled = jnp.tanh(pooled)
        return x, pooled


class ErnieLMHead(nn.Module):
    """Masked-LM head: transform + tied-embedding logits at fixed masked
    positions (static-shape analogue of reference ErnieLMPredictionHead,
    single_model.py:412-452)."""

    cfg: ErnieConfig

    @nn.compact
    def __call__(self, sequence_output, word_embeddings, masked_positions):
        cfg = self.cfg
        # gather [b, P, h] hidden states of the masked slots
        h = jnp.take_along_axis(
            sequence_output, masked_positions[..., None], axis=1
        )
        h = _dense(cfg.hidden_size, ("embed", None), "transform", dtype=cfg.dtype)(h)
        h = nn.gelu(h, approximate=cfg.hidden_act != "gelu")
        h = _layer_norm(cfg, "transform_norm")(h)
        logits = jnp.einsum(
            "bph,vh->bpv", h.astype(jnp.float32), word_embeddings.astype(jnp.float32)
        )
        bias = self.param(
            "decoder_bias",
            nn.with_logical_partitioning(nn.initializers.zeros_init(), ("vocab",)),
            (cfg.vocab_size,),
            jnp.float32,
        )
        return logits + bias


class ErnieForPretraining(nn.Module):
    """MLM + SOP heads (reference ErniePretrainingHeads + ErnieForPretraining,
    single_model.py:454-600). Returns (mlm_logits [b,P,V], sop_logits [b,2])."""

    cfg: ErnieConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, position_ids=None,
                 attention_mask=None, masked_positions=None, *, deterministic=True):
        model = ErnieModel(self.cfg, name="ernie")
        seq, pooled = model(
            input_ids, token_type_ids, position_ids, attention_mask,
            deterministic=deterministic,
        )
        if masked_positions is None:
            b, s = input_ids.shape
            masked_positions = jnp.zeros((b, 1), jnp.int32)
        word_emb = model.variables["params"]["word_embeddings"]
        word_emb = word_emb.value if isinstance(word_emb, nn.Partitioned) else word_emb
        mlm_logits = ErnieLMHead(self.cfg, name="lm_head")(
            seq, word_emb, masked_positions
        )
        sop_logits = _dense(2, ("embed", None), "sop_head", dtype=jnp.float32)(
            pooled.astype(jnp.float32)
        )
        return mlm_logits, sop_logits


class ErnieForSequenceClassification(nn.Module):
    """Pooled-output classification head (GLUE-style finetuning)."""

    cfg: ErnieConfig
    num_classes: int = 2

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, position_ids=None,
                 attention_mask=None, *, deterministic=True):
        _, pooled = ErnieModel(self.cfg, name="ernie")(
            input_ids, token_type_ids, position_ids, attention_mask,
            deterministic=deterministic,
        )
        pooled = dropout_layer(self.cfg.hidden_dropout_prob, "cls_dropout", self.cfg.fast_dropout)(
            pooled, deterministic=deterministic
        )
        return _dense(self.num_classes, ("embed", None), "classifier",
                      dtype=jnp.float32)(pooled.astype(jnp.float32))


def ernie_pretraining_loss(mlm_logits, sop_logits, masked_labels, masked_weights,
                           sop_labels=None):
    """(lm_loss, sop_loss): weighted masked-token CE + optional SOP CE
    (reference ErniePretrainingCriterion, single_model.py:632-700)."""
    logp = jax.nn.log_softmax(mlm_logits.astype(jnp.float32), axis=-1)
    tok = jnp.take_along_axis(logp, masked_labels[..., None], axis=-1)[..., 0]
    w = masked_weights.astype(jnp.float32)
    lm_loss = -(tok * w).sum() / jnp.maximum(w.sum(), 1.0)
    if sop_labels is None:
        return lm_loss, jnp.zeros((), jnp.float32)
    sop_logp = jax.nn.log_softmax(sop_logits.astype(jnp.float32), axis=-1)
    sop = jnp.take_along_axis(sop_logp, sop_labels[..., None], axis=-1)[..., 0]
    return lm_loss, -sop.mean()
