from fleetx_tpu.models.ernie.model import (  # noqa: F401
    ErnieConfig,
    ErnieModel,
    ErnieForPretraining,
    ErnieForSequenceClassification,
    ernie_pretraining_loss,
)
