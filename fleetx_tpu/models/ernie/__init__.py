"""ERNIE encoder family (reference models/language_model/ernie)."""

from fleetx_tpu.models.ernie.model import (  # noqa: F401
    ErnieConfig,
    ErnieModel,
    ErnieForPretraining,
    ErnieForSequenceClassification,
    ernie_pretraining_loss,
)
