"""MoEModule — GPT pretraining with MoE FFN + balance loss (reference
/root/reference/ppfleetx/models/language_model/language_module.py:704-819:
adds gate balance loss to the LM loss; the reference's manual mp/dp param
broadcast + expert no_sync bookkeeping :786-819 is unnecessary here — expert
params are mesh-sharded like any other)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fleetx_tpu.models.gpt.model import pretraining_loss
from fleetx_tpu.models.language_module import GPTModule

__all__ = ["MoEModule"]


class MoEModule(GPTModule):
    """GPT + mixture-of-experts FFN pretraining: adds the gate balance loss to
    the LM loss (reference language_module.py:704-819)."""
    def loss_fn(self, params, batch, rng, train: bool):
        tokens, position_ids, labels, loss_mask = self.cp_prepare(batch)
        logits, mutated = self.nets.apply(
            {"params": params},
            tokens,
            position_ids,
            deterministic=not train,
            rngs={"dropout": rng} if train and rng is not None else None,
            mutable=["intermediates"],
        )
        lm_loss = pretraining_loss(logits, labels, loss_mask)
        # each MoE layer sows one aux loss (stacked along the scan axis);
        # average over layers so balance_loss_weight is depth-invariant
        balance = jnp.asarray(0.0, jnp.float32)
        n_aux = 0
        for leaf in jax.tree.leaves(mutated.get("intermediates", {})):
            balance = balance + jnp.sum(leaf)
            n_aux += leaf.size
        if n_aux:
            balance = balance / n_aux
        weight = self.gpt_config.balance_loss_weight
        total = lm_loss + weight * balance
        return total, {"lm_loss": lm_loss, "balance_loss": balance}
