"""GPTFinetuneModule — GLUE sequence-classification finetuning
(reference /root/reference/ppfleetx/models/language_model/
language_module.py:222-483: per-task loss from config, metric classes,
pretrained-checkpoint loading with fused/split qkv conversion).

Loss: CE for classification, MSE for regression (STS-B); metric built from
``Model.metric`` (fleetx_tpu/models/metrics.py). Pretrained backbones load
from an export artifact via ``Model.pretrained`` with fused/split qkv
layout conversion (convert_qkv_layout); same-layout full-state resume still
goes through the engine's ckpt_dir mechanism.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from fleetx_tpu.models.gpt.model import GPTConfig, GPTForSequenceClassification
from fleetx_tpu.models.language_module import LanguageModule, resolve_compute_dtype
from fleetx_tpu.models.metrics import build_metric
from fleetx_tpu.utils.log import logger

__all__ = ["GPTFinetuneModule"]


class GPTFinetuneModule(LanguageModule):
    """Batch: {"tokens": [b,s], "seq_lens": [b], "labels": [b]}."""

    def get_model(self):
        model_cfg = self.cfg.Model if hasattr(self.cfg, "Model") else self.cfg
        gcfg = GPTConfig.from_model_config(model_cfg)
        eng = getattr(self.cfg, "Engine", None) or {}
        gcfg = GPTConfig(**{**gcfg.__dict__, "dtype": resolve_compute_dtype(eng)})
        self.gpt_config = gcfg

        # Task metadata: the GLUE task spec (num_classes/regression/metric)
        # is the source of truth when the data section names a GlueDataset
        # task; explicit Model settings override.
        spec = {}
        data = getattr(self.cfg, "Data", None) or {}
        ds = ((data.get("Train") or {}).get("dataset") or {}) if data else {}
        if ds.get("name") == "GlueDataset" and ds.get("task"):
            from fleetx_tpu.data.glue_dataset import GLUE_TASKS

            spec = GLUE_TASKS.get(str(ds["task"]).lower().replace("-", ""), {})
        self.num_classes = int(
            model_cfg.get("num_classes") or spec.get("num_classes") or 2
        )
        self.regression = bool(
            model_cfg["regression"] if model_cfg.get("regression") is not None
            else spec.get("regression")
        )
        metric_cfg = model_cfg.get("metric") or spec.get("metric") or {"name": "Accuracy"}
        if isinstance(metric_cfg, str):
            metric_cfg = {"name": metric_cfg}
        self.metric = build_metric(metric_cfg)
        return GPTForSequenceClassification(
            gcfg, num_classes=1 if self.regression else self.num_classes
        )

    def init_params(self, rng, batch):
        return self.nets.init(
            rng, batch["tokens"], seq_lens=batch.get("seq_lens")
        )

    def load_pretrained(self, params):
        """Map a pretrained GPT backbone (``Model.pretrained`` = export
        artifact dir) onto the fresh finetune tree with fused/split qkv
        conversion; the classification head keeps fresh init (reference
        checkpoint conversion, language_module.py:293-372)."""
        pre = (self.cfg.Model or {}).get("pretrained")
        if not pre:
            return None
        from fleetx_tpu.models.language_module import load_pretrained_gpt_backbone

        return load_pretrained_gpt_backbone(
            params, pre, self.gpt_config.fuse_attn_qkv
        )

    def loss_fn(self, params, batch, rng, train: bool):
        logits = self.nets.apply(
            {"params": params},
            batch["tokens"],
            None,
            None,
            batch.get("seq_lens"),
            deterministic=not train,
            rngs={"dropout": rng} if train and rng is not None else None,
        )
        labels = batch["labels"]
        if self.regression:
            preds = logits[:, 0]
            loss = jnp.mean((preds - labels.astype(jnp.float32)) ** 2)
            acc = -loss  # surrogate running metric
        else:
            logp = jax.nn.log_softmax(logits, axis=-1)
            loss = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
            acc = (jnp.argmax(logits, axis=-1) == labels).mean()
        return loss, {"acc": acc}

    # --------------------------------------------------------------- metric
    def predict_logits(self, params, batch):
        if not hasattr(self, "_predict_fn"):
            self._predict_fn = jax.jit(
                lambda p, t, sl: self.nets.apply({"params": p}, t, None, None, sl)
            )
        return self._predict_fn(params, batch["tokens"], batch["seq_lens"])

    def evaluate_dataset(self, params, loader) -> Dict[str, float]:
        """Full-metric eval (reference validation_step_end metric accumulate)."""
        self.metric.reset()
        n = 0
        for batch in loader:
            logits = np.asarray(self.predict_logits(params, batch))
            preds = logits[:, 0] if self.regression else logits
            self.metric.update(preds, np.asarray(batch["labels"]))
            n += logits.shape[0]
        vals = self.metric.accumulate()
        if not isinstance(vals, tuple):
            vals = (vals,)
        result = {"metric": vals if len(vals) > 1 else vals[0], "examples": n}
        logger.info("GLUE eval: %s", result)
        return result

    def input_spec(self):
        glb = self.cfg.Global
        data = getattr(self.cfg, "Data", None) or {}
        ds = ((data.get("Train") or {}).get("dataset") or {}) if data else {}
        seq = ds.get("max_seq_len") or 128
        b = glb.micro_batch_size or 1
        return {
            "tokens": jax.ShapeDtypeStruct((b, seq), jnp.int32),
            "seq_lens": jax.ShapeDtypeStruct((b,), jnp.int32),
            "labels": jax.ShapeDtypeStruct(
                (b,), jnp.float32 if self.regression else jnp.int32
            ),
        }
