"""Imagen text-to-image diffusion family (reference models/multimodal_model)."""

from fleetx_tpu.models.multimodal.unet import (  # noqa: F401
    EfficientUNet,
    UNetConfig,
    UNET_PRESETS,
    build_unet,
)
from fleetx_tpu.models.multimodal.imagen import (  # noqa: F401
    cosine_log_snr,
    imagen_criterion,
    q_sample,
)
