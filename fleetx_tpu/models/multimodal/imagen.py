"""Imagen diffusion math + criterion (reference
/root/reference/ppfleetx/models/multimodal_model/imagen/modeling.py:89-780:
ImagenCriterion with p2 loss weighting, cascading-DDPM q_sample/p_sample
over a continuous-time cosine log-SNR schedule).

All pure functions of (x, t, noise) — the ImagenModule owns rngs and the
UNet; samplers run under lax.fori_loop with static shapes."""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "cosine_log_snr",
    "log_snr_to_alpha_sigma",
    "q_sample",
    "imagen_criterion",
    "ddpm_sample",
]


def cosine_log_snr(t, s: float = 0.008):
    """Continuous-time cosine schedule's log-SNR (reference
    beta_cosine_log_snr, modeling.py): t in [0, 1]."""
    t = jnp.clip(t, 0.0, 0.9995)
    return -2.0 * jnp.log(jnp.tan((jnp.pi / 2) * (t + s) / (1 + s)))


def log_snr_to_alpha_sigma(log_snr):
    """Cosine-schedule helpers: log-SNR -> (alpha, sigma) diffusion
    coefficients."""
    alpha = jnp.sqrt(jax.nn.sigmoid(log_snr))
    sigma = jnp.sqrt(jax.nn.sigmoid(-log_snr))
    return alpha, sigma


def q_sample(x0, t, noise):
    """Forward diffusion: x_t = alpha(t) x0 + sigma(t) eps."""
    log_snr = cosine_log_snr(t)
    alpha, sigma = log_snr_to_alpha_sigma(log_snr)
    shape = (-1,) + (1,) * (x0.ndim - 1)
    return alpha.reshape(shape) * x0 + sigma.reshape(shape) * noise, log_snr


def imagen_criterion(pred, target, log_snr, p2_loss_weight_gamma: float = 0.0,
                     p2_loss_weight_k: float = 1.0):
    """Per-sample-weighted MSE (reference ImagenCriterion,
    modeling.py:89-130): w = (k + exp(log_snr))^-gamma; gamma=0 -> plain MSE."""
    loss = jnp.mean((pred.astype(jnp.float32) - target.astype(jnp.float32)) ** 2,
                    axis=tuple(range(1, pred.ndim)))
    if p2_loss_weight_gamma > 0.0:
        weight = (p2_loss_weight_k + jnp.exp(log_snr)) ** (-p2_loss_weight_gamma)
        loss = loss * weight
    return loss.mean()


def ddpm_sample(unet_apply, params, shape, rng, *, steps: int = 50,
                text_embeds=None, text_mask=None, lowres_cond_img=None):
    """Ancestral sampler over the cosine schedule (reference p_sample_loop,
    modeling.py:369-460). unet predicts eps; static shapes throughout."""
    rng, init_rng = jax.random.split(rng)
    x = jax.random.normal(init_rng, shape, jnp.float32)
    ts = jnp.linspace(1.0, 0.0, steps + 1)

    def body(i, carry):
        x, rng = carry
        t_now, t_next = ts[i], ts[i + 1]
        b = shape[0]
        tb = jnp.full((b,), t_now)
        eps = unet_apply(
            params, x, tb, text_embeds, text_mask, lowres_cond_img
        ).astype(jnp.float32)
        log_snr = cosine_log_snr(t_now)
        log_snr_next = cosine_log_snr(t_next)
        alpha, sigma = log_snr_to_alpha_sigma(log_snr)
        alpha_next, sigma_next = log_snr_to_alpha_sigma(log_snr_next)
        x0 = jnp.clip((x - sigma * eps) / jnp.maximum(alpha, 1e-8), -1.0, 1.0)
        # DDPM posterior mean/variance
        c_ = -jnp.expm1(log_snr - log_snr_next)
        mean = alpha_next * (x * (1 - c_) / jnp.maximum(alpha, 1e-8) + c_ * x0)
        var = (sigma_next ** 2) * c_
        rng, nrng = jax.random.split(rng)
        noise = jax.random.normal(nrng, shape, jnp.float32)
        x = mean + jnp.where(i < steps - 1, jnp.sqrt(jnp.maximum(var, 0.0)), 0.0) * noise
        return x, rng

    x, _ = jax.lax.fori_loop(0, steps, body, (x, rng))
    return x
