"""Efficient-UNet for Imagen, TPU-native flax implementation.

Capability parity with the reference's UNet zoo
(/root/reference/ppfleetx/models/multimodal_model/imagen/unet.py, 1,485 LoC,
and modeling.py:32-87 presets Unet64_397M / BaseUnet64 / SRUnet256 /
SRUnet1024): time-conditioned ResNet blocks with scale-shift, per-resolution
self-attention + text cross-attention transformer blocks, skip connections,
efficient (downsample-first) variant, low-res conditioning channel for the
SR cascade stages.

TPU-first: channels-last [B, H, W, C] conv layout, GroupNorm (no running
stats), attention over flattened spatial tokens hits the shared fused path.
Text conditioning consumes *precomputed* encoder embeddings [B, L, D] (the
reference embeds T5/DeBERTa in-process, utils.py:431 — precomputing is the
standard TPU data-hall recipe and keeps the train step text-model-free).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any

__all__ = ["UNetConfig", "EfficientUNet", "UNET_PRESETS", "build_unet"]


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    """Efficient-UNet shape/conditioning hyperparameters (reference
    imagen/unet.py presets)."""
    dim: int = 128
    dim_mults: Tuple[int, ...] = (1, 2, 3, 4)
    num_resnet_blocks: Union[int, Tuple[int, ...]] = 2
    layer_attns: Union[bool, Tuple[bool, ...]] = (False, True, True, True)
    layer_cross_attns: Union[bool, Tuple[bool, ...]] = (False, True, True, True)
    attn_heads: int = 8
    ff_mult: float = 2.0
    channels: int = 3
    cond_dim: int = 512  # text embedding dim
    lowres_cond: bool = False  # SR stages concat the upsampled low-res image
    memory_efficient: bool = False  # downsample before the resnet stack
    groups: int = 8
    dtype: Dtype = jnp.bfloat16

    def per_layer(self, v, i):
        if isinstance(v, (tuple, list)):
            return v[i]
        return v

    @classmethod
    def from_model_config(cls, model_cfg) -> "UNetConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in dict(model_cfg).items() if k in known and v is not None}
        for key in ("dim_mults", "num_resnet_blocks", "layer_attns", "layer_cross_attns"):
            if isinstance(kw.get(key), list):
                kw[key] = tuple(kw[key])
        if isinstance(kw.get("dtype"), str):
            kw["dtype"] = jnp.dtype(kw["dtype"]).type
        return cls(**kw)


# reference modeling.py:32-87
UNET_PRESETS = {
    "Unet64_397M": dict(dim=256, dim_mults=(1, 2, 3, 4), num_resnet_blocks=3,
                        layer_attns=(False, True, True, True),
                        layer_cross_attns=(False, True, True, True),
                        attn_heads=8, ff_mult=2.0, memory_efficient=False),
    "BaseUnet64": dict(dim=512, dim_mults=(1, 2, 3, 4), num_resnet_blocks=3,
                       layer_attns=(False, True, True, True),
                       layer_cross_attns=(False, True, True, True),
                       attn_heads=8, ff_mult=2.0, memory_efficient=False),
    "SRUnet256": dict(dim=128, dim_mults=(1, 2, 4, 8),
                      num_resnet_blocks=(2, 4, 8, 8),
                      layer_attns=(False, False, False, True),
                      layer_cross_attns=(False, False, False, True),
                      attn_heads=8, ff_mult=2.0, memory_efficient=True,
                      lowres_cond=True),
    "SRUnet1024": dict(dim=128, dim_mults=(1, 2, 4, 8),
                       num_resnet_blocks=(2, 4, 8, 8),
                       layer_attns=False,
                       layer_cross_attns=(False, False, False, True),
                       attn_heads=8, ff_mult=2.0, memory_efficient=True,
                       lowres_cond=True),
}


def build_unet(name: str, **overrides) -> "EfficientUNet":
    """UNet preset factory by name (Unet64_397M / BaseUnet64 / SRUnet256 /
    SRUnet1024)."""
    if name not in UNET_PRESETS:
        raise ValueError(f"unknown unet {name!r}; have {sorted(UNET_PRESETS)}")
    return EfficientUNet(UNetConfig(**{**UNET_PRESETS[name], **overrides}))


def _timestep_embedding(t, dim):
    """Sinusoidal embedding of continuous t in [0, 1]."""
    half = dim // 2
    freqs = jnp.exp(-jnp.log(10000.0) * jnp.arange(half) / max(half - 1, 1))
    args = t[:, None] * freqs[None, :] * 1000.0
    return jnp.concatenate([jnp.sin(args), jnp.cos(args)], axis=-1)


def _conv(features, kernel, name, dtype, strides=1):
    return nn.Conv(features, (kernel, kernel), (strides, strides),
                   padding="SAME", dtype=dtype, param_dtype=jnp.float32,
                   name=name)


class ResnetBlock(nn.Module):
    """GroupNorm-SiLU-conv x2 with time scale-shift conditioning
    (reference unet.py ResnetBlock)."""

    cfg: UNetConfig
    features: int

    @nn.compact
    def __call__(self, x, time_emb):
        c = self.cfg
        gn = lambda n, f: nn.GroupNorm(num_groups=min(c.groups, f),
                                       dtype=c.dtype, param_dtype=jnp.float32,
                                       name=n)
        h = gn("gn1", x.shape[-1])(x)
        h = nn.silu(h)
        h = _conv(self.features, 3, "conv1", c.dtype)(h)
        # time conditioning -> per-channel scale & shift
        ss = nn.Dense(2 * self.features, dtype=c.dtype, param_dtype=jnp.float32,
                      name="time_proj")(nn.silu(time_emb))
        scale, shift = jnp.split(ss[:, None, None, :], 2, axis=-1)
        h = gn("gn2", self.features)(h) * (1.0 + scale) + shift
        h = nn.silu(h)
        h = _conv(self.features, 3, "conv2", c.dtype)(h)
        if x.shape[-1] != self.features:
            x = _conv(self.features, 1, "skip", c.dtype)(x)
        return x + h


class TransformerBlock(nn.Module):
    """Self-attention (+ optional text cross-attention) + FF over flattened
    spatial tokens (reference unet.py TransformerBlock/CrossAttention)."""

    cfg: UNetConfig
    cross: bool

    @nn.compact
    def __call__(self, x, text_embeds=None, text_mask=None):
        c = self.cfg
        b, h, w, ch = x.shape
        nh = c.attn_heads
        hd = max(ch // nh, 8)
        tokens = x.reshape(b, h * w, ch)

        def attn(q_in, kv_in, name, kv_mask=None):
            q = nn.DenseGeneral((nh, hd), dtype=c.dtype, param_dtype=jnp.float32,
                                name=f"{name}_q")(q_in)
            k = nn.DenseGeneral((nh, hd), dtype=c.dtype, param_dtype=jnp.float32,
                                name=f"{name}_k")(kv_in)
            v = nn.DenseGeneral((nh, hd), dtype=c.dtype, param_dtype=jnp.float32,
                                name=f"{name}_v")(kv_in)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                                preferred_element_type=jnp.float32)
            logits = logits / jnp.sqrt(hd).astype(jnp.float32)
            if kv_mask is not None:
                logits = jnp.where(kv_mask[:, None, None, :].astype(bool),
                                   logits, -1e9)
            w_ = jax.nn.softmax(logits, axis=-1).astype(c.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", w_, v)
            return nn.DenseGeneral(ch, axis=(-2, -1), dtype=c.dtype,
                                   param_dtype=jnp.float32,
                                   name=f"{name}_out")(out)

        y = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32,
                         name="self_norm")(tokens)
        tokens = tokens + attn(y, y, "self_attn")
        if self.cross and text_embeds is not None:
            y = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32,
                             name="cross_norm")(tokens)
            t = text_embeds.astype(c.dtype)
            tokens = tokens + attn(y, t, "cross_attn", kv_mask=text_mask)
        y = nn.LayerNorm(dtype=c.dtype, param_dtype=jnp.float32,
                         name="ff_norm")(tokens)
        y = nn.Dense(int(ch * c.ff_mult), dtype=c.dtype, param_dtype=jnp.float32,
                     name="ff1")(y)
        y = nn.gelu(y)
        tokens = tokens + nn.Dense(ch, dtype=c.dtype, param_dtype=jnp.float32,
                                   name="ff2")(y)
        return tokens.reshape(b, h, w, ch)


class EfficientUNet(nn.Module):
    """Cascading-DDPM UNet stage (reference unet.py Unet, :592-1480).

    call(x_t [B,H,W,C], t [B], text_embeds [B,L,D], text_mask [B,L],
    lowres_cond_img [B,H,W,C] for SR stages) -> predicted noise.
    """

    cfg: UNetConfig

    @nn.compact
    def __call__(self, x, t, text_embeds=None, text_mask=None,
                 lowres_cond_img=None):
        c = self.cfg
        x = x.astype(c.dtype)
        if c.lowres_cond:
            if lowres_cond_img is None:
                raise ValueError("SR unet needs lowres_cond_img")
            x = jnp.concatenate([x, lowres_cond_img.astype(c.dtype)], axis=-1)

        time_dim = c.dim * 4
        temb = _timestep_embedding(t, c.dim)
        temb = nn.Dense(time_dim, param_dtype=jnp.float32, name="time_mlp1")(temb)
        temb = nn.silu(temb)
        temb = nn.Dense(time_dim, param_dtype=jnp.float32, name="time_mlp2")(temb)
        if text_embeds is not None:
            # pooled text -> added to time conditioning (reference unet.py
            # to_text_non_attn_cond)
            mask = (text_mask if text_mask is not None
                    else jnp.ones(text_embeds.shape[:2]))[..., None]
            pooled = (text_embeds * mask).sum(1) / jnp.maximum(mask.sum(1), 1.0)
            temb = temb + nn.Dense(time_dim, param_dtype=jnp.float32,
                                   name="text_pool_proj")(pooled.astype(jnp.float32))
        temb = temb.astype(c.dtype)

        x = _conv(c.dim, 3, "init_conv", c.dtype)(x)
        hs = []
        dims = [c.dim * m for m in c.dim_mults]
        n_stages = len(dims)

        for i, d in enumerate(dims):
            blocks = c.per_layer(c.num_resnet_blocks, i)
            if c.memory_efficient and i > 0:
                x = _conv(d, 3, f"down_{i}_pre", c.dtype, strides=2)(x)
            for j in range(blocks):
                x = ResnetBlock(c, d, name=f"down_{i}_res{j}")(x, temb)
                hs.append(x)
            if c.per_layer(c.layer_attns, i):
                x = TransformerBlock(
                    c, cross=bool(c.per_layer(c.layer_cross_attns, i)),
                    name=f"down_{i}_attn",
                )(x, text_embeds, text_mask)
                hs.append(x)
            if not c.memory_efficient and i < n_stages - 1:
                x = _conv(d, 3, f"down_{i}_post", c.dtype, strides=2)(x)

        x = ResnetBlock(c, dims[-1], name="mid_res1")(x, temb)
        x = TransformerBlock(
            c, cross=bool(c.per_layer(c.layer_cross_attns, n_stages - 1)),
            name="mid_attn",
        )(x, text_embeds, text_mask)
        x = ResnetBlock(c, dims[-1], name="mid_res2")(x, temb)

        for i in reversed(range(n_stages)):
            d = dims[i]
            blocks = c.per_layer(c.num_resnet_blocks, i)
            n_skips = blocks + (1 if c.per_layer(c.layer_attns, i) else 0)
            for j in range(n_skips):
                skip = hs.pop()
                if skip.shape[1] != x.shape[1]:
                    x = jax.image.resize(
                        x, (x.shape[0], skip.shape[1], skip.shape[2], x.shape[3]),
                        method="nearest",
                    )
                x = jnp.concatenate([x, skip], axis=-1)
                x = ResnetBlock(c, d, name=f"up_{i}_res{j}")(x, temb)
            if c.per_layer(c.layer_attns, i):
                x = TransformerBlock(
                    c, cross=bool(c.per_layer(c.layer_cross_attns, i)),
                    name=f"up_{i}_attn",
                )(x, text_embeds, text_mask)
            if i > 0:
                target = x.shape[1] * 2
                x = jax.image.resize(
                    x, (x.shape[0], target, target, x.shape[3]), method="nearest"
                )
                x = _conv(dims[i - 1], 3, f"up_{i}_conv", c.dtype)(x)

        x = _conv(c.dim, 3, "final_res", c.dtype)(x)
        x = nn.silu(x)
        out = nn.Conv(c.channels, (3, 3), padding="SAME", dtype=jnp.float32,
                      param_dtype=jnp.float32,
                      kernel_init=nn.initializers.zeros_init(),
                      name="final_conv")(x.astype(jnp.float32))
        return out
