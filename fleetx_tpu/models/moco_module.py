"""MoCo v1/v2 momentum-contrast pretraining
(reference /root/reference/ppfleetx/models/vision_model/moco/moco.py:36-235
and moco_module.py: momentum ("key") encoder updated by EMA, FIFO negative
queue, InfoNCE loss; v2 adds an MLP projection head).

TPU-first differences from the reference:
- The key encoder + queue live in ``TrainState.extra`` and are threaded
  functionally through the jitted step (the reference mutates nn.Layer
  buffers in-place).
- No ``concat_all_gather`` (moco.py:36) and no shuffling-BN
  (_batch_shuffle): under GSPMD the key batch is already a global array, so
  enqueueing "all-gathers" by construction, and the ResNet/ViT backbones
  here use GroupNorm, which has no cross-sample statistics to shuffle away.

Batch contract: {"query": [b,H,W,C], "key": [b,H,W,C]} — two augmented
views (ContrastiveViewsDataset below in fleetx_tpu/data/vision_dataset.py
emits them).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from fleetx_tpu.models.language_module import resolve_compute_dtype
from fleetx_tpu.models.module import BasicModule
from fleetx_tpu.models.vision.resnet import build_resnet
from fleetx_tpu.models.vision.vit import ViTConfig, ViT
from fleetx_tpu.utils.log import logger

__all__ = ["MOCOModule", "MOCOClsModule"]


class MOCOModule(BasicModule):
    """MoCo v1/v2 pretraining: InfoNCE over a momentum encoder + negative
    queue kept in TrainState.extra (reference moco_module.py)."""
    def get_model(self):
        model_cfg = self.cfg.Model if hasattr(self.cfg, "Model") else self.cfg
        self.dim = int(model_cfg.get("dim") or 128)
        self.K = int(model_cfg.get("queue_size") or 65536)
        self.m = float(model_cfg.get("momentum") or 0.999)
        self.T = float(model_cfg.get("temperature") or 0.07)
        self.mlp_head = bool(model_cfg.get("mlp") or False)  # v2
        eng = getattr(self.cfg, "Engine", None) or {}
        dtype = resolve_compute_dtype(eng)
        backbone = model_cfg.get("backbone") or "resnet50"

        import flax.linen as nn

        dim, mlp = self.dim, self.mlp_head
        is_resnet = str(backbone).startswith("resnet")
        if is_resnet:
            vit_cfg = None
        else:
            from fleetx_tpu.models.vision.vit import VIT_PRESETS

            if str(backbone) in VIT_PRESETS:
                preset = VIT_PRESETS[str(backbone)]
            elif str(backbone).lower() == "vit":
                preset = {}  # dimensions come from Model config directly
            else:
                raise ValueError(
                    f"unknown MoCo backbone {backbone!r}; have resnet* / "
                    f"'vit' / {sorted(VIT_PRESETS)}"
                )
            vit_cfg = ViTConfig.from_model_config(
                {**preset, **{k: v for k, v in dict(model_cfg).items()
                              if v is not None},
                 "num_classes": 0, "dtype": dtype}
            )
        resnet_kw = {}
        if is_resnet and model_cfg.get("width"):
            resnet_kw["width"] = int(model_cfg["width"])

        class Encoder(nn.Module):
            """Backbone + projection head -> L2-normalized embeddings."""

            @nn.compact
            def __call__(self, images):
                if is_resnet:
                    h = build_resnet(
                        str(backbone), num_classes=0, dtype=dtype, **resnet_kw
                    )(images)
                else:
                    h = ViT(vit_cfg, name="vit")(images, deterministic=True)
                h = h.astype(jnp.float32)
                if mlp:  # MoCo v2 head
                    h = nn.Dense(h.shape[-1], name="proj_hidden")(h)
                    h = nn.relu(h)
                z = nn.Dense(dim, name="proj_out")(h)
                return z / jnp.linalg.norm(z, axis=-1, keepdims=True).clip(1e-12)

        return Encoder()

    def init_params(self, rng, batch):
        return self.nets.init(rng, jnp.asarray(batch["query"]))

    def init_extra_state(self, params, batch):
        """key-encoder params start as a copy of the query encoder; queue
        starts as random normalized vectors (reference randn+normalize)."""
        key0 = jax.random.normal(jax.random.PRNGKey(1234), (self.dim, self.K))
        key0 = key0 / jnp.linalg.norm(key0, axis=0, keepdims=True).clip(1e-12)
        return {
            "key_params": jax.tree.map(jnp.asarray, params),
            "queue": key0.astype(jnp.float32),
            "queue_ptr": jnp.zeros((), jnp.int32),
        }

    def loss_fn_extra(self, params, extra, batch, rng, train: bool):
        q = self.nets.apply({"params": params}, batch["query"])
        k = self.nets.apply({"params": extra["key_params"]}, batch["key"])
        k = jax.lax.stop_gradient(k)

        l_pos = jnp.einsum("nc,nc->n", q, k)[:, None]  # [b, 1]
        l_neg = jnp.einsum("nc,ck->nk", q, extra["queue"])  # [b, K]
        logits = jnp.concatenate([l_pos, l_neg], axis=1) / self.T
        logp = jax.nn.log_softmax(logits, axis=-1)
        loss = -logp[:, 0].mean()
        acc = (jnp.argmax(logits, axis=-1) == 0).mean()

        new_extra = dict(extra)
        if train:
            # FIFO enqueue: batch is global under GSPMD, so this IS the
            # all-gathered enqueue of the reference (moco.py concat_all_gather)
            b = k.shape[0]
            ptr = extra["queue_ptr"]
            idx = (ptr + jnp.arange(b)) % self.K
            new_queue = extra["queue"].at[:, idx].set(k.T.astype(jnp.float32))
            new_extra["queue"] = new_queue
            new_extra["queue_ptr"] = (ptr + b) % self.K
        return loss, {"contrast_acc": acc}, new_extra

    def post_update_extra(self, new_params, extra):
        m = self.m
        extra = dict(extra)
        extra["key_params"] = jax.tree.map(
            lambda kp, qp: m * kp + (1.0 - m) * qp, extra["key_params"], new_params
        )
        return extra

    def loss_fn(self, params, batch, rng, train: bool):
        raise RuntimeError("MOCOModule uses loss_fn_extra (extra state)")

    def input_spec(self):
        glb = self.cfg.Global
        model_cfg = self.cfg.Model
        size = int(model_cfg.get("image_size") or 224)
        b = glb.micro_batch_size or 1
        return {
            "query": jax.ShapeDtypeStruct((b, size, size, 3), jnp.float32),
            "key": jax.ShapeDtypeStruct((b, size, size, 3), jnp.float32),
        }

    def training_step_end(self, log: Dict) -> None:
        from fleetx_tpu.models.vision_module import log_images_per_sec

        log_images_per_sec(self.cfg, log)


class MOCOClsModule(BasicModule):
    """Linear-probe classification on a frozen MoCo backbone (reference
    MOCOClsModule, /root/reference/ppfleetx/models/vision_model/
    moco_module.py: backbone frozen, only the linear head trains).

    Batch contract: {"images": [b,H,W,C], "labels": [b]}. Backbone params
    restore from a MoCo pretraining checkpoint; gradients stop at the
    feature boundary, so the optimizer only moves the head (frozen backbone
    weights receive zero gradient)."""

    def get_model(self):
        import flax.linen as nn

        model_cfg = self.cfg.Model if hasattr(self.cfg, "Model") else self.cfg
        eng = getattr(self.cfg, "Engine", None) or {}
        dtype = resolve_compute_dtype(eng)
        backbone = str(model_cfg.get("backbone") or "resnet50")
        num_classes = int(model_cfg.get("num_classes") or 1000)
        resnet_kw = {}
        if model_cfg.get("width"):
            resnet_kw["width"] = int(model_cfg["width"])

        class LinearProbe(nn.Module):
            """Frozen-backbone linear classifier for MoCo evaluation
            (reference MOCOClsModule)."""

            @nn.compact
            def __call__(self, images):
                h = build_resnet(backbone, num_classes=0, dtype=dtype,
                                 **resnet_kw)(images)
                h = jax.lax.stop_gradient(h.astype(jnp.float32))
                return nn.Dense(num_classes, name="cls_head")(h)

        return LinearProbe()

    def init_params(self, rng, batch):
        return self.nets.init(rng, jnp.asarray(batch["images"]))

    def load_pretrained(self, params):
        """Copy the frozen backbone from a MoCo pretraining artifact
        (Model.pretrained: an orbax params dir, or an export dir holding
        one under 'params'). Leaves whose path+shape match transfer; the
        fresh cls_head stays; zero backbone matches is an error — silently
        probing random features is the failure mode this guards."""
        import os

        model_cfg = self.cfg.Model if hasattr(self.cfg, "Model") else self.cfg
        src_dir = model_cfg.get("pretrained")
        if not src_dir:
            logger.warning(
                "MOCOClsModule without Model.pretrained: the linear probe "
                "will run on a RANDOM frozen backbone"
            )
            return None
        import orbax.checkpoint as ocp

        path = os.path.abspath(src_dir)
        if os.path.isdir(os.path.join(path, "checkpoints")):
            path = os.path.join(path, "checkpoints")  # Trainer output_dir
        step_dirs = [d for d in os.listdir(path) if d.isdigit()] \
            if os.path.isdir(path) else []
        if step_dirs:
            # Trainer CheckpointManager layout: checkpoints/<step>/{state,meta}
            mgr = ocp.CheckpointManager(path)
            step = mgr.latest_step()
            restored = mgr.restore(
                step, args=ocp.args.Composite(state=ocp.args.StandardRestore())
            )
            source = restored["state"]["params"]
        else:
            if os.path.isdir(os.path.join(path, "params")):
                path = os.path.join(path, "params")  # export artifact
            source = ocp.StandardCheckpointer().restore(path)
            if isinstance(source, dict) and "params" in source:
                source = source["params"]

        flat_src = {
            tuple(str(getattr(k, "key", k)) for k in p): v
            for p, v in jax.tree_util.tree_flatten_with_path(source)[0]
        }
        hits = [0]

        def take(pth, leaf):
            key = tuple(str(getattr(k, "key", k)) for k in pth)
            cand = flat_src.get(key)
            if cand is not None and getattr(cand, "shape", None) == leaf.shape:
                hits[0] += 1
                return jnp.asarray(cand, leaf.dtype)
            return leaf

        out = jax.tree_util.tree_map_with_path(take, params)
        if hits[0] == 0:
            raise ValueError(
                f"Model.pretrained={src_dir!r} shares no matching weights "
                "with the linear-probe backbone — wrong checkpoint?"
            )
        logger.info("loaded %d pretrained backbone tensors from %s",
                    hits[0], src_dir)
        return out

    def weight_decay_mask(self):
        """Decay only the trainable head: stop_gradient freezes backbone
        gradients but decoupled weight decay would still erode the frozen
        backbone without this mask."""
        def mask(params):
            def is_head(path, leaf):
                return any(
                    str(getattr(k, "key", k)) == "cls_head" for k in path
                )

            return jax.tree_util.tree_map_with_path(is_head, params)

        return mask

    def loss_fn(self, params, batch, rng, train: bool):
        del rng, train
        logits = self.nets.apply({"params": params}, batch["images"])
        labels = batch["labels"].astype(jnp.int32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        loss = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
        acc = (jnp.argmax(logits, axis=-1) == labels).mean()
        return loss, {"acc": acc}

    def input_spec(self):
        glb = self.cfg.Global
        model_cfg = self.cfg.Model
        size = int(model_cfg.get("image_size") or 224)
        b = glb.micro_batch_size or 1
        return {"images": jax.ShapeDtypeStruct((b, size, size, 3), jnp.float32)}

    def training_step_end(self, log: Dict) -> None:
        from fleetx_tpu.models.vision_module import log_images_per_sec

        log_images_per_sec(self.cfg, log)
