"""ProteinFoldingModule — trains the folding trunk with BERT-style heads.

The reference ships the trunk + parallelism pieces and points at the
downstream HelixFold app for the full training recipe
(/root/reference/projects/protein_folding/README.md:1-7); this module gives
the trunk a runnable pretraining objective inside the framework: a
masked-MSA head on the trunk's MSA output (AlphaFold Suppl. Alg. 2 line 20
MaskedMsaHead, the trunk-only loss that needs no structure module) plus a
distogram head on the pair output (Suppl. 1.9.8), so configs can exercise
the full DistEmbeddingsAndEvoformer under the Trainer/DAP machinery.

Batch contract (jnp arrays, see tests/test_folding_trunk.py _trunk_batch):
  target_feat, msa_feat, seq_mask, msa_mask, aatype, residue_index,
  extra_msa*, optional template_*/prev_*, plus for the losses:
  bert_mask [B, S, R], true_msa [B, S, R] and (optional)
  pseudo_beta [B, R, 3] / pseudo_beta_mask [B, R].
"""

from __future__ import annotations

from typing import Dict

import flax.linen as nn
import jax
import jax.numpy as jnp

from fleetx_tpu.models import register_module
from fleetx_tpu.models.language_module import resolve_compute_dtype
from fleetx_tpu.models.module import BasicModule
from fleetx_tpu.models.protein.folding import (
    DistEmbeddingsAndEvoformer,
    FoldingConfig,
)
from fleetx_tpu.models.protein.template import dgram_from_positions

__all__ = ["ProteinFoldingModule"]


class _TrunkWithHeads(nn.Module):
    cfg: FoldingConfig
    msa_classes: int = 23
    distogram_bins: int = 64

    @nn.compact
    def __call__(self, batch):
        out = DistEmbeddingsAndEvoformer(self.cfg, name="evoformer")(batch)
        out["msa_logits"] = nn.Dense(
            self.msa_classes, param_dtype=jnp.float32, dtype=jnp.float32,
            name="masked_msa_head",
        )(out["msa"].astype(jnp.float32))
        pair = out["pair"].astype(jnp.float32)
        half_logits = nn.Dense(
            self.distogram_bins, param_dtype=jnp.float32, dtype=jnp.float32,
            name="distogram_head",
        )(pair)
        # symmetrize (distances are symmetric)
        out["distogram_logits"] = half_logits + jnp.swapaxes(half_logits, -2, -3)
        return out


@register_module("ProteinFoldingModule")
class ProteinFoldingModule(BasicModule):
    """Folding-trunk training module: masked-MSA BERT loss over the Evoformer
    stack with DAP sharding."""
    def get_model(self):
        model_cfg = self.cfg.Model
        eng = getattr(self.cfg, "Engine", None) or {}
        dtype = resolve_compute_dtype(eng)
        fc = FoldingConfig.from_model_config({**dict(model_cfg), "dtype": dtype})
        self.folding_cfg = fc
        self.dist_min = float(model_cfg.get("distogram_min_bin") or 2.3125)
        self.dist_max = float(model_cfg.get("distogram_max_bin") or 21.6875)
        self.dist_bins = int(model_cfg.get("distogram_num_bins") or 64)
        return _TrunkWithHeads(fc, distogram_bins=self.dist_bins)

    def init_params(self, rng, batch):
        return self.nets.init(rng, self._jnp(batch))

    @staticmethod
    def _jnp(batch) -> Dict[str, jnp.ndarray]:
        return {k: jnp.asarray(v) for k, v in batch.items()}

    def loss_fn(self, params, batch, rng, train: bool):
        del rng, train
        out = self.nets.apply({"params": params["params"]}
                              if "params" in params else {"params": params},
                              self._jnp(batch))
        metrics: Dict[str, jnp.ndarray] = {}
        loss = jnp.float32(0.0)

        bert_mask = batch.get("bert_mask")
        if bert_mask is not None:
            logp = jax.nn.log_softmax(out["msa_logits"], axis=-1)
            true_msa = batch["true_msa"].astype(jnp.int32)
            ll = jnp.take_along_axis(logp, true_msa[..., None], axis=-1)[..., 0]
            m = bert_mask.astype(jnp.float32)
            msa_loss = -jnp.sum(ll * m) / (jnp.sum(m) + 1e-8)
            metrics["masked_msa_loss"] = msa_loss
            loss = loss + msa_loss

        pb = batch.get("pseudo_beta")
        if pb is not None:
            dgram = dgram_from_positions(
                pb, num_bins=self.dist_bins, min_bin=self.dist_min,
                max_bin=self.dist_max,
            )  # one-hot target bins [B, R, R, bins]
            logp = jax.nn.log_softmax(out["distogram_logits"], axis=-1)
            pbm = batch.get("pseudo_beta_mask")
            m2d = (pbm[..., :, None] * pbm[..., None, :]
                   if pbm is not None else jnp.ones(logp.shape[:-1]))
            ll = jnp.sum(logp * dgram, axis=-1)
            dist_loss = -jnp.sum(ll * m2d) / (jnp.sum(m2d) + 1e-8)
            metrics["distogram_loss"] = dist_loss
            loss = loss + dist_loss

        return loss, metrics
