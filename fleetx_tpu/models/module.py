"""Module contract — the Lightning-style boundary between engine and model
(reference BasicModule, /root/reference/ppfleetx/core/module/basic_module.py:
29-86). JAX twist: steps are pure functions of (params, batch, rng) returning
(loss, metrics) so the engine can jit/shard them; the module owns model
construction, loss, and batch pre/post hooks, not the training loop.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax

__all__ = ["BasicModule"]


class BasicModule:
    """Subclasses provide the model + loss; the Trainer owns jit/sharding."""

    def __init__(self, cfg):
        self.cfg = cfg
        # QAT (reference Quantization config section, qat_gpt_*.yaml +
        # eager_engine.py:159-160 _quant_mode): when enabled, loss_fns run
        # the forward on fake-quantized weights (STE gradients).
        q = (cfg.get("Quantization") or {}) if hasattr(cfg, "get") else {}
        self.quant_enabled = bool(q.get("enable"))
        self.quant_bits = int(q.get("weight_bits") or 8)
        self.quant_act = bool(q.get("activation_quantize_type"))
        self.act_bits = int(q.get("activation_bits") or 8)
        if self.quant_enabled:
            from fleetx_tpu.utils.log import logger

            wqt = q.get("weight_quantize_type")
            if wqt not in (None, "abs_max", "channel_wise_abs_max"):
                logger.warning(
                    "weight_quantize_type=%r unsupported; using per-channel "
                    "abs_max", wqt)
            aqt = q.get("activation_quantize_type")
            if aqt and aqt not in ("abs_max", "moving_average_abs_max"):
                logger.warning(
                    "activation_quantize_type=%r unsupported; using dynamic "
                    "abs_max", aqt)
            elif aqt == "moving_average_abs_max":
                logger.info(
                    "activation QAT uses dynamic per-tensor abs_max; the "
                    "moving average's purpose (static serving scales) does "
                    "not apply to the weight-only int8 export")
        self.nets = self.get_model()

    def act_quant_ctx(self):
        """Context manager fake-quantizing every nn.Dense INPUT during the
        wrapped apply (paddleslim activation QAT: observers on
        quantizable_layer_type=Linear inputs, reference
        qat_gpt_345M_mp8.yaml). A flax method interceptor keeps it
        model-family-agnostic — no per-model wiring, works under jit since
        interception happens at trace time. Identity context when disabled."""
        import contextlib

        if not (self.quant_enabled and self.quant_act):
            return contextlib.nullcontext()
        import flax.linen as nn

        from fleetx_tpu.ops.quant import fake_quant_act

        # paddleslim quantizable_layer_type = Conv2D + Linear (+ the mp
        # parallel Linears, which GSPMD folds into the same DenseGeneral)
        quantizable = (nn.Dense, nn.DenseGeneral, nn.Conv)

        def interceptor(next_fun, args, kwargs, context):
            if (isinstance(context.module, quantizable)
                    and context.method_name == "__call__" and args):
                args = (fake_quant_act(args[0], self.act_bits),) + args[1:]
            return next_fun(*args, **kwargs)

        return nn.intercept_methods(interceptor)

    def maybe_fake_quant(self, params):
        """Fake-quantize eligible weights for QAT; identity otherwise."""
        if not self.quant_enabled:
            return params
        from fleetx_tpu.ops.quant import fake_quant_tree

        return fake_quant_tree(params, bits=self.quant_bits)

    def load_pretrained(self, params):
        """Optionally map pretrained weights onto freshly initialized params
        (called by the Trainer after init). Return the updated tree, or None
        for no-op. Modules that finetune from a different architecture
        (e.g. a linear probe on a MoCo encoder) override this."""
        return None

    def weight_decay_mask(self):
        """Optional weight-decay mask fn(params)->bool tree for the
        optimizer; None uses the standard no-norm/no-bias mask. Modules with
        frozen subtrees override this so decay can't erode frozen weights."""
        return None

    # --- construction -----------------------------------------------------
    def get_model(self):
        raise NotImplementedError

    def init_params(self, rng: jax.Array, batch) -> Any:
        """Initialize (possibly abstractly, under jax.eval_shape) params."""
        raise NotImplementedError

    # --- steps (pure; engine jits them) ----------------------------------
    def loss_fn(
        self, params, batch, rng: Optional[jax.Array], train: bool
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Return (scalar loss, aux metrics dict)."""
        raise NotImplementedError

    def eval_metrics(self, params, batch) -> Dict[str, jax.Array]:
        loss, metrics = self.loss_fn(params, batch, None, train=False)
        return {"loss": loss, **metrics}

    # --- non-parameter training state (MoCo queue/momentum encoder, EMA...)
    def init_extra_state(self, params, batch):
        """Return a pytree of extra train state, or None. When not None the
        engine threads it through ``loss_fn_extra`` and
        ``post_update_extra`` each step (kept in TrainState.extra,
        checkpointed alongside params)."""
        return None

    def loss_fn_extra(self, params, extra, batch, rng, train: bool):
        """(loss, aux metrics, new_extra) for modules with extra state."""
        raise NotImplementedError

    def post_update_extra(self, new_params, extra):
        """Called after the optimizer step (e.g. momentum-encoder EMA)."""
        return extra

    # --- hooks ------------------------------------------------------------
    def pretreating_batch(self, batch):
        """Host-side batch re-pack hook (reference PP repacking,
        language_module.py:198-204)."""
        return batch

    def training_step_end(self, log: Dict[str, Any]) -> None:
        pass

    def validation_step_end(self, log: Dict[str, Any]) -> None:
        pass

    def input_spec(self):
        """Abstract (shape, dtype) spec of one device batch, for export."""
        return None
