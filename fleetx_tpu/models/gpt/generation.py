"""Autoregressive generation with kv-cache — greedy / temperature sampling /
top-k / top-p, plus logits processors.

Parity with the reference decode stack (/root/reference/ppfleetx/models/
language_model/gpt/dygraph/single_model.py:781-1247 ``GPTForGeneration`` and
processor.py logits processors), redesigned for XLA: the decode loop is a
``lax.while_loop`` over a static-shape token buffer (no dynamic shapes), the
cache is the flax 'cache' collection, and one compiled step serves the whole
generation — the reference re-runs a Python loop per token.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

__all__ = ["GenerationConfig", "generate", "process_logits"]


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    max_length: int = 64  # new tokens to generate
    min_length: int = 0
    decode_strategy: str = "sampling"  # 'greedy' | 'sampling'
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    eos_token_id: int = 50256
    pad_token_id: int = 50256
    forced_eos_token_id: Optional[int] = None

    @classmethod
    def from_config(cls, gen_cfg) -> "GenerationConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in dict(gen_cfg or {}).items() if k in known and v is not None}
        if "max_dec_len" in dict(gen_cfg or {}):
            kw["max_length"] = gen_cfg["max_dec_len"]
        return cls(**kw)


def process_logits(logits, tokens, cur_len, cfg: GenerationConfig):
    """Min-length EOS suppression, repetition penalty, forced EOS (reference
    processor.py: MinLengthLogitsProcessor, RepetitionPenaltyLogitsProcessor,
    ForcedEOSTokenLogitsProcessor)."""
    vocab = logits.shape[-1]
    if cfg.min_length > 0:
        logits = jnp.where(
            (cur_len < cfg.min_length)
            & (jnp.arange(vocab)[None, :] == cfg.eos_token_id),
            -1e9,
            logits,
        )
    if cfg.repetition_penalty != 1.0:
        # penalize every token already present in the sequence
        onehot_seen = jax.nn.one_hot(tokens, vocab, dtype=jnp.bool_.dtype).any(axis=1)
        penalized = jnp.where(
            logits > 0, logits / cfg.repetition_penalty, logits * cfg.repetition_penalty
        )
        logits = jnp.where(onehot_seen, penalized, logits)
    if cfg.forced_eos_token_id is not None:
        at_last = cur_len >= (tokens.shape[1] - 1)
        forced = jnp.full_like(logits, -1e9).at[:, cfg.forced_eos_token_id].set(0.0)
        logits = jnp.where(at_last, forced, logits)
    return logits


def _sample(logits, rng, cfg: GenerationConfig):
    if cfg.decode_strategy == "greedy":
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(cfg.temperature, 1e-6)
    if cfg.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -cfg.top_k][:, None]
        logits = jnp.where(logits < kth, -1e9, logits)
    if cfg.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; always keep the best
        cutoff_idx = jnp.sum(cum < cfg.top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -1e9, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def generate(
    model,
    variables: Dict[str, Any],
    input_ids: jax.Array,
    gen_cfg: GenerationConfig,
    rng: Optional[jax.Array] = None,
    attention_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Returns [batch, prompt_len + max_length] tokens (padded after EOS).

    Prefill runs the full prompt once to populate the cache; the while_loop
    then decodes one token per iteration with static shapes throughout.
    """
    if rng is None:
        rng = jax.random.PRNGKey(0)
    b, prompt_len = input_ids.shape
    total_len = prompt_len + gen_cfg.max_length

    params = variables["params"] if "params" in variables else variables

    # static token buffer
    tokens = jnp.full((b, total_len), gen_cfg.pad_token_id, jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, input_ids.astype(jnp.int32), (0, 0))

    # init cache at full length via a dummy decode-mode init
    init_vars = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((b, 1), jnp.int32),
        jnp.zeros((b, 1), jnp.int32),
        decode=True,
    )
    cache = init_vars["cache"]

    # prefill: feed the whole prompt, cache fills positions [0, prompt_len)
    pos = jnp.arange(prompt_len, dtype=jnp.int32)[None, :]
    logits, mut = model.apply(
        {"params": params, "cache": cache},
        input_ids.astype(jnp.int32),
        pos,
        decode=True,
        mutable=["cache"],
    )
    cache = mut["cache"]
    rng, step_rng = jax.random.split(rng)
    next_logits = process_logits(
        logits[:, -1, :], tokens, jnp.asarray(prompt_len), gen_cfg
    )
    next_tok = _sample(next_logits, step_rng, gen_cfg).astype(jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, next_tok[:, None], (0, prompt_len))
    finished = next_tok == gen_cfg.eos_token_id

    def cond(state):
        i, _, _, finished, _ = state
        return (i < total_len) & ~jnp.all(finished)

    def body(state):
        i, tokens, cache, finished, rng = state
        cur = jax.lax.dynamic_slice(tokens, (0, i - 1), (b, 1))
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            cur,
            (i - 1) * jnp.ones((b, 1), jnp.int32),
            decode=True,
            mutable=["cache"],
        )
        cache = mut["cache"]
        rng, step_rng = jax.random.split(rng)
        nl = process_logits(logits[:, -1, :], tokens, i, gen_cfg)
        tok = _sample(nl, step_rng, gen_cfg).astype(jnp.int32)
        tok = jnp.where(finished, gen_cfg.pad_token_id, tok)
        tokens = jax.lax.dynamic_update_slice(tokens, tok[:, None], (0, i))
        finished = finished | (tok == gen_cfg.eos_token_id)
        return i + 1, tokens, cache, finished, rng

    _, tokens, _, _, _ = jax.lax.while_loop(
        cond, body, (jnp.asarray(prompt_len + 1), tokens, cache, finished, rng)
    )
    return tokens
