"""Autoregressive generation with kv-cache — greedy / temperature sampling /
top-k / top-p, plus logits processors.

Parity with the reference decode stack (/root/reference/ppfleetx/models/
language_model/gpt/dygraph/single_model.py:781-1247 ``GPTForGeneration`` and
processor.py logits processors), redesigned for XLA: the decode loop is a
``lax.while_loop`` over a static-shape token buffer (no dynamic shapes), the
cache is the flax 'cache' collection, and one compiled step serves the whole
generation — the reference re-runs a Python loop per token.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

__all__ = ["GenerationConfig", "generate", "process_logits"]


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Decode-strategy knobs (reference GPTForGeneration config surface:
    top-k/p, beams, penalties, forced tokens)."""
    max_length: int = 64  # new tokens to generate
    min_length: int = 0
    decode_strategy: str = "sampling"  # 'greedy' | 'sampling' | 'beam_search'
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    eos_token_id: int = 50256
    pad_token_id: int = 50256
    forced_eos_token_id: Optional[int] = None
    # beam search (reference config surface single_model.py:803-818)
    num_beams: int = 1
    num_beam_groups: int = 1
    diversity_rate: float = 0.0
    length_penalty: float = 0.0
    early_stopping: bool = False
    forced_bos_token_id: Optional[int] = None
    num_return_sequences: int = 1

    @classmethod
    def from_config(cls, gen_cfg) -> "GenerationConfig":
        d = dict(gen_cfg or {})
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in known and v is not None}
        if d.get("max_dec_len") is not None:
            kw["max_length"] = d["max_dec_len"]
        if d.get("min_dec_len") is not None:
            kw["min_length"] = d["min_dec_len"]
        return cls(**kw)


def process_logits(logits, tokens, cur_len, cfg: GenerationConfig, *,
                   prompt_len=0, token_valid=None):
    """Min-length EOS suppression, repetition penalty, forced EOS (reference
    processor.py: MinLengthLogitsProcessor, RepetitionPenaltyLogitsProcessor,
    ForcedEOSTokenLogitsProcessor).

    ``cur_len`` is the absolute buffer position; min_length counts DECODED
    tokens, so the EOS ban runs while cur_len < prompt_len + min_length
    (the reference offsets min_length by the input length,
    single_model.py:1222). ``token_valid`` [b, total_len] marks buffer slots
    holding real tokens (False for left-pad slots and not-yet-generated
    tail), keeping the repetition penalty off pad/eos ghosts."""
    vocab = logits.shape[-1]
    if cfg.min_length > 0:
        logits = jnp.where(
            (cur_len < prompt_len + cfg.min_length)
            & (jnp.arange(vocab)[None, :] == cfg.eos_token_id),
            -1e9,
            logits,
        )
    if cfg.repetition_penalty != 1.0:
        # penalize every token already actually emitted/fed (not buffer pads)
        seen_pos = jnp.arange(tokens.shape[1])[None, :] < cur_len
        if token_valid is not None:
            seen_pos = seen_pos & token_valid
        onehot_seen = (
            jax.nn.one_hot(tokens, vocab, dtype=jnp.bool_.dtype)
            & seen_pos[..., None]
        ).any(axis=1)
        penalized = jnp.where(
            logits > 0, logits / cfg.repetition_penalty, logits * cfg.repetition_penalty
        )
        logits = jnp.where(onehot_seen, penalized, logits)
    if cfg.forced_eos_token_id is not None:
        at_last = cur_len >= (tokens.shape[1] - 1)
        forced = jnp.full_like(logits, -1e9).at[:, cfg.forced_eos_token_id].set(0.0)
        logits = jnp.where(at_last, forced, logits)
    return logits


def right_size_decode_cache(model, total_len: int):
    """(model, cache_len) with the kv cache sized to the decode span.

    Attention streams the whole cache every step, so a 1024-position cache
    for a 256-token decode would 4x the per-step HBM traffic; unless the
    caller preset ``decode_cache_len``, clone the model with the cache
    capped at ``total_len``. A preset that cannot hold the decode raises —
    an undersized cache would silently clamp writes to the last slot and
    corrupt the output."""
    if model.cfg.decode_cache_len is None:
        model = model.clone(
            cfg=dataclasses.replace(model.cfg, decode_cache_len=total_len))
    cache_len = model.cfg.decode_cache_len
    if cache_len < total_len:
        raise ValueError(
            f"decode_cache_len({cache_len}) cannot hold prompt_len + "
            f"max_length = {total_len}"
        )
    return model, cache_len


def _sample(logits, rng, cfg: GenerationConfig):
    if cfg.decode_strategy == "greedy":
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(cfg.temperature, 1e-6)
    if cfg.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -cfg.top_k][:, None]
        logits = jnp.where(logits < kth, -1e9, logits)
    if cfg.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; always keep the best
        cutoff_idx = jnp.sum(cum < cfg.top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -1e9, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def generate(
    model,
    variables: Dict[str, Any],
    input_ids: jax.Array,
    gen_cfg: GenerationConfig,
    rng: Optional[jax.Array] = None,
    attention_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Returns [batch, prompt_len + max_length] tokens (padded after EOS).

    Prefill runs the full prompt once to populate the cache; the while_loop
    then decodes one token per iteration with static shapes throughout.
    ``attention_mask`` [b, prompt_len] marks real prompt tokens (0 = left
    pad): pad slots are never attended to, and position ids are shifted so
    each row's first real token sits at position 0.
    """
    if gen_cfg.decode_strategy == "beam_search":
        from fleetx_tpu.models.gpt.beam_search import beam_search

        out = beam_search(model, variables, jnp.asarray(input_ids), gen_cfg,
                          attention_mask=attention_mask)
        # flatten [b, num_return_sequences, L] to the reference's
        # expand_inputs_for_generation row layout [b*nret, L]
        return out.reshape(-1, out.shape[-1])
    if rng is None:
        rng = jax.random.PRNGKey(0)
    b, prompt_len = input_ids.shape
    total_len = prompt_len + gen_cfg.max_length
    max_pos = model.cfg.max_position_embeddings
    if total_len > max_pos:
        raise ValueError(
            f"prompt_len({prompt_len}) + max_length({gen_cfg.max_length}) "
            f"exceeds max_position_embeddings({max_pos})"
        )
    model, cache_len = right_size_decode_cache(model, total_len)

    params = variables["params"] if "params" in variables else variables
    if attention_mask is None:
        attention_mask = jnp.ones((b, prompt_len), jnp.int32)
    attention_mask = attention_mask.astype(jnp.int32)
    # per-row left-pad count; generated token at buffer slot i has position
    # i - pad_count (first REAL token of each row sits at position 0)
    pad_counts = prompt_len - attention_mask.sum(axis=1)
    # which kv-cache slots hold real tokens: prompt slots per the mask,
    # everything generated afterwards is real
    kv_valid = jnp.concatenate(
        [attention_mask.astype(bool),
         jnp.ones((b, cache_len - prompt_len), bool)], axis=1,
    )
    kv_mask = kv_valid[:, None, None, :]  # [b, 1, 1(q), cache_len(kv)]
    # buffer-slot validity for the repetition penalty
    token_valid = jnp.concatenate(
        [attention_mask.astype(bool),
         jnp.ones((b, total_len - prompt_len), bool)], axis=1,
    )

    # static token buffer
    tokens = jnp.full((b, total_len), gen_cfg.pad_token_id, jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, input_ids.astype(jnp.int32), (0, 0))

    # init cache at full length: the fresh cache is deterministically zeros
    # (+ zero index), so build it from shapes only — no param sampling or
    # forward trace per call
    cache_shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((b, 1), jnp.int32),
            jnp.zeros((b, 1), jnp.int32),
            decode=True,
        )
    )["cache"]
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)

    # prefill: feed the whole prompt, cache fills positions [0, prompt_len)
    pos = jnp.clip(jnp.cumsum(attention_mask, axis=1) - 1, 0)
    logits, mut = model.apply(
        {"params": params, "cache": cache},
        input_ids.astype(jnp.int32),
        pos,
        kv_mask,
        decode=True,
        mutable=["cache"],
    )
    cache = mut["cache"]
    rng, step_rng = jax.random.split(rng)
    next_logits = process_logits(
        logits[:, -1, :], tokens, jnp.asarray(prompt_len), gen_cfg,
        prompt_len=prompt_len, token_valid=token_valid,
    )
    next_tok = _sample(next_logits, step_rng, gen_cfg).astype(jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, next_tok[:, None], (0, prompt_len))
    finished = next_tok == gen_cfg.eos_token_id

    def cond(state):
        i, _, _, finished, _ = state
        return (i < total_len) & ~jnp.all(finished)

    def body(state):
        i, tokens, cache, finished, rng = state
        cur = jax.lax.dynamic_slice(tokens, (0, i - 1), (b, 1))
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            cur,
            (i - 1 - pad_counts)[:, None].astype(jnp.int32),
            kv_mask,
            decode=True,
            mutable=["cache"],
        )
        cache = mut["cache"]
        rng, step_rng = jax.random.split(rng)
        nl = process_logits(logits[:, -1, :], tokens, i, gen_cfg,
                            prompt_len=prompt_len, token_valid=token_valid)
        tok = _sample(nl, step_rng, gen_cfg).astype(jnp.int32)
        tok = jnp.where(finished, gen_cfg.pad_token_id, tok)
        tokens = jax.lax.dynamic_update_slice(tokens, tok[:, None], (0, i))
        finished = finished | (tok == gen_cfg.eos_token_id)
        return i + 1, tokens, cache, finished, rng

    _, tokens, _, _, _ = jax.lax.while_loop(
        cond, body, (jnp.asarray(prompt_len + 1), tokens, cache, finished, rng)
    )
    return tokens
