"""Autoregressive generation with kv-cache — greedy / temperature sampling /
top-k / top-p, plus logits processors.

Parity with the reference decode stack (/root/reference/ppfleetx/models/
language_model/gpt/dygraph/single_model.py:781-1247 ``GPTForGeneration`` and
processor.py logits processors), redesigned for XLA: the decode loop is a
``lax.while_loop`` over a static-shape token buffer (no dynamic shapes), the
cache is the flax 'cache' collection, and one compiled step serves the whole
generation — the reference re-runs a Python loop per token.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

__all__ = ["GenerationConfig", "generate", "process_logits", "prompt_seen",
           "mark_seen", "init_decode_cache", "decode_step"]


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Decode-strategy knobs (reference GPTForGeneration config surface:
    top-k/p, beams, penalties, forced tokens)."""
    max_length: int = 64  # new tokens to generate
    min_length: int = 0
    decode_strategy: str = "sampling"  # 'greedy' | 'sampling' | 'beam_search'
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    eos_token_id: int = 50256
    pad_token_id: int = 50256
    forced_eos_token_id: Optional[int] = None
    # beam search (reference config surface single_model.py:803-818)
    num_beams: int = 1
    num_beam_groups: int = 1
    diversity_rate: float = 0.0
    length_penalty: float = 0.0
    early_stopping: bool = False
    forced_bos_token_id: Optional[int] = None
    num_return_sequences: int = 1

    @classmethod
    def from_config(cls, gen_cfg) -> "GenerationConfig":
        d = dict(gen_cfg or {})
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in known and v is not None}
        if d.get("max_dec_len") is not None:
            kw["max_length"] = d["max_dec_len"]
        if d.get("min_dec_len") is not None:
            kw["min_length"] = d["min_dec_len"]
        # surface config typos (e.g. `topk` for `top_k`) instead of silently
        # decoding with defaults; aliases + keys other components read from
        # the Generation section are not typos (use_cache: the kv-cache loop
        # is unconditional here; vocab_dir/seed: tokenizer + rng plumbing)
        aliases = {"max_dec_len", "min_dec_len", "use_cache", "vocab_dir",
                   "seed"}
        ignored = sorted(k for k in d if k not in known and k not in aliases)
        if ignored:
            from fleetx_tpu.utils.log import logger

            logger.warning(
                "GenerationConfig.from_config ignoring unknown keys %s "
                "(known: %s)", ignored, sorted(known | aliases),
            )
        return cls(**kw)


def process_logits(logits, seen, cur_len, cfg: GenerationConfig, *,
                   prompt_len=0, total_len=None):
    """Min-length EOS suppression, repetition penalty, forced EOS (reference
    processor.py: MinLengthLogitsProcessor, RepetitionPenaltyLogitsProcessor,
    ForcedEOSTokenLogitsProcessor).

    ``cur_len`` is the absolute buffer position; min_length counts DECODED
    tokens, so the EOS ban runs while cur_len < prompt_len + min_length
    (the reference offsets min_length by the input length,
    single_model.py:1222). ``seen`` is the [b, vocab] bool scoreboard of
    tokens already emitted/fed (required iff repetition_penalty != 1.0) —
    carried through the decode loop and updated in O(vocab) per step via
    :func:`mark_seen`, replacing the per-step O(total_len * vocab) one-hot
    rebuild over the whole token buffer. ``total_len`` is the token-buffer
    length (forced EOS fires at its last slot)."""
    vocab = logits.shape[-1]
    if cfg.min_length > 0:
        logits = jnp.where(
            (cur_len < prompt_len + cfg.min_length)
            & (jnp.arange(vocab)[None, :] == cfg.eos_token_id),
            -1e9,
            logits,
        )
    if cfg.repetition_penalty != 1.0:
        if seen is None:
            raise ValueError("repetition_penalty != 1.0 needs a seen-token "
                             "scoreboard (see prompt_seen/mark_seen)")
        penalized = jnp.where(
            logits > 0, logits / cfg.repetition_penalty, logits * cfg.repetition_penalty
        )
        logits = jnp.where(seen, penalized, logits)
    if cfg.forced_eos_token_id is not None:
        if total_len is None:
            raise ValueError("forced_eos_token_id needs total_len")
        at_last = cur_len >= (total_len - 1)
        forced = jnp.full_like(logits, -1e9).at[:, cfg.forced_eos_token_id].set(0.0)
        logits = jnp.where(at_last, forced, logits)
    return logits


def prompt_seen(input_ids, attention_mask, vocab: int):
    """[b, vocab] bool scoreboard of the tokens each prompt row actually
    contains (left-pad slots excluded). One O(prompt_len * vocab) pass at
    prefill; decode steps then extend it with :func:`mark_seen`."""
    onehot = jax.nn.one_hot(input_ids, vocab, dtype=jnp.bool_.dtype)
    return (onehot & attention_mask.astype(bool)[..., None]).any(axis=1)


def mark_seen(seen, tok):
    """Fold one sampled token [b] into the [b, vocab] scoreboard — O(vocab)
    per step vs the O(total_len * vocab) rebuild it replaces."""
    return seen | jax.nn.one_hot(tok, seen.shape[-1], dtype=jnp.bool_.dtype)


def right_size_decode_cache(model, total_len: int):
    """(model, cache_len) with the kv cache sized to the decode span.

    The dense fallback streams the whole cache every step, so a
    1024-position cache for a 256-token decode would 4x its per-step HBM
    traffic (the flash-decode kernel reads only the live prefix, but a
    right-sized buffer still saves HBM and beam-reorder traffic); unless
    the caller preset ``decode_cache_len``, clone the model with the cache
    capped at ``total_len`` (rounded up to the flash kernel's 8-row tile).
    A preset that cannot hold the decode raises — an undersized cache
    would silently clamp writes to the last slot and corrupt the output."""
    if model.cfg.decode_cache_len is None:
        cache_len = total_len
        if model.cfg.use_flash_attention:
            # round up to the flash-decode kernel's 8-row KV tile so the
            # Pallas fast path engages for any prompt/gen split; the kernel
            # never reads past cache_index, so the extra slots cost nothing
            cache_len += -cache_len % 8
        model = model.clone(
            cfg=dataclasses.replace(model.cfg, decode_cache_len=cache_len))
    cache_len = model.cfg.decode_cache_len
    if cache_len < total_len:
        raise ValueError(
            f"decode_cache_len({cache_len}) cannot hold prompt_len + "
            f"max_length = {total_len}"
        )
    return model, cache_len


def init_decode_cache(model, batch: int):
    """Zero decode kv-cache for ``batch`` rows at the model's cache length.

    The fresh cache is deterministically zeros (+ zero index), so it is
    built from ``eval_shape`` only — no param sampling or forward trace.
    THE cache constructor for every decode driver: ``generate()``,
    ``beam_search()``, and the continuous-batching serving engine
    (fleetx_tpu/serving/) all start from this tree, so its layout
    ([batch, cache_len, heads, head_dim] per layer + a scalar
    ``cache_index``; [num_pages, page_size, heads, head_dim] shared pages
    when the model carries ``cfg.decode_num_pages``) is defined in exactly
    one place."""
    cache_shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((batch, 1), jnp.int32),
            jnp.zeros((batch, 1), jnp.int32),
            decode=True,
        )
    )["cache"]
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)


def decode_step(model, params, cache, input_ids, position_ids, kv_mask=None,
                cache_positions=None, block_tables=None):
    """One cached decode forward: ``(logits, new_cache)``.

    The single reusable step both the ``generate()`` loop body and the
    serving engine's scheduler tick are built from (multi-token
    ``input_ids`` is the prefill case). ``cache_positions`` ([b] int32,
    optional) routes each row's kv write to its own offset — the
    continuous-batching path where slots sit at different decode depths;
    None keeps the shared ``cache_index`` scalar (the one-shot loop).
    ``block_tables`` ([b, pages_per_row] int32) comes along when the model
    carries a paged decode cache (``cfg.decode_num_pages``): each row's
    logical positions then live in the shared page pool at the physical
    pages its table names (serving/cache_manager.py)."""
    logits, mut = model.apply(
        {"params": params, "cache": cache},
        input_ids,
        position_ids,
        kv_mask,
        decode=True,
        cache_positions=cache_positions,
        block_tables=block_tables,
        mutable=["cache"],
    )
    return logits, mut["cache"]


def _top_p_cutoff_bisect(logits, top_p, iters: int = 40):
    """Probability threshold t such that keeping {prob >= t} matches the
    smallest descending-sorted prefix with cumulative prob >= top_p.
    ``top_p`` is a python float or a broadcastable [b, 1] array (the
    serving engine passes per-request values); rows with top_p >= 1 keep
    the whole distribution (the threshold bisects to 0).

    Bisection over the threshold: each step is one O(vocab) masked-sum VPU
    pass, replacing the O(vocab log vocab) full sort (TPU sorts lower to
    sorting networks — the dominant per-step scalar cost at GPT vocab
    sizes). The returned t always satisfies mass({prob >= t}) >= top_p, so
    the kept set is never too small and always contains the argmax; at
    float32 resolution near-tied probabilities at the cutoff may keep a
    tie the sort-based version would have dropped (measure-zero for real
    logits, and sampling is stochastic there anyway)."""
    probs = jax.nn.softmax(logits, axis=-1)

    def bisect(_, bounds):
        lo, hi = bounds
        mid = 0.5 * (lo + hi)
        mass = jnp.sum(jnp.where(probs >= mid, probs, 0.0), axis=-1,
                       keepdims=True)
        keep = mass >= top_p
        return jnp.where(keep, mid, lo), jnp.where(keep, hi, mid)

    lo = jnp.zeros((logits.shape[0], 1), jnp.float32)
    hi = jnp.full((logits.shape[0], 1), 1.1, jnp.float32)  # mass(>=1.1) == 0
    lo, _ = jax.lax.fori_loop(0, iters, bisect, (lo, hi))
    return probs, lo


def _sample(logits, rng, cfg: GenerationConfig):
    if cfg.decode_strategy == "greedy":
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(cfg.temperature, 1e-6)
    vocab = logits.shape[-1]
    # clamp: top_k >= vocab keeps the whole distribution (the previous
    # full-sort indexing crashed on [:, -top_k] out of range)
    top_k = min(cfg.top_k, vocab)
    if 0 < top_k < vocab:
        # one partial sort serves both filters: lax.top_k streams the vocab
        # once; the old path ran TWO full jnp.sort calls over [b, vocab]
        vals = jax.lax.top_k(logits, top_k)[0]  # descending [b, top_k]
        logits = jnp.where(logits < vals[:, -1:], -1e9, logits)
        if cfg.top_p < 1.0:
            # top-p inside the top-k survivors: the masked tail underflows
            # to exactly 0 probability, so softmax over `vals` equals the
            # full filtered softmax and the same partial sort is reused
            probs = jax.nn.softmax(vals, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            cutoff_idx = jnp.minimum(
                jnp.sum(cum < cfg.top_p, axis=-1, keepdims=True), top_k - 1
            )
            cutoff = jnp.take_along_axis(vals, cutoff_idx, axis=-1)
            logits = jnp.where(logits < cutoff, -1e9, logits)
    elif cfg.top_p < 1.0:
        # top_k off (or clamped to the whole vocab, a no-op filter): no
        # partial sort to piggyback on — bisect the probability threshold
        probs, thresh = _top_p_cutoff_bisect(logits, cfg.top_p)
        logits = jnp.where(probs >= thresh, logits, -1e9)
    return jax.random.categorical(rng, logits, axis=-1)


def generate(
    model,
    variables: Dict[str, Any],
    input_ids: jax.Array,
    gen_cfg: GenerationConfig,
    rng: Optional[jax.Array] = None,
    attention_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Returns [batch, prompt_len + max_length] tokens (padded after EOS).

    Prefill runs the full prompt once to populate the cache; the while_loop
    then decodes one token per iteration with static shapes throughout.
    ``attention_mask`` [b, prompt_len] marks real prompt tokens (0 = left
    pad): pad slots are never attended to, and position ids are shifted so
    each row's first real token sits at position 0.
    """
    if gen_cfg.decode_strategy == "beam_search":
        from fleetx_tpu.models.gpt.beam_search import beam_search

        out = beam_search(model, variables, jnp.asarray(input_ids), gen_cfg,
                          attention_mask=attention_mask)
        # flatten [b, num_return_sequences, L] to the reference's
        # expand_inputs_for_generation row layout [b*nret, L]
        return out.reshape(-1, out.shape[-1])
    if rng is None:
        rng = jax.random.PRNGKey(0)
    b, prompt_len = input_ids.shape
    total_len = prompt_len + gen_cfg.max_length
    max_pos = model.cfg.max_position_embeddings
    if total_len > max_pos:
        raise ValueError(
            f"prompt_len({prompt_len}) + max_length({gen_cfg.max_length}) "
            f"exceeds max_position_embeddings({max_pos})"
        )
    model, cache_len = right_size_decode_cache(model, total_len)

    params = variables["params"] if "params" in variables else variables
    if attention_mask is None:
        attention_mask = jnp.ones((b, prompt_len), jnp.int32)
    attention_mask = attention_mask.astype(jnp.int32)
    # per-row left-pad count; generated token at buffer slot i has position
    # i - pad_count (first REAL token of each row sits at position 0)
    pad_counts = prompt_len - attention_mask.sum(axis=1)
    # which kv-cache slots hold real tokens: prompt slots per the mask,
    # everything generated afterwards is real
    kv_valid = jnp.concatenate(
        [attention_mask.astype(bool),
         jnp.ones((b, cache_len - prompt_len), bool)], axis=1,
    )
    kv_mask = kv_valid[:, None, None, :]  # [b, 1, 1(q), cache_len(kv)]

    # static token buffer
    tokens = jnp.full((b, total_len), gen_cfg.pad_token_id, jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, input_ids.astype(jnp.int32), (0, 0))

    cache = init_decode_cache(model, b)

    # prefill: feed the whole prompt, cache fills positions [0, prompt_len)
    pos = jnp.clip(jnp.cumsum(attention_mask, axis=1) - 1, 0)
    logits, cache = decode_step(
        model, params, cache, input_ids.astype(jnp.int32), pos, kv_mask
    )
    vocab = logits.shape[-1]
    # repetition penalty reads a [b, vocab] seen-token scoreboard updated in
    # O(vocab) per step (mark_seen) instead of rebuilding a one-hot over the
    # whole [b, total_len] buffer every iteration; a 1-element dummy rides
    # the loop state when the penalty is off
    track_seen = gen_cfg.repetition_penalty != 1.0
    seen = (prompt_seen(input_ids.astype(jnp.int32), attention_mask, vocab)
            if track_seen else jnp.zeros((b, 1), jnp.bool_.dtype))
    rng, step_rng = jax.random.split(rng)
    next_logits = process_logits(
        logits[:, -1, :], seen if track_seen else None,
        jnp.asarray(prompt_len), gen_cfg, prompt_len=prompt_len,
        total_len=total_len,
    )
    next_tok = _sample(next_logits, step_rng, gen_cfg).astype(jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, next_tok[:, None], (0, prompt_len))
    if track_seen:
        seen = mark_seen(seen, next_tok)
    finished = next_tok == gen_cfg.eos_token_id

    def cond(state):
        i, _, _, _, finished, _ = state
        return (i < total_len) & ~jnp.all(finished)

    def body(state):
        i, tokens, seen, cache, finished, rng = state
        cur = jax.lax.dynamic_slice(tokens, (0, i - 1), (b, 1))
        logits, cache = decode_step(
            model, params, cache, cur,
            (i - 1 - pad_counts)[:, None].astype(jnp.int32), kv_mask,
        )
        rng, step_rng = jax.random.split(rng)
        nl = process_logits(logits[:, -1, :], seen if track_seen else None,
                            i, gen_cfg, prompt_len=prompt_len,
                            total_len=total_len)
        tok = _sample(nl, step_rng, gen_cfg).astype(jnp.int32)
        tok = jnp.where(finished, gen_cfg.pad_token_id, tok)
        tokens = jax.lax.dynamic_update_slice(tokens, tok[:, None], (0, i))
        if track_seen:
            seen = mark_seen(seen, tok)
        finished = finished | (tok == gen_cfg.eos_token_id)
        return i + 1, tokens, seen, cache, finished, rng

    _, tokens, _, _, _, _ = jax.lax.while_loop(
        cond, body,
        (jnp.asarray(prompt_len + 1), tokens, seen, cache, finished, rng),
    )
    return tokens
