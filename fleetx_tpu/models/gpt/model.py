"""GPT model family — TPU-native Flax implementation.

Capability parity with the reference's THREE hand-written GPT variants —
single-card (/root/reference/ppfleetx/models/language_model/gpt/dygraph/
single_model.py:68-1247), TP/PP/SP hybrid (dygraph/hybrid_model.py:49-1096)
and auto-parallel (auto/auto_model.py:88-697) — collapsed into ONE model:
logical-axis annotations (vocab/heads/mlp/embed) make the same module run
single-device, tensor-parallel (Column/RowParallelLinear semantics via GSPMD),
ZeRO-sharded, and sequence-parallel, with pipeline handled by the stage axis
in fleetx_tpu/parallel/pipeline.py.

Reference feature map:
- fuse_attn_qkv (single_model.py:108-131)        -> ``fuse_attn_qkv`` flag
- selective recompute full/full_attn/core_attn + no_recompute_layers
  (single_model.py:270-345,473-475)              -> ``remat_*`` fields, named
  checkpoint policies over the scanned layer stack
- sequence_parallel [s/n,b,h] Scatter/Gather ops (sequence_parallel_utils.py)
  -> ``act_seq`` sharding constraint; XLA emits the all-gather/reduce-scatter
- tied-embedding logits via parallel_matmul (hybrid_model.py:49-71)
  -> einsum against the (vocab, embed)-partitioned embedding table
- kv-cache generation (single_model.py:781-1247) -> flax 'cache' collection
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.ad_checkpoint import checkpoint_name

from fleetx_tpu.ops.attention import causal_attention

Dtype = Any

default_kernel_init = nn.initializers.normal(stddev=0.02)


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """GPT model hyperparameters incl. parallel/remat/flash switches
    (reference GPTModel construction args)."""
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_layers: int = 24
    num_attention_heads: int = 16
    ffn_hidden_size: Optional[int] = None
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 1024
    initializer_range: float = 0.02
    fuse_attn_qkv: bool = True
    sequence_parallel: bool = False
    use_recompute: bool = False
    recompute_granularity: Optional[str] = None  # full | full_attn | core_attn
    # extra checkpoint_name'd tensors to SAVE on top of the granularity's
    # base save-set: trades HBM for less backward recompute. Named sites:
    # 'qkv_out' (skip re-running the qkv projection), 'ffn_gelu' (skip
    # up_proj + gelu — the widest activation), 'mlp_out', 'attn_out'.
    # v5e guidance in docs/PERFORMANCE.md.
    recompute_extra_saves: Optional[Tuple[str, ...]] = None
    no_recompute_layers: Optional[Tuple[int, ...]] = None
    use_flash_attention: bool = True
    # hidden dropouts via the lowbias32 counter hash (ops/dropout.py) —
    # one threefry fold per call instead of a per-element keystream;
    # measured ~12%/step on v5e at 345M. False restores nn.Dropout.
    fast_dropout: bool = True
    scan_layers: bool = True
    dtype: Dtype = jnp.bfloat16  # compute dtype; params always fp32
    # pipeline parallelism (consumed by fleetx_tpu/parallel/pipeline.py)
    pp_degree: int = 1
    num_microbatches: int = 1
    # context parallelism: ring attention over the 'cp' mesh axis; inputs
    # must be in zig-zag sequence order (parallel/context_parallel.py)
    cp_degree: int = 1
    # MoE (consumed by fleetx_tpu/parallel/moe.py when num_experts > 1)
    num_experts: int = 1
    expert_mode: bool = False
    gate: str = "gshard"
    top_k: int = 2
    capacity_factor: float = 1.2
    # 'einsum' = dense [n,E,C] dispatch masks (fastest at small E);
    # 'scatter' = index scatter/gather, O(n) dispatch memory (large E);
    # 'auto' picks scatter once the dense masks would dominate memory
    moe_dispatch: str = "auto"
    # virtual/interleaved pipeline: each physical stage owns this many
    # non-contiguous layer chunks (reference num_virtual_pipeline_stages,
    # hybrid_model.py:1095)
    virtual_pp_degree: int = 1
    # virtual-chunk schedule: True fuses the v chunk passes into one
    # streamed scan (parallel/pipeline.py module docstring), False chains
    # per-chunk scans; None resolves from FLEETX_VPP_STREAM (default on)
    virtual_pp_stream: Optional[bool] = None
    balance_loss_weight: float = 0.01
    # decode kv-cache length; None = max_position_embeddings. Generation
    # drivers set this to prompt_len + max_length so per-step cache traffic
    # (attention reads, beam reorders) scales with the actual decode span,
    # not the model's position ceiling.
    decode_cache_len: Optional[int] = None
    # paged decode cache (serving/cache_manager.py): when decode_num_pages
    # is set, decode-mode kv caches are ONE shared pool of
    # [decode_num_pages, decode_page_size, heads, head_dim] pages instead
    # of per-row [b, decode_cache_len, ...] buffers; each row addresses
    # its logical [0, decode_cache_len) window through a block table of
    # page indices (``block_tables`` threading). decode_page_size must be
    # a multiple of 8 for the paged flash-decode kernel, and
    # decode_cache_len a multiple of decode_page_size.
    decode_num_pages: Optional[int] = None
    decode_page_size: Optional[int] = None
    # decode kv-cache precision: None keeps K/V at the compute dtype;
    # "int8" stores both the slot cache and the paged pool as int8 with
    # per-vector fp32 scales (ops/quant.quantize_kv) — ~2x tokens per HBM
    # byte on the bandwidth-bound decode path. The flash-decode kernels
    # dequantize in VMEM; dense fallbacks dequantize via the shared
    # helper, so every attention path sees identical values
    # (docs/QUANTIZATION.md; FLEETX_SERVING_KV_DTYPE wires it in serving).
    decode_kv_dtype: Optional[str] = None
    # fuse the LM head matmul + cross-entropy into the Pallas blockwise
    # kernel (ops/pallas/ce_loss.py): the [tokens, vocab] logits never
    # materialize. Opt-in; intended for mp=1 runs (a vocab-sharded
    # embedding would be gathered around the kernel).
    fused_ce: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def ffn_size(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @classmethod
    def from_model_config(cls, model_cfg) -> "GPTConfig":
        """Build from a YAML ``Model`` section (reference schema)."""
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in dict(model_cfg).items() if k in known and v is not None}
        if isinstance(kw.get("dtype"), str):
            kw["dtype"] = jnp.dtype(kw["dtype"]).type
        nrl = kw.get("no_recompute_layers")
        if nrl is not None:
            kw["no_recompute_layers"] = tuple(nrl)
        res = kw.get("recompute_extra_saves")
        if res is not None:
            if isinstance(res, str):  # "qkv_out,ffn_gelu" CLI/-o form
                res = [s for s in res.split(",") if s]
            kw["recompute_extra_saves"] = tuple(res)
        if model_cfg.get("num_experts") and model_cfg["num_experts"] > 1:
            kw["expert_mode"] = True
        return cls(**kw)


def _dense(features, logical_axes, name, use_bias=True, dtype=jnp.bfloat16):
    """Dense with logical-axis-partitioned kernel; bias follows the kernel's
    output axes. The logical axes are what make this 'column parallel'
    (out axis on mp) or 'row parallel' (in axis on mp) under the rules."""
    return nn.DenseGeneral(
        features=features,
        axis=-1,
        use_bias=use_bias,
        dtype=dtype,
        param_dtype=jnp.float32,
        kernel_init=nn.with_logical_partitioning(default_kernel_init, logical_axes),
        bias_init=nn.with_logical_partitioning(nn.initializers.zeros_init(), logical_axes[1:]),
        name=name,
    )


def attn_out_dense(hidden_size, dtype, name="out_proj"):
    """Row-parallel attention output projection [.., heads, kv] -> [.., embed]
    — shared by GPT/ERNIE/ViT attention blocks."""
    return nn.DenseGeneral(
        features=hidden_size,
        axis=(-2, -1),
        use_bias=True,
        dtype=dtype,
        param_dtype=jnp.float32,
        kernel_init=nn.with_logical_partitioning(
            default_kernel_init, ("heads", "kv", "embed")
        ),
        bias_init=nn.with_logical_partitioning(nn.initializers.zeros_init(), ("norm",)),
        name=name,
    )


class SelfAttention(nn.Module):
    """Causal self-attention with optional fused qkv and kv-cache decode.

    TP semantics: q/k/v projections are column-parallel over ``heads``,
    out-projection row-parallel over ``embed`` (reference
    hybrid_model.py:131-174's ColumnParallelLinear/RowParallelLinear)."""

    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, attn_mask=None, *, deterministic=True, decode=False,
                 cache_positions=None, block_tables=None):
        cfg = self.cfg
        h, nh, hd = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim

        if cfg.fuse_attn_qkv:
            qkv = _dense((nh, 3 * hd), ("embed", "heads", "kv"), "qkv_proj", dtype=cfg.dtype)(x)
            qkv = checkpoint_name(qkv, "qkv_out")
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            q = _dense((nh, hd), ("embed", "heads", "kv"), "q_proj", dtype=cfg.dtype)(x)
            k = _dense((nh, hd), ("embed", "heads", "kv"), "k_proj", dtype=cfg.dtype)(x)
            v = _dense((nh, hd), ("embed", "heads", "kv"), "v_proj", dtype=cfg.dtype)(x)
            q, k, v = (checkpoint_name(t, "qkv_out") for t in (q, k, v))

        causal = True
        if decode:
            kv_pad_mask = attn_mask  # pre-causal-merge mask: left-pad layout
            k, v, attn_mask, decode_end, paged, kv_scales = self._update_cache(
                k, v, attn_mask, cache_positions, block_tables
            )
            causal = False  # the cache mask encodes absolute-position causality
            if paged is not None:
                # Page-granular cache (serving): k/v above are the RAW
                # shared page pools. Single-query steps take the paged
                # flash kernel (block table rides scalar prefetch, HBM
                # traffic = the row's live pages); everything else gathers
                # each row's logical buffer and joins the dense fallback.
                from fleetx_tpu.ops.pallas.decode_attention import (
                    flash_decode_paged_attention,
                    paged_gather_kv,
                )

                tables = paged
                if decode_end is not None and self._flash_decode_ok(
                    kv_pad_mask, tables.shape[1] * cfg.decode_page_size,
                    deterministic, tile_len=cfg.decode_page_size,
                ):
                    out = flash_decode_paged_attention(
                        q, k, v, tables=tables, end=decode_end,
                        starts=self._pad_starts(kv_pad_mask, q.shape[0]),
                        k_scale=kv_scales and kv_scales[0],
                        v_scale=kv_scales and kv_scales[1],
                        mesh=self._decode_shard_mesh(),
                    )
                    out = checkpoint_name(out, "core_attn_out")
                    return self._out_proj(out)
                k = paged_gather_kv(k, tables)
                v = paged_gather_kv(v, tables)
                if kv_scales is not None:
                    # dense fallback over an int8 pool: gather each row's
                    # scale pages through the same table, dequantize via
                    # the shared helper (ops/quant.py)
                    from fleetx_tpu.ops.quant import dequantize_kv

                    k = dequantize_kv(
                        k, paged_gather_kv(kv_scales[0], tables), q.dtype)
                    v = dequantize_kv(
                        v, paged_gather_kv(kv_scales[1], tables), q.dtype)
                    kv_scales = None
            elif decode_end is not None and self._flash_decode_ok(
                kv_pad_mask, k.shape[1], deterministic, batch=q.shape[0]
            ):
                # Single-query fast path: the Pallas flash-decode kernel reads
                # only the KV blocks inside [starts, cache_index) — per-step
                # HBM traffic scales with the decoded prefix, not the cache
                # capacity (fleetx_tpu/ops/pallas/decode_attention.py).
                from fleetx_tpu.ops.pallas.decode_attention import (
                    flash_decode_attention,
                )

                out = flash_decode_attention(
                    q, k, v, end=decode_end,
                    starts=self._pad_starts(kv_pad_mask, q.shape[0]),
                    k_scale=kv_scales and kv_scales[0],
                    v_scale=kv_scales and kv_scales[1],
                    mesh=self._decode_shard_mesh(),
                )
                out = checkpoint_name(out, "core_attn_out")
                return self._out_proj(out)
            if kv_scales is not None:
                # contiguous dense fallback (prefill, custom masks, off-TPU)
                # over the int8 slot cache: dequantize the full buffers via
                # the shared helper — correctness paths cost what dense
                # always cost, the flash path above never materializes this
                from fleetx_tpu.ops.quant import dequantize_kv

                k = dequantize_kv(k, kv_scales[0], q.dtype)
                v = dequantize_kv(v, kv_scales[1], q.dtype)

        if cfg.cp_degree > 1 and not decode:
            # Ring attention: sequence stays sharded over the cp axis; KV
            # blocks rotate with ppermute (parallel/context_parallel.py).
            # Attention dropout runs inside the per-hop flash kernels and is
            # keyed on global positions — the mask matches the non-cp path.
            if attn_mask is not None:
                raise NotImplementedError(
                    "context parallelism does not support a custom attn_mask"
                )
            from fleetx_tpu.parallel.context_parallel import ring_self_attention

            cp_dropout_rng = None
            if cfg.attention_probs_dropout_prob > 0.0 and not deterministic:
                cp_dropout_rng = self.make_rng("dropout")
            out = ring_self_attention(
                q, k, v, causal=causal, expected_cp=cfg.cp_degree,
                dropout_rate=(0.0 if deterministic
                              else cfg.attention_probs_dropout_prob),
                dropout_rng=cp_dropout_rng,
            )
            out = checkpoint_name(out, "core_attn_out")
            return self._out_proj(out)

        dropout_rng = None
        if cfg.attention_probs_dropout_prob > 0.0 and not deterministic:
            dropout_rng = self.make_rng("dropout")
        out = causal_attention(
            q,
            k,
            v,
            causal=causal,
            attn_mask=attn_mask,
            dropout_rate=cfg.attention_probs_dropout_prob,
            dropout_rng=dropout_rng,
            deterministic=deterministic,
            # decode steps that miss the flash-decode fast path (prefill,
            # custom masks) land here; causal_attention's own shape checks
            # route them to the XLA path, so the flag no longer needs the
            # `and not decode` guard
            use_flash=cfg.use_flash_attention,
            # pp>1 applies stages under nn.vmap; a nested shard_map there
            # would fight the stage sharding (parallel/pipeline.py)
            mesh_shard=cfg.pp_degree == 1,
        )
        out = checkpoint_name(out, "core_attn_out")
        return self._out_proj(out)

    def _out_proj(self, out):
        cfg = self.cfg
        out = attn_out_dense(cfg.hidden_size, cfg.dtype)(out)
        return checkpoint_name(out, "attn_out")

    def _update_cache(self, k, v, attn_mask, cache_positions=None,
                      block_tables=None):
        """Incremental decode: append this step's k/v at cache_index and
        build the absolute-position causal mask (query i at absolute position
        start+i may see cache positions <= start+i). Cache layout
        [batch, max_len, heads, head_dim].

        ``cache_positions`` ([b] int32, optional) gives each batch row its
        OWN write offset instead of the shared scalar ``cache_index`` — the
        continuous-batching serving path (fleetx_tpu/serving/) runs slots at
        different decode depths in one batched step, so row b writes at
        ``cache_positions[b]`` and attends the per-row causal window
        ``[0, cache_positions[b] + s)``. The scalar ``cache_index`` is still
        advanced (to the max write end) so one-shot callers interleaving
        both styles stay consistent. Multi-token calls (s > 1) with
        ``cache_positions`` are the CHUNKED-prefill seam: successive calls
        at increasing offsets write a prompt's K/V incrementally, and the
        absolute-position causal mask keeps each chunk's queries reading
        exactly the prefix earlier chunks wrote — byte-identical to one
        whole-prompt call (docs/SERVING.md chunked prefill).

        When ``cfg.decode_num_pages`` is set the cache is page-granular and
        ``block_tables`` ([b, pages_per_row] int32) must come along with
        ``cache_positions`` — see :meth:`_update_paged_cache`.

        When ``cfg.decode_kv_dtype == "int8"`` the cache leaves store int8
        values plus ``cached_key_scale``/``cached_value_scale`` fp32 leaves
        of per-vector scales (``[..., max_len, nh, 1]``): this step's k/v
        quantize on write via ``ops/quant.quantize_kv``, and the returned
        buffers are the RAW int8 caches with ``kv_scales`` carrying the
        scale buffers — the flash kernel dequantizes in VMEM, the dense
        fallback dequantizes in the caller.

        Returns ``(k, v, attn_mask, decode_end, paged, kv_scales)``:
        ``decode_end`` is the number of live cache positions after this
        step's write (the single-query flash-decode kernel's upper bound;
        per-row [b] under ``cache_positions``) — None during init and for
        multi-token (prefill) calls, where the fast path does not apply.
        ``paged`` is None on this contiguous layout (the paged branch
        returns the block tables and RAW page pools instead of gathered
        buffers); ``kv_scales`` is None at the native kv dtype."""
        if self.cfg.decode_num_pages is not None:
            return self._update_paged_cache(
                k, v, attn_mask, cache_positions, block_tables
            )
        quant = self.cfg.decode_kv_dtype == "int8"
        is_init = not self.has_variable("cache", "cached_key")
        b, s, nh, hd = k.shape
        max_len = (self.cfg.decode_cache_len
                   if self.cfg.decode_cache_len is not None
                   else self.cfg.max_position_embeddings)
        ck = self.variable(
            "cache", "cached_key", jnp.zeros, (b, max_len, nh, hd),
            jnp.int8 if quant else k.dtype
        )
        cv = self.variable(
            "cache", "cached_value", jnp.zeros, (b, max_len, nh, hd),
            jnp.int8 if quant else v.dtype
        )
        if quant:
            # per-vector fp32 scales; the trailing 1 keeps the batch axis
            # at -4 so scatter_slot and friends treat them like K/V leaves
            cks = self.variable(
                "cache", "cached_key_scale", jnp.zeros,
                (b, max_len, nh, 1), jnp.float32
            )
            cvs = self.variable(
                "cache", "cached_value_scale", jnp.zeros,
                (b, max_len, nh, 1), jnp.float32
            )
        idx = self.variable("cache", "cache_index", lambda: jnp.array(0, jnp.int32))
        decode_end = None
        kv_scales = None
        if not is_init:
            if quant:
                from fleetx_tpu.ops.quant import quantize_kv

                k_w, k_s = quantize_kv(k)
                v_w, v_s = quantize_kv(v)
            else:
                k_w, v_w = k, v
            k_pos = jnp.arange(max_len)
            if cache_positions is None:
                start = idx.value
                ck.value = jax.lax.dynamic_update_slice(ck.value, k_w, (0, start, 0, 0))
                cv.value = jax.lax.dynamic_update_slice(cv.value, v_w, (0, start, 0, 0))
                if quant:
                    cks.value = jax.lax.dynamic_update_slice(
                        cks.value, k_s, (0, start, 0, 0))
                    cvs.value = jax.lax.dynamic_update_slice(
                        cvs.value, v_s, (0, start, 0, 0))
                idx.value = start + s
                if s == 1:
                    decode_end = idx.value
                q_pos = start + jnp.arange(s)  # absolute query positions
                causal = (k_pos[None, :] <= q_pos[:, None])[None, None, :, :]
            else:
                wpos = cache_positions.astype(jnp.int32)  # [b] write offsets
                row_update = jax.vmap(
                    lambda buf, new, p: jax.lax.dynamic_update_slice(
                        buf, new, (p, 0, 0))
                )
                ck.value = row_update(ck.value, k_w, wpos)
                cv.value = row_update(cv.value, v_w, wpos)
                if quant:
                    cks.value = row_update(cks.value, k_s, wpos)
                    cvs.value = row_update(cvs.value, v_s, wpos)
                idx.value = jnp.max(wpos) + s
                if s == 1:
                    decode_end = wpos + 1  # [b]: per-row live window end
                q_pos = wpos[:, None] + jnp.arange(s)[None, :]  # [b, s]
                causal = (k_pos[None, None, :] <= q_pos[:, :, None])[:, None, :, :]
            k, v = ck.value, cv.value
            if quant:
                kv_scales = (cks.value, cvs.value)
            attn_mask = (
                causal
                if attn_mask is None
                else (attn_mask.astype(bool) & causal)
            )
        return k, v, attn_mask, decode_end, None, kv_scales

    def _update_paged_cache(self, k, v, attn_mask, cache_positions,
                            block_tables):
        """Page-granular decode cache write (``cfg.decode_num_pages`` set).

        The cache leaves are ONE pool of ``[num_pages, page_size, nh, hd]``
        shared pages; logical position ``p`` of row ``b`` lives at physical
        page ``block_tables[b, p // page_size]``, offset ``p % page_size``.
        This step's k/v rows scatter through the tables (positions clamped
        to the logical capacity: bucket-tail/pinned writes land on the
        row's LAST logical slot or — through a zeroed table entry — on the
        reserved trash page 0, both beyond every live window; see
        serving/cache_manager.py for the safety argument). The causal mask
        is built over LOGICAL positions, so the dense fallback can consume
        it after :func:`paged_gather_kv` unchanged.

        When ``cfg.decode_kv_dtype == "int8"`` the pools store int8 with
        per-vector fp32 scale pools (``[num_pages, ps, nh, 1]``) scattered
        through the same block tables — see :meth:`_update_cache`.

        Returns ``(k_pages, v_pages, attn_mask, decode_end, tables,
        kv_scales)``: raw pools + tables so the caller picks paged-flash
        vs gather-dense without materializing both."""
        cfg = self.cfg
        quant = cfg.decode_kv_dtype == "int8"
        is_init = not self.has_variable("cache", "cached_key")
        b, s, nh, hd = k.shape
        ps = cfg.decode_page_size
        if ps is None or ps % 8:
            raise ValueError(
                f"decode_page_size must be a multiple of 8, got {ps}")
        max_len = (cfg.decode_cache_len if cfg.decode_cache_len is not None
                   else cfg.max_position_embeddings)
        if max_len % ps:
            raise ValueError(
                f"decode_cache_len {max_len} must be a multiple of "
                f"decode_page_size {ps}")
        ck = self.variable(
            "cache", "cached_key", jnp.zeros,
            (cfg.decode_num_pages, ps, nh, hd), jnp.int8 if quant else k.dtype
        )
        cv = self.variable(
            "cache", "cached_value", jnp.zeros,
            (cfg.decode_num_pages, ps, nh, hd), jnp.int8 if quant else v.dtype
        )
        if quant:
            cks = self.variable(
                "cache", "cached_key_scale", jnp.zeros,
                (cfg.decode_num_pages, ps, nh, 1), jnp.float32
            )
            cvs = self.variable(
                "cache", "cached_value_scale", jnp.zeros,
                (cfg.decode_num_pages, ps, nh, 1), jnp.float32
            )
        idx = self.variable("cache", "cache_index", lambda: jnp.array(0, jnp.int32))
        decode_end = None
        paged = None
        kv_scales = None
        if not is_init:
            if cache_positions is None or block_tables is None:
                raise ValueError(
                    "a paged decode cache needs cache_positions AND "
                    "block_tables (the serving engine threads both)")
            if quant:
                from fleetx_tpu.ops.quant import quantize_kv

                k_w, k_s = quantize_kv(k)
                v_w, v_s = quantize_kv(v)
            else:
                k_w, v_w = k, v
            wpos = cache_positions.astype(jnp.int32)       # [b] write offsets
            tables = block_tables.astype(jnp.int32)        # [b, n_pages_row]
            pos = wpos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
            pos = jnp.minimum(pos, max_len - 1)            # [b, s] logical
            page = jnp.take_along_axis(tables, pos // ps, axis=1)
            ck.value = ck.value.at[page.reshape(-1), (pos % ps).reshape(-1)
                                   ].set(k_w.reshape(b * s, nh, hd))
            cv.value = cv.value.at[page.reshape(-1), (pos % ps).reshape(-1)
                                   ].set(v_w.reshape(b * s, nh, hd))
            if quant:
                cks.value = cks.value.at[
                    page.reshape(-1), (pos % ps).reshape(-1)
                ].set(k_s.reshape(b * s, nh, 1))
                cvs.value = cvs.value.at[
                    page.reshape(-1), (pos % ps).reshape(-1)
                ].set(v_s.reshape(b * s, nh, 1))
                kv_scales = (cks.value, cvs.value)
            idx.value = jnp.max(wpos) + s
            if s == 1:
                decode_end = wpos + 1  # [b]: per-row live logical length
            k_pos = jnp.arange(max_len)
            q_pos = wpos[:, None] + jnp.arange(s)[None, :]  # [b, s] logical
            causal = (k_pos[None, None, :] <= q_pos[:, :, None])[:, None, :, :]
            attn_mask = (causal if attn_mask is None
                         else attn_mask.astype(bool) & causal)
            paged = tables
            k, v = ck.value, cv.value
        return k, v, attn_mask, decode_end, paged, kv_scales

    def _flash_decode_ok(self, kv_pad_mask, cache_len: int,
                         deterministic: bool, tile_len: Optional[int] = None,
                         batch: Optional[int] = None) -> bool:
        """Static dispatch check for the single-query flash-decode path.

        The kernel handles exactly the generation-loop mask shape: an
        optional [b, 1, 1, cache_len] key-validity mask whose False slots
        are the contiguous left-pad prefix (generate()/beam_search() build
        exactly this). Anything else — arbitrary masks, attention dropout,
        untileable cache lengths — falls back to the dense XLA path.

        An ambient multi-device mesh no longer forces the fallback (the
        PR 1 guard): when the heads divide over the ``mp`` extent the
        kernels run per-shard inside ``shard_map`` over the local head
        slice (``mesh=`` on the kernel entry points). Meshes whose mp
        does not divide the heads — or, on the CONTIGUOUS layout, whose
        dp/fsdp extent does not divide ``batch`` (one-shot callers keep
        the cache batch-sharded over those axes; a shard_map that
        replicated it would all-gather the cache per step) — still fall
        back to the dense path.

        ``tile_len`` is the buffer length the kernel must tile: the page
        size on the paged path (one page is the DMA/gather unit there),
        defaulting to ``cache_len`` on the contiguous path. ``batch``
        engages the data-axis divisibility check (contiguous layout
        only — the paged pools are serving-owned and batch-replicated)."""
        cfg = self.cfg
        if not cfg.use_flash_attention:
            return False
        if not (deterministic or cfg.attention_probs_dropout_prob == 0.0):
            return False
        if kv_pad_mask is not None and (
            kv_pad_mask.ndim != 4
            or kv_pad_mask.shape[1] != 1
            or kv_pad_mask.shape[2] != 1
            or kv_pad_mask.shape[3] != cache_len
        ):
            return False
        from fleetx_tpu.ops.pallas.decode_attention import (
            decode_flash_supported,
            decode_mesh_shardable,
        )

        mesh = self._decode_shard_mesh()
        if mesh is not None and not decode_mesh_shardable(
                mesh, cfg.num_attention_heads, batch):
            return False
        return decode_flash_supported(
            cache_len if tile_len is None else tile_len)

    @staticmethod
    def _decode_shard_mesh():
        """The ambient mesh the flash-decode kernels shard_map over, or
        None for the bare (single-device) kernel call."""
        from fleetx_tpu.parallel.mesh import ambient_mesh

        mesh = ambient_mesh()
        if mesh is None or mesh.size <= 1:
            return None
        return mesh

    @staticmethod
    def _pad_starts(kv_pad_mask, batch: int):
        """Per-row first live cache position from the [b, 1, 1, cache_len]
        key-validity mask; None mask = no padding.

        The window the kernel attends is [starts, cache_index), so the mask
        contract is: False slots form a contiguous left-pad prefix (the
        generation loop's layout), with any further False slots only at
        positions the cache index has not reached yet (a right-padded
        layout is therefore also exact). Taking the FIRST True — rather
        than counting all False slots — keeps right-padded masks correct;
        arbitrary interior holes are outside the fast path's contract
        (docs/PERFORMANCE.md) and cannot be detected at trace time."""
        if kv_pad_mask is None:
            return None
        starts = jnp.argmax(
            kv_pad_mask.astype(bool)[:, 0, 0, :], axis=-1
        ).astype(jnp.int32)
        return jnp.broadcast_to(starts, (batch,))


class MLP(nn.Module):
    """FFN: column-parallel up (embed→mlp), gelu, row-parallel down
    (mlp→embed) — reference linear1/linear2 (hybrid_model.py:546-563)."""

    cfg: GPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = _dense(cfg.ffn_size, ("embed", "mlp"), "up_proj", dtype=cfg.dtype)(x)
        x = checkpoint_name(nn.gelu(x, approximate=True), "ffn_gelu")
        x = _dense(cfg.hidden_size, ("mlp", "embed"), "down_proj", dtype=cfg.dtype)(x)
        return checkpoint_name(x, "mlp_out")


def _dropout(cfg, name):
    """Hidden-dropout layer: hash-based by default (see ops/dropout.py);
    ``fast_dropout: False`` restores flax's threefry nn.Dropout."""
    from fleetx_tpu.ops.dropout import dropout_layer

    return dropout_layer(cfg.hidden_dropout_prob, name, cfg.fast_dropout)


def _layer_norm(cfg, name):
    return nn.LayerNorm(
        epsilon=1e-5,
        dtype=cfg.dtype,
        param_dtype=jnp.float32,
        scale_init=nn.with_logical_partitioning(nn.initializers.ones_init(), ("norm",)),
        bias_init=nn.with_logical_partitioning(nn.initializers.zeros_init(), ("norm",)),
        name=name,
    )


class DecoderLayer(nn.Module):
    """Pre-LN transformer decoder layer (reference TransformerDecoderLayer,
    single_model.py:286-505)."""

    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, attn_mask=None, deterministic=True, decode=False,
                 cache_positions=None, block_tables=None):
        cfg = self.cfg
        x = _constrain_act(x, cfg)
        residual = x
        y = _layer_norm(cfg, "norm1")(x)
        y = SelfAttention(cfg, name="attn")(
            y, attn_mask, deterministic=deterministic, decode=decode,
            cache_positions=cache_positions, block_tables=block_tables,
        )
        y = _dropout(cfg, "attn_dropout")(y, deterministic=deterministic)
        x = residual + y
        residual = x
        y = _layer_norm(cfg, "norm2")(x)
        if cfg.expert_mode:
            from fleetx_tpu.parallel.moe import MoEMLP

            y = MoEMLP(cfg, name="moe_mlp")(y)
        else:
            y = MLP(cfg, name="mlp")(y)
        y = _dropout(cfg, "mlp_dropout")(y, deterministic=deterministic)
        x = residual + y
        return _constrain_act(x, cfg)


def _constrain_act(x, cfg: GPTConfig):
    """Activation sharding: batch over data axes; seq over mp iff sequence
    parallel (replaces the reference's explicit ScatterOp/GatherOp layout
    management, sequence_parallel_utils.py:83-136)."""
    if x.ndim == 3:
        return nn.with_logical_constraint(x, ("act_batch", "act_seq", "act_embed"))
    return x


class _ScanLayer(nn.Module):
    """Adapter giving DecoderLayer the (carry, out) contract nn.scan wants."""

    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, attn_mask, deterministic, decode,
                 cache_positions=None, block_tables=None):
        x = DecoderLayer(self.cfg, name="layer")(
            x, attn_mask, deterministic, decode, cache_positions,
            block_tables
        )
        return x, None


# every checkpoint_name site in this model; a typo'd save name would
# otherwise silently match nothing and masquerade as the base save-set
_CHECKPOINT_NAMES = frozenset(
    {"qkv_out", "core_attn_out", "attn_out", "ffn_gelu", "mlp_out"}
)


def _remat_policy(cfg: GPTConfig):
    if not cfg.use_recompute:
        return None
    g = cfg.recompute_granularity or "full"
    extra = tuple(cfg.recompute_extra_saves or ())
    unknown = set(extra) - _CHECKPOINT_NAMES
    if unknown:
        raise ValueError(
            f"recompute_extra_saves {sorted(unknown)} match no "
            f"checkpoint_name site; known: {sorted(_CHECKPOINT_NAMES)}"
        )
    if g == "full":
        if extra:  # 'full' + saves = a graded point between full and attn
            return jax.checkpoint_policies.save_only_these_names(*extra)
        return jax.checkpoint_policies.nothing_saveable
    if g == "full_attn":
        return jax.checkpoint_policies.save_only_these_names(
            "attn_out", *extra)
    if g == "core_attn":
        return jax.checkpoint_policies.save_only_these_names(
            "core_attn_out", *extra)
    raise ValueError(f"unknown recompute_granularity {g!r}")


class GPTModel(nn.Module):
    """Embeddings + decoder stack + final LN (reference GPTModel,
    single_model.py:548-657). Returns hidden states [b, s, h]."""

    cfg: GPTConfig

    @nn.compact
    def __call__(self, input_ids, position_ids=None, attn_mask=None, *,
                 deterministic=True, decode=False, cache_positions=None,
                 block_tables=None):
        cfg = self.cfg
        word_emb = self.param(
            "word_embeddings",
            nn.with_logical_partitioning(
                nn.initializers.normal(cfg.initializer_range), ("vocab", "embed")
            ),
            (cfg.vocab_size, cfg.hidden_size),
            jnp.float32,
        )
        pos_emb = self.param(
            "position_embeddings",
            nn.with_logical_partitioning(
                nn.initializers.normal(cfg.initializer_range), (None, "embed")
            ),
            (cfg.max_position_embeddings, cfg.hidden_size),
            jnp.float32,
        )
        if position_ids is None:
            # decode callers must pass explicit position_ids per step
            position_ids = jnp.arange(input_ids.shape[1])[None, :]
            position_ids = jnp.broadcast_to(position_ids, input_ids.shape)
        x = word_emb[input_ids] + pos_emb[position_ids]
        x = x.astype(cfg.dtype)
        x = _constrain_act(x, cfg)
        x = _dropout(cfg, "embed_dropout")(x, deterministic=deterministic)

        x = self._decoder_stack(x, attn_mask, deterministic=deterministic,
                                decode=decode, cache_positions=cache_positions,
                                block_tables=block_tables)
        x = _layer_norm(cfg, "final_norm")(x)
        return _constrain_act(x, cfg)

    def _decoder_stack(self, x, attn_mask, *, deterministic, decode,
                       cache_positions=None, block_tables=None):
        cfg = self.cfg
        policy = _remat_policy(cfg)
        selective = cfg.no_recompute_layers
        if cfg.pp_degree > 1 and not decode:
            from fleetx_tpu.parallel.pipeline import PipelinedStack

            layer_cls = _ScanLayer
            if policy is not None:
                layer_cls = nn.remat(
                    _ScanLayer, policy=policy, prevent_cse=False, static_argnums=(3, 4)
                )
            return PipelinedStack(
                cfg,
                layer_cls,
                cfg.pp_degree,
                max(cfg.num_microbatches, 1),
                virtual_pp=max(cfg.virtual_pp_degree, 1),
                stream=cfg.virtual_pp_stream,
                name="layers",
            )(x, attn_mask, deterministic)
        if cfg.scan_layers and not selective:
            layer_cls = _ScanLayer
            if policy is not None:
                layer_cls = nn.remat(
                    _ScanLayer,
                    policy=policy,
                    prevent_cse=False,
                    static_argnums=(3, 4),
                )
            stack = nn.scan(
                layer_cls,
                variable_axes={"params": 0, "cache": 0, "intermediates": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast, nn.broadcast, nn.broadcast,
                         nn.broadcast, nn.broadcast),
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            x, _ = stack(cfg, name="layers")(x, attn_mask, deterministic,
                                             decode, cache_positions,
                                             block_tables)
            return x
        # Unrolled path: needed for per-layer recompute opt-out
        # (no_recompute_layers, reference single_model.py:473-475).
        skip = set(selective or ())
        for i in range(cfg.num_layers):
            layer_cls = DecoderLayer
            if policy is not None and i not in skip:
                layer_cls = nn.remat(
                    DecoderLayer, policy=policy, prevent_cse=False, static_argnums=(3, 4)
                )
            x = layer_cls(cfg, name=f"layer_{i}")(
                x, attn_mask, deterministic, decode, cache_positions,
                block_tables
            )
        return x


class GPTForPretraining(nn.Module):
    """LM head with tied embeddings: logits = h @ word_emb^T (reference
    GPTForPretraining + parallel_matmul, single_model.py:660-699,
    hybrid_model.py:49-71 — the vocab-parallel matmul + allgather is GSPMD's
    job here)."""

    cfg: GPTConfig

    @nn.compact
    def __call__(self, input_ids, position_ids=None, attn_mask=None, *,
                 deterministic=True, decode=False, cache_positions=None,
                 block_tables=None, labels=None):
        backbone = GPTModel(self.cfg, name="gpt")
        x = backbone(
            input_ids,
            position_ids,
            attn_mask,
            deterministic=deterministic,
            decode=decode,
            cache_positions=cache_positions,
            block_tables=block_tables,
        )
        word_emb = backbone.variables["params"]["word_embeddings"]
        emb = word_emb.value if isinstance(word_emb, nn.Partitioned) else word_emb
        if labels is not None and self.cfg.fused_ce:
            # blockwise fused LM-head + CE: returns PER-TOKEN loss [b, s]
            # (callers apply loss_mask); the [b, s, vocab] logits never
            # exist — ops/pallas/ce_loss.py
            from fleetx_tpu.ops.pallas.ce_loss import fused_linear_ce

            b, s, hd = x.shape
            tok = fused_linear_ce(
                x.reshape(b * s, hd), emb.astype(self.cfg.dtype),
                labels.reshape(-1),
            )
            return tok.reshape(b, s)
        logits = jnp.einsum(
            "bsh,vh->bsv", x, emb.astype(self.cfg.dtype),
            preferred_element_type=jnp.float32,
        )
        return logits


class GPTForSequenceClassification(nn.Module):
    """Classification over the last non-pad token's hidden state (reference
    GPTForSequenceClassification, single_model.py:739-778: score head,
    gather at sequence end)."""

    cfg: GPTConfig
    num_classes: int = 2

    @nn.compact
    def __call__(self, input_ids, position_ids=None, attn_mask=None,
                 seq_lens=None, *, deterministic=True):
        x = GPTModel(self.cfg, name="gpt")(
            input_ids, position_ids, attn_mask, deterministic=deterministic
        )
        if seq_lens is None:
            last = jnp.full((input_ids.shape[0],), input_ids.shape[1] - 1, jnp.int32)
        else:
            last = jnp.maximum(seq_lens - 1, 0).astype(jnp.int32)
        pooled = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
        return _dense(self.num_classes, ("embed", None), "score",
                      dtype=jnp.float32, use_bias=False)(pooled.astype(jnp.float32))


def convert_qkv_layout(gpt_params: dict, to_fused: bool) -> dict:
    """Convert attention projection params between the fused single-matmul
    layout (``qkv_proj``: kernel [..., embed, heads, 3*kv]) and the split
    layout (``q_proj``/``k_proj``/``v_proj``: kernel [..., embed, heads, kv])
    — the reference's finetune checkpoint converter
    (/root/reference/ppfleetx/models/language_model/language_module.py:
    293-372 ``process_qkv_weight``). Pure tree rewrite; works on raw arrays
    (callers unbox first) at any nesting depth, including scan-stacked
    [num_layers, ...] leaves."""
    import numpy as _np

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if k == "qkv_proj" and not to_fused and isinstance(v, dict):
                kern, bias = v.get("kernel"), v.get("bias")
                for idx, name in enumerate(("q_proj", "k_proj", "v_proj")):
                    part = {}
                    if kern is not None:
                        part["kernel"] = _np.array_split(_np.asarray(kern), 3, axis=-1)[idx]
                    if bias is not None:
                        part["bias"] = _np.array_split(_np.asarray(bias), 3, axis=-1)[idx]
                    out[name] = part
            elif k == "q_proj" and to_fused and isinstance(v, dict):
                parts = [node[n] for n in ("q_proj", "k_proj", "v_proj")]
                fused = {}
                if parts[0].get("kernel") is not None:
                    fused["kernel"] = _np.concatenate(
                        [_np.asarray(pp["kernel"]) for pp in parts], axis=-1
                    )
                if parts[0].get("bias") is not None:
                    fused["bias"] = _np.concatenate(
                        [_np.asarray(pp["bias"]) for pp in parts], axis=-1
                    )
                out["qkv_proj"] = fused
            elif k in ("k_proj", "v_proj") and to_fused:
                continue  # folded into qkv_proj above
            else:
                out[k] = walk(v)
        return out

    return walk(gpt_params)


def masked_loss_mean(token_loss: jax.Array, loss_mask: jax.Array):
    """Loss-mask-weighted mean of per-token losses (the reference
    criterion's reduction, single_model.py:727-736)."""
    loss_mask = loss_mask.astype(jnp.float32).reshape(token_loss.shape)
    return (token_loss * loss_mask).sum() / jnp.maximum(loss_mask.sum(), 1.0)


def pretraining_loss(logits: jax.Array, labels: jax.Array, loss_mask: jax.Array):
    """Masked LM cross-entropy (reference GPTPretrainingCriterion,
    single_model.py:702-736; the TP ParallelCrossEntropy variant
    hybrid_model.py:857-904 is unnecessary — logits arrive vocab-sharded and
    XLA handles the sharded log-softmax reduction)."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return masked_loss_mean(logz - label_logits, loss_mask)
