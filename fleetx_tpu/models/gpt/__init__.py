"""GPT decoder family: model, generation, beam search (reference models/language_model/gpt)."""
