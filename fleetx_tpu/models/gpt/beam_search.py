"""Beam search / diverse group-beam search decode — static-shape lax.while_loop.

Parity surface: the reference's generation config accepts ``decode_strategy:
beam_search`` with num_beams / num_beam_groups / diversity_rate /
length_penalty / early_stopping / forced_bos_token_id (/root/reference/
ppfleetx/models/language_model/gpt/dygraph/single_model.py:803-818,
1188-1247) and ships the Hamming-diversity and forced-BOS logits processors
(.../gpt/dygraph/processor.py:60-200) — but its dispatch raises "Not support
beam_search strategy yet". This module implements the full semantics the
config promises, TPU-style: one compiled ``lax.while_loop`` over a
``[batch, num_beams, total_len]`` token buffer, kv-cache batched over
``batch*num_beams`` and re-gathered per step, EOS hypotheses banked into a
fixed-size finished store (no dynamic shapes anywhere).

Scoring follows the conventional beam-search objective the reference's
config keys describe: hypothesis score = sum(logprob) / length**length_penalty,
with optional per-group Hamming diversity (arXiv:1610.02424): a token already
picked by an earlier group at the same step is penalized by diversity_rate.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from fleetx_tpu.models.gpt.generation import (
    GenerationConfig,
    mark_seen,
    process_logits,
    prompt_seen,
)

__all__ = ["beam_search"]

NEG_INF = -1.0e7  # large-but-finite so score arithmetic stays NaN-free


def _length_norm(length, penalty: float):
    return jnp.maximum(length, 1).astype(jnp.float32) ** penalty


def _flat_parent(parent: jax.Array, nb: int) -> jax.Array:
    """[b, nb] per-row beam indices -> [b*nb] global row indices."""
    b = parent.shape[0]
    return (jnp.arange(b, dtype=jnp.int32)[:, None] * nb + parent).reshape(-1)


def _gather_beams(tree, parent: jax.Array, nb: int, batch_axes,
                  cache_len: int = 0, suffix_start: int = 0):
    """Reindex the beam dimension of every leaf along its batch axis.
    ``batch_axes`` mirrors ``tree`` with the per-leaf batch-axis index (None
    for beam-invariant leaves like scan cache_index scalars) — cache leaves
    under nn.scan carry a leading layer axis, so the batch axis is NOT
    always 0 and is detected by the caller from shape diffs.

    ``suffix_start`` > 0 limits the reorder of kv leaves (position dim ==
    ``cache_len``, right after the batch dim) to positions >=
    ``suffix_start``: the prompt region of the cache is IDENTICAL across
    the beams of a batch row (prefill runs once per row and parents stay
    within the row), so physically reordering it is pure wasted HBM
    traffic — the dominant per-step cost at small decode spans. The
    dynamic_update_slice writes back in place on the donated while-loop
    carry."""
    flat = _flat_parent(parent, nb)

    def one(x, axis):
        if axis is None:
            return x
        pos_axis = axis + 1
        if (suffix_start > 0 and x.ndim > pos_axis
                and x.shape[pos_axis] == cache_len):
            start = (0,) * pos_axis + (suffix_start,) \
                + (0,) * (x.ndim - pos_axis - 1)
            sizes = list(x.shape)
            sizes[pos_axis] = cache_len - suffix_start
            suffix = jax.lax.dynamic_slice(x, start, sizes)
            suffix = jnp.take(suffix, flat, axis=axis)
            return jax.lax.dynamic_update_slice(x, suffix, start)
        return jnp.take(x, flat, axis=axis)

    return jax.tree.map(one, tree, batch_axes)


def beam_search(
    model,
    variables: Dict[str, Any],
    input_ids: jax.Array,
    gen_cfg: GenerationConfig,
    attention_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Returns [batch, num_return_sequences, prompt_len + max_length] tokens.

    Deterministic (no rng). Prompt rows may be left-padded via
    ``attention_mask`` exactly like :func:`generate`.
    """
    nb = int(gen_cfg.num_beams)
    ng = int(gen_cfg.num_beam_groups or 1)
    if nb < 1 or nb % ng:
        raise ValueError(f"num_beams={nb} must be a positive multiple of "
                         f"num_beam_groups={ng}")
    if ng > 1 and gen_cfg.diversity_rate <= 0.0:
        raise ValueError("group beam search needs diversity_rate > 0")
    nret = int(gen_cfg.num_return_sequences or 1)
    if nret > nb:
        raise ValueError("num_return_sequences cannot exceed num_beams")
    sub = nb // ng  # beams per group
    lp = float(gen_cfg.length_penalty)

    b, prompt_len = input_ids.shape
    total_len = prompt_len + gen_cfg.max_length
    max_pos = model.cfg.max_position_embeddings
    if total_len > max_pos:
        raise ValueError(
            f"prompt_len({prompt_len}) + max_length({gen_cfg.max_length}) "
            f"exceeds max_position_embeddings({max_pos})"
        )
    from fleetx_tpu.models.gpt.generation import right_size_decode_cache

    model, cache_len = right_size_decode_cache(model, total_len)
    params = variables["params"] if "params" in variables else variables
    eos = gen_cfg.eos_token_id
    pad = gen_cfg.pad_token_id

    if attention_mask is None:
        attention_mask = jnp.ones((b, prompt_len), jnp.int32)
    attention_mask = attention_mask.astype(jnp.int32)
    # flatten beams into the batch: every per-row quantity tiles to b*nb
    am_f = jnp.repeat(attention_mask, nb, axis=0)  # [b*nb, prompt]
    pad_counts = prompt_len - am_f.sum(axis=1)
    kv_valid = jnp.concatenate(
        [am_f.astype(bool), jnp.ones((b * nb, cache_len - prompt_len), bool)],
        axis=1,
    )
    kv_mask = kv_valid[:, None, None, :]

    ids_f = jnp.repeat(input_ids.astype(jnp.int32), nb, axis=0)
    tokens = jnp.full((b * nb, total_len), pad, jnp.int32)
    tokens = jax.lax.dynamic_update_slice(tokens, ids_f, (0, 0))

    cache_shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((b * nb, 1), jnp.int32),
            jnp.zeros((b * nb, 1), jnp.int32),
            decode=True,
        )
    )["cache"]
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)

    # prefill once per batch row (all beams share the prompt), then repeat
    # the cache across the beam dimension. Cache leaves may carry leading
    # scan-stacked layer axes, so the batch axis is located by diffing the
    # batch-b cache shape against the batch-b*nb one.
    cache1_shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((b, 1), jnp.int32),
            jnp.zeros((b, 1), jnp.int32),
            decode=True,
        )
    )["cache"]
    cache1 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache1_shapes)
    pos1 = jnp.clip(jnp.cumsum(attention_mask, axis=1) - 1, 0)
    kv_mask1 = kv_valid.reshape(b, nb, 1, 1, -1)[:, 0]
    logits, mut = model.apply(
        {"params": params, "cache": cache1},
        input_ids.astype(jnp.int32), pos1, kv_mask1,
        decode=True, mutable=["cache"],
    )

    def expand_beams(small, big_spec):
        if small.shape == big_spec.shape:
            return small  # beam-invariant (cache_index scalars etc.)
        axis = next(
            i for i, (s_dim, b_dim) in enumerate(zip(small.shape, big_spec.shape))
            if s_dim != b_dim
        )
        return jnp.repeat(small, nb, axis=axis)

    cache = jax.tree.map(expand_beams, mut["cache"], cache_shapes)
    # per-leaf batch axis: the dim where the batch-b and batch-b*nb cache
    # shapes differ (None = beam-invariant leaf)
    cache_batch_axes = jax.tree.map(
        lambda small, big: next(
            (i for i, (s_dim, b_dim) in enumerate(zip(small.shape, big.shape))
             if s_dim != b_dim), None),
        cache1_shapes, cache_shapes,
    )
    prefill_logits = jnp.repeat(logits[:, -1, :], nb, axis=0)

    vocab = prefill_logits.shape[-1]
    # [b*nb, vocab] seen-token scoreboard for the repetition penalty —
    # gathered with the beam parents each step and extended in O(vocab)
    # (vs the old per-step one-hot rebuild over the whole token buffer)
    track_seen = gen_cfg.repetition_penalty != 1.0
    seen = (prompt_seen(ids_f, am_f, vocab) if track_seen
            else jnp.zeros((b * nb, 1), jnp.bool_.dtype))
    # beam 0 of each group live, the rest -inf so step 1 fans out distinctly;
    # groups evolve independently, so each group gets one live seed beam.
    group_seed = jnp.zeros((nb,), bool).at[jnp.arange(ng) * sub].set(True)
    live_scores = jnp.where(group_seed, 0.0, NEG_INF)
    live_scores = jnp.tile(live_scores[None, :], (b, 1))  # [b, nb]

    fin_tokens = jnp.full((b, nb, total_len), pad, jnp.int32)
    fin_scores = jnp.full((b, nb), NEG_INF, jnp.float32)

    def beam_step(i, tokens, seen, cache, live_scores, fin_tokens, fin_scores,
                  step_logits):
        """One decode position: pick successors per group, bank EOS
        hypotheses. ``step_logits`` [b*nb, V] are this position's logits."""
        logp = jax.nn.log_softmax(step_logits.astype(jnp.float32), axis=-1)
        logp = process_logits(
            logp, seen if track_seen else None, i, gen_cfg,
            prompt_len=prompt_len, total_len=total_len,
        )
        if gen_cfg.forced_bos_token_id is not None:
            # force the FIRST generated token (reference
            # ForcedBOSTokenLogitsProcessor, processor.py:166-180)
            at_first = i == prompt_len
            forced = jnp.full_like(logp, NEG_INF).at[
                :, gen_cfg.forced_bos_token_id].set(0.0)
            logp = jnp.where(at_first, forced, logp)
        logp = logp.reshape(b, nb, vocab)

        new_tokens = tokens
        new_live = jnp.zeros_like(live_scores)
        parent_all = jnp.zeros((b, nb), jnp.int32)
        tok_all = jnp.zeros((b, nb), jnp.int32)
        picked_onehot = jnp.zeros((b, vocab), jnp.float32)  # diversity counts

        decoded_len = (i + 1 - prompt_len).astype(jnp.float32)
        for g in range(ng):  # static unroll over groups
            sl = slice(g * sub, (g + 1) * sub)
            glogp = logp[:, sl, :]
            if ng > 1:
                # Hamming diversity: penalize tokens earlier groups chose at
                # this step (processor.py HammingDiversityLogitsProcessor)
                glogp = glogp - gen_cfg.diversity_rate * picked_onehot[:, None, :]
            cand = live_scores[:, sl, None] + glogp  # [b, sub, V]
            flat = cand.reshape(b, sub * vocab)
            # 2*sub candidates: enough non-EOS survivors even if the top sub
            # all want to finish (t5x-style over-provisioning)
            k = min(2 * sub, sub * vocab)
            top_scores, top_idx = jax.lax.top_k(flat, k)
            top_parent = (top_idx // vocab).astype(jnp.int32) + g * sub
            top_tok = (top_idx % vocab).astype(jnp.int32)
            is_eos = top_tok == eos

            # bank EOS candidates into the finished store (score normalized)
            norm = top_scores / _length_norm(decoded_len, lp)
            eos_scores = jnp.where(is_eos, norm, NEG_INF)  # [b, k]
            # candidate finished sequences: parent's tokens + eos at slot i
            parent_toks = jnp.take_along_axis(
                tokens.reshape(b, nb, total_len),
                top_parent[..., None], axis=1,
            )  # [b, k, L]
            cand_fin = jax.vmap(
                lambda t, tk: jax.lax.dynamic_update_index_in_dim(
                    t, tk, i, axis=-1),
                in_axes=(0, 0),
            )(parent_toks.reshape(b * k, total_len),
              jnp.broadcast_to(jnp.int32(eos), (b * k,))).reshape(b, k, total_len)
            all_fin_scores = jnp.concatenate([fin_scores, eos_scores], axis=1)
            all_fin_tokens = jnp.concatenate(
                [fin_tokens, cand_fin], axis=1)
            best_scores, best_idx = jax.lax.top_k(all_fin_scores, nb)
            fin_scores = best_scores
            fin_tokens = jnp.take_along_axis(
                all_fin_tokens, best_idx[..., None], axis=1)

            # live successors: best sub non-EOS candidates
            live_cand = jnp.where(is_eos, NEG_INF, top_scores)
            g_scores, g_pick = jax.lax.top_k(live_cand, sub)
            g_parent = jnp.take_along_axis(top_parent, g_pick, axis=1)
            g_tok = jnp.take_along_axis(top_tok, g_pick, axis=1)

            new_live = new_live.at[:, sl].set(g_scores)
            parent_all = parent_all.at[:, sl].set(g_parent)
            tok_all = tok_all.at[:, sl].set(g_tok)
            if ng > 1:
                picked_onehot = picked_onehot + jax.nn.one_hot(
                    g_tok, vocab, dtype=jnp.float32).sum(axis=1)

        # reorder beams to their parents, append the chosen tokens
        new_tokens = jnp.take(tokens, _flat_parent(parent_all, nb), axis=0)
        new_tokens = jax.lax.dynamic_update_slice(
            new_tokens, tok_all.reshape(b * nb, 1), (0, i))
        if track_seen:
            # the scoreboard follows its beam through the reorder, then the
            # chosen token is folded in
            seen = jnp.take(seen, _flat_parent(parent_all, nb), axis=0)
            seen = mark_seen(seen, tok_all.reshape(-1))
        cache = _gather_beams(cache, parent_all, nb, cache_batch_axes,
                              cache_len=cache_len, suffix_start=prompt_len)
        return new_tokens, seen, cache, new_live, fin_tokens, fin_scores

    # first decode position consumes the prefill logits
    tokens, seen, cache, live_scores, fin_tokens, fin_scores = beam_step(
        jnp.asarray(prompt_len), tokens, seen, cache, live_scores, fin_tokens,
        fin_scores, prefill_logits,
    )

    def cond(state):
        i, _, _, _, live_scores, _, fin_scores = state
        # a live beam can still improve on the worst banked hypothesis iff
        # its optimistic final score beats it (HF/t5x early-termination rule);
        # with early_stopping the bank being full ends the search outright.
        decoded = jnp.maximum(i - prompt_len, 1).astype(jnp.float32)
        if gen_cfg.early_stopping:
            bank_full = jnp.all(fin_scores > NEG_INF / 2, axis=1)
            return (i < total_len) & ~jnp.all(bank_full)
        else:
            # optimistic bound: scores only decrease (logprobs <= 0), so the
            # best a live beam can reach is its current sum at the most
            # favorable normalization length still reachable
            max_decoded = jnp.float32(total_len - prompt_len)
            norm_now = _length_norm(decoded, lp)
            norm_end = _length_norm(max_decoded, lp)
            best_possible = jnp.maximum(
                live_scores / norm_now, live_scores / norm_end)
        improvable = jnp.any(
            best_possible.max(axis=1) > fin_scores.min(axis=1))
        return (i < total_len) & improvable

    def body(state):
        i, tokens, seen, cache, live_scores, fin_tokens, fin_scores = state
        cur = jax.lax.dynamic_slice(tokens, (0, i - 1), (b * nb, 1))
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            cur,
            (i - 1 - pad_counts)[:, None].astype(jnp.int32),
            kv_mask,
            decode=True,
            mutable=["cache"],
        )
        tokens, seen, cache, live_scores, fin_tokens, fin_scores = beam_step(
            i, tokens, seen, mut["cache"], live_scores, fin_tokens,
            fin_scores, logits[:, -1, :],
        )
        return i + 1, tokens, seen, cache, live_scores, fin_tokens, fin_scores

    (i, tokens, seen, cache, live_scores, fin_tokens,
     fin_scores) = jax.lax.while_loop(
        cond, body,
        (jnp.asarray(prompt_len + 1), tokens, seen, cache, live_scores,
         fin_tokens, fin_scores),
    )

    # if a batch row banked nothing (no EOS fit in the budget), fall back to
    # the best live beams at their final-length normalization
    decoded = jnp.maximum(i - prompt_len, 1).astype(jnp.float32)
    live_norm = live_scores / _length_norm(decoded, lp)
    all_scores = jnp.concatenate([fin_scores, live_norm], axis=1)
    all_tokens = jnp.concatenate(
        [fin_tokens, tokens.reshape(b, nb, total_len)], axis=1)
    _, order = jax.lax.top_k(all_scores, nret)
    return jnp.take_along_axis(all_tokens, order[..., None], axis=1)
