"""Vision Transformer, TPU-native flax implementation.

Capability parity with the reference ViT zoo
(/root/reference/ppfleetx/models/vision_model/vit/vit.py:100-443 and
vision_model/layers/: patch embedding, fused-qkv attention, MLP, droppath,
class-token pooling, 14 size presets up to ViT-6B).

TPU-first: patch embedding is a Conv (maps to MXU), attention reuses the
shared fused path (ops/attention.py), TP sharding is the same logical-axis
annotation scheme as GPT/ERNIE so ViT-G/6B presets shard over mp/fsdp
without model changes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from fleetx_tpu.models.gpt.model import (
    _constrain_act,
    _dense,
    _layer_norm,
    attn_out_dense,
    default_kernel_init,
)
from fleetx_tpu.ops.attention import causal_attention
from fleetx_tpu.ops.dropout import dropout_layer

Dtype = Any

__all__ = ["ViTConfig", "ViT", "VIT_PRESETS", "build_vision_model"]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    """ViT backbone hyperparameters (reference vit.py presets)."""
    image_size: int = 224
    patch_size: int = 16
    in_channels: int = 3
    num_classes: int = 1000
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    mlp_ratio: float = 4.0
    qkv_bias: bool = True
    drop_rate: float = 0.0
    attn_drop_rate: float = 0.0
    drop_path_rate: float = 0.0
    representation_size: Optional[int] = None
    # 'gelu_tanh' (reference default) or 'gelu' (erf; HF ViT checkpoints)
    hidden_act: str = "gelu_tanh"
    # hash-based hidden dropout (ops/dropout.py); False restores nn.Dropout
    fast_dropout: bool = True
    # flash attention for the encoder blocks (seq 197 pads to a single
    # 200-row kernel tile in ops/attention.py); False restores XLA attention
    use_flash_attention: bool = True
    use_recompute: bool = False
    dtype: Dtype = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @classmethod
    def from_model_config(cls, model_cfg) -> "ViTConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in dict(model_cfg).items() if k in known and v is not None}
        if isinstance(kw.get("dtype"), str):
            kw["dtype"] = jnp.dtype(kw["dtype"]).type
        return cls(**kw)


# name -> config overrides (reference vit.py:261-443 presets)
VIT_PRESETS = {
    "ViT_tiny_patch16_224": dict(patch_size=16, hidden_size=192, num_layers=12, num_attention_heads=3),
    "ViT_small_patch16_224": dict(patch_size=16, hidden_size=384, num_layers=12, num_attention_heads=6),
    "ViT_base_patch16_224": dict(patch_size=16, hidden_size=768, num_layers=12, num_attention_heads=12),
    "ViT_base_patch16_384": dict(image_size=384, patch_size=16, hidden_size=768, num_layers=12, num_attention_heads=12),
    "ViT_base_patch32_224": dict(patch_size=32, hidden_size=768, num_layers=12, num_attention_heads=12),
    "ViT_base_patch32_384": dict(image_size=384, patch_size=32, hidden_size=768, num_layers=12, num_attention_heads=12),
    "ViT_large_patch16_224": dict(patch_size=16, hidden_size=1024, num_layers=24, num_attention_heads=16),
    "ViT_large_patch16_384": dict(image_size=384, patch_size=16, hidden_size=1024, num_layers=24, num_attention_heads=16),
    "ViT_large_patch32_224": dict(patch_size=32, hidden_size=1024, num_layers=24, num_attention_heads=16),
    "ViT_large_patch32_384": dict(image_size=384, patch_size=32, hidden_size=1024, num_layers=24, num_attention_heads=16),
    "ViT_huge_patch14_224": dict(patch_size=14, hidden_size=1280, num_layers=32, num_attention_heads=16),
    "ViT_huge_patch14_384": dict(image_size=384, patch_size=14, hidden_size=1280, num_layers=32, num_attention_heads=16),
    "ViT_g_patch14_224": dict(patch_size=14, hidden_size=1408, num_layers=40, num_attention_heads=16, mlp_ratio=48 / 11),
    "ViT_G_patch14_224": dict(patch_size=14, hidden_size=1664, num_layers=48, num_attention_heads=16, mlp_ratio=64 / 13),
    "ViT_6B_patch14_224": dict(patch_size=14, hidden_size=2320, num_layers=80, num_attention_heads=16),
}


class DropPath(nn.Module):
    """Stochastic depth — drop whole residual branches per sample
    (reference vision_model/layers/droppath.py)."""

    rate: float

    @nn.compact
    def __call__(self, x, deterministic=True):
        if self.rate == 0.0 or deterministic:
            return x
        keep = 1.0 - self.rate
        rng = self.make_rng("dropout")
        mask_shape = (x.shape[0],) + (1,) * (x.ndim - 1)
        mask = jax.random.bernoulli(rng, keep, mask_shape)
        return jnp.where(mask, x / keep, 0.0)


class ViTBlock(nn.Module):
    """Pre-LN transformer encoder block with droppath (reference
    vision_model/layers)."""
    cfg: ViTConfig
    drop_path: float = 0.0

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.cfg
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        y = _layer_norm(cfg, "norm1")(x)
        qkv = _dense((nh, 3 * hd), ("embed", "heads", "kv"), "qkv_proj", dtype=cfg.dtype,
                     use_bias=cfg.qkv_bias)(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        dropout_rng = None
        if cfg.attn_drop_rate > 0.0 and not deterministic:
            dropout_rng = self.make_rng("dropout")
        y = causal_attention(
            q, k, v,
            causal=False,
            dropout_rate=cfg.attn_drop_rate,
            dropout_rng=dropout_rng,
            deterministic=deterministic,
            # seq 197 (196 patches + cls) pads to 200 inside the dispatch
            # (one kernel tile); use_flash_attention: False restores XLA
            use_flash=cfg.use_flash_attention,
        )
        y = attn_out_dense(cfg.hidden_size, cfg.dtype)(y)
        y = dropout_layer(cfg.drop_rate, "proj_drop", cfg.fast_dropout)(y, deterministic=deterministic)
        x = x + DropPath(self.drop_path, name="drop_path1")(y, deterministic)

        y = _layer_norm(cfg, "norm2")(x)
        y = _dense(int(cfg.hidden_size * cfg.mlp_ratio), ("embed", "mlp"), "fc1",
                   dtype=cfg.dtype)(y)
        y = nn.gelu(y, approximate=cfg.hidden_act != "gelu")
        y = _dense(cfg.hidden_size, ("mlp", "embed"), "fc2", dtype=cfg.dtype)(y)
        y = dropout_layer(cfg.drop_rate, "mlp_drop", cfg.fast_dropout)(y, deterministic=deterministic)
        x = x + DropPath(self.drop_path, name="drop_path2")(y, deterministic)
        return _constrain_act(x, cfg)


class ViT(nn.Module):
    """Patch embed + cls token + encoder + classification head. Input images
    are channels-last [b, H, W, C] (TPU conv layout)."""

    cfg: ViTConfig

    @nn.compact
    def __call__(self, images, *, deterministic=True):
        cfg = self.cfg
        b = images.shape[0]
        x = nn.Conv(
            features=cfg.hidden_size,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            padding="VALID",
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                default_kernel_init, (None, None, None, "embed")
            ),
            name="patch_embed",
        )(images.astype(cfg.dtype))
        x = x.reshape(b, -1, cfg.hidden_size)  # [b, patches, h]

        cls_token = self.param(
            "cls_token",
            nn.with_logical_partitioning(nn.initializers.zeros_init(), (None, None, "embed")),
            (1, 1, cfg.hidden_size),
            jnp.float32,
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls_token, (b, 1, cfg.hidden_size)).astype(cfg.dtype), x],
            axis=1,
        )
        pos_emb = self.param(
            "pos_embed",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.02), (None, None, "embed")
            ),
            (1, cfg.num_patches + 1, cfg.hidden_size),
            jnp.float32,
        )
        x = x + pos_emb.astype(cfg.dtype)
        x = dropout_layer(cfg.drop_rate, "pos_drop", cfg.fast_dropout)(x, deterministic=deterministic)
        x = _constrain_act(x, cfg)

        # linearly-increasing stochastic depth (reference vit.py dpr rule)
        for i in range(cfg.num_layers):
            dp = cfg.drop_path_rate * i / max(cfg.num_layers - 1, 1)
            block = ViTBlock
            if cfg.use_recompute:
                block = nn.remat(ViTBlock, static_argnums=(2,))
            x = block(cfg, dp, name=f"block_{i}")(x, deterministic)

        x = _layer_norm(cfg, "final_norm")(x)
        x = x[:, 0]  # cls token
        if cfg.representation_size:
            x = _dense(cfg.representation_size, ("embed", None), "pre_logits",
                       dtype=cfg.dtype)(x)
            x = jnp.tanh(x)
        if cfg.num_classes == 0:  # backbone mode (MoCo etc.): pooled features
            return x
        logits = _dense(cfg.num_classes, ("embed", None), "head",
                        dtype=jnp.float32)(x.astype(jnp.float32))
        return logits


def build_vision_model(name: str, **overrides) -> ViT:
    """Model-zoo factory (reference vision_model/factory.py)."""
    if name not in VIT_PRESETS:
        raise ValueError(f"unknown vision model {name!r}; have {sorted(VIT_PRESETS)}")
    kw = {**VIT_PRESETS[name], **overrides}
    return ViT(ViTConfig(**kw))
