"""Vision backbones: ViT presets, ResNet for MoCo (reference models/vision_model)."""

from fleetx_tpu.models.vision.vit import (  # noqa: F401
    ViT,
    ViTConfig,
    build_vision_model,
    VIT_PRESETS,
)
