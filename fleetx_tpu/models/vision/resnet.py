"""ResNet backbones (reference MoCo uses paddle.vision resnet50,
/root/reference/ppfleetx/models/vision_model/moco/moco.py:94-120).

TPU-first choice: GroupNorm instead of BatchNorm. No running statistics
means no mutable batch_stats collection threading through the engine, and
MoCo needs no shuffling-BN trick (the reference shuffles keys across GPUs
purely to stop intra-batch BN statistics leakage, moco.py's
_batch_shuffle; GroupNorm has no cross-sample statistics to leak)."""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["ResNet", "ResNetConfig", "RESNET_PRESETS", "build_resnet"]

Dtype = Any


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    """ResNet depth/width hyperparameters for the MoCo backbone."""
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)
    bottleneck: bool = True
    width: int = 64
    num_classes: int = 0  # 0 = return pooled features
    groups: int = 32  # GroupNorm groups
    dtype: Dtype = jnp.bfloat16


RESNET_PRESETS = {
    "resnet18": dict(stage_sizes=(2, 2, 2, 2), bottleneck=False),
    "resnet34": dict(stage_sizes=(3, 4, 6, 3), bottleneck=False),
    "resnet50": dict(stage_sizes=(3, 4, 6, 3), bottleneck=True),
    "resnet101": dict(stage_sizes=(3, 4, 23, 3), bottleneck=True),
}


def _conv(features, kernel, strides, name, dtype):
    return nn.Conv(
        features, (kernel, kernel), (strides, strides),
        padding="SAME", use_bias=False, dtype=dtype, param_dtype=jnp.float32,
        name=name,
    )


class _Block(nn.Module):
    cfg: ResNetConfig
    features: int
    strides: int = 1

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        gn = lambda name: nn.GroupNorm(
            num_groups=min(cfg.groups, self.features), dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name,
        )
        residual = x
        if cfg.bottleneck:
            y = nn.relu(gn("gn1")(_conv(self.features, 1, 1, "conv1", cfg.dtype)(x)))
            y = nn.relu(gn("gn2")(_conv(self.features, 3, self.strides, "conv2", cfg.dtype)(y)))
            out_f = self.features * 4
            y = nn.GroupNorm(num_groups=min(cfg.groups, out_f), dtype=cfg.dtype,
                             param_dtype=jnp.float32, name="gn3")(
                _conv(out_f, 1, 1, "conv3", cfg.dtype)(y)
            )
        else:
            y = nn.relu(gn("gn1")(_conv(self.features, 3, self.strides, "conv1", cfg.dtype)(x)))
            out_f = self.features
            y = gn("gn2")(_conv(out_f, 3, 1, "conv2", cfg.dtype)(y))
        if residual.shape[-1] != out_f or self.strides != 1:
            residual = nn.GroupNorm(
                num_groups=min(cfg.groups, out_f), dtype=cfg.dtype,
                param_dtype=jnp.float32, name="gn_proj",
            )(_conv(out_f, 1, self.strides, "conv_proj", cfg.dtype)(residual))
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Input [b, H, W, C] channels-last; returns pooled features [b, F] (or
    logits when num_classes > 0)."""

    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = x.astype(cfg.dtype)
        x = _conv(cfg.width, 7, 2, "conv_stem", cfg.dtype)(x)
        x = nn.GroupNorm(num_groups=min(cfg.groups, cfg.width), dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="gn_stem")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            for b in range(n_blocks):
                x = _Block(
                    cfg,
                    features=cfg.width * (2 ** stage),
                    strides=2 if stage > 0 and b == 0 else 1,
                    name=f"stage{stage}_block{b}",
                )(x)
        x = x.mean(axis=(1, 2))  # global average pool
        if cfg.num_classes:
            x = nn.Dense(cfg.num_classes, dtype=jnp.float32,
                         param_dtype=jnp.float32, name="fc")(x.astype(jnp.float32))
        return x


def build_resnet(name: str, **overrides) -> ResNet:
    """ResNet factory by depth name (resnet50 etc.)."""
    if name not in RESNET_PRESETS:
        raise ValueError(f"unknown resnet {name!r}; have {sorted(RESNET_PRESETS)}")
    return ResNet(ResNetConfig(**{**RESNET_PRESETS[name], **overrides}))
