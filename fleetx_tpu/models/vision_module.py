"""GeneralClsModule — image-classification training/eval
(reference /root/reference/ppfleetx/models/vision_model/
general_classification_module.py:31-140: CE loss with label smoothing,
mixup, top-1/top-5 accuracy).

TPU-first: mixup runs *inside* the jitted loss (jax.random.beta + batch
roll) instead of in the host collate fn — no host-side RNG state and the
whole step stays one XLA program.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from fleetx_tpu.models.language_module import resolve_compute_dtype
from fleetx_tpu.models.module import BasicModule
from fleetx_tpu.models.vision.vit import ViTConfig, ViT, build_vision_model
from fleetx_tpu.utils.log import logger

__all__ = ["GeneralClsModule"]


def log_images_per_sec(cfg, log: Dict) -> None:
    """Vision train-log line: images/s global (ips_total) and per-process
    (the benchmark-parsed ips field). Shared by GeneralClsModule and
    MOCOModule; the engine's element-count ips is pixels for image batches."""
    import jax

    images_total = cfg.Global.global_batch_size / max(log["batch_cost"], 1e-9)
    logger.train(
        "[train] epoch: %d, batch: %d, loss: %.9f, avg_batch_cost: %.5f sec, "
        "speed: %.2f step/s, ips_total: %.0f images/s, ips: %.0f images/s, "
        "learning rate: %.3e",
        log["epoch"], log["batch"], log["loss"], log["batch_cost"],
        1.0 / max(log["batch_cost"], 1e-9),
        images_total,
        images_total / max(jax.process_count(), 1),
        log["lr"],
    )


def _soft_ce(logits, targets, label_smoothing=0.0):
    """Cross-entropy with dense (possibly mixed) targets [b, C]."""
    n_cls = logits.shape[-1]
    if targets.ndim == 1:
        targets = jax.nn.one_hot(targets, n_cls)
    if label_smoothing > 0.0:
        targets = targets * (1.0 - label_smoothing) + label_smoothing / n_cls
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -(targets * logp).sum(axis=-1).mean()


class GeneralClsModule(BasicModule):
    """Batch contract: {"images": [b,H,W,C] float32, "labels": [b] int32}."""

    def get_model(self):
        import dataclasses

        model_cfg = self.cfg.Model if hasattr(self.cfg, "Model") else self.cfg
        name = model_cfg.get("name")
        fields = {f.name for f in dataclasses.fields(ViTConfig)}
        overrides = {
            k: v for k, v in dict(model_cfg).items()
            if k in fields and v is not None
        }
        eng = getattr(self.cfg, "Engine", None) or {}
        overrides["dtype"] = resolve_compute_dtype(eng)
        self.mixup_alpha = float(model_cfg.get("mixup_alpha") or 0.0)
        self.label_smoothing = float(model_cfg.get("label_smoothing") or 0.0)
        if name:
            model = build_vision_model(name, **overrides)
        else:
            model = ViT(ViTConfig(**overrides))
        self.vit_config = model.cfg
        return model

    def init_params(self, rng, batch):
        return self.nets.init(rng, jnp.asarray(batch["images"]))

    def serving_forward(self, input_spec):
        """Serving contract: images -> class logits (export/inference)."""
        def fwd(p, batch):
            return self.nets.apply({"params": p}, batch["images"])

        return fwd, ["images"]

    def loss_fn(self, params, batch, rng, train: bool):
        images = batch["images"]
        labels = batch["labels"]
        n_cls = self.vit_config.num_classes
        targets = jax.nn.one_hot(labels, n_cls)
        apply_rngs = None
        if train and rng is not None:
            mix_rng, drop_rng = jax.random.split(rng)
            apply_rngs = {"dropout": drop_rng}
            if self.mixup_alpha > 0.0:
                lam = jax.random.beta(mix_rng, self.mixup_alpha, self.mixup_alpha)
                # roll-by-one pairing: static, vectorized, permutation-free
                images = lam * images + (1.0 - lam) * jnp.roll(images, 1, axis=0)
                targets = lam * targets + (1.0 - lam) * jnp.roll(targets, 1, axis=0)
        logits = self.nets.apply(
            {"params": params}, images, deterministic=not train, rngs=apply_rngs
        )
        loss = _soft_ce(logits, targets, self.label_smoothing)
        acc = (jnp.argmax(logits, axis=-1) == labels).mean()
        return loss, {"acc": acc}

    def input_spec(self):
        glb = self.cfg.Global
        b = glb.micro_batch_size or 1
        c = self.vit_config
        return {
            "images": jax.ShapeDtypeStruct(
                (b, c.image_size, c.image_size, c.in_channels), jnp.float32
            ),
            "labels": jax.ShapeDtypeStruct((b,), jnp.int32),
        }

    def training_step_end(self, log: Dict) -> None:
        log_images_per_sec(self.cfg, log)

    def validation_step_end(self, log: Dict) -> None:
        logger.eval(
            "[eval] epoch: %d, batch: %d, loss: %.9f, avg_eval_cost: %.5f sec",
            log["epoch"], log["batch"], log["loss"], log["batch_cost"],
        )
