"""GPTEvalModule — offline WikiText perplexity / LAMBADA cloze accuracy
(reference /root/reference/ppfleetx/models/language_model/
language_module.py:586-703: swaps the eval dataset class and scores
sum-of-log-probs (PPL) or exact-match on the target word (ACC))."""

from __future__ import annotations

import math
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from fleetx_tpu.models.language_module import GPTModule

__all__ = ["GPTEvalModule"]


class GPTEvalModule(GPTModule):
    """Batch contract: same (tokens, position_ids, labels, loss_mask) dict;
    scoring accumulates un-normalized nll + mask counts host-side."""

    def __init__(self, cfg):
        super().__init__(cfg)
        eval_cfg = cfg.get("Offline_Eval") or {}
        self.eval_type = "lambada" if eval_cfg.get("cloze_eval") else "wikitext"
        self._score_fn = None

    def score_batch(self, params, batch) -> Dict[str, np.ndarray]:
        if self._score_fn is None:
            def score(params, batch):
                tokens, position_ids, labels, loss_mask = self.cp_prepare(batch)
                logits = self.nets.apply(
                    {"params": params}, tokens, position_ids
                ).astype(jnp.float32)
                logz = jax.nn.logsumexp(logits, axis=-1)
                tgt = jnp.take_along_axis(
                    logits, labels[..., None], axis=-1
                )[..., 0]
                nll = (logz - tgt) * loss_mask
                # cloze correctness: every masked target predicted exactly
                # (per-row any() is order-invariant under the zig-zag permute)
                pred = jnp.argmax(logits, axis=-1)
                wrong = ((pred != labels) & (loss_mask > 0)).any(axis=1)
                return {
                    "nll_sum": nll.sum(),
                    "token_count": loss_mask.sum(),
                    "correct": (~wrong).sum(),
                    "examples": jnp.asarray(tokens.shape[0], jnp.float32),
                }

            self._score_fn = jax.jit(score)
        return {k: np.asarray(v) for k, v in self._score_fn(params, batch).items()}

    def evaluate_dataset(self, params, loader) -> Dict[str, float]:
        total = {"nll_sum": 0.0, "token_count": 0.0, "correct": 0.0, "examples": 0.0}
        for batch in loader:
            out = self.score_batch(params, batch)
            for k in total:
                total[k] += float(out[k])
        if self.eval_type == "lambada":
            acc = total["correct"] / max(total["examples"], 1.0)
            return {"acc": acc, "examples": int(total["examples"])}
        ppl = math.exp(total["nll_sum"] / max(total["token_count"], 1.0))
        return {"ppl": ppl, "tokens": int(total["token_count"])}
