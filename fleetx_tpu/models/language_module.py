"""Language-model modules (reference /root/reference/ppfleetx/models/
language_model/language_module.py:47-222).

One GPTModule serves every topology — the reference's class-per-parallelism
dispatch (GPTModel | GPTModelHybrid | GPTForPretrainingPipe picked by
nranks/pp_degree, language_module.py:153-188) is unnecessary when sharding is
annotation-driven.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from fleetx_tpu.models.gpt.model import (
    GPTConfig,
    GPTForPretraining,
    pretraining_loss,
)
from fleetx_tpu.models.module import BasicModule
from fleetx_tpu.utils.log import logger

__all__ = ["LanguageModule", "GPTModule"]


class LanguageModule(BasicModule):
    """Adds LM-style logging: loss, lr, avg step cost, ips (tokens/s) — the
    ``ips:`` keyword line is what the benchmark harness parses (reference
    run_benchmark.sh:20-22)."""

    def training_step_end(self, log: Dict) -> None:
        # mfu rides the same parsed line: tokens/s alone is not comparable
        # across configs, and the BENCH_* records already report MFU — the
        # live log should speak the same language (docs/OBSERVABILITY.md).
        # "-" when XLA exposed no flops for this step program.
        mfu = log.get("mfu")
        logger.train(
            "[train] epoch: %d, batch: %d, loss: %.9f, avg_batch_cost: %.5f sec, "
            "speed: %.2f step/s, ips_total: %.0f tokens/s, ips: %.0f tokens/s, "
            "mfu: %s, learning rate: %.3e",
            log["epoch"],
            log["batch"],
            log["loss"],
            log["batch_cost"],
            1.0 / max(log["batch_cost"], 1e-9),
            log["ips_total"],
            log["ips"],
            ("%.4f" % mfu) if mfu is not None else "-",
            log["lr"],
        )

    def validation_step_end(self, log: Dict) -> None:
        logger.eval(
            "[eval] epoch: %d, batch: %d, loss: %.9f, avg_eval_cost: %.5f sec",
            log["epoch"],
            log["batch"],
            log["loss"],
            log["batch_cost"],
        )


def resolve_compute_dtype(engine_cfg):
    """AMP config → compute dtype. fp16 maps to bf16: TPU-native mixed
    precision needs no loss scaling (the reference's GradScaler + AMP-O2
    decorate, eager_engine.py:162-172, has no TPU equivalent to need)."""
    mp = (engine_cfg.get("mix_precision") or {}) if isinstance(engine_cfg, dict) else {}
    name = mp.get("dtype") or ("bfloat16" if mp.get("use_pure_fp16") else "float32")
    return {"bfloat16": jnp.bfloat16, "float16": jnp.bfloat16,
            "float32": jnp.float32}[str(name)]


def load_pretrained_gpt_backbone(params, artifact_dir, fuse_attn_qkv):
    """Merge a pretrained GPT backbone from an export artifact into a fresh
    param tree: weights copied by path under the 'gpt' subtree, fused/split
    qkv layouts converted to the target config, heads without a pretrained
    counterpart left at fresh init (reference checkpoint conversion,
    language_module.py:293-372). Shared by GPTModule (pretrain/eval/
    generation warm starts, e.g. a converted HF GPT-2) and
    GPTFinetuneModule."""
    import numpy as np

    from fleetx_tpu.models.gpt.model import convert_qkv_layout
    from fleetx_tpu.utils.export import load_exported

    _, src_params, _ = load_exported(artifact_dir)
    src = src_params.get("gpt", src_params)
    src = convert_qkv_layout(src, to_fused=fuse_attn_qkv)
    if "gpt" not in params:
        raise ValueError("params have no 'gpt' backbone subtree")

    stats = {"matched": 0, "fresh": 0}

    def merge(dst, srcd, path):
        out = {}
        for k, v in dst.items():
            here = f"{path}/{k}"
            if isinstance(v, dict):
                out[k] = (
                    merge(v, srcd[k], here)
                    if isinstance(srcd.get(k), dict) else v
                )
            elif k in srcd:
                sv = np.asarray(srcd[k])
                if sv.shape != np.shape(v):
                    raise ValueError(
                        f"pretrained shape mismatch at {here}: "
                        f"{sv.shape} vs {np.shape(v)}"
                    )
                out[k] = sv.astype(np.asarray(v).dtype)
                stats["matched"] += 1
            else:
                out[k] = v  # no pretrained counterpart: keep fresh init
                stats["fresh"] += 1
        return out

    new = dict(params)
    new["gpt"] = merge(params["gpt"], src, "gpt")
    if stats["matched"] == 0:
        raise ValueError(
            f"no parameter in {artifact_dir} matched the target tree — "
            "layouts disagree (e.g. scan_layers on one side only); refusing "
            "to 'warm start' from random init"
        )
    logger.info(
        "loaded pretrained backbone from %s (%d leaves matched, %d fresh)",
        artifact_dir, stats["matched"], stats["fresh"],
    )
    return new


def init_pipeline_params_via_sequential(nets, rng, tokens):
    """Initialize a pp>1 GPT through its SEQUENTIAL twin, then remap.

    The pipeline scopes (nn.scan over ticks -> nn.vmap over stages -> nn.scan
    over layers) fold the init RNG differently than the plain layer scan, so
    initializing the pp model directly gives different weights than the
    single-device model for the same seed. Parallelism must stay a layout
    choice (sharded 1-step loss == single-device loss): init the pp=1 twin,
    reshape [L, ...] -> [pp, L/pp, ...] with the checkpoint converter, and
    graft the values into the pp model's own axis-metadata boxes so sharding
    derivation still sees the pipeline's logical axes ('stage', 'layers')."""
    import dataclasses

    import flax
    import flax.linen as nn

    from fleetx_tpu.parallel.pipeline import sequential_params_to_pipeline

    gcfg = nets.cfg
    seq_cfg = dataclasses.replace(
        gcfg, pp_degree=1, num_microbatches=1, virtual_pp_degree=1,
        scan_layers=True, no_recompute_layers=None,
    )
    seq_vars = type(nets)(seq_cfg).init(rng, tokens)
    is_box = lambda x: isinstance(x, nn.meta.AxisMetadata)
    unboxed = jax.tree.map(
        lambda x: x.unbox() if is_box(x) else x,
        flax.core.unfreeze(seq_vars),
        is_leaf=is_box,
    )
    remapped = sequential_params_to_pipeline(
        unboxed, gcfg.pp_degree, max(gcfg.virtual_pp_degree, 1),
        stream=getattr(gcfg, "virtual_pp_stream", None),
    )
    abstract = jax.eval_shape(lambda r: nets.init(r, tokens), rng)
    flat_abs = flax.traverse_util.flatten_dict(
        flax.core.unfreeze(abstract), sep="/"
    )
    flat_val = flax.traverse_util.flatten_dict(
        flax.core.unfreeze(remapped), sep="/"
    )
    if set(flat_abs) != set(flat_val):
        missing = set(flat_abs) ^ set(flat_val)
        raise ValueError(
            f"sequential->pipeline param remap mismatch at: {sorted(missing)[:5]}"
        )
    out = {
        k: box.replace_boxed(flat_val[k].astype(box.unbox().dtype))
        if is_box(box) else flat_val[k]
        for k, box in flat_abs.items()
    }
    return flax.traverse_util.unflatten_dict(out, sep="/")


class GPTModule(LanguageModule):
    """GPT pretraining module: batch = (tokens, position_ids, labels,
    loss_mask)."""

    def get_model(self):
        model_cfg = self.cfg.Model if hasattr(self.cfg, "Model") else self.cfg
        gcfg = GPTConfig.from_model_config(model_cfg)
        eng = getattr(self.cfg, "Engine", None) or {}
        extra = {"dtype": resolve_compute_dtype(eng)}
        dist = getattr(self.cfg, "Distributed", None) or {}
        pp = dist.get("pp_degree") or 1
        if pp > 1:
            # PP folds grad accumulation into the pipeline's microbatch
            # stream (reference pipeline_configs accumulate_steps semantics,
            # env.py:103-107)
            extra["pp_degree"] = pp
            extra["num_microbatches"] = max(eng.get("accumulate_steps") or 1, 1)
        cp = dist.get("cp_degree") or 1
        if cp > 1:
            extra["cp_degree"] = cp
        gcfg = GPTConfig(**{**gcfg.__dict__, **extra})
        if gcfg.fused_ce:
            # the fused LM-head+CE kernel needs a lane-aligned PER-SHARD
            # vocab block (mp>1 runs the vocab-parallel form); cp/pp stay
            # demoted — fall back to the XLA logits path instead of
            # crashing at trace time
            from fleetx_tpu.ops.pallas.ce_loss import fit_vocab_block

            mp = dist.get("mp_degree") or 1
            why = None
            if gcfg.vocab_size % mp or fit_vocab_block(
                    gcfg.vocab_size // mp) is None:
                why = (f"vocab {gcfg.vocab_size} / mp {mp} admits no "
                       "lane-aligned block (128-multiple or 64)")
            elif cp > 1 or pp > 1:
                # mp>1 is supported (vocab-parallel kernel); cp would
                # gather the seq-sharded hidden states and pp runs the
                # loss outside the validated path
                why = f"cp_degree={cp}/pp_degree={pp} (validated for 1/1)"
            if why:
                logger.warning(
                    "Model.fused_ce disabled: %s; using the XLA logits "
                    "path", why)
                gcfg = GPTConfig(**{**gcfg.__dict__, "fused_ce": False})
        sharding = dist.get("sharding") or {}
        self._data_world = (dist.get("dp_degree") or 1) * (
            sharding.get("sharding_degree") or 1)
        self.gpt_config = gcfg
        return GPTForPretraining(gcfg)

    def init_params(self, rng, batch):
        tokens = batch["tokens"]
        if (getattr(self.gpt_config, "pp_degree", 1) or 1) <= 1:
            return self.nets.init(rng, tokens)
        return init_pipeline_params_via_sequential(self.nets, rng, tokens)

    def load_pretrained(self, params):
        """``Model.pretrained`` (export artifact dir, e.g. from
        tools/convert_hf_gpt2.py) warm-starts the GPT backbone for
        pretraining / eval / generation modules."""
        pre = (self.cfg.Model or {}).get("pretrained")
        if not pre:
            return None
        return load_pretrained_gpt_backbone(
            params, pre, self.gpt_config.fuse_attn_qkv
        )

    def cp_prepare(self, batch):
        """(tokens, position_ids, labels, loss_mask), zig-zag-permuted along
        the sequence when context parallelism is on.

        Ring attention runs on zig-zag sequence order; tokens/labels/mask are
        permuted identically and true positions carried explicitly, so the
        order-invariant masked losses/scores need no un-permute. Every module
        that feeds the GPT model (pretrain/MoE/eval) must go through here.
        """
        tokens = batch["tokens"]
        position_ids = batch.get("position_ids")
        labels = batch.get("labels")
        loss_mask = batch.get("loss_mask")
        cp = getattr(self.gpt_config, "cp_degree", 1)
        if cp <= 1:
            return tokens, position_ids, labels, loss_mask
        from fleetx_tpu.parallel.context_parallel import zigzag_split

        if position_ids is None:
            position_ids = jnp.broadcast_to(
                jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :], tokens.shape
            )
        z = lambda x: None if x is None else zigzag_split(x, cp, axis=1)
        return z(tokens), z(position_ids), z(labels), z(loss_mask)

    def loss_fn(self, params, batch, rng, train: bool):
        tokens, position_ids, labels, loss_mask = self.cp_prepare(batch)
        rngs = {"dropout": rng} if train and rng is not None else None
        nd = getattr(self, "_data_world", 1)
        # per-SHARD token count must stay 8-aligned (the kernel shard_maps
        # over dp/fsdp); otherwise fall back to the logits path
        shard_ok = labels.size % nd != 0 or (labels.size // nd) % 8 == 0
        if (getattr(self.gpt_config, "fused_ce", False)
                and labels.size % 8 == 0 and shard_ok):
            # fused LM-head+CE path: the model returns per-token losses
            # and [b, s, vocab] logits never materialize (Model.fused_ce,
            # ops/pallas/ce_loss.py)
            from fleetx_tpu.models.gpt.model import masked_loss_mean

            token_loss = self.nets.apply(
                {"params": params}, tokens, position_ids,
                deterministic=not train, rngs=rngs, labels=labels,
            )
            return masked_loss_mean(token_loss, loss_mask), {}
        logits = self.nets.apply(
            {"params": params},
            tokens,
            position_ids,
            deterministic=not train,
            rngs=rngs,
        )
        loss = pretraining_loss(logits, labels, loss_mask)
        return loss, {}

    def input_spec(self):
        glb = self.cfg.Global
        seq = self.cfg.Data.Train.dataset.max_seq_len if self.cfg.Data else 1024
        b = glb.micro_batch_size or 1
        return {
            "tokens": jax.ShapeDtypeStruct((b, seq), jnp.int32),
            "position_ids": jax.ShapeDtypeStruct((b, seq), jnp.int32),
        }
