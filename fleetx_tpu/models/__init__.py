"""Model registry: ``build_module(cfg)`` dispatches on ``cfg.Model.module``
(reference /root/reference/ppfleetx/models/__init__.py:30-34, minus the
eval-by-name — an explicit registry is greppable and safe)."""

from __future__ import annotations

_REGISTRY = {}


def register_module(name):
    """Class decorator adding a Module to the build_module registry."""
    def deco(cls):
        _REGISTRY[name] = cls
        return cls

    return deco


def build_module(cfg):
    """Instantiate the Module named by cfg.Model.module (reference
    models/__init__.py:30-34)."""
    name = cfg.Model.module
    module_cls = _get(name)
    return module_cls(cfg)


def _get(name):
    _populate()
    if name not in _REGISTRY:
        raise ValueError(f"unknown module {name!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def _populate():
    """Lazy imports so `import fleetx_tpu.models` stays light."""
    if _REGISTRY:
        return
    from fleetx_tpu.models.language_module import GPTModule

    _REGISTRY["GPTModule"] = GPTModule
    for name, path, attr in [
        ("GPTGenerationModule", "fleetx_tpu.models.language_module_generation", "GPTGenerationModule"),
        ("GPTEvalModule", "fleetx_tpu.models.language_module_eval", "GPTEvalModule"),
        ("GPTFinetuneModule", "fleetx_tpu.models.language_module_finetune", "GPTFinetuneModule"),
        ("MoEModule", "fleetx_tpu.models.moe_module", "MoEModule"),
        ("GeneralClsModule", "fleetx_tpu.models.vision_module", "GeneralClsModule"),
        ("MOCOModule", "fleetx_tpu.models.moco_module", "MOCOModule"),
        ("MOCOClsModule", "fleetx_tpu.models.moco_module", "MOCOClsModule"),
        ("ErnieModule", "fleetx_tpu.models.ernie_module", "ErnieModule"),
        ("ImagenModule", "fleetx_tpu.models.imagen_module", "ImagenModule"),
        ("ProteinFoldingModule", "fleetx_tpu.models.protein_module", "ProteinFoldingModule"),
    ]:
        try:
            mod = __import__(path, fromlist=[attr])
            _REGISTRY[name] = getattr(mod, attr)
        except ImportError:
            pass  # family not built yet; registry reports what exists
