"""GPTGenerationModule — text-in/text-out generation driver (reference
/root/reference/ppfleetx/models/language_model/language_module.py:484-585)."""

from __future__ import annotations

from typing import List, Optional, Union

import jax
import numpy as np

from fleetx_tpu.models.gpt.generation import GenerationConfig, generate
from fleetx_tpu.models.language_module import GPTModule

__all__ = ["GPTGenerationModule"]


class GPTGenerationModule(GPTModule):
    """Serving module for decode: wraps GPTModel with the sampling/beam
    generation stack (reference language_module.py:484-585)."""
    def __init__(self, cfg):
        super().__init__(cfg)
        self.generation_cfg = GenerationConfig.from_config(cfg.get("Generation"))
        self._tokenizer = None
        self._variables = None
        self._compiled_generate = None

    @property
    def tokenizer(self):
        if self._tokenizer is None:
            from fleetx_tpu.data.tokenizers.gpt_tokenizer import GPTTokenizer

            vocab_dir = (self.cfg.get("Generation") or {}).get("vocab_dir")
            self._tokenizer = GPTTokenizer.from_pretrained(vocab_dir)
        return self._tokenizer

    def set_state(self, variables):
        """Install trained variables ({'params': ...}). Pipeline-trained
        param trees (gpt/layers/pipe/stages/...) are remapped to the
        sequential scan layout the decode path uses."""
        from fleetx_tpu.parallel.pipeline import maybe_pipeline_params_to_sequential

        self._variables = maybe_pipeline_params_to_sequential(variables)

    def generate_ids(
        self,
        input_ids: np.ndarray,
        rng: Optional[jax.Array] = None,
        attention_mask: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        if self._variables is None:
            raise RuntimeError("call set_state(variables) first")
        if self._compiled_generate is None:
            gen_cfg = self.generation_cfg

            def run(variables, ids, rng, mask):
                return generate(self.nets, variables, ids, gen_cfg, rng,
                                attention_mask=mask)

            self._compiled_generate = jax.jit(run)
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        if attention_mask is None:
            attention_mask = np.ones_like(input_ids, dtype=np.int32)
        return np.asarray(
            self._compiled_generate(self._variables, input_ids, rng, attention_mask)
        )

    def generate(self, text: Union[str, List[str]], rng=None) -> List[str]:
        """Tokenize -> decode loop -> detokenize (left-pads a batch of
        prompts to equal length)."""
        prompts = [text] if isinstance(text, str) else list(text)
        tok = self.tokenizer
        encoded = [tok.encode(p) for p in prompts]
        max_len = max(len(e) for e in encoded)
        pad = tok.pad_token_id
        ids = np.full((len(encoded), max_len), pad, np.int32)
        mask = np.zeros((len(encoded), max_len), np.int32)
        for i, e in enumerate(encoded):
            ids[i, max_len - len(e):] = e  # left-pad so decode starts aligned
            mask[i, max_len - len(e):] = 1
        out = self.generate_ids(ids, rng, attention_mask=mask)
        results = []
        for i, e in enumerate(encoded):
            gen = out[i, max_len:]
            gen = gen[gen != pad]
            results.append(tok.decode(gen.tolist()))
        return results
