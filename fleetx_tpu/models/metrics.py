"""GLUE metrics — accumulate/compute ports of the reference metric classes
(/root/reference/ppfleetx/models/language_model/metrics.py:31-692:
AccuracyAndF1, Mcc, PearsonAndSpearman, MultiLabelsMetric), reimplemented in
numpy with the same update/accumulate contract: ``update(preds, labels)``
per batch, ``accumulate()`` for the final value(s), ``reset()`` between
epochs. Metrics run host-side on gathered outputs — no reason to burn MXU
cycles on confusion-matrix bookkeeping."""

from __future__ import annotations

from typing import Tuple

import numpy as np

__all__ = ["Accuracy", "AccuracyAndF1", "Mcc", "PearsonAndSpearman",
           "MultiLabelsMetric", "build_metric"]


def _to_pred_labels(preds: np.ndarray) -> np.ndarray:
    preds = np.asarray(preds)
    return preds.argmax(axis=-1) if preds.ndim > 1 else preds


class Accuracy:
    """Top-1 accuracy accumulator (reference metrics.py Accuracy)."""
    def __init__(self, **_):
        self.reset()

    def reset(self):
        self.correct = 0
        self.total = 0

    def update(self, preds, labels):
        p = _to_pred_labels(preds)
        l = np.asarray(labels).reshape(p.shape)
        self.correct += int((p == l).sum())
        self.total += p.size

    def accumulate(self) -> float:
        return self.correct / max(self.total, 1)


class AccuracyAndF1:
    """(acc, precision, recall, f1, (acc+f1)/2) — reference metrics.py:31-178
    (binary tasks: positive class = 1)."""

    def __init__(self, pos_label: int = 1, **_):
        self.pos_label = pos_label
        self.reset()

    def reset(self):
        self.tp = self.fp = self.fn = 0
        self.correct = 0
        self.total = 0

    def update(self, preds, labels):
        p = _to_pred_labels(preds)
        l = np.asarray(labels).reshape(p.shape)
        pos = self.pos_label
        self.tp += int(((p == pos) & (l == pos)).sum())
        self.fp += int(((p == pos) & (l != pos)).sum())
        self.fn += int(((p != pos) & (l == pos)).sum())
        self.correct += int((p == l).sum())
        self.total += p.size

    def accumulate(self) -> Tuple[float, float, float, float, float]:
        acc = self.correct / max(self.total, 1)
        precision = self.tp / max(self.tp + self.fp, 1)
        recall = self.tp / max(self.tp + self.fn, 1)
        f1 = (
            2 * precision * recall / (precision + recall)
            if precision + recall > 0
            else 0.0
        )
        return acc, precision, recall, f1, (acc + f1) / 2


class Mcc:
    """Matthews correlation coefficient (CoLA) — reference metrics.py:180-303."""

    def __init__(self, **_):
        self.reset()

    def reset(self):
        self.tp = self.fp = self.tn = self.fn = 0

    def update(self, preds, labels):
        p = _to_pred_labels(preds)
        l = np.asarray(labels).reshape(p.shape)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())
        self.tn += int(((p == 0) & (l == 0)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self) -> Tuple[float]:
        tp, fp, tn, fn = self.tp, self.fp, self.tn, self.fn
        denom = np.sqrt(
            float(tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)
        )
        return ((tp * tn - fp * fn) / denom if denom else 0.0,)


class PearsonAndSpearman:
    """(pearson, spearman, mean) for regression (STS-B) — reference
    metrics.py:305-443."""

    def __init__(self, **_):
        self.reset()

    def reset(self):
        self.preds = []
        self.labels = []

    def update(self, preds, labels):
        p = np.asarray(preds).reshape(-1)
        self.preds.append(p.astype(np.float64))
        self.labels.append(np.asarray(labels).reshape(-1).astype(np.float64))

    @staticmethod
    def _pearson(a, b):
        a = a - a.mean()
        b = b - b.mean()
        denom = np.sqrt((a * a).sum() * (b * b).sum())
        return float((a * b).sum() / denom) if denom else 0.0

    @staticmethod
    def _rank(x):
        order = np.argsort(x)
        ranks = np.empty_like(order, dtype=np.float64)
        ranks[order] = np.arange(len(x), dtype=np.float64)
        # average ties
        uniq, inv, counts = np.unique(x, return_inverse=True, return_counts=True)
        sums = np.zeros(len(uniq))
        np.add.at(sums, inv, ranks)
        return sums[inv] / counts[inv]

    def accumulate(self) -> Tuple[float, float, float]:
        p = np.concatenate(self.preds) if self.preds else np.zeros(0)
        l = np.concatenate(self.labels) if self.labels else np.zeros(0)
        if len(p) < 2:
            return 0.0, 0.0, 0.0
        pearson = self._pearson(p, l)
        spearman = self._pearson(self._rank(p), self._rank(l))
        return pearson, spearman, (pearson + spearman) / 2


class MultiLabelsMetric:
    """Macro/micro precision/recall/F1 over multi-class predictions —
    reference metrics.py:445-692 (used by token/sequence multi-label
    tasks)."""

    def __init__(self, num_labels: int, **_):
        assert num_labels > 1
        self.num_labels = num_labels
        self.reset()

    def reset(self):
        n = self.num_labels
        self.tp = np.zeros(n, np.int64)
        self.fp = np.zeros(n, np.int64)
        self.fn = np.zeros(n, np.int64)

    def update(self, preds, labels):
        p = _to_pred_labels(preds)
        l = np.asarray(labels).reshape(p.shape)
        for c in range(self.num_labels):
            self.tp[c] += int(((p == c) & (l == c)).sum())
            self.fp[c] += int(((p == c) & (l != c)).sum())
            self.fn[c] += int(((p != c) & (l == c)).sum())

    def accumulate(self, average: str = "macro") -> Tuple[float, float, float]:
        tp, fp, fn = self.tp, self.fp, self.fn
        if average == "micro":
            precision = tp.sum() / max(tp.sum() + fp.sum(), 1)
            recall = tp.sum() / max(tp.sum() + fn.sum(), 1)
            f1 = (
                2 * precision * recall / (precision + recall)
                if precision + recall > 0
                else 0.0
            )
            return float(precision), float(recall), float(f1)
        with np.errstate(divide="ignore", invalid="ignore"):
            prec_c = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1), 0.0)
            rec_c = np.where(tp + fn > 0, tp / np.maximum(tp + fn, 1), 0.0)
            f1_c = np.where(
                prec_c + rec_c > 0, 2 * prec_c * rec_c / np.maximum(prec_c + rec_c, 1e-12), 0.0
            )
        return float(prec_c.mean()), float(rec_c.mean()), float(f1_c.mean())


_METRICS = {
    "Accuracy": Accuracy,
    "AccuracyAndF1": AccuracyAndF1,
    "Mcc": Mcc,
    "PearsonAndSpearman": PearsonAndSpearman,
    "MultiLabelsMetric": MultiLabelsMetric,
}


def build_metric(cfg):
    """Metric factory by config name (reference GLUE metric selection)."""
    cfg = dict(cfg or {})
    name = cfg.pop("name", "Accuracy")
    if name not in _METRICS:
        raise ValueError(f"unknown metric {name!r}; have {sorted(_METRICS)}")
    return _METRICS[name](**cfg)
