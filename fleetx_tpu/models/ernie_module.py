"""ErnieModule — masked-LM + sentence-order-prediction pretraining
(reference /root/reference/ppfleetx/models/language_model/ernie/
ernie_module.py:69-160: training_step = lm_loss + sop_loss, ips logging).

Batch contract (static shapes, see fleetx_tpu/data/ernie_dataset.py):
  input_ids        [b, s] int32 (padded with pad_token_id)
  token_type_ids   [b, s] int32 (segment A=0 / B=1)
  masked_positions [b, P] int32 (0-padded slots)
  masked_labels    [b, P] int32
  masked_weights   [b, P] float32 (1 for real predictions, 0 for padding)
  sop_labels       [b]    int32 (1 = segments in order, 0 = swapped)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fleetx_tpu.models.ernie.model import (
    ErnieConfig,
    ErnieForPretraining,
    ernie_pretraining_loss,
)
from fleetx_tpu.models.language_module import LanguageModule, resolve_compute_dtype

__all__ = ["ErnieModule"]


class ErnieModule(LanguageModule):
    """ERNIE pretraining: masked-LM + sentence-order-prediction losses
    (reference ernie_module.py:69-121)."""
    def get_model(self):
        model_cfg = self.cfg.Model if hasattr(self.cfg, "Model") else self.cfg
        ecfg = ErnieConfig.from_model_config(model_cfg)
        eng = getattr(self.cfg, "Engine", None) or {}
        ecfg = ErnieConfig(**{**ecfg.__dict__, "dtype": resolve_compute_dtype(eng)})
        self.ernie_config = ecfg
        self.binary_head = bool(model_cfg.get("binary_head", True))
        return ErnieForPretraining(ecfg)

    def init_params(self, rng, batch):
        return self.nets.init(
            rng,
            batch["input_ids"],
            batch.get("token_type_ids"),
            masked_positions=batch["masked_positions"],
        )

    def loss_fn(self, params, batch, rng, train: bool):
        mlm_logits, sop_logits = self.nets.apply(
            {"params": params},
            batch["input_ids"],
            batch.get("token_type_ids"),
            None,
            None,
            batch["masked_positions"],
            deterministic=not train,
            rngs={"dropout": rng} if train and rng is not None else None,
        )
        lm_loss, sop_loss = ernie_pretraining_loss(
            mlm_logits,
            sop_logits,
            batch["masked_labels"],
            batch["masked_weights"],
            batch.get("sop_labels") if self.binary_head else None,
        )
        return lm_loss + sop_loss, {"lm_loss": lm_loss, "sop_loss": sop_loss}

    def input_spec(self):
        glb = self.cfg.Global
        data = getattr(self.cfg, "Data", None) or {}
        ds = ((data.get("Train") or {}).get("dataset") or {}) if data else {}
        seq = ds.get("max_seq_len") or 512
        P = ds.get("max_predictions_per_seq") or 80
        b = glb.micro_batch_size or 1
        return {
            "input_ids": jax.ShapeDtypeStruct((b, seq), jnp.int32),
            "token_type_ids": jax.ShapeDtypeStruct((b, seq), jnp.int32),
            "masked_positions": jax.ShapeDtypeStruct((b, P), jnp.int32),
            "masked_labels": jax.ShapeDtypeStruct((b, P), jnp.int32),
            "masked_weights": jax.ShapeDtypeStruct((b, P), jnp.float32),
            "sop_labels": jax.ShapeDtypeStruct((b,), jnp.int32),
        }
