"""Full rigid-transform / quaternion-affine library for protein models.

Breadth parity with the reference's op zoo — r3.py (Vecs/Rots/Rigids with
~30 free functions, /root/reference/ppfleetx/models/protein_folding/
r3.py:44-487) and quat_affine.py (QuatAffine with pre_compose /
apply_to_point / invert_point, quat_affine.py:190-340) — redesigned for
XLA:

- the reference's structs-of-scalars (Vecs as three separate tensors,
  Rots as nine) exist to dodge framework slicing overheads; under XLA a
  plain [..., 3] vector / [..., 3, 3] matrix fuses identically, so the
  whole vecs_* family collapses into jnp (vecs_add = +, vecs_dot_vecs =
  sum(a*b, -1), vecs_cross_vecs = jnp.cross, vecs_robust_norm/normalize
  below). What remains is the genuinely rigid-body algebra.
- ``Rigid`` is a NamedTuple, hence a pytree: it maps/scans/vmaps like any
  array and threads through lax.scan carries without flattening helpers
  (the reference needs rigids_to_list/rigids_from_list for that).
- ``QuatAffine.invert`` is implemented (the reference leaves it
  ``pass  # TODO``, quat_affine.py:338-340).

The trunk's own needs (rigids_from_3_points, quat<->rot, torsion frames)
live in geometry.py/all_atom.py; this module carries the rest of the
surface so a structure module can land without new geometry code.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from fleetx_tpu.models.protein.geometry import (
    make_transform_from_reference,
    quat_to_rot,
    rot_to_quat,
)

__all__ = [
    "Rigid", "QuatAffine", "identity_rigid", "compose_rigids",
    "invert_rigid", "apply_rigid", "apply_inverse_rigid",
    "rots_from_two_vecs", "robust_norm", "robust_normalize",
    "rigid_from_tensor4x4", "rigid_to_tensor_flat9",
    "rigid_from_tensor_flat9", "rigid_to_tensor_flat12",
    "rigid_from_tensor_flat12", "quat_multiply", "quat_multiply_by_vec",
    "make_canonical_transform",
]


class Rigid(NamedTuple):
    """Rigid transform: g = rot @ l + trans (rot [..., 3, 3], trans [..., 3]).
    NamedTuple => pytree: vmap/scan/tree_map work directly (the reference's
    rigids_to_list/from_list round-trips, r3.py:278-343, are unneeded)."""

    rot: jax.Array
    trans: jax.Array


def identity_rigid(shape=(), dtype=jnp.float32) -> Rigid:
    """Identity transform batched to ``shape`` (reference identity_rigids)."""
    rot = jnp.broadcast_to(jnp.eye(3, dtype=dtype), (*shape, 3, 3))
    return Rigid(rot, jnp.zeros((*shape, 3), dtype))


def compose_rigids(a: Rigid, b: Rigid) -> Rigid:
    """a ∘ b: apply b first, then a (reference rigids_mul_rigids)."""
    return Rigid(a.rot @ b.rot,
                 jnp.einsum("...ij,...j->...i", a.rot, b.trans) + a.trans)


def invert_rigid(r: Rigid) -> Rigid:
    """g^-1: transpose rotation, counter-rotate the negated translation
    (reference invert_rigids)."""
    inv_rot = jnp.swapaxes(r.rot, -1, -2)
    return Rigid(inv_rot, -jnp.einsum("...ij,...j->...i", inv_rot, r.trans))


def apply_rigid(r: Rigid, point: jax.Array) -> jax.Array:
    """local -> global (reference rigids_mul_vecs)."""
    return jnp.einsum("...ij,...j->...i", r.rot, point) + r.trans


def apply_inverse_rigid(r: Rigid, point: jax.Array) -> jax.Array:
    """global -> local without materializing the inverse (reference
    rigids_mul_vecs(invert_rigids(r), x))."""
    return jnp.einsum("...ji,...j->...i", r.rot, point - r.trans)


def robust_norm(v: jax.Array, epsilon: float = 1e-8) -> jax.Array:
    """Norm with a sqrt-domain guard (reference vecs_robust_norm)."""
    return jnp.sqrt(jnp.sum(v * v, axis=-1) + epsilon)


def robust_normalize(v: jax.Array, epsilon: float = 1e-8) -> jax.Array:
    """Unit vector with the same guarded norm (reference
    vecs_robust_normalize)."""
    return v / robust_norm(v, epsilon)[..., None]


def rots_from_two_vecs(e0_unnormalized: jax.Array,
                       e1_unnormalized: jax.Array) -> jax.Array:
    """Gram-Schmidt rotation whose x-axis is e0 and xy-plane spans e0, e1
    (reference r3.rots_from_two_vecs; columns are the frame axes)."""
    e0 = robust_normalize(e0_unnormalized)
    c = jnp.sum(e1_unnormalized * e0, axis=-1, keepdims=True)
    e1 = robust_normalize(e1_unnormalized - c * e0)
    e2 = jnp.cross(e0, e1)
    return jnp.stack([e0, e1, e2], axis=-1)


# ------------------------------------------------- tensor conversions
def rigid_from_tensor4x4(m: jax.Array) -> Rigid:
    """Homogeneous [..., 4, 4] -> Rigid (reference rigids_from_tensor4x4)."""
    return Rigid(m[..., :3, :3], m[..., :3, 3])


def rigid_to_tensor_flat9(r: Rigid) -> jax.Array:
    """[..., 9]: 2 rotation columns + translation (the minimal encoding the
    reference ships for checkpoint compactness, r3.py:353-358); the third
    column is re-derived by cross product on load."""
    return jnp.concatenate(
        [r.rot[..., :, 0], r.rot[..., :, 1], r.trans], axis=-1)


def rigid_from_tensor_flat9(m: jax.Array) -> Rigid:
    """[..., 9] -> Rigid: Gram-Schmidt the two stored columns back into a
    rotation (reference rigids_from_tensor_flat9)."""
    e0, e1, trans = m[..., 0:3], m[..., 3:6], m[..., 6:9]
    return Rigid(rots_from_two_vecs(e0, e1), trans)


def rigid_to_tensor_flat12(r: Rigid) -> jax.Array:
    """[..., 12]: full row-major rotation + translation."""
    rot_flat = r.rot.reshape(*r.rot.shape[:-2], 9)
    return jnp.concatenate([rot_flat, r.trans], axis=-1)


def rigid_from_tensor_flat12(m: jax.Array) -> Rigid:
    """[..., 12] -> Rigid (reference rigids_from_tensor_flat12)."""
    return Rigid(m[..., :9].reshape(*m.shape[:-1], 3, 3), m[..., 9:12])


# ------------------------------------------------- quaternion algebra
# quat-product coefficient tensors (w, x, y, z basis; standard Hamilton
# product written as an einsum so it vectorizes over any batch shape)
def _quat_basis():
    QW = jnp.array([[1, 0, 0, 0], [0, -1, 0, 0], [0, 0, -1, 0], [0, 0, 0, -1]],
                   jnp.float32)
    QX = jnp.array([[0, 1, 0, 0], [1, 0, 0, 0], [0, 0, 0, 1], [0, 0, -1, 0]],
                   jnp.float32)
    QY = jnp.array([[0, 0, 1, 0], [0, 0, 0, -1], [1, 0, 0, 0], [0, 1, 0, 0]],
                   jnp.float32)
    QZ = jnp.array([[0, 0, 0, 1], [0, 0, 1, 0], [0, -1, 0, 0], [1, 0, 0, 0]],
                   jnp.float32)
    return jnp.stack([QW, QX, QY, QZ])  # [4(out), 4(a), 4(b)]


def quat_multiply(quat1: jax.Array, quat2: jax.Array) -> jax.Array:
    """Hamilton product (reference quat_affine.quat_multiply)."""
    basis = _quat_basis().astype(quat1.dtype)
    return jnp.einsum("oab,...a,...b->...o", basis, quat1, quat2)


def quat_multiply_by_vec(quat: jax.Array, vec: jax.Array) -> jax.Array:
    """quat * (0, vec) — the linearized-update primitive the structure
    module's backbone update uses (reference quat_multiply_by_vec)."""
    basis = _quat_basis().astype(quat.dtype)
    return jnp.einsum("oab,...a,...b->...o", basis[:, :, 1:], quat, vec)


class QuatAffine:
    """Quaternion + translation affine (reference QuatAffine,
    quat_affine.py:190-340). Rotation is cached alongside the quaternion so
    repeated point applications don't re-derive it."""

    def __init__(self, quaternion, translation, rotation=None,
                 normalize: bool = True):
        if normalize and quaternion is not None:
            quaternion = quaternion / robust_norm(quaternion)[..., None]
        if rotation is None:
            rotation = quat_to_rot(quaternion)
        self.quaternion = quaternion
        self.rotation = rotation
        self.translation = translation

    @classmethod
    def from_tensor(cls, tensor: jax.Array, normalize: bool = False):
        return cls(tensor[..., 0:4], tensor[..., 4:7], normalize=normalize)

    def to_tensor(self) -> jax.Array:
        return jnp.concatenate([self.quaternion, self.translation], axis=-1)

    def to_rigid(self) -> Rigid:
        return Rigid(self.rotation, self.translation)

    @classmethod
    def from_rigid(cls, r: Rigid) -> "QuatAffine":
        return cls(rot_to_quat(r.rot), r.trans, rotation=r.rot,
                   normalize=False)

    def scale_translation(self, position_scale) -> "QuatAffine":
        return QuatAffine(self.quaternion, position_scale * self.translation,
                          rotation=self.rotation, normalize=False)

    def stop_rot_gradient(self) -> "QuatAffine":
        """Detach the rotation (AlphaFold trains the structure module with
        rotation gradients stopped between iterations)."""
        return QuatAffine(
            jax.lax.stop_gradient(self.quaternion), self.translation,
            rotation=jax.lax.stop_gradient(self.rotation), normalize=False)

    def pre_compose(self, update: jax.Array) -> "QuatAffine":
        """Apply a length-6 update (vector-quaternion (1, x, y, z) +
        translation) BEFORE this transform (reference pre_compose)."""
        vector_quat = update[..., 0:3]
        trans_update = update[..., 3:6]
        new_quat = self.quaternion + quat_multiply_by_vec(
            self.quaternion, vector_quat)
        new_trans = self.translation + jnp.einsum(
            "...ij,...j->...i", self.rotation, trans_update)
        return QuatAffine(new_quat, new_trans)

    def apply_to_point(self, point: jax.Array, extra_dims: int = 0):
        """Transform [..., 3] points; ``extra_dims`` trailing point axes are
        broadcast against the transform (e.g. N points per residue)."""
        rotation, translation = self.rotation, self.translation
        for _ in range(extra_dims):
            rotation = rotation[..., None, :, :]
            translation = translation[..., None, :]
        return jnp.einsum("...ij,...j->...i", rotation, point) + translation

    def invert_point(self, transformed_point: jax.Array,
                     extra_dims: int = 0):
        rotation, translation = self.rotation, self.translation
        for _ in range(extra_dims):
            rotation = rotation[..., None, :, :]
            translation = translation[..., None, :]
        return jnp.einsum("...ji,...j->...i", rotation,
                          transformed_point - translation)

    def invert(self) -> "QuatAffine":
        """Inverse transform (the reference leaves this TODO,
        quat_affine.py:338-340): conjugate quaternion, back-rotated negated
        translation."""
        conj = self.quaternion * jnp.asarray([1.0, -1.0, -1.0, -1.0],
                                             self.quaternion.dtype)
        inv_rot = jnp.swapaxes(self.rotation, -1, -2)
        inv_trans = -jnp.einsum("...ij,...j->...i", inv_rot, self.translation)
        return QuatAffine(conj, inv_trans, rotation=inv_rot, normalize=False)


def make_canonical_transform(n_xyz: jax.Array, ca_xyz: jax.Array,
                             c_xyz: jax.Array):
    """(rot, trans) moving CA to origin, C onto +x, N into the xy plane
    (reference make_canonical_transform): the INVERSE of the backbone frame
    geometry.make_transform_from_reference builds."""
    rot, trans = make_transform_from_reference(n_xyz, ca_xyz, c_xyz)
    inv = invert_rigid(Rigid(rot, trans))
    return inv.rot, inv.trans
