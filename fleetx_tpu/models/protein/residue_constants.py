"""Amino-acid constants needed by the folding trunk.

Capability parity with the reference's residue_constants
(/root/reference/ppfleetx/models/protein_folding/residue_constants.py:1-961,
itself the standard AlphaFold tables): this module keeps only what the
trunk (template embedding + torsion-angle featurization, evoformer) consumes
— residue type codes, the atom37 vocabulary, and the chi-angle definitions —
and derives the derived tables (masks, index tensors) programmatically
instead of hard-coding them. The underlying values are physical chemistry
(PDB atom nomenclature and side-chain dihedral definitions), identical in
any correct implementation.
"""

from __future__ import annotations

import functools

import numpy as np

# one-letter codes in the canonical AlphaFold order
restypes = [
    "A", "R", "N", "D", "C", "Q", "E", "G", "H", "I",
    "L", "K", "M", "F", "P", "S", "T", "W", "Y", "V",
]
restype_order = {r: i for i, r in enumerate(restypes)}
restype_num = len(restypes)  # 20; UNK gets index 20
unk_restype_index = restype_num

restype_1to3 = {
    "A": "ALA", "R": "ARG", "N": "ASN", "D": "ASP", "C": "CYS",
    "Q": "GLN", "E": "GLU", "G": "GLY", "H": "HIS", "I": "ILE",
    "L": "LEU", "K": "LYS", "M": "MET", "F": "PHE", "P": "PRO",
    "S": "SER", "T": "THR", "W": "TRP", "Y": "TYR", "V": "VAL",
}
restype_3to1 = {v: k for k, v in restype_1to3.items()}

# the 37 heavy-atom name vocabulary (atom37 layout); index = position in
# the per-residue coordinate tensor. Backbone first: N, CA, C, CB, O.
atom_types = [
    "N", "CA", "C", "CB", "O", "CG", "CG1", "CG2", "OG", "OG1", "SG", "CD",
    "CD1", "CD2", "ND1", "ND2", "OD1", "OD2", "SD", "CE", "CE1", "CE2",
    "CE3", "NE", "NE1", "NE2", "OE1", "OE2", "CH2", "NH1", "NH2", "OH",
    "CZ", "CZ2", "CZ3", "NZ", "OXT",
]
atom_order = {a: i for i, a in enumerate(atom_types)}
atom_type_num = len(atom_types)  # 37

# side-chain dihedral (chi) definitions: the 4 atoms spanning each rotatable
# bond, per residue (PDB nomenclature; chi_k rotates about bond atoms 2-3)
chi_angles_atoms = {
    "ALA": [],
    "ARG": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD"],
            ["CB", "CG", "CD", "NE"], ["CG", "CD", "NE", "CZ"]],
    "ASN": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "OD1"]],
    "ASP": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "OD1"]],
    "CYS": [["N", "CA", "CB", "SG"]],
    "GLN": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD"],
            ["CB", "CG", "CD", "OE1"]],
    "GLU": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD"],
            ["CB", "CG", "CD", "OE1"]],
    "GLY": [],
    "HIS": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "ND1"]],
    "ILE": [["N", "CA", "CB", "CG1"], ["CA", "CB", "CG1", "CD1"]],
    "LEU": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD1"]],
    "LYS": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD"],
            ["CB", "CG", "CD", "CE"], ["CG", "CD", "CE", "NZ"]],
    "MET": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "SD"],
            ["CB", "CG", "SD", "CE"]],
    "PHE": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD1"]],
    "PRO": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD"]],
    "SER": [["N", "CA", "CB", "OG"]],
    "THR": [["N", "CA", "CB", "OG1"]],
    "TRP": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD1"]],
    "TYR": [["N", "CA", "CB", "CG"], ["CA", "CB", "CG", "CD1"]],
    "VAL": [["N", "CA", "CB", "CG1"]],
}

# chi angles whose terminal atom pair is chemically symmetric, making the
# angle pi-periodic (ASP chi2, GLU chi3, PHE chi2, TYR chi2)
_PI_PERIODIC = {("ASP", 1), ("GLU", 2), ("PHE", 1), ("TYR", 1)}


@functools.cache
def chi_angles_mask_array() -> np.ndarray:
    """[21, 4] float32: which chi angles exist per restype (+UNK row)."""
    mask = np.zeros((restype_num + 1, 4), np.float32)
    for i, r in enumerate(restypes):
        mask[i, : len(chi_angles_atoms[restype_1to3[r]])] = 1.0
    return mask


# list-of-lists view matching the reference's `chi_angles_mask` (20 rows)
chi_angles_mask = [list(row) for row in chi_angles_mask_array()[:restype_num]]


@functools.cache
def chi_pi_periodic_array() -> np.ndarray:
    """[21, 4] float32: 1 where the chi angle is pi-periodic (+UNK row)."""
    out = np.zeros((restype_num + 1, 4), np.float32)
    for i, r in enumerate(restypes):
        for k in range(4):
            if (restype_1to3[r], k) in _PI_PERIODIC:
                out[i, k] = 1.0
    return out


chi_pi_periodic = [list(row) for row in chi_pi_periodic_array()[:restype_num]]


@functools.cache
def chi_atom_indices_array() -> np.ndarray:
    """[21, 4, 4] int32 atom37 indices of each chi angle's 4 atoms (zeros
    where undefined; +UNK row) — the reference builds this at call time
    (all_atom.py get_chi_atom_indices)."""
    out = np.zeros((restype_num + 1, 4, 4), np.int32)
    for i, r in enumerate(restypes):
        for k, atoms in enumerate(chi_angles_atoms[restype_1to3[r]]):
            out[i, k] = [atom_order[a] for a in atoms]
    return out
