"""Evoformer trunk (HelixFold/AlphaFold2), TPU-native flax implementation.

Capability parity with the reference protein-folding modules
(/root/reference/ppfleetx/models/protein_folding/evoformer.py:41-482 and
attentions.py:33-560): MSA row attention with pair bias, MSA column
(+global) attention, MSA transition, outer-product mean, triangle
multiplication (outgoing/incoming), triangle attention (starting/ending
node), pair transition — composed into EvoformerIteration / EvoformerStack.

Distribution: the reference threads hand-written DAP collectives through
every module (evoformer.py:151-470 calls dap.row_to_col etc.); here each
block simply declares its preferred sharding layout
(fleetx_tpu/parallel/dap.py) and GSPMD materializes the axis-swap
all_to_alls. The per-layer stack runs under ``nn.scan`` (one compiled
layer body, reference runs 48 iterations eagerly).

Tensor shapes (batch-first, TPU layout):
  msa_act  [B, S, R, Cm]   S = MSA sequences, R = residues
  pair_act [B, R, R, Cz]
  msa_mask [B, S, R], pair_mask [B, R, R]
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from fleetx_tpu.parallel.dap import (
    col_sharded,
    pair_col_sharded,
    pair_row_sharded,
    row_sharded,
)

Dtype = Any

__all__ = [
    "EvoformerConfig",
    "EvoformerIteration",
    "EvoformerStack",
    "GlobalAttention",
    "MSAColumnGlobalAttention",
]

BIG_NEG = -1e9


@dataclasses.dataclass(frozen=True)
class EvoformerConfig:
    """Evoformer stack hyperparameters (msa/pair channels, heads, block
    counts)."""
    msa_channel: int = 256
    pair_channel: int = 128
    num_heads_msa: int = 8
    num_heads_pair: int = 4
    msa_transition_factor: int = 4
    pair_transition_factor: int = 4
    outer_product_dim: int = 32
    triangle_mult_dim: int = 128
    num_layers: int = 48
    # extra-MSA stack variant (AlphaFold Suppl. Alg. 18): column attention
    # becomes global (mean-query) attention over the deep MSA axis
    global_column_attention: bool = False
    gating: bool = True
    use_recompute: bool = False
    scan_layers: bool = True
    dtype: Dtype = jnp.bfloat16

    @classmethod
    def from_model_config(cls, model_cfg) -> "EvoformerConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in dict(model_cfg).items() if k in known and v is not None}
        if isinstance(kw.get("dtype"), str):
            kw["dtype"] = jnp.dtype(kw["dtype"]).type
        return cls(**kw)


def _ln(name, dtype=None):
    return nn.LayerNorm(epsilon=1e-5, dtype=dtype, param_dtype=jnp.float32,
                        name=name)


def _dense(features, name, use_bias=True, init="linear", dtype=None):
    inits = {
        "linear": nn.initializers.lecun_normal(),
        "relu": nn.initializers.he_normal(),
        "final": nn.initializers.zeros_init(),
        "gate": nn.initializers.zeros_init(),
    }
    return nn.DenseGeneral(
        features=features, use_bias=use_bias, dtype=dtype,
        param_dtype=jnp.float32,
        kernel_init=inits[init],
        bias_init=(nn.initializers.ones_init() if init == "gate"
                   else nn.initializers.zeros_init()),
        name=name,
    )


class GatedAttention(nn.Module):
    """Multi-head attention with optional pair bias and sigmoid gating
    (reference attentions.py:33-147 Attention)."""

    cfg: EvoformerConfig
    num_heads: int
    out_dim: int

    @nn.compact
    def __call__(self, q_data, m_data, bias, nonbatched_bias=None):
        nh = self.num_heads
        dt = self.cfg.dtype
        ch = q_data.shape[-1]
        hd = ch // nh
        q_data = q_data.astype(dt)
        m_data = m_data.astype(dt)
        q = _dense((nh, hd), "query_w", use_bias=False, dtype=dt)(q_data) * hd ** -0.5
        k = _dense((nh, hd), "key_w", use_bias=False, dtype=dt)(m_data)
        v = _dense((nh, hd), "value_w", use_bias=False, dtype=dt)(m_data)
        # [..., nh, q, k]; softmax in fp32 for stability
        logits = jnp.einsum("...qhd,...khd->...hqk", q, k,
                            preferred_element_type=jnp.float32) + bias
        if nonbatched_bias is not None:
            logits = logits + nonbatched_bias
        weights = jax.nn.softmax(logits, axis=-1).astype(dt)
        out = jnp.einsum("...hqk,...khd->...qhd", weights, v)
        if self.cfg.gating:
            gate = jax.nn.sigmoid(
                _dense((nh, hd), "gating_w", init="gate", dtype=dt)(q_data)
            )
            out = out * gate
        return nn.DenseGeneral(
            features=self.out_dim, axis=(-2, -1), dtype=dt,
            param_dtype=jnp.float32,
            kernel_init=nn.initializers.zeros_init(), name="output_w",
        )(out)


class MSARowAttentionWithPairBias(nn.Module):
    """Row-wise MSA self-attention biased by pair activations (reference
    attentions.py:243-315)."""

    cfg: EvoformerConfig

    @nn.compact
    def __call__(self, msa_act, msa_mask, pair_act):
        c = self.cfg
        msa_act = row_sharded(msa_act)
        msa_act = _ln("query_norm", c.dtype)(msa_act.astype(c.dtype))
        pair = _ln("feat_2d_norm", c.dtype)(pair_act.astype(c.dtype))
        # pair bias: [B, R, R, h] -> [B, 1, h, R, R] shared across sequences
        bias2d = _dense(c.num_heads_msa, "feat_2d_w", use_bias=False, dtype=c.dtype)(pair)
        bias2d = jnp.moveaxis(bias2d, -1, -3)[:, None].astype(jnp.float32)
        mask_bias = (1.0 - msa_mask[:, :, None, None, :]) * BIG_NEG
        out = GatedAttention(c, c.num_heads_msa, c.msa_channel, name="attn")(
            msa_act, msa_act, mask_bias, nonbatched_bias=bias2d
        )
        return out


class MSAColumnAttention(nn.Module):
    """Column-wise MSA self-attention (reference attentions.py:365-408):
    transpose S<->R, row-attend, transpose back."""

    cfg: EvoformerConfig

    @nn.compact
    def __call__(self, msa_act, msa_mask):
        c = self.cfg
        msa_act = col_sharded(msa_act)
        x = jnp.swapaxes(msa_act, -2, -3)  # [B, R, S, C]
        m = jnp.swapaxes(msa_mask, -1, -2)  # [B, R, S]
        x = _ln("query_norm", c.dtype)(x.astype(c.dtype))
        mask_bias = (1.0 - m[:, :, None, None, :]) * BIG_NEG
        out = GatedAttention(c, c.num_heads_msa, c.msa_channel, name="attn")(
            x, x, mask_bias
        )
        return jnp.swapaxes(out, -2, -3)


class GlobalAttention(nn.Module):
    """Mean-query global attention (reference attentions.py:150-241
    GlobalAttention; Suppl. Alg. 19 lines 2-7): queries are averaged over
    the attended axis, keys/values are single-head, gating restores a
    per-position output."""

    cfg: EvoformerConfig
    num_heads: int
    out_dim: int

    @nn.compact
    def __call__(self, q_data, m_data, q_mask):
        nh = self.num_heads
        dt = self.cfg.dtype
        ch = q_data.shape[-1]
        hd = ch // nh
        q_data = q_data.astype(dt)
        m_data = m_data.astype(dt)
        k = _dense(hd, "key_w", use_bias=False, dtype=dt)(m_data)
        v = _dense(hd, "value_w", use_bias=False, dtype=dt)(m_data)
        denom = jnp.sum(q_mask, axis=-1, keepdims=True) + 1e-10  # [..., 1]
        q_avg = jnp.sum(q_data * q_mask[..., None].astype(dt), axis=-2) / denom.astype(dt)
        q = _dense((nh, hd), "query_w", use_bias=False, dtype=dt)(q_avg) * hd ** -0.5
        bias = ((1.0 - q_mask) * BIG_NEG)[..., None, :]  # [..., 1, K]
        logits = jnp.einsum("...hd,...kd->...hk", q, k,
                            preferred_element_type=jnp.float32) + bias
        weights = jax.nn.softmax(logits, axis=-1).astype(dt)
        wa = jnp.einsum("...hk,...kd->...hd", weights, v)
        if self.cfg.gating:
            gate = jax.nn.sigmoid(
                _dense((nh, hd), "gating_w", init="gate", dtype=dt)(q_data)
            )  # [..., K, h, d]
            out = wa[..., None, :, :] * gate
        else:
            out = wa[..., None, :, :]
        return nn.DenseGeneral(
            features=self.out_dim, axis=(-2, -1), dtype=dt,
            param_dtype=jnp.float32,
            kernel_init=nn.initializers.zeros_init(), name="output_w",
        )(out)


class MSAColumnGlobalAttention(nn.Module):
    """Column-wise global attention for the deep extra-MSA stack
    (reference attentions.py:317-363)."""

    cfg: EvoformerConfig

    @nn.compact
    def __call__(self, msa_act, msa_mask):
        c = self.cfg
        msa_act = col_sharded(msa_act)
        x = jnp.swapaxes(msa_act, -2, -3)  # [B, R, S, C]
        m = jnp.swapaxes(msa_mask, -1, -2).astype(jnp.float32)  # [B, R, S]
        x = _ln("query_norm", c.dtype)(x.astype(c.dtype))
        out = GlobalAttention(c, c.num_heads_msa, c.msa_channel, name="attn")(
            x, x, m
        )
        return jnp.swapaxes(out, -2, -3)


class Transition(nn.Module):
    """2-layer MLP transition (reference evoformer.py Transition blocks)."""

    cfg: EvoformerConfig
    factor: int

    @nn.compact
    def __call__(self, act):
        ch = act.shape[-1]
        dt = self.cfg.dtype
        act = _ln("input_norm", dt)(act.astype(dt))
        act = _dense(ch * self.factor, "transition1", init="relu", dtype=dt)(act)
        act = jax.nn.relu(act)
        return _dense(ch, "transition2", init="final", dtype=dt)(act)


class OuterProductMean(nn.Module):
    """MSA -> pair update (reference outer_product_mean.py): mean over
    sequences of outer products of per-residue projections."""

    cfg: EvoformerConfig

    @nn.compact
    def __call__(self, msa_act, msa_mask):
        c = self.cfg
        d = c.outer_product_dim
        act = _ln("layer_norm_input", c.dtype)(msa_act.astype(c.dtype))
        a = _dense(d, "left_projection", dtype=c.dtype)(act) * msa_mask[..., None]
        b = _dense(d, "right_projection", dtype=c.dtype)(act) * msa_mask[..., None]
        # outer product, mean over MSA sequences: [B, R, R, d*d]
        outer = jnp.einsum("xsiu,xsjv->xijuv", a, b)
        norm = jnp.einsum("xsi,xsj->xij", msa_mask, msa_mask)[..., None, None]
        outer = outer / (norm + 1e-3)
        outer = outer.reshape(outer.shape[:-2] + (d * d,))
        return _dense(c.pair_channel, "output_w", init="final", dtype=c.dtype)(
            outer.astype(c.dtype)
        )


class TriangleMultiplication(nn.Module):
    """Triangle multiplicative update (reference attentions.py:488-560);
    outgoing = edges ik,jk; incoming = edges ki,kj."""

    cfg: EvoformerConfig
    outgoing: bool = True

    @nn.compact
    def __call__(self, pair_act, pair_mask):
        c = self.cfg
        d = c.triangle_mult_dim
        pair_act = pair_row_sharded(pair_act)
        act = _ln("layer_norm", c.dtype)(pair_act.astype(c.dtype))
        mask = pair_mask[..., None].astype(c.dtype)
        left = _dense(d, "left_projection", dtype=c.dtype)(act) * mask
        right = _dense(d, "right_projection", dtype=c.dtype)(act) * mask
        left_g = jax.nn.sigmoid(_dense(d, "left_gate", init="gate", dtype=c.dtype)(act))
        right_g = jax.nn.sigmoid(_dense(d, "right_gate", init="gate", dtype=c.dtype)(act))
        left = left * left_g
        right = right * right_g
        if self.outgoing:
            out = jnp.einsum("bikd,bjkd->bijd", left, right)
        else:
            out = jnp.einsum("bkid,bkjd->bijd", left, right)
        out = _ln("center_layer_norm", c.dtype)(out)
        out = _dense(c.pair_channel, "output_projection", init="final",
                     dtype=c.dtype)(out)
        gate = jax.nn.sigmoid(
            _dense(c.pair_channel, "gating_linear", init="gate", dtype=c.dtype)(act)
        )
        return out * gate


class TriangleAttention(nn.Module):
    """Triangle self-attention around starting/ending node (reference
    attentions.py:410-486)."""

    cfg: EvoformerConfig
    starting: bool = True

    @nn.compact
    def __call__(self, pair_act, pair_mask):
        c = self.cfg
        if self.starting:
            pair_act = pair_row_sharded(pair_act)
        else:
            pair_act = pair_col_sharded(pair_act)
            pair_act = jnp.swapaxes(pair_act, -2, -3)
            pair_mask = jnp.swapaxes(pair_mask, -1, -2)
        act = _ln("query_norm", c.dtype)(pair_act.astype(c.dtype))
        bias2d = _dense(c.num_heads_pair, "feat_2d_w", use_bias=False,
                        dtype=c.dtype)(act)
        bias2d = jnp.moveaxis(bias2d, -1, -3)[:, None].astype(jnp.float32)
        mask_bias = (1.0 - pair_mask[:, :, None, None, :]) * BIG_NEG
        out = GatedAttention(c, c.num_heads_pair, c.pair_channel, name="attn")(
            act, act, mask_bias, nonbatched_bias=bias2d
        )
        if not self.starting:
            out = jnp.swapaxes(out, -2, -3)
        return out


class EvoformerIteration(nn.Module):
    """One Evoformer block (reference evoformer.py:41-482, forward :460)."""

    cfg: EvoformerConfig

    @nn.compact
    def __call__(self, msa_act, pair_act, msa_mask, pair_mask):
        c = self.cfg
        add = lambda x, y: (x + y.astype(x.dtype))
        msa_act = add(msa_act, MSARowAttentionWithPairBias(
            c, name="msa_row_attention_with_pair_bias"
        )(msa_act, msa_mask, pair_act))
        if c.global_column_attention:
            msa_act = add(msa_act, MSAColumnGlobalAttention(
                c, name="msa_column_global_attention"
            )(msa_act, msa_mask))
        else:
            msa_act = add(msa_act, MSAColumnAttention(
                c, name="msa_column_attention"
            )(msa_act, msa_mask))
        msa_act = add(msa_act, Transition(
            c, c.msa_transition_factor, name="msa_transition"
        )(msa_act))
        pair_act = add(pair_act, OuterProductMean(c, name="outer_product_mean")(
            msa_act, msa_mask
        ))
        pair_act = add(pair_act, TriangleMultiplication(
            c, outgoing=True, name="triangle_multiplication_outgoing"
        )(pair_act, pair_mask))
        pair_act = add(pair_act, TriangleMultiplication(
            c, outgoing=False, name="triangle_multiplication_incoming"
        )(pair_act, pair_mask))
        pair_act = add(pair_act, TriangleAttention(
            c, starting=True, name="triangle_attention_starting_node"
        )(pair_act, pair_mask))
        pair_act = add(pair_act, TriangleAttention(
            c, starting=False, name="triangle_attention_ending_node"
        )(pair_act, pair_mask))
        pair_act = add(pair_act, Transition(
            c, c.pair_transition_factor, name="pair_transition"
        )(pair_act))
        return msa_act, pair_act


class _ScanIteration(nn.Module):
    cfg: EvoformerConfig

    @nn.compact
    def __call__(self, carry, _):
        msa_act, pair_act, msa_mask, pair_mask = carry
        msa_act, pair_act = EvoformerIteration(self.cfg, name="iteration")(
            msa_act, pair_act, msa_mask, pair_mask
        )
        return (msa_act, pair_act, msa_mask, pair_mask), None


class EvoformerStack(nn.Module):
    """num_layers Evoformer iterations (reference DistEmbeddingsAndEvoformer
    runs the list eagerly, evoformer.py:484-700; here nn.scan compiles one
    body)."""

    cfg: EvoformerConfig

    @nn.compact
    def __call__(self, msa_act, pair_act, msa_mask, pair_mask):
        c = self.cfg
        layer_cls = _ScanIteration
        if c.use_recompute:
            layer_cls = nn.remat(_ScanIteration, prevent_cse=False)
        if c.scan_layers:
            stack = nn.scan(
                layer_cls,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=c.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            (msa_act, pair_act, _, _), _ = stack(c, name="layers")(
                (msa_act, pair_act, msa_mask, pair_mask), None
            )
        else:
            for i in range(c.num_layers):
                (msa_act, pair_act, msa_mask, pair_mask), _ = layer_cls(
                    c, name=f"layers_{i}"
                )((msa_act, pair_act, msa_mask, pair_mask), None)
        return msa_act, pair_act
