"""Template embedding (AlphaFold Suppl. Alg. 16/17) — flax/TPU-native.

Capability parity with the reference's template.py
(/root/reference/ppfleetx/models/protein_folding/template.py:36-359:
TemplatePair, SingleTemplateEmbedding, TemplateEmbedding): per-template
pair features (distogram of pseudo-beta positions, one-hot aatypes,
backbone-frame unit vectors) run through a small triangle-update stack,
then a pointwise attention folds the templates into the query pair
representation. Templates are processed with vmap over the template axis
(the reference python-loops them), and the pair stack reuses the
evoformer's triangle blocks under a narrowed config.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp

from fleetx_tpu.models.protein import geometry, residue_constants as rc
from fleetx_tpu.models.protein.evoformer import (
    EvoformerConfig,
    Transition,
    TriangleAttention,
    TriangleMultiplication,
    _dense,
    _ln,
)

__all__ = ["TemplateConfig", "TemplateEmbedding", "dgram_from_positions"]

BIG_NEG = -1e9


@dataclasses.dataclass(frozen=True)
class TemplateConfig:
    """Template-embedding hyperparameters (reference template.py)."""
    enabled: bool = True
    embed_torsion_angles: bool = True
    use_template_unit_vector: bool = False
    pair_stack_channel: int = 64
    num_blocks: int = 2
    num_heads: int = 4
    attention_key_dim: int = 64
    dgram_min_bin: float = 3.25
    dgram_max_bin: float = 50.75
    dgram_num_bins: int = 39
    dtype: Any = jnp.bfloat16


def dgram_from_positions(positions, num_bins, min_bin, max_bin):
    """One-hot distogram of pairwise distances (reference common.py
    dgram_from_positions): bucket the squared distance between residues
    into num_bins edges linearly spaced in distance."""
    lower = jnp.linspace(min_bin, max_bin, num_bins) ** 2
    upper = jnp.concatenate([lower[1:], jnp.asarray([1e8])])
    d2 = jnp.sum(
        (positions[..., :, None, :] - positions[..., None, :, :]) ** 2,
        axis=-1,
        keepdims=True,
    )
    return ((d2 > lower) * (d2 < upper)).astype(jnp.float32)


def _pair_stack_cfg(cfg: TemplateConfig) -> EvoformerConfig:
    """Evoformer block config narrowed to the template pair stack's dims."""
    return EvoformerConfig(
        pair_channel=cfg.pair_stack_channel,
        num_heads_pair=cfg.num_heads,
        triangle_mult_dim=cfg.pair_stack_channel,
        pair_transition_factor=2,
        dtype=cfg.dtype,
    )


class TemplatePair(nn.Module):
    """One block of the TemplatePairStack (Suppl. Alg. 16 lines 2-6)."""

    cfg: TemplateConfig

    @nn.compact
    def __call__(self, pair_act, pair_mask):
        c = _pair_stack_cfg(self.cfg)
        add = lambda x, y: x + y.astype(x.dtype)
        pair_act = add(pair_act, TriangleAttention(
            c, starting=True, name="triangle_attention_starting_node"
        )(pair_act, pair_mask))
        pair_act = add(pair_act, TriangleAttention(
            c, starting=False, name="triangle_attention_ending_node"
        )(pair_act, pair_mask))
        pair_act = add(pair_act, TriangleMultiplication(
            c, outgoing=True, name="triangle_multiplication_outgoing"
        )(pair_act, pair_mask))
        pair_act = add(pair_act, TriangleMultiplication(
            c, outgoing=False, name="triangle_multiplication_incoming"
        )(pair_act, pair_mask))
        pair_act = add(pair_act, Transition(
            c, c.pair_transition_factor, name="pair_transition"
        )(pair_act))
        return pair_act


class SingleTemplateEmbedding(nn.Module):
    """Embed one template into a pair representation (Suppl. Alg. 2 l.9+11).

    Inputs are single-template slices: aatype [B, N], pseudo-beta [B, N, 3],
    atom positions [B, N, 37, 3], masks accordingly."""

    cfg: TemplateConfig

    @nn.compact
    def __call__(self, batch: Dict[str, jnp.ndarray], mask_2d):
        c = self.cfg
        dt = c.dtype
        n_res = batch["template_aatype"].shape[-1]

        tmask = batch["template_pseudo_beta_mask"]
        tmask_2d = tmask[..., :, None] * tmask[..., None, :]
        dgram = dgram_from_positions(
            batch["template_pseudo_beta"],
            num_bins=c.dgram_num_bins, min_bin=c.dgram_min_bin,
            max_bin=c.dgram_max_bin,
        )
        aatype = jax.nn.one_hot(batch["template_aatype"], 22)

        to_concat = [
            dgram,
            tmask_2d[..., None],
            jnp.broadcast_to(
                aatype[..., None, :, :], aatype.shape[:-2] + (n_res, n_res, 22)
            ),
            jnp.broadcast_to(
                aatype[..., :, None, :], aatype.shape[:-2] + (n_res, n_res, 22)
            ),
        ]

        # backbone-frame unit vectors: each residue j's CA expressed in
        # residue i's backbone frame, normalized (reference template.py
        # :222-258 via quat_affine)
        n_i, ca_i, c_i = (rc.atom_order[a] for a in ("N", "CA", "C"))
        pos = batch["template_all_atom_positions"]
        rot, trans = geometry.make_transform_from_reference(
            n_xyz=pos[..., n_i, :],
            ca_xyz=pos[..., ca_i, :],
            c_xyz=pos[..., c_i, :],
        )
        # rot/trans: [B, N, ...]; express every CA in every residue's frame
        points = trans[..., None, :, :]  # [B, 1, N, 3] global CA positions
        vec = geometry.apply_inverse_rigid(
            rot[..., :, None, :, :], trans[..., :, None, :], points
        )  # [B, N(frames), N(points), 3]
        inv_dist = jax.lax.rsqrt(1e-6 + jnp.sum(vec**2, axis=-1))
        atom_masks = batch["template_all_atom_masks"]
        backbone_mask = (
            atom_masks[..., n_i] * atom_masks[..., ca_i] * atom_masks[..., c_i]
        )
        backbone_mask_2d = (
            backbone_mask[..., :, None] * backbone_mask[..., None, :]
        )
        inv_dist = inv_dist * backbone_mask_2d
        unit_vector = vec * inv_dist[..., None]
        if not c.use_template_unit_vector:
            unit_vector = jnp.zeros_like(unit_vector)
        to_concat.append(unit_vector)
        to_concat.append(backbone_mask_2d[..., None])

        act = jnp.concatenate(
            [t.astype(dt) for t in to_concat], axis=-1
        )
        act = act * backbone_mask_2d[..., None].astype(dt)
        act = _dense(c.pair_stack_channel, "embedding2d", dtype=dt)(act)

        for i in range(c.num_blocks):
            act = TemplatePair(c, name=f"pair_stack_{i}")(act, mask_2d)
        return _ln("output_layer_norm", dt)(act)


class TemplateEmbedding(nn.Module):
    """Embed all templates and attend the query pair act over them
    (Suppl. Alg. 17 TemplatePointwiseAttention)."""

    cfg: TemplateConfig

    @nn.compact
    def __call__(self, query_embedding, template_batch, mask_2d):
        c = self.cfg
        dt = c.dtype
        cz = query_embedding.shape[-1]

        single = nn.vmap(
            SingleTemplateEmbedding,
            in_axes=(1, None),
            out_axes=1,
            variable_axes={"params": None},
            split_rngs={"params": False},
        )(c, name="single_template_embedding")
        per_template = {
            k: v for k, v in template_batch.items() if k != "template_mask"
        }
        templ_repr = single(per_template, mask_2d)  # [B, T, R, R, ct]

        # pointwise attention: each (i, j) pair position queries over the
        # template axis
        nh, kd = c.num_heads, c.attention_key_dim // c.num_heads
        q = _dense((nh, kd), "query_w", use_bias=False, dtype=dt)(
            query_embedding.astype(dt)
        ) * kd ** -0.5                                  # [B, R, R, h, d]
        k = _dense((nh, kd), "key_w", use_bias=False, dtype=dt)(
            templ_repr.astype(dt)
        )                                               # [B, T, R, R, h, d]
        v = _dense((nh, kd), "value_w", use_bias=False, dtype=dt)(
            templ_repr.astype(dt)
        )
        logits = jnp.einsum(
            "brshd,btrshd->brsht", q, k, preferred_element_type=jnp.float32
        )
        tmask = template_batch["template_mask"].astype(jnp.float32)
        logits = logits + (1.0 - tmask[:, None, None, None, :]) * BIG_NEG
        weights = jax.nn.softmax(logits, axis=-1).astype(dt)
        out = jnp.einsum("brsht,btrshd->brshd", weights, v)
        emb = nn.DenseGeneral(
            features=cz, axis=(-2, -1), dtype=dt, param_dtype=jnp.float32,
            kernel_init=nn.initializers.zeros_init(), name="output_w",
        )(out)
        # zero contribution when no templates exist
        any_template = (jnp.sum(tmask, axis=-1) > 0.0).astype(emb.dtype)
        return emb * any_template[:, None, None, None]
