"""DistEmbeddingsAndEvoformer — the full folding trunk composition.

Capability parity with the reference's DistEmbeddingsAndEvoformer
(/root/reference/ppfleetx/models/protein_folding/evoformer.py:484-859;
AlphaFold Suppl. Alg. 2 "Inference" lines 5-18): input embedder, recycling
embedder, relative-position embedder, template embedding (+ torsion-angle
rows appended to the MSA), extra-MSA stack with global column attention,
and the main Evoformer stack, emitting {msa, pair, single, msa_first_row}.

Distribution: the reference hand-places dap.scatter/gather and bp
broadcasts around each stack; here the axial layout is declared through
the blocks' sharding constraints (fleetx_tpu/parallel/dap.py) and GSPMD
inserts the scatter/gather/all-to-all collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp

from fleetx_tpu.models.protein import all_atom
from fleetx_tpu.models.protein.evoformer import (
    EvoformerConfig,
    EvoformerStack,
    _dense,
    _ln,
)
from fleetx_tpu.models.protein.template import (
    TemplateConfig,
    TemplateEmbedding,
    dgram_from_positions,
)

__all__ = ["FoldingConfig", "DistEmbeddingsAndEvoformer"]

# target_feat is a 22-dim one-hot (20 aa + unknown + gap), msa_feat 49-dim
TARGET_FEAT_DIM = 22
MSA_FEAT_DIM = 49
EXTRA_MSA_FEAT_DIM = 25  # 23 one-hot + has_deletion + deletion_value


@dataclasses.dataclass(frozen=True)
class FoldingConfig:
    """Hyperparameters of the full folding trunk (msa/pair dims, template +
    extra-MSA stacks)."""
    msa_channel: int = 256
    pair_channel: int = 128
    seq_channel: int = 384
    extra_msa_channel: int = 64
    evoformer_num_block: int = 48
    extra_msa_stack_num_block: int = 4
    max_relative_feature: int = 32
    recycle_pos: bool = True
    recycle_features: bool = True
    prev_pos_min_bin: float = 3.25
    prev_pos_max_bin: float = 20.75
    prev_pos_num_bins: int = 15
    template: TemplateConfig = dataclasses.field(default_factory=TemplateConfig)
    num_heads_msa: int = 8
    num_heads_pair: int = 4
    outer_product_dim: int = 32
    triangle_mult_dim: int = 0  # 0 = follow pair_channel (reference coupling)
    use_recompute: bool = False
    scan_layers: bool = True
    dtype: Any = jnp.bfloat16

    def evoformer_cfg(self, extra: bool) -> EvoformerConfig:
        return EvoformerConfig(
            msa_channel=self.extra_msa_channel if extra else self.msa_channel,
            pair_channel=self.pair_channel,
            num_heads_msa=self.num_heads_msa,
            num_heads_pair=self.num_heads_pair,
            num_layers=(self.extra_msa_stack_num_block if extra
                        else self.evoformer_num_block),
            outer_product_dim=self.outer_product_dim,
            triangle_mult_dim=self.triangle_mult_dim or self.pair_channel,
            global_column_attention=extra,
            use_recompute=self.use_recompute,
            scan_layers=self.scan_layers,
            dtype=self.dtype,
        )

    @classmethod
    def from_model_config(cls, model_cfg) -> "FoldingConfig":
        d = dict(model_cfg)
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in known and v is not None}
        if isinstance(kw.get("dtype"), str):
            kw["dtype"] = jnp.dtype(kw["dtype"]).type
        if isinstance(kw.get("template"), dict):
            tkw = {k: v for k, v in kw["template"].items()
                   if k in {f.name for f in dataclasses.fields(TemplateConfig)}}
            tkw.setdefault("dtype", kw.get("dtype", jnp.bfloat16))
            kw["template"] = TemplateConfig(**tkw)
        return cls(**kw)


class DistEmbeddingsAndEvoformer(nn.Module):
    """Input embeddings + template + extra-MSA + Evoformer composition
    (reference evoformer.py:484-859), DAP-sharded over the cp axis."""
    cfg: FoldingConfig

    @nn.compact
    def __call__(self, batch: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        c = self.cfg
        dt = c.dtype

        # ---- InputEmbedder (Suppl. Alg. 3)
        target = batch["target_feat"].astype(dt)
        preprocess_1d = _dense(c.msa_channel, "preprocess_1d", dtype=dt)(target)
        msa_act = preprocess_1d[:, None] + _dense(
            c.msa_channel, "preprocess_msa", dtype=dt
        )(batch["msa_feat"].astype(dt))
        left = _dense(c.pair_channel, "left_single", dtype=dt)(target)
        right = _dense(c.pair_channel, "right_single", dtype=dt)(target)
        pair_act = left[:, :, None] + right[:, None, :]

        seq_mask = batch["seq_mask"]
        mask_2d = seq_mask[:, :, None] * seq_mask[:, None, :]

        # ---- RecyclingEmbedder (Suppl. Alg. 32)
        if c.recycle_pos and "prev_pos" in batch:
            prev_pb = all_atom.pseudo_beta_fn(batch["aatype"], batch["prev_pos"])
            dgram = dgram_from_positions(
                prev_pb, num_bins=c.prev_pos_num_bins,
                min_bin=c.prev_pos_min_bin, max_bin=c.prev_pos_max_bin,
            )
            pair_act += _dense(c.pair_channel, "prev_pos_linear", dtype=dt)(
                dgram.astype(dt)
            )
        if c.recycle_features:
            if "prev_msa_first_row" in batch:
                prev_first = _ln("prev_msa_first_row_norm", dt)(
                    batch["prev_msa_first_row"].astype(dt)
                )
                msa_act = msa_act.at[:, 0].add(prev_first)
            if "prev_pair" in batch:
                pair_act += _ln("prev_pair_norm", dt)(
                    batch["prev_pair"].astype(dt)
                )

        # ---- relpos (Suppl. Alg. 4/5)
        if c.max_relative_feature:
            pos = batch["residue_index"]
            offset = pos[:, :, None] - pos[:, None, :]
            rel = jax.nn.one_hot(
                jnp.clip(offset + c.max_relative_feature,
                         0, 2 * c.max_relative_feature),
                2 * c.max_relative_feature + 1,
            )
            pair_act += _dense(c.pair_channel, "pair_activations", dtype=dt)(
                rel.astype(dt)
            )

        # ---- TemplateEmbedder (Suppl. Alg. 2 lines 9-13)
        if c.template.enabled and "template_aatype" in batch:
            template_batch = {
                k: v for k, v in batch.items() if k.startswith("template_")
            }
            pair_act += TemplateEmbedding(c.template, name="template_embedding")(
                pair_act, template_batch, mask_2d.astype(dt)
            ).astype(pair_act.dtype)

        # ---- ExtraMSAEmbedder + extra-MSA stack (Suppl. Alg. 18)
        extra_1hot = jax.nn.one_hot(batch["extra_msa"], 23)
        extra_feat = jnp.concatenate(
            [
                extra_1hot,
                batch["extra_has_deletion"][..., None],
                batch["extra_deletion_value"][..., None],
            ],
            axis=-1,
        )
        extra_act = _dense(c.extra_msa_channel, "extra_msa_activations",
                           dtype=dt)(extra_feat.astype(dt))
        _, pair_act = EvoformerStack(
            c.evoformer_cfg(extra=True), name="extra_msa_stack"
        )(extra_act, pair_act, batch["extra_msa_mask"], mask_2d)

        msa_mask = batch["msa_mask"]
        num_seq = batch["msa_feat"].shape[1]

        # ---- template torsion-angle rows appended to the MSA
        # (Suppl. Alg. 2 lines 7-8)
        if (c.template.enabled and c.template.embed_torsion_angles
                and "template_aatype" in batch):
            n_templ, n_res = batch["template_aatype"].shape[1:3]
            aatype_1hot = jax.nn.one_hot(batch["template_aatype"], 22)
            ret = all_atom.atom37_to_torsion_angles(
                aatype=batch["template_aatype"],
                all_atom_pos=batch["template_all_atom_positions"],
                all_atom_mask=batch["template_all_atom_masks"],
                placeholder_for_undefined=True,
            )
            template_features = jnp.concatenate(
                [
                    aatype_1hot,
                    ret["torsion_angles_sin_cos"].reshape(
                        -1, n_templ, n_res, 14),
                    ret["alt_torsion_angles_sin_cos"].reshape(
                        -1, n_templ, n_res, 14),
                    ret["torsion_angles_mask"],
                ],
                axis=-1,
            ).astype(dt)
            template_act = _dense(
                c.msa_channel, "template_single_embedding", init="relu",
                dtype=dt,
            )(template_features)
            template_act = jax.nn.relu(template_act)
            template_act = _dense(
                c.msa_channel, "template_projection", dtype=dt
            )(template_act)
            msa_act = jnp.concatenate([msa_act, template_act], axis=1)
            torsion_mask = ret["torsion_angles_mask"][..., 2].astype(
                msa_mask.dtype
            )
            msa_mask = jnp.concatenate([msa_mask, torsion_mask], axis=1)

        # ---- main Evoformer stack (Suppl. Alg. 2 lines 17-18)
        msa_act, pair_act = EvoformerStack(
            c.evoformer_cfg(extra=False), name="evoformer"
        )(msa_act, pair_act, msa_mask, mask_2d)

        single = _dense(c.seq_channel, "single_activations", dtype=dt)(
            msa_act[:, 0]
        )
        return {
            "single": single,
            "pair": pair_act,
            # crop template rows away so MaskedMsaHead never sees them
            "msa": msa_act[:, :num_seq],
            "msa_first_row": msa_act[:, 0],
        }
