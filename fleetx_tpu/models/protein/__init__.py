from fleetx_tpu.models.protein.evoformer import (  # noqa: F401
    EvoformerConfig,
    EvoformerIteration,
    EvoformerStack,
)
