"""Protein-folding trunk (Evoformer, templates, geometry; reference models/protein_folding)."""

from fleetx_tpu.models.protein.evoformer import (  # noqa: F401
    EvoformerConfig,
    EvoformerIteration,
    EvoformerStack,
    GlobalAttention,
    MSAColumnGlobalAttention,
)
from fleetx_tpu.models.protein.folding import (  # noqa: F401
    DistEmbeddingsAndEvoformer,
    FoldingConfig,
)
from fleetx_tpu.models.protein.rigid import (  # noqa: F401
    QuatAffine,
    Rigid,
)
from fleetx_tpu.models.protein.template import (  # noqa: F401
    TemplateConfig,
    TemplateEmbedding,
)
