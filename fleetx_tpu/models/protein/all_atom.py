"""Atom-level featurization: torsion angles and pseudo-beta positions.

Capability parity with the reference's all_atom.py
(/root/reference/ppfleetx/models/protein_folding/all_atom.py:52-248
``atom37_to_torsion_angles``) in idiomatic JAX: the chi-angle atom tables
come precomputed from residue_constants (the reference rebuilds them per
call), gathers use jnp.take/take_along_axis instead of a hand-rolled
batched_gather, and frames use the [..., 3, 3] geometry module rather than
struct-of-scalars r3.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from fleetx_tpu.models.protein import geometry, residue_constants as rc

__all__ = ["atom37_to_torsion_angles", "pseudo_beta_fn"]


def pseudo_beta_fn(aatype, all_atom_positions, all_atom_masks=None):
    """CB coordinates (CA for glycine) — the residue position used for
    distograms (reference evoformer.py _pseudo_beta_fn)."""
    is_gly = aatype == rc.restype_order["G"]
    ca = rc.atom_order["CA"]
    cb = rc.atom_order["CB"]
    pseudo_beta = jnp.where(
        is_gly[..., None],
        all_atom_positions[..., ca, :],
        all_atom_positions[..., cb, :],
    )
    if all_atom_masks is None:
        return pseudo_beta
    mask = jnp.where(is_gly, all_atom_masks[..., ca], all_atom_masks[..., cb])
    return pseudo_beta, mask


def atom37_to_torsion_angles(
    aatype: jnp.ndarray,          # [B, T, N] int
    all_atom_pos: jnp.ndarray,    # [B, T, N, 37, 3]
    all_atom_mask: jnp.ndarray,   # [B, T, N, 37]
    placeholder_for_undefined: bool = False,
) -> Dict[str, jnp.ndarray]:
    """The 7 torsion angles per residue in sin/cos encoding:
    [pre_omega, phi, psi, chi1..chi4], plus the pi-flipped alternates for
    ambiguous chis and the per-angle validity mask."""
    aatype = jnp.minimum(aatype.astype(jnp.int32), rc.unk_restype_index)

    # previous residue's atoms (zero-padded at the chain start)
    prev_pos = jnp.pad(
        all_atom_pos[..., :-1, :, :], [(0, 0), (0, 0), (1, 0), (0, 0), (0, 0)]
    )
    prev_mask = jnp.pad(
        all_atom_mask[..., :-1, :], [(0, 0), (0, 0), (1, 0), (0, 0)]
    )

    # [B, T, N, 4(atoms), 3] per backbone torsion
    pre_omega_atom_pos = jnp.concatenate(
        [prev_pos[..., 1:3, :], all_atom_pos[..., 0:2, :]], axis=-2
    )  # prev CA, prev C, this N, this CA
    phi_atom_pos = jnp.concatenate(
        [prev_pos[..., 2:3, :], all_atom_pos[..., 0:3, :]], axis=-2
    )  # prev C, this N, CA, C
    psi_atom_pos = jnp.concatenate(
        [all_atom_pos[..., 0:3, :], all_atom_pos[..., 4:5, :]], axis=-2
    )  # this N, CA, C, O

    pre_omega_mask = (
        jnp.prod(prev_mask[..., 1:3], axis=-1)
        * jnp.prod(all_atom_mask[..., 0:2], axis=-1)
    )
    phi_mask = prev_mask[..., 2] * jnp.prod(all_atom_mask[..., 0:3], axis=-1)
    psi_mask = (
        jnp.prod(all_atom_mask[..., 0:3], axis=-1) * all_atom_mask[..., 4]
    )

    # chi atoms: table lookup by aatype -> [B, T, N, 4(chis), 4(atoms)]
    chi_atom_indices = jnp.asarray(rc.chi_atom_indices_array())
    atom_indices = chi_atom_indices[aatype]
    # gather positions along the atom37 axis -> [B, T, N, 4, 4, 3]
    flat_idx = atom_indices.reshape(*aatype.shape, 16)
    chis_atom_pos = jnp.take_along_axis(
        all_atom_pos, flat_idx[..., None].repeat(3, -1), axis=-2
    ).reshape(*aatype.shape, 4, 4, 3)

    chi_angles_mask = jnp.asarray(rc.chi_angles_mask_array())
    chis_mask = chi_angles_mask[aatype]  # [B, T, N, 4]
    chi_atoms_present = jnp.take_along_axis(
        all_atom_mask, flat_idx, axis=-1
    ).reshape(*aatype.shape, 4, 4)
    chis_mask = chis_mask * jnp.prod(chi_atoms_present, axis=-1)

    # [B, T, N, 7, 4, 3]
    torsions_atom_pos = jnp.concatenate(
        [
            pre_omega_atom_pos[..., None, :, :],
            phi_atom_pos[..., None, :, :],
            psi_atom_pos[..., None, :, :],
            chis_atom_pos,
        ],
        axis=-3,
    )
    torsion_angles_mask = jnp.concatenate(
        [
            pre_omega_mask[..., None],
            phi_mask[..., None],
            psi_mask[..., None],
            chis_mask,
        ],
        axis=-1,
    )

    # frame per torsion from atoms (1, 2) with atom 0 in the xy-plane;
    # the 4th atom's (z, y) in that frame encode (sin, cos)
    rot, trans = geometry.rigids_from_3_points(
        point_on_neg_x_axis=torsions_atom_pos[..., 1, :],
        origin=torsions_atom_pos[..., 2, :],
        point_on_xy_plane=torsions_atom_pos[..., 0, :],
    )
    forth_rel = geometry.apply_inverse_rigid(
        rot, trans, torsions_atom_pos[..., 3, :]
    )
    sin_cos = jnp.stack([forth_rel[..., 2], forth_rel[..., 1]], axis=-1)
    sin_cos = sin_cos / jnp.sqrt(
        jnp.sum(sin_cos**2, axis=-1, keepdims=True) + 1e-8
    )
    # psi is measured to the O atom, which sits pi away from the chi
    # convention: mirror it
    sin_cos = sin_cos * jnp.asarray([1.0, 1.0, -1.0, 1.0, 1.0, 1.0, 1.0])[
        None, None, None, :, None
    ]

    chi_is_ambiguous = jnp.asarray(rc.chi_pi_periodic_array())[aatype]
    mirror = jnp.concatenate(
        [jnp.ones(aatype.shape + (3,)), 1.0 - 2.0 * chi_is_ambiguous], axis=-1
    )
    alt_sin_cos = sin_cos * mirror[..., None]

    if placeholder_for_undefined:
        placeholder = jnp.stack(
            [jnp.ones(sin_cos.shape[:-1]), jnp.zeros(sin_cos.shape[:-1])],
            axis=-1,
        )
        m = torsion_angles_mask[..., None]
        sin_cos = sin_cos * m + placeholder * (1 - m)
        alt_sin_cos = alt_sin_cos * m + placeholder * (1 - m)

    return {
        "torsion_angles_sin_cos": sin_cos,          # [B, T, N, 7, 2]
        "alt_torsion_angles_sin_cos": alt_sin_cos,  # [B, T, N, 7, 2]
        "torsion_angles_mask": torsion_angles_mask, # [B, T, N, 7]
    }
