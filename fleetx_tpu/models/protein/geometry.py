"""Rigid-body 3D geometry for the folding trunk.

Capability parity with the reference's r3.py (490 LoC) and quat_affine.py
(613 LoC) (/root/reference/ppfleetx/models/protein_folding/), redesigned for
XLA: where the reference carries structs-of-scalars (r3.Vecs with separate
x/y/z tensors, 9-field Rots) to dodge framework overheads, here vectors are
plain [..., 3] arrays and rotations [..., 3, 3] matrices — XLA fuses the
small einsums and keeps everything vectorized, so the struct juggling buys
nothing on TPU.

Conventions: a rigid transform is the pair (rot [..., 3, 3], trans [..., 3])
mapping local -> global points: g = rot @ l + trans.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

__all__ = [
    "rigids_from_3_points",
    "invert_rigid",
    "apply_rigid",
    "apply_inverse_rigid",
    "rot_to_quat",
    "quat_to_rot",
    "make_transform_from_reference",
]


def rigids_from_3_points(point_on_neg_x_axis, origin, point_on_xy_plane,
                         eps: float = 1e-8):
    """Gram-Schmidt frame from three points (reference
    r3.rigids_from_3_points_vecs; AlphaFold Suppl. Alg. 21): the x-axis
    points from `point_on_neg_x_axis` to `origin`, the xy-plane contains
    `point_on_xy_plane`. Returns (rot [..., 3, 3], trans [..., 3])."""
    e0 = origin - point_on_neg_x_axis
    e1 = point_on_xy_plane - origin
    e0 = e0 / jnp.sqrt(jnp.sum(e0**2, -1, keepdims=True) + eps)
    e1 = e1 - e0 * jnp.sum(e0 * e1, -1, keepdims=True)
    e1 = e1 / jnp.sqrt(jnp.sum(e1**2, -1, keepdims=True) + eps)
    e2 = jnp.cross(e0, e1)
    rot = jnp.stack([e0, e1, e2], axis=-1)  # columns are the basis vectors
    return rot, origin


def invert_rigid(rot, trans):
    """Inverse rigid transform: (R, t) -> (R^T, -R^T t)."""
    inv_rot = jnp.swapaxes(rot, -1, -2)
    inv_trans = -jnp.einsum("...ij,...j->...i", inv_rot, trans)
    return inv_rot, inv_trans


def apply_rigid(rot, trans, point):
    """g = R @ p + t with broadcasting over leading dims."""
    return jnp.einsum("...ij,...j->...i", rot, point) + trans


def apply_inverse_rigid(rot, trans, point):
    """R^T @ (p - t): maps a global point into the local frame (reference
    QuatAffine.invert_point, quat_affine.py)."""
    return jnp.einsum("...ji,...j->...i", rot, point - trans)


def rot_to_quat(rot, unstack_inputs: bool = False):
    """Rotation matrix [..., 3, 3] -> unit quaternion [..., 4] (w, x, y, z).

    Uses the eigenvector-free branch selection of the reference
    (quat_affine.py rot_to_quat): build the four squared-magnitude
    candidates and normalize the largest for numerical safety."""
    del unstack_inputs
    xx, xy, xz = rot[..., 0, 0], rot[..., 0, 1], rot[..., 0, 2]
    yx, yy, yz = rot[..., 1, 0], rot[..., 1, 1], rot[..., 1, 2]
    zx, zy, zz = rot[..., 2, 0], rot[..., 2, 1], rot[..., 2, 2]
    # 4 candidate quaternions, one per dominant component
    qw = jnp.stack([1.0 + xx + yy + zz, zy - yz, xz - zx, yx - xy], -1)
    qx = jnp.stack([zy - yz, 1.0 + xx - yy - zz, xy + yx, xz + zx], -1)
    qy = jnp.stack([xz - zx, xy + yx, 1.0 - xx + yy - zz, yz + zy], -1)
    qz = jnp.stack([yx - xy, xz + zx, yz + zy, 1.0 - xx - yy + zz], -1)
    cands = jnp.stack([qw, qx, qy, qz], -2)  # [..., 4(cand), 4(quat)]
    norms = jnp.sum(cands**2, -1)  # [..., 4]
    best = jnp.argmax(norms, -1)
    q = jnp.take_along_axis(cands, best[..., None, None].repeat(4, -1),
                            axis=-2)[..., 0, :]
    return q / jnp.linalg.norm(q, axis=-1, keepdims=True)


def quat_to_rot(q):
    """Unit quaternion [..., 4] (w, x, y, z) -> rotation matrix [..., 3, 3]."""
    w, x, y, z = q[..., 0], q[..., 1], q[..., 2], q[..., 3]
    r = jnp.stack(
        [
            1 - 2 * (y * y + z * z), 2 * (x * y - w * z), 2 * (x * z + w * y),
            2 * (x * y + w * z), 1 - 2 * (x * x + z * z), 2 * (y * z - w * x),
            2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x * x + y * y),
        ],
        axis=-1,
    )
    return r.reshape(q.shape[:-1] + (3, 3))


def make_transform_from_reference(n_xyz, ca_xyz, c_xyz) -> Tuple:
    """Backbone frame from N/CA/C coordinates (reference
    quat_affine.make_transform_from_reference): CA at the origin, C on the
    +x axis, N in the xy-plane with positive y. Returns (rot, trans) such
    that apply_inverse_rigid maps global points into the residue frame."""
    rot, trans = rigids_from_3_points(
        point_on_neg_x_axis=2.0 * ca_xyz - c_xyz,  # C on +x
        origin=ca_xyz,
        point_on_xy_plane=n_xyz,
    )
    return rot, trans
