"""ImagenModule — text-to-image diffusion pretraining (reference
/root/reference/ppfleetx/models/multimodal_model/multimodal_module.py +
imagen/modeling.py ImagenModel.forward: pick a cascade stage, q_sample,
predict noise, p2-weighted MSE).

Batch contract (text embeddings are PRECOMPUTED, see unet.py docstring):
  images       [b, H, W, 3] float32 in [-1, 1]
  text_embeds  [b, L, D] float32
  text_mask    [b, L] float32/int
For SR stages (unet_number > 1) the low-res conditioning image is derived
in-graph by area-downsampling the target (reference resize_image_to)."""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from fleetx_tpu.models.language_module import resolve_compute_dtype
from fleetx_tpu.models.module import BasicModule
from fleetx_tpu.models.multimodal.imagen import imagen_criterion, q_sample
from fleetx_tpu.models.multimodal.unet import (
    UNetConfig,
    EfficientUNet,
    build_unet,
)
from fleetx_tpu.models.vision_module import log_images_per_sec

__all__ = ["ImagenModule"]


class ImagenModule(BasicModule):
    """Imagen diffusion training module: UNet denoiser + cosine log-SNR
    schedule over precomputed text embeddings."""
    def get_model(self):
        model_cfg = self.cfg.Model if hasattr(self.cfg, "Model") else self.cfg
        eng = getattr(self.cfg, "Engine", None) or {}
        dtype = resolve_compute_dtype(eng)
        name = model_cfg.get("unet_name")
        self.image_size = int(model_cfg.get("image_size") or 64)
        self.lowres_size = model_cfg.get("lowres_size")  # set for SR stages
        self.p2_gamma = float(model_cfg.get("p2_loss_weight_gamma") or 0.0)
        self.p2_k = float(model_cfg.get("p2_loss_weight_k") or 1.0)
        overrides = {"dtype": dtype}
        if model_cfg.get("cond_dim"):
            overrides["cond_dim"] = int(model_cfg["cond_dim"])
        if name:
            model = build_unet(name, **overrides)
        else:
            model = EfficientUNet(UNetConfig.from_model_config(
                {**dict(model_cfg), **overrides}
            ))
        self.unet_config = model.cfg
        return model

    def _lowres(self, images):
        if not self.unet_config.lowres_cond:
            return None
        size = int(self.lowres_size or self.image_size // 4)
        b, h, w, ch = images.shape
        low = jax.image.resize(images, (b, size, size, ch), method="linear")
        return jax.image.resize(low, (b, h, w, ch), method="nearest")

    def init_params(self, rng, batch):
        images = jnp.asarray(batch["images"])
        t = jnp.zeros((images.shape[0],), jnp.float32)
        return self.nets.init(
            rng, images, t, jnp.asarray(batch["text_embeds"]),
            jnp.asarray(batch["text_mask"]), self._lowres(images),
        )

    def loss_fn(self, params, batch, rng, train: bool):
        images = batch["images"]
        b = images.shape[0]
        if rng is None:
            rng = jax.random.PRNGKey(0)
        t_rng, n_rng = jax.random.split(rng)
        t = jax.random.uniform(t_rng, (b,))
        noise = jax.random.normal(n_rng, images.shape, jnp.float32)
        x_t, log_snr = q_sample(images, t, noise)
        pred = self.nets.apply(
            {"params": params}, x_t, t, batch.get("text_embeds"),
            batch.get("text_mask"), self._lowres(images),
        )
        loss = imagen_criterion(pred, noise, log_snr, self.p2_gamma, self.p2_k)
        return loss, {}

    def input_spec(self):
        glb = self.cfg.Global
        model_cfg = self.cfg.Model
        b = glb.micro_batch_size or 1
        s = self.image_size
        L = int(model_cfg.get("max_text_len") or 64)
        D = int(self.unet_config.cond_dim)
        return {
            "images": jax.ShapeDtypeStruct((b, s, s, 3), jnp.float32),
            "text_embeds": jax.ShapeDtypeStruct((b, L, D), jnp.float32),
            "text_mask": jax.ShapeDtypeStruct((b, L), jnp.float32),
        }

    def serving_forward(self, input_spec):
        """Serve one UNet denoising step eps(x_t, t, text); samplers drive
        it in a loop (ddpm_sample). SR stages take the clean low-res
        conditioning image as an explicit input — at serving time ``images``
        is the *noisy* x_t, so the conditioning cannot be derived from it
        the way training derives it from the clean target."""
        spec = {k: input_spec[k] for k in ("images", "text_embeds", "text_mask")}
        b = spec["images"].shape[0]
        spec["t"] = jax.ShapeDtypeStruct((b,), jnp.float32)
        if self.unet_config.lowres_cond:
            spec["lowres_cond_img"] = jax.ShapeDtypeStruct(
                spec["images"].shape, jnp.float32
            )

        def fn(p, feed):
            low = feed.get("lowres_cond_img") if self.unet_config.lowres_cond else None
            return self.nets.apply(
                {"params": p}, feed["images"], feed["t"], feed.get("text_embeds"),
                feed.get("text_mask"), low,
            )

        return fn, spec

    def training_step_end(self, log: Dict) -> None:
        log_images_per_sec(self.cfg, log)
