"""Host-side span tracing with an XLA-profiler bridge.

``with span("serving.tick"):`` records one nested host span into a
bounded ring buffer (``FLEETX_OBS_SPANS`` spans, oldest dropped) AND —
the bridge — enters a ``jax.profiler.TraceAnnotation`` of the same name,
so when a profiling window is open (``jax.profiler.start_trace`` /
``Profiler.enable`` in the Trainer) the host phases show up in the
``.trace.json.gz`` timeline aligned with the XLA kernels they launched:
admission next to its prefill fusion, the decode tick over its kernel,
the train data/step/callback phases over the step program. Outside a
profiling window TraceAnnotation is a near-free TraceMe no-op, so spans
stay on permanently.

The ring buffer is exported as Chrome-trace JSON
(:meth:`SpanRecorder.chrome_trace`, ``chrome://tracing`` / Perfetto
loadable) by ``tools/obs_dump.py`` or ``GET /trace`` on the exposition
server — the always-on, no-profiler view of where host time went.

Span taxonomy (docs/OBSERVABILITY.md): dotted snake_case names,
``<subsystem>.<phase>`` — ``serving.tick``, ``serving.admit``,
``serving.prefill``, ``serving.decode``, ``serving.rollback``,
``serving.recover``, ``train.data``, ``train.step``, ``train.callback``.
Nesting is tracked per thread; attrs ride into the Chrome trace as
``args``.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import os
import threading
import time
from typing import Dict, List, Optional

from fleetx_tpu.obs._util import env_int, json_safe as _json_safe

__all__ = ["Span", "SpanRecorder", "get_recorder", "span"]


@dataclasses.dataclass
class Span:
    """One completed host span (times from ``time.perf_counter``)."""

    name: str
    start_s: float
    end_s: float
    thread_id: int
    depth: int
    attrs: Dict

    @property
    def duration_s(self) -> float:
        """Wall-clock length of the span."""
        return self.end_s - self.start_s


class SpanRecorder:
    """Bounded ring buffer of completed spans + Chrome-trace export.

    Capacity 0 disables recording entirely (the TraceAnnotation bridge
    in :func:`span` still runs — profiler alignment costs nothing)."""

    def __init__(self, capacity: Optional[int] = None):
        cap = (env_int("FLEETX_OBS_SPANS", 4096, minimum=0)
               if capacity is None else capacity)
        self.capacity = max(cap, 0)
        self._lock = threading.Lock()
        self._spans: collections.deque = collections.deque(
            maxlen=self.capacity or 1)
        self._local = threading.local()
        self.dropped = 0  # spans pushed out of the ring (or cap-0 culled)

    def record(self, s: Span) -> None:
        """Append one completed span (oldest evicted at capacity)."""
        if self.capacity == 0:
            self.dropped += 1
            return
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(s)

    def spans(self) -> List[Span]:
        """Current ring contents, oldest first."""
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        """Empty the ring (tests / between benchmark passes)."""
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    # ---------------------------------------------------- nesting helpers
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    # -------------------------------------------------------------- export
    def chrome_trace(self) -> Dict:
        """Chrome-trace JSON dict (``traceEvents`` of complete ``X``
        events, microsecond timestamps) — load in chrome://tracing or
        Perfetto; ``tools/obs_dump.py`` writes it to disk."""
        pid = os.getpid()
        events = [{
            "ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": "fleetx_obs host spans"},
        }]
        for s in self.spans():
            events.append({
                "ph": "X",
                "pid": pid,
                "tid": s.thread_id,
                "name": s.name,
                "ts": s.start_s * 1e6,
                "dur": max(s.duration_s, 0.0) * 1e6,
                "args": {k: _json_safe(v) for k, v in s.attrs.items()},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}


_RECORDER = SpanRecorder()
_XPROF = os.environ.get("FLEETX_OBS_XPROF", "1") == "1"


def get_recorder() -> SpanRecorder:
    """The process-global span recorder."""
    return _RECORDER


def _trace_annotation(name: str):
    """The profiler bridge: a ``jax.profiler.TraceAnnotation`` context
    (None when jax is unavailable or ``FLEETX_OBS_XPROF=0``)."""
    if not _XPROF:
        return None
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — tracing must never break the host
        return None


@contextlib.contextmanager
def span(name: str, recorder: Optional[SpanRecorder] = None, **attrs):
    """Record one nested host span named ``name`` (module docstring);
    ``attrs`` become Chrome-trace args. Re-entrant and thread-safe;
    exceptions propagate (the span still closes and records)."""
    rec = recorder or _RECORDER
    stack = rec._stack()
    ann = _trace_annotation(name)
    if ann is not None:
        ann.__enter__()
    start = time.perf_counter()
    stack.append(name)
    try:
        yield
    finally:
        stack.pop()
        end = time.perf_counter()
        if ann is not None:
            ann.__exit__(None, None, None)
        rec.record(Span(
            name=name, start_s=start, end_s=end,
            thread_id=threading.get_ident(), depth=len(stack), attrs=attrs,
        ))
