"""Shared stdlib HTTP-server plumbing (one definition, two servers).

The observability exposition server (``obs/http.py``) and the serving
front door / replica RPC servers (``serving/api/``) are all the same
shape: a ``ThreadingHTTPServer`` on a daemon thread, bound to an
ephemeral-capable ``(host, port)``, with JSON-bodied handlers that
silence the per-request stderr log. This module is that shape, factored
once:

- :class:`JsonHandler` — ``BaseHTTPRequestHandler`` with the ``_send``/
  ``_send_json`` helpers (Content-Length always set, so clients never
  wait on a dangling socket) and the silent ``log_message``.
- :class:`HttpDaemon` — owns a ``ThreadingHTTPServer`` + daemon serving
  thread with idempotent ``start()``/``stop()`` and ``port``/``url``
  properties that resolve port-0 ephemeral binds (the test idiom).

Subclasses add routes by overriding ``do_GET``/``do_POST``; servers add
state by passing attributes through :meth:`HttpDaemon.__init__`'s
``context`` dict (exposed on the HTTP server object, reachable from a
handler as ``self.server.context``) — handler classes stay stateless
per the ``http.server`` contract.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

__all__ = ["HttpDaemon", "JsonHandler"]


class JsonHandler(BaseHTTPRequestHandler):
    """Request-handler base: byte/JSON senders + silenced access log."""

    server_version = "fleetx/1"

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        """One complete response: status, Content-Type/Length, body."""
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, payload) -> None:
        """JSON-encode ``payload`` and send it with ``code``."""
        self._send(code, json.dumps(payload).encode(),
                   "application/json; charset=utf-8")

    def _read_body(self) -> bytes:
        """The request body per its Content-Length (b"" when absent)."""
        try:
            length = int(self.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            length = 0
        return self.rfile.read(length) if length > 0 else b""

    def _read_json(self):
        """Parse the request body as JSON ({} for an empty body);
        malformed JSON raises ``ValueError`` for the caller's 400."""
        body = self._read_body()
        if not body:
            return {}
        try:
            return json.loads(body)
        except json.JSONDecodeError as e:
            raise ValueError(f"request body is not valid JSON: {e}")

    def log_message(self, format, *args):  # noqa: A002 — http.server API
        """Silence per-request stderr lines (scrapes/streams every few
        seconds would otherwise flood workload logs)."""


class HttpDaemon:
    """A ``ThreadingHTTPServer`` on a daemon thread: started once,
    stoppable, ephemeral-port friendly. ``context`` entries become
    attributes on the underlying server's ``context`` dict so handlers
    reach shared state via ``self.server.context[...]``."""

    def __init__(self, handler_cls, port: int = 0, host: str = "127.0.0.1",
                 context: Optional[Dict] = None, thread_name: str =
                 "fleetx-http"):
        self._server = ThreadingHTTPServer((host, port), handler_cls)
        self._server.daemon_threads = True
        self._server.context = dict(context or {})
        self._thread: Optional[threading.Thread] = None
        self._thread_name = thread_name
        self.host = host

    @property
    def server(self) -> ThreadingHTTPServer:
        """The underlying stdlib server (handlers see it as
        ``self.server``)."""
        return self._server

    @property
    def port(self) -> int:
        """Actual bound port (resolves port-0 ephemeral binds)."""
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        """Base URL of the (running or startable) server."""
        return f"http://{self.host}:{self.port}"

    def start(self) -> "HttpDaemon":
        """Serve on a daemon thread; returns self. Idempotent."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name=self._thread_name, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Shut the listener down and join the serving thread."""
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
