"""Unified observability layer: metrics registry, span tracing,
structured events, HTTP exposition.

The substrate every subsystem reports through (docs/OBSERVABILITY.md):

- :mod:`fleetx_tpu.obs.registry` — process-wide Counter/Gauge/Histogram
  families with labels and bounded percentile reservoirs; Prometheus
  text + JSON snapshot expositions.
- :mod:`fleetx_tpu.obs.tracing` — nested host spans in a ring buffer,
  Chrome-trace export, and a ``jax.profiler.TraceAnnotation`` bridge so
  host phases line up with XLA kernels inside profiler traces.
- :mod:`fleetx_tpu.obs.events` — bounded log of typed operational
  events (sentry skips, quarantines, recoveries, shutdowns), asserted
  on by the chaos suite.
- :mod:`fleetx_tpu.obs.http` — stdlib daemon-thread server: ``GET
  /metrics`` ``/snapshot`` ``/trace`` ``/healthz`` (drain-aware),
  enabled by ``FLEETX_OBS_PORT``.

Everything here is host-side and read-only with respect to the data
path: the serving byte-parity suites run with instrumentation enabled.

    from fleetx_tpu.obs import emit, get_registry, span

    ticks = get_registry().counter(TICKS_METRIC)  # a "fleetx_*" literal —
    with span("serving.tick"):                    # snake_case, fleetx_
        ticks.inc()                               # prefix, and a row in
    emit("engine_recovery", number=1)             # docs/OBSERVABILITY.md
                                                  # (lint-enforced)
"""

from fleetx_tpu.obs.events import Event, EventLog, emit, get_event_log
from fleetx_tpu.obs.http import (
    ObsServer,
    get_server,
    health_report,
    health_status,
    healthz_payload,
    maybe_start_from_env,
    register_health,
    unregister_health,
)
from fleetx_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from fleetx_tpu.obs.tracing import Span, SpanRecorder, get_recorder, span

__all__ = [
    "Counter",
    "Event",
    "EventLog",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "ObsServer",
    "Span",
    "SpanRecorder",
    "emit",
    "get_event_log",
    "get_recorder",
    "get_registry",
    "get_server",
    "health_report",
    "health_status",
    "healthz_payload",
    "maybe_start_from_env",
    "register_health",
    "span",
    "unregister_health",
]
