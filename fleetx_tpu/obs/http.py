"""Stdlib-only HTTP exposition server for the observability layer.

A daemon-thread ``ThreadingHTTPServer`` (no third-party deps) serving
the endpoint contract docs/OBSERVABILITY.md pins down:

- ``GET /metrics``  — Prometheus text exposition of the registry.
- ``GET /snapshot`` — JSON: registry snapshot + event log window +
  health status + span-ring stats (full spans via ``/trace``).
- ``GET /trace``    — Chrome-trace JSON of the host span ring buffer
  (load in chrome://tracing / Perfetto).
- ``GET /healthz``  — 200 while every registered health probe passes,
  503 otherwise, with a small JSON body carrying the rotate-out REASON,
  not just the code: ``state`` (``ok`` / ``draining`` / ``dead``, the
  worst across probes), ``role`` (the serving phase a disaggregated
  router keys placement on), ``queue_depth``, ``queue_tokens`` and
  ``active`` (summed over
  probes that report them), plus per-probe booleans, the failing names,
  and each probe's full report under ``detail``. Probes may return a
  plain bool (healthy yes/no) or a dict with a ``state`` key — the
  serving engine returns its drain-aware ``ServingEngine.health()``
  dict, so ``request_shutdown()`` (SIGTERM) flips a replica to 503
  ``state: "draining"`` *while it drains* and ``RecoveryExhausted`` to
  ``state: "dead"`` — exactly the rotate-me-out signal the
  multi-replica serving router load-balances on (docs/SERVING.md
  "Multi-replica router").

Enable by setting ``FLEETX_OBS_PORT`` (``maybe_start_from_env`` is
called by the Trainer and ServingEngine constructors, so any training
or serving process becomes scrapeable with one env var; port 0 binds an
ephemeral port — useful in tests). Binds ``FLEETX_OBS_HOST`` (default
127.0.0.1: metrics can leak prompts/config — exposing beyond localhost
is an explicit operator choice). All handlers are read-only: nothing an
external scraper does can perturb the data path.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Optional, Tuple

from fleetx_tpu.obs.events import get_event_log
from fleetx_tpu.obs.httpd import HttpDaemon, JsonHandler
from fleetx_tpu.obs.registry import get_registry
from fleetx_tpu.obs.tracing import get_recorder

__all__ = [
    "ObsServer",
    "get_server",
    "health_report",
    "health_status",
    "healthz_payload",
    "maybe_start_from_env",
    "register_health",
    "snapshot_payload",
    "unregister_health",
]

_health_lock = threading.Lock()
_health_probes: Dict[str, Callable[[], bool]] = {}


def register_health(name: str, probe: Callable[[], object]) -> None:
    """Register a named liveness probe for ``/healthz``. ``probe()``
    returns either a bool (True = healthy) or a report dict with a
    ``state`` key (``"ok"`` = healthy; ``"draining"``/``"dead"`` are the
    standard unhealthy states, extra keys like ``queue_depth``/``active``
    ride into the healthz body); a raising probe counts as failing. Re-
    registering a name replaces it (callers pair with
    ``weakref.finalize`` to unregister at owner teardown)."""
    with _health_lock:
        _health_probes[name] = probe


def unregister_health(name: str) -> None:
    """Remove a probe (no-op when absent)."""
    with _health_lock:
        _health_probes.pop(name, None)


def health_report() -> Tuple[bool, Dict[str, bool], Dict[str, Dict]]:
    """(all healthy, {probe: healthy}, {probe: report dict}) over the
    registered probes. Bool-returning probes get a synthesized report
    (``state`` ``"ok"``/``"dead"`` — a bare bool carries no drain
    nuance); dict-returning probes are healthy iff ``state == "ok"`` and
    their report passes through verbatim. A raising probe is unhealthy
    with the error in its report. No probes registered = healthy (a bare
    process serves 200)."""
    with _health_lock:
        probes = dict(_health_probes)
    results, details = {}, {}
    for name, probe in probes.items():
        try:
            out = probe()
        except Exception as e:  # noqa: BLE001 — a broken probe is unhealthy
            results[name] = False
            details[name] = {"state": "dead",
                             "error": f"{type(e).__name__}: {e}"}
            continue
        if isinstance(out, dict):
            healthy = out.get("state") == "ok"
            results[name] = healthy
            if out.get("state") not in ("ok", "draining", "dead"):
                # normalize reports without a recognized state so the
                # body's aggregate can never contradict the status code
                # (an unhealthy stateless report must aggregate as dead,
                # not default to ok)
                out = {**out, "state": "ok" if healthy else "dead"}
            details[name] = out
        else:
            results[name] = bool(out)
            details[name] = {"state": "ok" if out else "dead"}
    return all(results.values()), results, details


def health_status() -> Tuple[bool, Dict[str, bool]]:
    """(all healthy, {probe name: healthy}) — the boolean view of
    :func:`health_report` (kept for callers that only gate on 200/503)."""
    ok, results, _ = health_report()
    return ok, results


def healthz_payload() -> Tuple[bool, Dict]:
    """(healthy, the ``/healthz`` JSON body). The body leads with the
    aggregate rotate-out reason — ``state`` is the WORST across probes
    (``dead`` > ``draining`` > ``ok``) — and sums ``queue_depth``/
    ``active`` over the probes that report them, so a single-engine
    replica's body reads directly as that engine's health dict."""
    ok, results, details = health_report()
    states = [d.get("state", "ok") for d in details.values()]
    state = ("dead" if "dead" in states
             else "draining" if "draining" in states else "ok")
    # phase role (docs/SERVING.md "Disaggregated prefill/decode"): a
    # single-engine replica's probe carries it; a phase-aware router
    # scraping this body keys prefill placement on it + queue_tokens
    roles = {d["role"] for d in details.values() if "role" in d}
    role = roles.pop() if len(roles) == 1 else "both"

    def total(key):
        # probe reports are caller-supplied: a malformed load field must
        # degrade to 0, never crash the handler (the contract is that a
        # broken probe reads as unhealthy, not as a dead endpoint)
        n = 0
        for d in details.values():
            try:
                n += int(d.get(key, 0))
            except (TypeError, ValueError):
                pass
        return n

    body = {
        "status": "ok" if ok else "unhealthy",
        "state": state,
        "role": role,
        "queue_depth": total("queue_depth"),
        "queue_tokens": total("queue_tokens"),
        "active": total("active"),
        "probes": results,
        "failing": sorted(n for n, v in results.items() if not v),
        "detail": details,
    }
    return ok, body


def snapshot_payload() -> Dict:
    """THE ``/snapshot`` payload (one definition — the HTTP handler and
    ``tools/obs_dump.py``'s in-process dump both serve exactly this, so
    the two surfaces cannot drift)."""
    ok, body = healthz_payload()
    rec = get_recorder()
    return {
        "metrics": get_registry().snapshot(),
        "events": get_event_log().snapshot(),
        "health": {"ok": ok, "state": body["state"],
                   "probes": body["probes"], "detail": body["detail"]},
        "spans": {"recorded": len(rec.spans()),
                  "dropped": rec.dropped,
                  "capacity": rec.capacity},
    }


class _Handler(JsonHandler):
    """Request handler over the module-global registry/events/spans
    (``_send``/``_send_json``/silent logging come from the shared
    :class:`~fleetx_tpu.obs.httpd.JsonHandler` base)."""

    server_version = "fleetx-obs/1"

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        """Route the four read-only endpoints (404 otherwise)."""
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            self._send(200, get_registry().prometheus_text().encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            ok, body = healthz_payload()
            self._send_json(200 if ok else 503, body)
        elif path == "/snapshot":
            self._send_json(200, snapshot_payload())
        elif path == "/trace":
            self._send_json(200, get_recorder().chrome_trace())
        else:
            self._send_json(404, {"error": f"unknown path {self.path!r}",
                                  "endpoints": ["/metrics", "/snapshot",
                                                "/trace", "/healthz"]})


class ObsServer(HttpDaemon):
    """The exposition server: daemon thread, started once, stoppable
    (the shared :class:`~fleetx_tpu.obs.httpd.HttpDaemon` plumbing under
    the obs routes)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        super().__init__(_Handler, port=port, host=host,
                         thread_name="fleetx-obs-http")


_server_lock = threading.Lock()
_server: Optional[ObsServer] = None
_server_failed = False


def get_server() -> Optional[ObsServer]:
    """The running env-gated server, if any."""
    return _server


def maybe_start_from_env() -> Optional[ObsServer]:
    """Start the process-global server when ``FLEETX_OBS_PORT`` is set
    (unset/empty = off; ``0`` = ephemeral port). Idempotent and cheap —
    the Trainer and ServingEngine constructors call it — and a bind
    failure (port taken by a sibling replica) logs and disables rather
    than killing the workload."""
    global _server, _server_failed
    raw = os.environ.get("FLEETX_OBS_PORT", "")
    if raw == "":
        return None
    with _server_lock:
        if _server is not None:
            return _server
        if _server_failed:
            return None  # already failed + logged once; don't retry/re-log
        try:
            port = int(raw)
            _server = ObsServer(
                port=port, host=os.environ.get("FLEETX_OBS_HOST",
                                               "127.0.0.1")).start()
        except Exception as e:  # noqa: BLE001 — obs must never kill the job
            from fleetx_tpu.utils.log import logger

            _server_failed = True
            logger.error("obs: FLEETX_OBS_PORT=%s server failed to start "
                         "(%s: %s); exposition disabled for this process",
                         raw, type(e).__name__, e)
            return None
        from fleetx_tpu.utils.log import logger

        logger.info("obs: exposition server listening on %s "
                    "(/metrics /snapshot /trace /healthz)", _server.url)
        return _server
