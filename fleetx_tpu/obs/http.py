"""Stdlib-only HTTP exposition server for the observability layer.

A daemon-thread ``ThreadingHTTPServer`` (no third-party deps) serving
the endpoint contract docs/OBSERVABILITY.md pins down:

- ``GET /metrics``  — Prometheus text exposition of the registry.
- ``GET /snapshot`` — JSON: registry snapshot + event log window +
  health status + span-ring stats (full spans via ``/trace``).
- ``GET /trace``    — Chrome-trace JSON of the host span ring buffer
  (load in chrome://tracing / Perfetto).
- ``GET /healthz``  — 200 ``{"status": "ok"}`` while every registered
  health probe passes, 503 ``{"status": "unhealthy", "failing": [...]}``
  otherwise. The serving engine registers a drain-aware probe, so
  ``request_shutdown()`` (SIGTERM) flips a replica to 503 *while it
  drains* — exactly the rotate-me-out signal the multi-replica router
  (ROADMAP item 3) load-balances on.

Enable by setting ``FLEETX_OBS_PORT`` (``maybe_start_from_env`` is
called by the Trainer and ServingEngine constructors, so any training
or serving process becomes scrapeable with one env var; port 0 binds an
ephemeral port — useful in tests). Binds ``FLEETX_OBS_HOST`` (default
127.0.0.1: metrics can leak prompts/config — exposing beyond localhost
is an explicit operator choice). All handlers are read-only: nothing an
external scraper does can perturb the data path.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from fleetx_tpu.obs.events import get_event_log
from fleetx_tpu.obs.registry import get_registry
from fleetx_tpu.obs.tracing import get_recorder

__all__ = [
    "ObsServer",
    "get_server",
    "health_status",
    "maybe_start_from_env",
    "register_health",
    "snapshot_payload",
    "unregister_health",
]

_health_lock = threading.Lock()
_health_probes: Dict[str, Callable[[], bool]] = {}


def register_health(name: str, probe: Callable[[], bool]) -> None:
    """Register a named liveness probe for ``/healthz``. ``probe()``
    returns True when healthy; a raising probe counts as failing. Re-
    registering a name replaces it (callers pair with
    ``weakref.finalize`` to unregister at owner teardown)."""
    with _health_lock:
        _health_probes[name] = probe


def unregister_health(name: str) -> None:
    """Remove a probe (no-op when absent)."""
    with _health_lock:
        _health_probes.pop(name, None)


def health_status() -> Tuple[bool, Dict[str, bool]]:
    """(all healthy, {probe name: healthy}) over the registered probes.
    No probes registered = healthy (a bare process serves 200)."""
    with _health_lock:
        probes = dict(_health_probes)
    results = {}
    for name, probe in probes.items():
        try:
            results[name] = bool(probe())
        except Exception:  # noqa: BLE001 — a broken probe is "unhealthy"
            results[name] = False
    return all(results.values()), results


def snapshot_payload() -> Dict:
    """THE ``/snapshot`` payload (one definition — the HTTP handler and
    ``tools/obs_dump.py``'s in-process dump both serve exactly this, so
    the two surfaces cannot drift)."""
    ok, results = health_status()
    rec = get_recorder()
    return {
        "metrics": get_registry().snapshot(),
        "events": get_event_log().snapshot(),
        "health": {"ok": ok, "probes": results},
        "spans": {"recorded": len(rec.spans()),
                  "dropped": rec.dropped,
                  "capacity": rec.capacity},
    }


class _Handler(BaseHTTPRequestHandler):
    """Request handler over the module-global registry/events/spans."""

    server_version = "fleetx-obs/1"

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, payload) -> None:
        self._send(code, json.dumps(payload).encode(),
                   "application/json; charset=utf-8")

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        """Route the four read-only endpoints (404 otherwise)."""
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            self._send(200, get_registry().prometheus_text().encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            ok, results = health_status()
            self._send_json(
                200 if ok else 503,
                {"status": "ok" if ok else "unhealthy",
                 "probes": results,
                 "failing": sorted(n for n, v in results.items() if not v)})
        elif path == "/snapshot":
            self._send_json(200, snapshot_payload())
        elif path == "/trace":
            self._send_json(200, get_recorder().chrome_trace())
        else:
            self._send_json(404, {"error": f"unknown path {self.path!r}",
                                  "endpoints": ["/metrics", "/snapshot",
                                                "/trace", "/healthz"]})

    def log_message(self, format, *args):  # noqa: A002 — http.server API
        """Silence per-request stderr lines (scrapes every few seconds
        would otherwise flood training logs)."""


class ObsServer:
    """The exposition server: daemon thread, started once, stoppable."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None
        self.host = host

    @property
    def port(self) -> int:
        """Actual bound port (resolves port-0 ephemeral binds)."""
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        """Base URL of the running server."""
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ObsServer":
        """Serve on a daemon thread; returns self. Idempotent."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="fleetx-obs-http", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Shut the listener down and join the serving thread."""
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


_server_lock = threading.Lock()
_server: Optional[ObsServer] = None
_server_failed = False


def get_server() -> Optional[ObsServer]:
    """The running env-gated server, if any."""
    return _server


def maybe_start_from_env() -> Optional[ObsServer]:
    """Start the process-global server when ``FLEETX_OBS_PORT`` is set
    (unset/empty = off; ``0`` = ephemeral port). Idempotent and cheap —
    the Trainer and ServingEngine constructors call it — and a bind
    failure (port taken by a sibling replica) logs and disables rather
    than killing the workload."""
    global _server, _server_failed
    raw = os.environ.get("FLEETX_OBS_PORT", "")
    if raw == "":
        return None
    with _server_lock:
        if _server is not None:
            return _server
        if _server_failed:
            return None  # already failed + logged once; don't retry/re-log
        try:
            port = int(raw)
            _server = ObsServer(
                port=port, host=os.environ.get("FLEETX_OBS_HOST",
                                               "127.0.0.1")).start()
        except Exception as e:  # noqa: BLE001 — obs must never kill the job
            from fleetx_tpu.utils.log import logger

            _server_failed = True
            logger.error("obs: FLEETX_OBS_PORT=%s server failed to start "
                         "(%s: %s); exposition disabled for this process",
                         raw, type(e).__name__, e)
            return None
        from fleetx_tpu.utils.log import logger

        logger.info("obs: exposition server listening on %s "
                    "(/metrics /snapshot /trace /healthz)", _server.url)
        return _server
