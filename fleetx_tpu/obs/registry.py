"""Process-wide metrics registry: Counter / Gauge / Histogram families.

The one place every subsystem publishes numbers through (docs/
OBSERVABILITY.md): ``ServingMetrics`` rides it for queue/TTFT/tick
stats, the Trainer for step-time/tokens-per-s/MFU, the event log for
per-kind event counts. Design constraints, in order:

- **read-only on the data path**: instruments are plain host-side
  counters guarded by one registry lock — no device work, no jax import,
  nothing an instrumented tick could perturb (the serving byte-parity
  suites run with instrumentation on).
- **bounded memory forever**: histograms keep ``count/sum/min/max``
  exactly and a ``deque(maxlen=FLEETX_OBS_RESERVOIR)`` reservoir for
  percentiles, so a replica that retires ten million requests holds the
  same few KiB a fresh one does (the fix for the unbounded
  ``ttft_s``/``latency_s`` lists PR 8 left behind).
- **two read surfaces**: :meth:`MetricsRegistry.prometheus_text` (the
  ``GET /metrics`` wire format — histograms expose as summaries with
  reservoir quantiles) and :meth:`MetricsRegistry.snapshot` (JSON-safe
  dict, embedded in bench records and ``GET /snapshot``).

Metric names must be snake_case; names registered under ``fleetx_tpu/``
must additionally carry the ``fleetx_`` prefix and a row in the
docs/OBSERVABILITY.md metric table — ``tests/test_codestyle.py``'s
metric lint enforces both, so the exposition surface cannot drift
undocumented. One process-global default registry (:func:`get_registry`)
serves the common case; tests build private ones.
"""

from __future__ import annotations

import collections
import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Tuple

from fleetx_tpu.obs._util import env_int

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
]

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def _reservoir_cap() -> int:
    """Default histogram reservoir size (``FLEETX_OBS_RESERVOIR``)."""
    return env_int("FLEETX_OBS_RESERVOIR", 4096, minimum=1)


class Counter:
    """Monotonic counter child (one label combination of a family)."""

    kind = "counter"

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        """Add ``n`` (must be >= 0) to the counter."""
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        """Current count."""
        return self._value


class Gauge:
    """Set-to-current-value gauge child."""

    kind = "gauge"

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0

    def set(self, v: float) -> None:
        """Set the gauge to ``v``."""
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        """Add ``n`` (may be negative) to the gauge."""
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        """Current value."""
        return self._value


class Histogram:
    """Distribution child: exact count/sum/min/max + bounded reservoir.

    ``count``/``sum`` (and hence ``mean``) are exact over every
    observation ever made; percentiles come from the newest
    ``reservoir_cap`` samples — the recent-behavior window percentiles
    are meant to describe on a long-lived replica."""

    kind = "histogram"

    def __init__(self, lock: threading.RLock, reservoir_cap: int):
        self._lock = lock
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.reservoir: collections.deque = collections.deque(
            maxlen=reservoir_cap)

    def observe(self, v: float) -> None:
        """Record one sample."""
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.reservoir.append(v)

    @property
    def mean(self) -> Optional[float]:
        """Exact mean over all observations (None when empty)."""
        return self.sum / self.count if self.count else None

    def quantiles(self, qs) -> List[Optional[float]]:
        """Reservoir percentiles for every ``q`` in [0, 100] of ``qs``
        from ONE snapshot + sort (the lock is held only for the O(n)
        copy — a scrape computing p50/p95/p99 never blocks the data
        path's ``observe()`` calls behind a sort). Linear interpolation
        between closest ranks, matching ``numpy.percentile``'s
        default; all-None when empty."""
        with self._lock:
            data = list(self.reservoir)
        if not data:
            return [None] * len(qs)
        data.sort()
        out = []
        for q in qs:
            if len(data) == 1:
                out.append(data[0])
                continue
            rank = (len(data) - 1) * (q / 100.0)
            lo = int(math.floor(rank))
            hi = min(lo + 1, len(data) - 1)
            frac = rank - lo
            out.append(data[lo] * (1.0 - frac) + data[hi] * frac)
        return out

    def percentile(self, q: float) -> Optional[float]:
        """Single reservoir percentile (see :meth:`quantiles`)."""
        return self.quantiles((q,))[0]


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One named metric + its per-label-combination children.

    With no labelnames the family has exactly one anonymous child and
    the instrument methods (``inc``/``set``/``observe``...) delegate to
    it, so unlabeled metrics read like plain instruments."""

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 kind: str, labelnames: Tuple[str, ...],
                 reservoir_cap: Optional[int]):
        self._registry = registry
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = labelnames
        self._reservoir_cap = reservoir_cap
        self._children: Dict[Tuple[str, ...], object] = {}

    def _make_child(self):
        if self.kind == "histogram":
            cap = self._reservoir_cap or _reservoir_cap()
            return Histogram(self._registry._lock, cap)
        return _KINDS[self.kind](self._registry._lock)

    def labels(self, **labelvalues: str):
        """Child for one label combination (created on first use)."""
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(labelvalues)}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._registry._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def remove(self, **labelvalues: str) -> None:
        """Drop one label combination's child (no-op when absent) —
        owners of per-instance series (e.g. ``ServingMetrics``'
        ``engine="<n>"`` children) remove them at teardown so a process
        that cycles engines doesn't accumulate dead series forever."""
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._registry._lock:
            self._children.pop(key, None)

    def _solo(self):
        if self.labelnames:
            raise ValueError(
                f"metric {self.name!r} is labeled {self.labelnames}; "
                "call .labels(...) first")
        return self.labels()

    # unlabeled-family conveniences — each validates the family is
    # actually unlabeled and the kind supports the verb
    def inc(self, n: float = 1.0) -> None:
        """Unlabeled counter/gauge increment."""
        self._solo().inc(n)

    def set(self, v: float) -> None:
        """Unlabeled gauge set."""
        self._solo().set(v)

    def observe(self, v: float) -> None:
        """Unlabeled histogram observation."""
        self._solo().observe(v)

    @property
    def value(self) -> float:
        """Unlabeled counter/gauge value."""
        return self._solo().value

    def series(self) -> List[Tuple[Dict[str, str], object]]:
        """(labels dict, child) pairs, stable insertion order."""
        with self._registry._lock:
            items = list(self._children.items())
        return [(dict(zip(self.labelnames, key)), child)
                for key, child in items]


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: Dict[str, str], extra: Iterable[Tuple[str, str]] = ()
                ) -> str:
    pairs = [f'{k}="{_escape_label(str(v))}"' for k, v in labels.items()]
    pairs += [f'{k}="{_escape_label(str(v))}"' for k, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt_value(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if not math.isfinite(v):  # int(inf) raises; Prometheus spells it +Inf
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class MetricsRegistry:
    """Named metric families + the two exposition surfaces."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}

    def _register(self, name: str, help: str, kind: str,
                  labelnames: Tuple[str, ...],
                  reservoir_cap: Optional[int] = None) -> _Family:
        if not _NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} must be snake_case "
                "([a-z][a-z0-9_]*)")
        for ln in labelnames:
            if not _NAME_RE.match(ln):
                raise ValueError(f"label name {ln!r} must be snake_case")
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind} "
                        f"with labels {fam.labelnames}; cannot re-register "
                        f"as {kind} with labels {tuple(labelnames)}")
                return fam
            fam = _Family(self, name, help, kind, tuple(labelnames),
                          reservoir_cap)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Tuple[str, ...] = ()) -> _Family:
        """Register (or fetch) a counter family."""
        return self._register(name, help, "counter", tuple(labelnames))

    def gauge(self, name: str, help: str = "",
              labelnames: Tuple[str, ...] = ()) -> _Family:
        """Register (or fetch) a gauge family."""
        return self._register(name, help, "gauge", tuple(labelnames))

    def histogram(self, name: str, help: str = "",
                  labelnames: Tuple[str, ...] = (),
                  reservoir_cap: Optional[int] = None) -> _Family:
        """Register (or fetch) a histogram family (bounded reservoir;
        cap defaults to ``FLEETX_OBS_RESERVOIR``)."""
        return self._register(name, help, "histogram", tuple(labelnames),
                              reservoir_cap)

    def families(self) -> List[_Family]:
        """All registered families, registration order."""
        with self._lock:
            return list(self._families.values())

    def clear(self) -> None:
        """Drop every family (tests only — live instruments held by
        producers keep working but stop being exposed)."""
        with self._lock:
            self._families.clear()

    # -------------------------------------------------------- expositions
    def prometheus_text(self) -> str:
        """Prometheus text exposition (format 0.0.4). Histograms expose
        as summaries: reservoir quantiles + exact ``_sum``/``_count``."""
        out = []
        for fam in self.families():
            series = fam.series()
            if not series:
                continue
            if fam.help:
                out.append(f"# HELP {fam.name} {fam.help}")
            ptype = "summary" if fam.kind == "histogram" else fam.kind
            out.append(f"# TYPE {fam.name} {ptype}")
            for labels, child in series:
                if fam.kind == "histogram":
                    vals = child.quantiles((50, 95, 99))  # one sort
                    for q, v in zip((0.5, 0.95, 0.99), vals):
                        if v is None:
                            continue
                        out.append(
                            f"{fam.name}"
                            f"{_fmt_labels(labels, [('quantile', q)])} "
                            f"{_fmt_value(v)}")
                    out.append(f"{fam.name}_sum{_fmt_labels(labels)} "
                               f"{_fmt_value(child.sum)}")
                    out.append(f"{fam.name}_count{_fmt_labels(labels)} "
                               f"{_fmt_value(child.count)}")
                else:
                    out.append(f"{fam.name}{_fmt_labels(labels)} "
                               f"{_fmt_value(child.value)}")
        return "\n".join(out) + "\n" if out else ""

    def snapshot(self) -> Dict:
        """JSON-safe dict view: ``{name: {type, help, series: [...]}}``.
        Histogram series carry exact count/sum/mean/min/max plus
        reservoir p50/p95/p99."""
        out = {}
        for fam in self.families():
            series = []
            for labels, child in fam.series():
                entry: Dict = {"labels": labels}
                if fam.kind == "histogram":
                    p50, p95, p99 = child.quantiles((50, 95, 99))
                    entry.update(
                        count=child.count, sum=child.sum, mean=child.mean,
                        min=child.min, max=child.max,
                        p50=p50, p95=p95, p99=p99,
                    )
                else:
                    entry["value"] = child.value
                series.append(entry)
            if series:
                out[fam.name] = {"type": fam.kind, "help": fam.help,
                                 "series": series}
        return out


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global default registry."""
    return _REGISTRY
