"""Shared helpers for the obs modules (one definition, three users)."""

from __future__ import annotations

import os

__all__ = ["env_int", "json_safe"]


def env_int(name: str, default: int, minimum: int = 0) -> int:
    """``FLEETX_OBS_*`` capacity knob: int env var clamped to
    ``minimum``; malformed values fall back to ``default`` (a typo'd
    knob must degrade to defaults, never crash the workload)."""
    try:
        return max(int(os.environ.get(name, default)), minimum)
    except ValueError:
        return default


def json_safe(v):
    """Coerce one attr value to a JSON-serializable primitive
    (everything non-primitive stringifies)."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)
