"""Structured event log: bounded deque of typed operational events.

Where metrics answer "how much/how fast", events answer "what happened":
a sentry skipped step 14, checkpoint step 300 was quarantined, the
serving engine recovered, request 7 was retired as poison. Producers
call :func:`emit` at the site; consumers query the log in snapshots
(``GET /snapshot``), assert on it in chaos tests
(``tools/chaos_check.py`` verifies every injected fault banked its
expected event), and watch per-kind counts through the
``fleetx_events_total{kind=...}`` registry counter.

Known kinds (docs/OBSERVABILITY.md has the full table + attrs):

- training: ``sentry_skip``, ``sentry_abort``, ``save_failure``,
  ``checkpoint_quarantine``
- serving: ``engine_recovery``, ``poison_retired``, ``cache_full``,
  ``tick_fault``, ``tick_timeout``, ``queue_reject``, ``drain_reject``,
  ``request_timeout``, ``request_cancelled``, ``callback_error``,
  ``shutdown``
- router: ``replica_out``, ``replica_back``, ``replica_dead``,
  ``request_migrated``, ``router_stranded``
- chaos: ``fault_injected``

The set is open — any snake_case kind is accepted — but new kinds
belong in the doc table. The log is bounded (``FLEETX_OBS_EVENTS``
events, oldest dropped) so a replica can emit forever; per-kind counts
stay exact in the registry counter even after eviction.
"""

from __future__ import annotations

import collections
import dataclasses
import re
import threading
import time
from typing import Dict, List, Optional

from fleetx_tpu.obs._util import env_int, json_safe as _json_safe
from fleetx_tpu.obs.registry import get_registry

__all__ = ["Event", "EventLog", "emit", "get_event_log"]

_KIND_RE = re.compile(r"^[a-z][a-z0-9_]*$")


@dataclasses.dataclass
class Event:
    """One structured event: kind + unix time + free-form attrs."""

    kind: str
    time_s: float
    attrs: Dict

    def as_dict(self) -> Dict:
        """JSON-safe view (snapshot/exposition shape)."""
        return {"kind": self.kind, "time_s": self.time_s,
                "attrs": {k: _json_safe(v) for k, v in self.attrs.items()}}


def _env_cap() -> int:
    return env_int("FLEETX_OBS_EVENTS", 1024, minimum=1)


class EventLog:
    """Bounded, thread-safe event log + the per-kind registry counter."""

    def __init__(self, capacity: Optional[int] = None, registry=None):
        self._events: collections.deque = collections.deque(
            maxlen=capacity or _env_cap())
        self._lock = threading.Lock()
        self._counter = (registry or get_registry()).counter(
            "fleetx_events_total",
            "Structured events emitted, by kind (fleetx_tpu/obs/events.py)",
            labelnames=("kind",),
        )

    def emit(self, kind: str, **attrs) -> Event:
        """Record one event; returns it. ``kind`` must be snake_case."""
        if not _KIND_RE.match(kind):
            raise ValueError(f"event kind {kind!r} must be snake_case")
        ev = Event(kind=kind, time_s=time.time(), attrs=attrs)
        with self._lock:
            self._events.append(ev)
        self._counter.labels(kind=kind).inc()
        return ev

    def find(self, kind: Optional[str] = None, **attrs) -> List[Event]:
        """Events matching ``kind`` (None = all) whose attrs contain
        every given key/value, oldest first."""
        with self._lock:
            events = list(self._events)
        out = []
        for ev in events:
            if kind is not None and ev.kind != kind:
                continue
            if any(ev.attrs.get(k) != v for k, v in attrs.items()):
                continue
            out.append(ev)
        return out

    def last(self, kind: Optional[str] = None, **attrs) -> Optional[Event]:
        """Most recent matching event (None when none match)."""
        hits = self.find(kind, **attrs)
        return hits[-1] if hits else None

    def counts(self) -> Dict[str, int]:
        """Per-kind counts over the CURRENT window (the registry's
        ``fleetx_events_total`` keeps lifetime counts past eviction)."""
        out: Dict[str, int] = {}
        with self._lock:
            for ev in self._events:
                out[ev.kind] = out.get(ev.kind, 0) + 1
        return out

    def snapshot(self) -> List[Dict]:
        """JSON-safe list of the current window, oldest first."""
        with self._lock:
            return [ev.as_dict() for ev in self._events]

    def clear(self) -> None:
        """Empty the window (tests / chaos scenario isolation); the
        lifetime registry counter is left untouched."""
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


_EVENTS = EventLog()


def get_event_log() -> EventLog:
    """The process-global event log."""
    return _EVENTS


def emit(kind: str, **attrs) -> Event:
    """Emit onto the process-global log (see :class:`EventLog.emit`)."""
    return _EVENTS.emit(kind, **attrs)
