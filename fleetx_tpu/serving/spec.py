"""Speculative-decoding proposers for the serving engine.

Speculative decoding (docs/SERVING.md "Speculative decoding";
Leviathan et al., "Fast Inference from Transformers via Speculative
Decoding") splits each decode tick into DRAFT and VERIFY: a cheap
proposer guesses up to ``k`` next tokens per active request, the engine
writes them into the request's pages and scores all ``k+1`` positions
with ONE batched prefill-shaped call, and acceptance keeps the longest
prefix the target model agrees with — greedy outputs are byte-identical
to the non-speculative engine by construction, sampling outputs are
distribution-preserving via standard speculative rejection.

This module owns the PROPOSER side of that split, behind one small
protocol (:class:`Proposer`) so operators can plug their own:

- :class:`NgramProposer` (the default): host-side prompt-lookup / n-gram
  drafting — match the request's trailing n-gram against its own
  ``prompt + generated`` history and propose the tokens that followed
  the previous occurrence. Zero extra device memory or compute; shines
  exactly on the shared-system-prompt, code-edit, and
  retrieval-grounded workloads this repo's serving stack optimizes for
  (the continuation is literally in the context).
- :class:`DraftModelProposer`: a small GPT drafts ``k`` greedy tokens
  per tick through its OWN decode lanes (a private slot-layout KV cache
  sized ``[slots, cache_len]`` for the draft model's dims — the main
  page pool's page shapes are the target model's, so the draft keeps a
  sibling cache rather than aliasing those pages). It rides the same
  decode seams as the engine: ``decode_step`` with per-row
  ``cache_positions``, bucketed multi-token catch-up prefills, and the
  int8 weight-only dequant-in-jit machinery when handed a quantized
  tree. Draft-lane rollback is the same host-side pointer move the
  engine uses — rejected draft KV beyond the live window is never
  attended, so a mis-predicted tail costs nothing.

A proposer can NEVER affect correctness — verification gates every
token — only the acceptance rate (and therefore the speedup). That is
why the draft cache needs no crash-safety machinery of its own:
``reset()`` simply zeroes the lane pointers and the next ``propose()``
re-prefills lazily from host truth (the engine calls it from
``recover()``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Protocol, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["DraftModelProposer", "NgramProposer", "Proposer",
           "build_proposer"]

# slot -> (prompt + generated history, max draft tokens wanted this tick)
SpecRequests = Dict[int, Tuple[np.ndarray, int]]


class Proposer(Protocol):
    """The draft side of speculative decoding (module docstring).

    The engine drives one proposer per tick: ``propose()`` over the
    active lanes, ``observe()`` after verification tells each lane how
    many tokens were actually emitted (so stateful proposers rewind
    their rejected tails), ``on_retire()`` frees a lane, ``reset()``
    drops all lane state after an engine recovery (the next
    ``propose()`` rebuilds lazily from the histories the engine passes
    — which are host truth, so recovery stays byte-identical).
    Proposals are suggestions only: verification gates every token, so
    a proposer bug can cost acceptance rate, never correctness."""

    name: str

    def bind(self, slots: int, cache_len: int) -> None:
        """Size per-lane state for ``slots`` decode lanes."""
        ...

    def propose(self, requests: SpecRequests, k: int
                ) -> Dict[int, np.ndarray]:
        """Draft up to ``min(k, cap)`` tokens per requested lane; lanes
        may be omitted from the result (no draft this tick)."""
        ...

    def observe(self, slot: int, emitted: int) -> None:
        """Verification emitted ``emitted`` tokens for ``slot``."""
        ...

    def on_retire(self, slot: int) -> None:
        """The request holding ``slot`` retired; free its lane state."""
        ...

    def reset(self) -> None:
        """Drop all lane state (engine recovery rebuilt the device)."""
        ...


class NgramProposer:
    """Prompt-lookup drafting: propose the continuation of the most
    recent earlier occurrence of the request's trailing n-gram inside
    its own ``prompt + generated`` history (longest ``n`` in
    ``[min_n, max_n]`` wins). Pure host state-free string matching —
    zero device memory, zero extra model FLOPs — and exactly the
    drafting mode that wins on repetitive / template / retrieval
    contexts where the continuation already appears verbatim."""

    name = "ngram"

    def __init__(self, max_n: int = 3, min_n: int = 1):
        if not 1 <= min_n <= max_n:
            raise ValueError(
                f"need 1 <= min_n <= max_n, got ({min_n}, {max_n})")
        self.max_n = max_n
        self.min_n = min_n

    def bind(self, slots: int, cache_len: int) -> None:
        """Stateless — nothing to size."""

    def propose(self, requests: SpecRequests, k: int
                ) -> Dict[int, np.ndarray]:
        """Suffix-match each lane's history; omit lanes with no match."""
        out = {}
        for slot, (hist, cap) in requests.items():
            if cap <= 0:
                continue
            d = self._match(np.asarray(hist, np.int64), min(cap, k))
            if d.size:
                out[slot] = d
        return out

    def _match(self, hist: np.ndarray, cap: int) -> np.ndarray:
        """Tokens that followed the most recent earlier occurrence of
        the trailing n-gram (longest n first); empty when none recurs."""
        size = len(hist)
        for n in range(self.max_n, self.min_n - 1, -1):
            if size <= n:
                continue
            pattern = hist[size - n:]
            windows = np.lib.stride_tricks.sliding_window_view(hist, n)
            # candidate starts: every position but the pattern's own
            hits = np.nonzero(
                (windows[:size - n] == pattern).all(axis=1))[0]
            if hits.size:
                start = int(hits[-1]) + n
                return hist[start:start + cap].astype(np.int32)
        return np.empty(0, np.int32)

    def observe(self, slot: int, emitted: int) -> None:
        """Stateless — the next propose() re-reads the history."""

    def on_retire(self, slot: int) -> None:
        """Stateless — nothing held per lane."""

    def reset(self) -> None:
        """Stateless — nothing to drop."""


def _gather_slot(cache, slot):
    """Slice one lane's row out of a slot-layout cache tree (the inverse
    of :func:`~fleetx_tpu.serving.cache_manager.scatter_slot`): K/V
    leaves keep their ``[..., batch, cache_len, heads, head_dim]``
    suffix with the batch axis cut to 1; rank-<4 leaves (the
    ``cache_index`` scalars) pass through untouched."""

    def take(big):
        if big.ndim < 4:
            return big
        starts = (0,) * (big.ndim - 4) + (slot, 0, 0, 0)
        sizes = big.shape[:big.ndim - 4] + (1,) + big.shape[big.ndim - 3:]
        return jax.lax.dynamic_slice(big, starts, sizes)

    return jax.tree.map(take, cache)


class DraftModelProposer:
    """Draft-model speculative decoding: a small GPT predicts ``k``
    greedy tokens per active lane each tick (module docstring).

    Per-lane state is exactly the engine's: a slot-layout decode cache
    ``[slots, cache_len]`` for the DRAFT model's dims, a host
    ``lengths`` mirror (KV valid over ``[0, lengths)``), and the last
    emitted token. The sync protocol is catch-up-then-draft:
    ``propose()`` first prefills any history the draft cache is missing
    (a fresh admission's whole prompt; the single token a
    fully-accepted tick leaves behind; everything after a
    ``reset()``) through bucketed multi-token ``decode_step`` calls at
    the lane's absolute positions, then runs ``k`` batched single-token
    greedy steps — the draft KV for accepted tokens is already in place
    for the next tick, and ``observe()`` rewinds the live length past
    the rejected tail (host pointer move; stale KV beyond the window is
    never attended — the engine's own no-zeroing contract).

    Handed an int8 weight-only tree (``{"_q8", "_scale"}`` leaves, e.g.
    the engine's own params under ``FLEETX_SERVING_SPEC_DRAFT=self``
    with ``FLEETX_SERVING_WEIGHT_DTYPE=int8``), every jitted call
    dequantizes in-jit exactly like the engine's — the draft rides the
    same quantization machinery."""

    name = "draft"

    def __init__(self, model, variables, prefill_bucket: int = 32):
        self._base_model = model
        v = variables
        self.params = (v["params"]
                       if isinstance(v, dict) and "params" in v else v)
        self.prefill_bucket = max(int(prefill_bucket), 1)
        self.model = None  # sized at bind()

    def bind(self, slots: int, cache_len: int) -> None:
        """Clone the draft model onto a private slot-layout decode cache
        (no pages, no kv quantization — the draft cache is small and
        its contents are only ever suggestions)."""
        from fleetx_tpu.models.gpt.generation import init_decode_cache

        self.model = self._base_model.clone(cfg=dataclasses.replace(
            self._base_model.cfg, decode_cache_len=cache_len,
            decode_num_pages=None, decode_page_size=None,
            decode_kv_dtype=None))
        self.slots = slots
        self.cache_len = cache_len
        self.cache = init_decode_cache(self.model, slots)
        self.lengths = np.zeros(slots, np.int64)
        self.last_tok = np.zeros(slots, np.int32)
        self._written: Dict[int, int] = {}  # lane -> draft KV positions
        self._step_jit = jax.jit(self._step_fn)
        self._catchup_jits = {}

    def _dequant(self, params):
        """In-jit dequant seam: ``dequantize_tree_int8`` expands
        ``{"_q8", "_scale"}`` leaves and passes float leaves through
        untouched (a free identity on unquantized trees inside jit),
        so the one call handles both — no separate detection to drift
        from ops/quant's leaf format."""
        from fleetx_tpu.ops.quant import dequantize_tree_int8

        return dequantize_tree_int8(params, dtype=jnp.float32)

    def _step_fn(self, params, cache, last_tok, lengths, active):
        """One batched greedy draft token for every lane (inactive lanes
        ride along pinned to the last cache row, outputs discarded —
        the engine's decode-tick pattern)."""
        params = self._dequant(params)
        max_pos = self.model.cfg.max_position_embeddings
        wpos = jnp.where(active, lengths, self.cache_len - 1)
        posid = jnp.where(active, jnp.minimum(lengths, max_pos - 1), 0)
        from fleetx_tpu.models.gpt.generation import decode_step

        logits, cache = decode_step(
            self.model, params, cache, last_tok[:, None], posid[:, None],
            None, cache_positions=wpos)
        tok = jnp.argmax(logits[:, -1, :].astype(jnp.float32),
                         axis=-1).astype(jnp.int32)
        return cache, tok

    def _make_catchup(self, bucket: int):
        """Jitted lane catch-up: write ``bucket`` history tokens' draft
        KV at absolute positions ``wpos..`` of one lane (gather the row,
        one multi-token cached forward, scatter back). Logits are
        discarded — catch-up is KV ingestion only."""
        from fleetx_tpu.models.gpt.generation import decode_step
        from fleetx_tpu.serving.cache_manager import scatter_slot

        max_pos = self.model.cfg.max_position_embeddings

        def catchup(params, cache, ids, wpos, slot):
            params = self._dequant(params)
            small = _gather_slot(cache, slot)
            pos = jnp.minimum(
                wpos + jnp.arange(bucket, dtype=jnp.int32),
                max_pos - 1)[None, :]
            _, small = decode_step(self.model, params, small, ids[None, :],
                                   pos, None, cache_positions=wpos[None])
            return scatter_slot(cache, small, slot)

        return jax.jit(catchup)

    def _catchup(self, slot: int, hist: np.ndarray) -> None:
        """Prefill ``hist[lengths[slot] : len(hist)-1]`` into the lane
        (the last history token is next tick's feed, like the engine)."""
        lo = int(self.lengths[slot])
        hi = len(hist) - 1
        n = hi - lo
        if n <= 0:
            return
        bucket = -(-n // self.prefill_bucket) * self.prefill_bucket
        bucket = min(max(bucket, n), self.cache_len - lo)
        fn = self._catchup_jits.get(bucket)
        if fn is None:
            fn = self._catchup_jits[bucket] = self._make_catchup(bucket)
        padded = np.zeros(bucket, np.int32)
        padded[:n] = hist[lo:hi]
        self.cache = fn(self.params, self.cache, jnp.asarray(padded),
                        jnp.asarray(lo, jnp.int32),
                        jnp.asarray(slot, jnp.int32))
        self.lengths[slot] = hi

    def propose(self, requests: SpecRequests, k: int
                ) -> Dict[int, np.ndarray]:
        """Catch each lane up to its history, then ``k`` batched greedy
        draft steps; returns per-lane proposals clipped to their caps."""
        out: Dict[int, np.ndarray] = {}
        self._written = {}
        if not requests or k <= 0:
            return out
        for slot in sorted(requests):
            hist, _ = requests[slot]
            if self.lengths[slot] > len(hist) - 1:
                self.lengths[slot] = 0  # reused lane: rebuild from zero
            self._catchup(slot, np.asarray(hist, np.int64))
            self.last_tok[slot] = int(hist[-1])
        active = np.zeros(self.slots, bool)
        for slot, (_, cap) in requests.items():
            if cap > 0:
                active[slot] = True
        if not active.any():
            return out
        cur = jnp.asarray(self.last_tok)
        lens = jnp.asarray(self.lengths.astype(np.int32))
        act = jnp.asarray(active)
        cache = self.cache
        cols = []
        for i in range(k):
            cache, tok = self._step_jit(self.params, cache, cur,
                                        lens + i, act)
            cur = tok
            cols.append(np.asarray(tok))
        self.cache = cache
        for slot, (_, cap) in requests.items():
            if active[slot]:
                self._written[slot] = k
                out[slot] = np.asarray([c[slot] for c in cols[:cap]],
                                       np.int32)
        return out

    def observe(self, slot: int, emitted: int) -> None:
        """Advance the lane past the verified tokens: of the ``k`` draft
        positions propose() wrote (feeding last_tok, d1, ..), the first
        ``emitted`` hold correct-history KV (accepted drafts ARE the
        emitted tokens); the rest is the rejected tail the pointer
        rewind abandons. A fully-accepted tick leaves the lane one
        token short — the next propose()'s catch-up writes it."""
        self.lengths[slot] += min(emitted, self._written.pop(slot, 0))

    def on_retire(self, slot: int) -> None:
        """Free the lane; the next tenant's catch-up overwrites from 0
        (stale rows beyond the live window are never attended)."""
        self.lengths[slot] = 0
        self._written.pop(slot, None)

    def reset(self) -> None:
        """Engine recovery: drop every lane pointer; the next propose()
        re-prefills each lane from the (host-truth) history it is
        handed — deterministic, so post-recovery drafts are the same
        drafts."""
        self.lengths[:] = 0
        self._written = {}


def build_proposer(kind: str, model, variables,
                   prefill_bucket: int = 32) -> "Proposer":
    """Resolve ``FLEETX_SERVING_SPEC_DRAFT`` to a proposer: unset/``0``/
    ``ngram`` = prompt-lookup drafting; ``1``/``self`` = a draft-model
    proposer drafting with the serving model itself (every draft
    accepted — a correctness/testing configuration, not a speedup; real
    deployments pass a small model via the ``spec_proposer`` kwarg)."""
    kind = (kind or "").strip().lower()
    if kind in ("", "0", "ngram"):
        return NgramProposer()
    if kind in ("1", "self"):
        return DraftModelProposer(model, variables,
                                  prefill_bucket=prefill_bucket)
    raise ValueError(
        f"FLEETX_SERVING_SPEC_DRAFT={kind!r}: expected 'ngram' (default), "
        "or '1'/'self' (draft with the serving model itself); custom draft "
        "models ride the ServingEngine(spec_proposer=...) kwarg")
