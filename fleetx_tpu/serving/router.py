"""ServingRouter: health-aware dispatch + zero-token-loss failover over
N ``ServingEngine`` replicas.

One replica is now production-shaped (paged, crash-safe, observable,
quantized, mesh-sharded) — but "heavy traffic from millions of users"
means N replicas, and replicas FAIL. This module is the replica-level
failure domain: the router fronts N engines (each optionally a mesh
slice, docs/SERVING.md "Mesh-sharded serving") and turns the library
into a deployable service whose availability story does not end at one
process's ``recover()``.

**Dispatch.** Requests queue in PER-TENANT lanes (``submit(tenant=...)``,
threaded from the API's ``X-Fleetx-Tenant`` header) and dispatch by
deficit round robin over the lanes: each scheduling round grants every
backlogged lane a token quantum scaled by its :class:`TenantPolicy`
weight, and a lane spends its accumulated deficit on its own FIFO head
(cost = prompt tokens + decode budget), so a flooding tenant can at most
consume its weighted share while everyone else keeps draining. Lanes
with a higher ``priority`` dispatch strictly first, and a paid lane's
deadline-at-risk request may PREEMPT a lower-priority in-flight request
through the same cancel + ``submit(history=...)`` machinery migration
uses — the victim re-queues at its OWN lane head with its delivered
tokens as history, so preemption never loses a token (the
exactly-one-result invariant is untouched: preemption is a migration
with a different trigger). ``dispatch="fifo"``
(``FLEETX_ROUTER_DISPATCH``) restores the old single-FIFO order — the
bench's DRR-vs-FIFO A/B. Admission is bounded per lane AND fleet-wide
(``FLEETX_ROUTER_MAX_QUEUE``): a tenant past its lane bound, request
rate, or token budget sheds with
:class:`~fleetx_tpu.serving.engine.QueueFull` scoped to ITS lane — the
flooding tenant absorbs its own backpressure instead of the fleet's.
Placement of each dispatched request goes to the
least-loaded in-rotation replica, scored by its health report's
``queue_depth + active``. PREFIX AFFINITY pins sessions to warm caches:
the hash of a prompt's longest full-page prefix maps to the replica
whose refcounted trie already owns those pages (recorded at first
dispatch), so a template/system-prompt workload keeps hitting the same
replica's warm trie instead of re-prefilling on a random one. Affinity
falls back to least-loaded the moment its replica is rotated out or its
queue is full — a preference, never a correctness dependency.

**Health-based rotate-out.** Each replica is probed through the PR 9
``/healthz`` contract — in-process the router calls
``ServingEngine.health()`` directly, which returns exactly the JSON
body the HTTP endpoint serves (``state`` ok/draining/dead + queue
depth + active), so a cross-process router consuming ``GET /healthz``
sees the identical report. ``draining`` rotates the replica out of
dispatch but keeps ticking it (it is finishing its own work — SIGTERM
drain); ``dead`` or a raising probe makes it a SUSPECT: rotated out,
re-probed on a bounded exponential backoff
(``FLEETX_ROUTER_PROBE_BACKOFF`` ticks, doubling per consecutive
failure), and only after ``FLEETX_ROUTER_PROBE_MAX`` consecutive
failures marked DEAD — a transient probe flap (network blip, the
``FLEETX_FAULT_PROBE_FLAP`` injector) costs a rotation round-trip,
never a replica.

**Zero-token-loss failover.** The router durably holds every request's
prompt + emitted-token history, fed from the engine's existing
``on_token`` callbacks (the in-process stand-in for the streaming
response a network router proxies — the history IS what the client has
already seen). When a replica dies — killed mid-burst, probe
escalation, or :class:`RecoveryExhausted` out of its ``step()`` — its
in-flight requests re-queue at the router head in submission order and
re-dispatch to a survivor with ``submit(history=...)``: the engine's
admit-with-history seam replays ``prompt + history[:-1]`` through the
PR 8 replay prefill (one call, prefix-trie-shared), reconstructs the
request's RNG position, and decoding continues from the last delivered
token. Greedy streams are BYTE-IDENTICAL to a never-killed run;
sampling streams are RNG-position-exact because the router re-sends
the same per-request key. History tokens are never re-emitted through
``on_token`` — the client already has them.

**Phase-disaggregated routing.** Replicas advertise a ``role`` in the
same health report (``prefill`` / ``decode`` / ``both``); the router
learns it at construction and refreshes it on every probe. Fresh
prompts prefer PREFILL-role replicas — priced by their health report's
``queue_tokens`` (prefill cost scales with prompt tokens, not request
count) — which run chunked prefill to the first token and PARK. Each
router tick then runs a HANDOFF phase: finished prefills export their
KV pages as checksummed wire blobs (``export_kv``), the request
re-queues at the head carrying the payloads, and the next dispatch
lands it on a decode replica whose ``submit(kv_payloads=...)`` revives
the shipped pages — decoding continues from the first token with no
second prefill, byte-identical to a colocated run. Every failure in
that chain (export fault, dead prefill replica, a decode replica
rejecting a corrupt blob at the wire checksum) falls back to the
replay ladder above: the first token is already in the durable
history, so the request replays on any survivor — slower, never
wrong. When no prefill replica is in rotation the fleet degrades to
colocated dispatch; when no decode replica is reachable, prefill
replicas serve as replay-decoders of last resort.

**Graceful degradation.** Queued requests past their ``queue_ttl_s`` /
``deadline_s`` are shed with ``finish_reason="timeout"`` (partial
tokens kept for migrated requests) instead of clogging the queue;
dispatch forwards the REMAINING deadline to the replica so the global
budget holds across migrations. A replica that turns suspect triggers
HEDGED re-dispatch (``FLEETX_ROUTER_HEDGE``): its requests migrate to
survivors immediately rather than waiting out the probe escalation,
and if the suspect later proves healthy the router cancels the stale
engine-side copies before ticking it again — EXACTLY-ONE-RESULT is the
invariant (every submitted request reaches exactly one terminal
:class:`ServingResult`; duplicates are structurally impossible because
a result only finalizes through the single dispatched-map entry and
``_finalize`` is idempotent). If every replica is dead the router
strands the remainder loudly (``finish_reason="error"``,
``router_stranded`` event) rather than hanging its caller.

Streaming callbacks keep the ENGINE's delivery semantics: tokens arrive
in order, and only a fault that rolls back an already-emitted token can
re-deliver it (the engine's at-least-once-under-fault contract); the
final result token list is always exact. After a replica recovers
in-place (rolled-back tick), the router re-bases its history from
``engine.emitted_tokens`` — the in-process analogue of a streaming
client re-syncing its stream offset on resume.

The router is synchronous and single-threaded like the engine: one
``step()`` probes, dispatches, ticks every live replica once, and
collects results. ``drain()`` loops to completion; ``shutdown()``
drains every replica gracefully and finalizes the rest. Observability:
``fleetx_router_*`` metrics + ``replica_out`` / ``replica_back`` /
``replica_dead`` / ``request_migrated`` events
(docs/OBSERVABILITY.md); chaos coverage in ``tools/chaos_check.py``
(``router_kill``, ``router_saturation``) and the SLO goodput record in
``tools/bench_serving.py`` (serving/workload.py generates the trace).
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import time
import weakref
import zlib
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from fleetx_tpu.obs.events import emit as obs_emit
from fleetx_tpu.obs.registry import get_registry
from fleetx_tpu.obs.tracing import span
from fleetx_tpu.resilience.faults import ReplicaKilled, faults
from fleetx_tpu.serving.engine import (
    QueueFull,
    RecoveryExhausted,
    ServingResult,
    ShuttingDown,
    _env_float,
    _env_int,
)
from fleetx_tpu.serving.metrics import _drop_series
from fleetx_tpu.utils.log import logger

__all__ = ["ReplicaState", "RouterMetrics", "ServingRouter", "TenantPolicy"]

#: lane every request without an explicit tenant lands in — one default
#: lane makes DRR degenerate to the old single FIFO, so tenant-less
#: callers keep byte-identical dispatch order
DEFAULT_TENANT = "default"


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """Admission + scheduling policy for one tenant's router lane
    (docs/SERVING.md "Per-tenant QoS & autoscaling").

    ``weight`` scales the lane's deficit-round-robin quantum — its
    guaranteed share of dispatch tokens under contention. ``priority``
    orders strict dispatch tiers (higher dispatches first) and is what
    arms preemption. ``rate_rps`` / ``token_budget`` are per-second
    admission buckets (requests and cost tokens respectively; 0 = no
    limit) refilled continuously on the router clock; ``max_queue``
    bounds THIS tenant's lane (0 = unbounded). Every limit sheds with a
    lane-scoped :class:`QueueFull` — the tenant that exceeds its
    contract absorbs its own backpressure. ``preempt`` arms the
    deadline-at-risk preemption path (None = armed iff priority > 0)."""

    weight: float = 1.0
    priority: int = 0
    rate_rps: float = 0.0
    token_budget: float = 0.0
    max_queue: int = 0
    preempt: Optional[bool] = None

    @property
    def preempts(self) -> bool:
        """Whether this lane's deadline-at-risk requests may preempt."""
        return self.priority > 0 if self.preempt is None else self.preempt


@dataclasses.dataclass
class _TenantLane:
    """One tenant's FIFO queue + DRR deficit + admission-bucket state."""

    name: str
    policy: TenantPolicy
    queue: List["_RouterRequest"] = dataclasses.field(default_factory=list)
    deficit: float = 0.0
    # token buckets: level is "how much is available now", refilled
    # continuously from the policy rates on the router's swappable clock
    rate_level: float = 0.0
    budget_level: float = 0.0
    refilled: Optional[float] = None


class ReplicaState:
    """Replica lifecycle states (module docstring "rotate-out")."""

    OK = "ok"              # in rotation: receives dispatches, ticked
    SUSPECT = "suspect"    # probe failing: out of rotation, backoff re-probe
    DRAINING = "draining"  # finishing its own work: ticked, no dispatches
    DEAD = "dead"          # gone: never touched again, requests migrated


@dataclasses.dataclass
class _Replica:
    """One fronted engine + the router's view of it."""

    index: int
    engine: object
    state: str = ReplicaState.OK
    # phase role learned from the health report ("prefill"/"decode"/
    # "both"): prefill replicas get fresh prompts priced in queue
    # TOKENS and are polled for finished prefills to hand off
    role: str = "both"
    # model family served (the /healthz ``model`` key): dispatch filters
    # by it BEFORE load/affinity — a GPT prompt never lands on an ERNIE
    # replica, and fallback stays inside the family group
    model: str = "gpt"
    probe_failures: int = 0          # consecutive non-ok probes
    next_probe_tick: int = 0         # backoff schedule while suspect
    dispatched: Dict[int, int] = dataclasses.field(default_factory=dict)
    # engine rids hedged away while suspect; cancelled if/when it rejoins
    stale: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _RouterRequest:
    """One router-level request across dispatches/migrations."""

    rid: int
    prompt: np.ndarray
    model: str                    # family group this request dispatches to
    kw: Dict                      # engine submit kwargs (decode knobs)
    rng_key: jax.Array            # SAME key at every dispatch (RNG parity)
    on_token: Optional[object]
    submit_time: float
    queue_ttl_s: float
    deadline_s: float
    # when THIS queue residency began: reset at every (re-)enqueue, so
    # the queue TTL measures waiting — a migrated request that already
    # ran for minutes must not be shed the instant it re-queues
    # (deadline_s stays anchored to submit_time: total lifetime)
    queued_since: float = 0.0
    affinity_key: Optional[int] = None
    state: str = "queued"         # queued | dispatched | finished
    replica: Optional[int] = None
    engine_rid: Optional[int] = None
    dispatches: int = 0
    first_token_time: Optional[float] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    # disaggregated handoff: wire-format page blobs export_kv() shipped,
    # consumed by the next dispatch (cleared on success OR on a decode-
    # side ValueError — the replay fallback never re-sends bad blobs)
    kv_payloads: Optional[list] = None
    tenant: str = DEFAULT_TENANT
    preemptions: int = 0          # times evicted for a higher-priority lane


class RouterMetrics:
    """``fleetx_router_*`` registry instruments for one router, labeled
    ``router="<n>"`` (docs/OBSERVABILITY.md has the table). The same
    owned-series + weakref-finalize discipline as ``ServingMetrics``:
    cycling routers cannot grow ``/metrics`` forever."""

    _labels = itertools.count()

    def __init__(self, registry=None):
        reg = registry or get_registry()
        self.router_label = str(next(self._labels))
        lab = {"router": self.router_label}
        self._owned = owned = []

        def child(fam):
            owned.append((fam, dict(lab)))
            return fam.labels(**lab)

        def counter(name, help):
            return child(reg.counter(name, help, ("router",)))

        def gauge(name, help):
            return child(reg.gauge(name, help, ("router",)))

        def hist(name, help):
            return child(reg.histogram(name, help, ("router",)))

        self._g_replicas = gauge(
            "fleetx_router_replicas",
            "Replicas this router fronts (dead ones included)")
        self._g_in_rotation = gauge(
            "fleetx_router_replicas_in_rotation",
            "Replicas currently receiving dispatches (state ok)")
        self._g_queue_depth = gauge(
            "fleetx_router_queue_depth",
            "Requests waiting in the router-level queue")
        self._c_ticks = counter(
            "fleetx_router_ticks_total", "Router scheduler ticks executed")
        self._c_dispatched = counter(
            "fleetx_router_dispatched_total",
            "Dispatches to a replica (migrations re-count)")
        self._c_affinity = counter(
            "fleetx_router_affinity_hits_total",
            "Dispatches placed by prefix affinity (warm-trie pin)")
        self._c_migrated = counter(
            "fleetx_router_migrated_total",
            "In-flight requests migrated off a suspect/dead replica")
        self._c_deaths = counter(
            "fleetx_router_replica_deaths_total",
            "Replicas marked dead (probe escalation, kill, "
            "RecoveryExhausted)")
        self._c_probe_failures = counter(
            "fleetx_router_probe_failures_total",
            "Health probes that returned non-ok or raised")
        self._c_rejected = counter(
            "fleetx_router_rejected_total",
            "Submits refused by the bounded router queue")
        self._c_shed = counter(
            "fleetx_router_shed_total",
            "Queued requests shed by queue-TTL/deadline expiry")
        self._c_preempted = counter(
            "fleetx_router_preempted_total",
            "In-flight requests preempted for a higher-priority lane's "
            "deadline-at-risk request (zero-loss: victims re-queue with "
            "history)")
        self._finished_family = reg.counter(
            "fleetx_router_finished_total",
            "Requests that reached their one terminal result, by reason",
            ("router", "reason"))
        # per-tenant QoS families, labeled (router, tenant) — children
        # materialize lazily per tenant seen, owned for finalize-cleanup
        tl = ("router", "tenant")
        self._tenant_families = {
            "queue_depth": reg.gauge(
                "fleetx_router_tenant_queue_depth",
                "Requests waiting in this tenant's router lane", tl),
            "shed": reg.counter(
                "fleetx_router_tenant_shed_total",
                "This tenant's requests refused at admission (lane bound, "
                "rate, token budget) or shed from its lane by "
                "TTL/deadline", tl),
            "preempted": reg.counter(
                "fleetx_router_tenant_preempted_total",
                "This tenant's in-flight requests preempted by a "
                "higher-priority lane", tl),
            "dispatched": reg.counter(
                "fleetx_router_tenant_dispatched_total",
                "Dispatches of this tenant's requests (migrations "
                "re-count)", tl),
            "tokens": reg.counter(
                "fleetx_router_tenant_tokens_total",
                "Tokens delivered in this tenant's terminal results", tl),
            "goodput_share": reg.gauge(
                "fleetx_router_tenant_goodput_share",
                "This tenant's fraction of all tokens this router "
                "delivered", tl),
        }
        self._tenant_children: Dict[Tuple[str, str], object] = {}
        self._per_tenant: Dict[str, Dict[str, int]] = {}
        self._h_ttft = hist(
            "fleetx_router_ttft_seconds",
            "Router submit -> first token on the host (end-to-end across "
            "queueing, dispatch, and any migration)")
        self._h_latency = hist(
            "fleetx_router_request_latency_seconds",
            "Router submit -> terminal result latency")
        self._h_queue_depth = hist(
            "fleetx_router_queue_depth_per_tick",
            "Router queue depth sampled once per tick")
        self._reasons: Dict[str, object] = {}
        weakref.finalize(self, _drop_series, owned)

    def _tenant_child(self, key: str, tenant: str):
        """Memoized per-tenant child of one QoS family (owned for the
        weakref-finalize cleanup like every other child)."""
        child = self._tenant_children.get((key, tenant))
        if child is None:
            labels = {"router": self.router_label, "tenant": tenant}
            fam = self._tenant_families[key]
            self._owned.append((fam, labels))
            child = fam.labels(**labels)
            self._tenant_children[(key, tenant)] = child
        return child

    def _tenant_stats(self, tenant: str) -> Dict[str, int]:
        return self._per_tenant.setdefault(
            tenant, {"shed": 0, "preempted": 0, "dispatched": 0,
                     "tokens": 0})

    def record_reject(self, tenant: str = DEFAULT_TENANT) -> None:
        """A submit was refused at admission (queue bound/rate/budget)."""
        self._c_rejected.inc()
        self._tenant_stats(tenant)["shed"] += 1
        self._tenant_child("shed", tenant).inc()

    def record_shed(self, tenant: str = DEFAULT_TENANT) -> None:
        """A queued request was shed by TTL/deadline expiry."""
        self._c_shed.inc()
        self._tenant_stats(tenant)["shed"] += 1
        self._tenant_child("shed", tenant).inc()

    def record_probe_failure(self) -> None:
        """A health probe returned non-ok or raised."""
        self._c_probe_failures.inc()

    def record_dispatch(self, affinity: bool,
                        tenant: str = DEFAULT_TENANT) -> None:
        """One dispatch placed (``affinity`` = via the prefix pin)."""
        self._c_dispatched.inc()
        if affinity:
            self._c_affinity.inc()
        self._tenant_stats(tenant)["dispatched"] += 1
        self._tenant_child("dispatched", tenant).inc()

    def record_preempted(self, victim_tenant: str) -> None:
        """One in-flight request preempted for a higher-priority lane."""
        self._c_preempted.inc()
        self._tenant_stats(victim_tenant)["preempted"] += 1
        self._tenant_child("preempted", victim_tenant).inc()

    def observe_tenant_queue(self, tenant: str, depth: int) -> None:
        """Per-tick lane-depth gauge sample."""
        self._tenant_child("queue_depth", tenant).set(depth)

    def record_tenant_tokens(self, tenant: str, n_tokens: int) -> None:
        """Terminal result delivered ``n_tokens`` to ``tenant``; refresh
        every tenant's delivered-token share gauge."""
        st = self._tenant_stats(tenant)
        st["tokens"] += int(n_tokens)
        if n_tokens:
            self._tenant_child("tokens", tenant).inc(int(n_tokens))
        total = sum(s["tokens"] for s in self._per_tenant.values())
        if total:
            for t, s in self._per_tenant.items():
                self._tenant_child("goodput_share", t).set(
                    s["tokens"] / total)

    def record_migrated(self) -> None:
        """One in-flight request migrated off its replica."""
        self._c_migrated.inc()

    def record_replica_death(self) -> None:
        """One replica was marked dead."""
        self._c_deaths.inc()

    def record_finished(self, reason: str, latency_s: float) -> None:
        """One request reached its terminal result."""
        child = self._reasons.get(reason)
        if child is None:
            labels = {"router": self.router_label, "reason": reason}
            self._owned.append((self._finished_family, labels))
            child = self._reasons[reason] = self._finished_family.labels(
                **labels)
        child.inc()
        self._h_latency.observe(latency_s)

    def observe_ttft(self, ttft_s: float) -> None:
        """First token of a request reached the caller."""
        self._h_ttft.observe(ttft_s)

    def observe_tick(self, queue_depth: int, replicas: int,
                     in_rotation: int) -> None:
        """Per-tick gauge sample."""
        self._c_ticks.inc()
        self._g_queue_depth.set(queue_depth)
        self._g_replicas.set(replicas)
        self._g_in_rotation.set(in_rotation)
        self._h_queue_depth.observe(queue_depth)

    @property
    def finish_reasons(self) -> Dict[str, int]:
        """``{finish_reason: count}`` over terminal results."""
        return {r: int(c.value) for r, c in self._reasons.items()
                if int(c.value)}

    def snapshot(self) -> Dict:
        """Aggregate dict the benches/tests consume."""
        ticks = int(self._c_ticks.value)
        ttft_p50, ttft_p99 = self._h_ttft.quantiles((50, 99))
        lat_p50, lat_p99 = self._h_latency.quantiles((50, 99))
        return {
            "replicas": int(self._g_replicas.value),
            "replicas_in_rotation": int(self._g_in_rotation.value),
            "queue_depth": int(self._g_queue_depth.value),
            "queue_depth_mean": (self._h_queue_depth.sum / ticks
                                 if ticks else 0.0),
            "ticks": ticks,
            "dispatched": int(self._c_dispatched.value),
            "affinity_hits": int(self._c_affinity.value),
            "migrated": int(self._c_migrated.value),
            "replica_deaths": int(self._c_deaths.value),
            "probe_failures": int(self._c_probe_failures.value),
            "rejected": int(self._c_rejected.value),
            "shed": int(self._c_shed.value),
            "preempted": int(self._c_preempted.value),
            "per_tenant": {t: dict(s) for t, s in self._per_tenant.items()},
            "finished": sum(self.finish_reasons.values()),
            "finish_reasons": self.finish_reasons,
            "ttft_s_p50": ttft_p50,
            "ttft_s_p99": ttft_p99,
            "latency_s_p50": lat_p50,
            "latency_s_p99": lat_p99,
        }


class ServingRouter:
    """Fault-tolerant request router over N serving replicas (module
    docstring). ``replicas`` is a list of constructed ``ServingEngine``s
    — each replica's slots/pages/mesh are its own capacity, the router
    only consumes the submit/step/health/result surface."""

    _AFFINITY_CAP = 65536  # prefix pins kept (insertion-ordered, oldest out)
    _HOT_PREFIX_CAP = 32   # most-reused prefixes tracked for prewarming
    _MAX_DRR_ROUNDS = 4096  # converges far earlier; loud loop backstop

    #: capability flag the API server probes before threading
    #: ``submit(tenant=...)`` — plain engines don't take the kwarg
    supports_tenants = True

    def __init__(self, replicas, *, max_queue: Optional[int] = None,
                 queue_ttl_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 probe_every: Optional[int] = None,
                 probe_max_failures: Optional[int] = None,
                 probe_backoff_ticks: Optional[int] = None,
                 hedge: Optional[bool] = None,
                 affinity: Optional[bool] = None,
                 base_seed: int = 0,
                 metrics: Optional[RouterMetrics] = None,
                 tenants: Optional[Dict[str, TenantPolicy]] = None,
                 dispatch: Optional[str] = None,
                 preempt: Optional[bool] = None,
                 preempt_risk_frac: Optional[float] = None,
                 drr_quantum: Optional[int] = None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self._replicas = [_Replica(index=i, engine=e,
                                   role=getattr(e, "role", "both"),
                                   model=getattr(e, "model_family", "gpt"))
                          for i, e in enumerate(replicas)]
        self.max_queue = (max_queue if max_queue is not None
                          else _env_int("FLEETX_ROUTER_MAX_QUEUE", 0))
        self.queue_ttl_s = (queue_ttl_s if queue_ttl_s is not None
                            else _env_float("FLEETX_ROUTER_QUEUE_TTL_S", 0.0))
        self.deadline_s = (deadline_s if deadline_s is not None
                           else _env_float("FLEETX_ROUTER_DEADLINE_S", 0.0))
        # probing cadence: in-process probes are a method call, so the
        # default probes every tick; a cross-process router GETting
        # /healthz raises this to its scrape budget
        self.probe_every = max(1, probe_every if probe_every is not None
                               else _env_int("FLEETX_ROUTER_PROBE_EVERY", 1))
        self.probe_max_failures = max(
            1, probe_max_failures if probe_max_failures is not None
            else _env_int("FLEETX_ROUTER_PROBE_MAX", 3))
        self.probe_backoff_ticks = max(
            1, probe_backoff_ticks if probe_backoff_ticks is not None
            else _env_int("FLEETX_ROUTER_PROBE_BACKOFF", 2))
        self.hedge = (hedge if hedge is not None
                      else _env_int("FLEETX_ROUTER_HEDGE", 1) == 1)
        self.affinity = (affinity if affinity is not None
                         else _env_int("FLEETX_ROUTER_AFFINITY", 1) == 1)
        # affinity granularity: the page is the trie-sharing unit, so the
        # pinned prefix is the longest FULL-page run (0 disables when the
        # fleet is not paged — there is no warm trie to pin to)
        page_sizes = {e.page_size for e in replicas if e.paged}
        self._affinity_page = min(page_sizes) if page_sizes else 0
        self._affinity_map: Dict[int, int] = {}  # prefix hash -> replica
        # the tightest per-request capacity PER MODEL GROUP, so caller
        # mistakes (over-long prompts, unservable strategies) raise AT
        # SUBMIT like the engine's contract — not as a delayed
        # finish_reason="error" result out of the first dispatch.
        # ``submit_limit`` is the protocol seam (the smallest REJECTED
        # size); the getattr fallback keeps pre-protocol engine doubles
        # (tests, RPC proxies) working on the old cache/position formula
        self._limits: Dict[str, int] = {}
        for rep in self._replicas:
            e = rep.engine
            lim = getattr(e, "submit_limit", None)
            if lim is None:
                lim = min(e.cache_len,
                          e.model.cfg.max_position_embeddings)
            self._limits[rep.model] = min(
                self._limits.get(rep.model, lim), lim)
        # single-model callers never name a family: replica 0's group is
        # the default, which on a homogeneous fleet is the whole fleet
        self._default_model = self._replicas[0].model
        self._limit = self._limits[self._default_model]
        self._base_key = jax.random.PRNGKey(base_seed)
        self.metrics = metrics or RouterMetrics()
        # ---- per-tenant QoS dispatch (module docstring "Dispatch") ----
        self.dispatch_mode = (
            dispatch if dispatch is not None
            else os.environ.get("FLEETX_ROUTER_DISPATCH", "drr"))
        if self.dispatch_mode not in ("drr", "fifo"):
            raise ValueError(
                f"dispatch mode {self.dispatch_mode!r} (want drr|fifo)")
        self.preempt_enabled = (
            preempt if preempt is not None
            else _env_int("FLEETX_ROUTER_PREEMPT", 1) == 1)
        self.preempt_risk_frac = max(0.0, (
            preempt_risk_frac if preempt_risk_frac is not None
            else _env_float("FLEETX_ROUTER_PREEMPT_RISK_FRAC", 0.5)))
        self.drr_quantum = max(1, (
            drr_quantum if drr_quantum is not None
            else _env_int("FLEETX_ROUTER_DRR_QUANTUM", 256)))
        self._tenant_policies: Dict[str, TenantPolicy] = dict(tenants or {})
        self._lanes: Dict[str, _TenantLane] = {}
        for name in self._tenant_policies:  # eager: stable DRR lane order
            self._lane(name)
        # most-reused full-page prefixes seen at submit — what a freshly
        # spawned replica prewarms from the shared page store
        self._hot_prefixes: Dict[int, list] = {}  # key -> [prefix, hits]
        self._requests: Dict[int, _RouterRequest] = {}
        self._results: Dict[int, ServingResult] = {}
        self._next_id = 0
        self._ticks = 0
        self._shutting_down = False
        self._now = time.perf_counter  # swappable clock (chaos tests)

    # ------------------------------------------------------------ submit

    def submit(self, prompt, *, max_length: Optional[int] = None,
               min_length: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               decode_strategy: Optional[str] = None,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None, top_p: Optional[float] = None,
               seed: Optional[int] = None, on_token=None,
               queue_ttl_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               model: Optional[str] = None,
               tenant: Optional[str] = None) -> int:
        """Queue one request; returns its router-level id. The kwargs
        mirror ``ServingEngine.submit`` (they are forwarded verbatim at
        every dispatch); ``seed`` pins the request's sampling stream —
        the SAME key re-sends at each migration, which is what makes
        sampling failover RNG-position-exact. ``model`` names the family
        group to dispatch into (default: replica 0's family, so
        single-model callers never change); an unserved family raises
        ValueError at submit, loudly. ``tenant`` names the QoS lane the
        request queues in (default: the shared ``"default"`` lane);
        admission enforces that lane's :class:`TenantPolicy` bounds.
        Raises :class:`QueueFull` at the fleet-wide
        ``FLEETX_ROUTER_MAX_QUEUE`` bound or any per-lane limit (the
        message names the lane) and :class:`ShuttingDown` after
        :meth:`shutdown` began."""
        if self._shutting_down:
            raise ShuttingDown(
                "router is shutting down; submit to another cluster")
        tenant = tenant if tenant else DEFAULT_TENANT
        if self.max_queue and self.queue_depth >= self.max_queue:
            self._shed_expired(self._now())  # dead entries don't hold slots
        if self.max_queue and self.queue_depth >= self.max_queue:
            self.metrics.record_reject(tenant)
            obs_emit("queue_reject", router=self.metrics.router_label,
                     queue_depth=self.queue_depth, tenant=tenant)
            raise QueueFull(
                f"router queue is full ({self.queue_depth}/{self.max_queue}"
                " waiting); retry later or raise FLEETX_ROUTER_MAX_QUEUE")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if decode_strategy is not None and decode_strategy not in (
                "greedy", "sampling"):
            raise ValueError(
                f"decode_strategy {decode_strategy!r} not servable by "
                "continuous batching (beam search needs one-shot "
                "generate())")
        if model is None:
            model = self._default_model
        if model not in self._limits:
            raise ValueError(
                f"model {model!r} is not served by this fleet (serving: "
                f"{sorted(self._limits)})")
        if prompt.size >= self._limits[model]:
            raise ValueError(
                f"prompt_len {prompt.size} is not servable by any "
                f"{model!r} replica (tightest per-request limit "
                f"{self._limits[model]})")
        lane = self._lane(tenant)
        self._admit_lane(lane, prompt, max_length)
        rid = self._next_id
        self._next_id += 1
        rng_key = (jax.random.PRNGKey(int(seed)) if seed is not None
                   else jax.random.fold_in(self._base_key, rid))
        kw = {}
        for name, value in (("max_length", max_length),
                            ("min_length", min_length),
                            ("eos_token_id", eos_token_id),
                            ("decode_strategy", decode_strategy),
                            ("temperature", temperature),
                            ("top_k", top_k), ("top_p", top_p)):
            if value is not None:
                kw[name] = value
        now = self._now()
        req = _RouterRequest(
            rid=rid, prompt=prompt, model=model, kw=kw, rng_key=rng_key,
            on_token=on_token, submit_time=now, queued_since=now,
            queue_ttl_s=float(queue_ttl_s if queue_ttl_s is not None
                              else self.queue_ttl_s),
            deadline_s=float(deadline_s if deadline_s is not None
                             else self.deadline_s),
            affinity_key=self._affinity_key(prompt),
            tenant=tenant,
        )
        self._requests[rid] = req
        lane.queue.append(req)
        if req.affinity_key is not None:
            self._note_hot_prefix(req.affinity_key, prompt)
        return rid

    # --------------------------------------------- tenant lanes (QoS)

    def _lane(self, tenant: str) -> _TenantLane:
        """The tenant's lane, created on first sight with its configured
        :class:`TenantPolicy` (or the open default policy)."""
        lane = self._lanes.get(tenant)
        if lane is None:
            lane = self._lanes[tenant] = _TenantLane(
                name=tenant,
                policy=self._tenant_policies.get(tenant, TenantPolicy()))
        return lane

    def _cost(self, req: _RouterRequest) -> float:
        """DRR/budget cost of one request in TOKENS: prompt plus the
        decode budget it asked for (the same units prefill replicas are
        priced in — a flooding tenant pays for the work it books, not
        the requests it counts)."""
        return float(req.prompt.size) + float(
            req.kw.get("max_length", 0) or 0)

    def _refill_buckets(self, lane: _TenantLane, now: float) -> None:
        """Continuous token-bucket refill on the router clock. Burst
        capacity is one second's worth of each rate — enough to absorb
        a bursty arrival at the contracted average."""
        pol = lane.policy
        if lane.refilled is None:
            lane.rate_level = max(pol.rate_rps, 1.0)
            lane.budget_level = pol.token_budget
        else:
            dt = max(0.0, now - lane.refilled)
            lane.rate_level = min(max(pol.rate_rps, 1.0),
                                  lane.rate_level + dt * pol.rate_rps)
            lane.budget_level = min(pol.token_budget,
                                    lane.budget_level
                                    + dt * pol.token_budget)
        lane.refilled = now

    def _admit_lane(self, lane: _TenantLane, prompt: np.ndarray,
                    max_length: Optional[int]) -> None:
        """Per-lane admission control: lane queue bound, request-rate
        bucket, token-budget bucket. Every refusal is a
        :class:`QueueFull` scoped to THIS lane — the tenant exceeding
        its contract sheds its own requests, never the fleet's."""
        pol = lane.policy
        why = None
        if pol.max_queue and len(lane.queue) >= pol.max_queue:
            why = (f"lane is full ({len(lane.queue)}/{pol.max_queue} "
                   "waiting)")
        else:
            now = self._now()
            self._refill_buckets(lane, now)
            cost = float(prompt.size) + float(max_length or 0)
            if pol.rate_rps and lane.rate_level < 1.0:
                why = f"request rate above {pol.rate_rps}/s"
            elif pol.token_budget and lane.budget_level < cost:
                why = (f"token budget exhausted (request costs "
                       f"{cost:.0f} tokens, {lane.budget_level:.0f} "
                       f"available at {pol.token_budget}/s)")
            else:
                if pol.rate_rps:
                    lane.rate_level -= 1.0
                if pol.token_budget:
                    lane.budget_level -= cost
        if why is not None:
            self.metrics.record_reject(lane.name)
            obs_emit("queue_reject", router=self.metrics.router_label,
                     tenant=lane.name, queue_depth=len(lane.queue))
            raise QueueFull(f"tenant {lane.name!r}: {why}; retry later "
                            "or raise this tenant's TenantPolicy limits")

    def _queued(self) -> List[_RouterRequest]:
        """Queued requests across every lane in global submission order
        (migrated/preempted re-queues sit at their lane heads and carry
        the oldest rids, so rid order IS the legacy single-FIFO order)."""
        out = [r for lane in self._lanes.values() for r in lane.queue]
        out.sort(key=lambda r: r.rid)
        return out

    def _prune_lanes(self) -> None:
        """Drop dispatched/finalized requests out of every lane queue."""
        for lane in self._lanes.values():
            if any(r.state != "queued" for r in lane.queue):
                lane.queue = [r for r in lane.queue if r.state == "queued"]

    def _requeue_head(self, reqs: List[_RouterRequest]) -> None:
        """Re-queue migrated/continued requests at their OWN lane heads
        in submission order (the lane-aware version of the old
        head-of-queue prepend)."""
        for req in sorted(reqs, key=lambda r: r.rid, reverse=True):
            self._lane(req.tenant).queue.insert(0, req)

    def _note_hot_prefix(self, key: int, prompt: np.ndarray) -> None:
        """Track the most-reused full-page prefixes (bounded): the warm
        set :meth:`hot_prefixes` hands the autoscaler for prewarming a
        fresh replica's trie from the shared page store."""
        ent = self._hot_prefixes.get(key)
        if ent is not None:
            ent[1] += 1
            return
        n = (prompt.size // self._affinity_page) * self._affinity_page
        self._hot_prefixes[key] = [np.ascontiguousarray(prompt[:n]), 1]
        while len(self._hot_prefixes) > self._HOT_PREFIX_CAP:
            coldest = min(self._hot_prefixes,
                          key=lambda k: self._hot_prefixes[k][1])
            del self._hot_prefixes[coldest]

    def hot_prefixes(self, k: int = 8) -> List[np.ndarray]:
        """The ``k`` most-reused full-page prompt prefixes this router
        has admitted — what a freshly spawned replica prewarms from the
        shared :class:`DiskPageStore` before taking traffic."""
        ents = sorted(self._hot_prefixes.values(), key=lambda e: -e[1])
        return [e[0] for e in ents[:k]]

    def _affinity_key(self, prompt: np.ndarray) -> Optional[int]:
        """Hash of the longest FULL-page prompt prefix (None when
        affinity is off, the fleet is unpaged, or no page fills): the
        page is the trie-sharing granularity, so this is exactly the
        prefix whose warm pages a previous session may have parked."""
        if not self.affinity or not self._affinity_page:
            return None
        n = (prompt.size // self._affinity_page) * self._affinity_page
        if n == 0:
            return None
        return zlib.crc32(np.ascontiguousarray(prompt[:n]).tobytes())

    # -------------------------------------------------------------- step

    def step(self) -> Dict:
        """One router tick: shed expired queued work, probe due replicas
        (rotate out / escalate / rejoin), dispatch the queue, tick every
        live replica once (collecting results and handling death), and
        strand the remainder loudly if the whole fleet is gone. Returns
        a summary dict."""
        self._ticks += 1
        now = self._now()
        shed = self._shed_expired(now)
        self._probe_due()
        handoff = self._handoff()
        dispatched = self._dispatch()
        finished, migrated = self._tick_replicas()
        stranded = self._strand_if_no_replicas()
        in_rotation = sum(r.state == ReplicaState.OK for r in self._replicas)
        self.metrics.observe_tick(self.queue_depth, len(self._replicas),
                                  in_rotation)
        for lane in self._lanes.values():
            self.metrics.observe_tenant_queue(lane.name, len(lane.queue))
        return {"dispatched": dispatched, "finished": finished,
                "migrated": migrated, "handoff": handoff,
                "shed": shed + stranded,
                "queue_depth": self.queue_depth,
                "in_rotation": in_rotation,
                "replica_states": [r.state for r in self._replicas]}

    def drain(self, max_ticks: Optional[int] = None
              ) -> Dict[int, ServingResult]:
        """Tick until every submitted request has its terminal result
        (or ``max_ticks``), then return-and-clear the finished results."""
        n = 0
        while any(r.state != "finished" for r in self._requests.values()):
            self.step()
            n += 1
            if max_ticks is not None and n >= max_ticks:
                break
        out, self._results = self._results, {}
        for rid in out:
            self._requests.pop(rid, None)
        return out

    def result(self, request_id: int) -> Optional[ServingResult]:
        """Finished result for ``request_id`` (None while in flight)."""
        return self._results.get(request_id)

    def take_result(self, request_id: int) -> Optional[ServingResult]:
        """Remove and return one finished result (None while in flight)."""
        res = self._results.pop(request_id, None)
        if res is not None:
            self._requests.pop(request_id, None)
        return res

    def cancel(self, request_id: int) -> bool:
        """Cancel a queued or dispatched request (exactly one terminal
        result with ``finish_reason="cancelled"``, partial tokens kept).
        False when unknown or already finished."""
        req = self._requests.get(request_id)
        if req is None or req.state == "finished":
            return False
        if req.state == "dispatched":
            rep = self._replicas[req.replica]
            rep.dispatched.pop(req.engine_rid, None)
            if rep.state not in (ReplicaState.DEAD,):
                try:
                    rep.engine.cancel(req.engine_rid)
                    rep.engine.take_result(req.engine_rid)  # drop the copy
                except Exception:  # noqa: BLE001 — a dying replica is fine
                    pass
        else:
            lane = self._lane(req.tenant)
            lane.queue = [r for r in lane.queue if r.rid != request_id]
        self._finalize(req, "cancelled")
        obs_emit("request_cancelled", request=request_id,
                 router=self.metrics.router_label)
        return True

    def shutdown(self, grace_s: Optional[float] = None
                 ) -> Dict[int, ServingResult]:
        """Graceful cluster drain: stop router admission, ask every live
        replica to drain (``request_shutdown``), tick until every request
        has its terminal result (replicas retire leftovers at their grace
        deadline), finalize still-queued requests as ``"shutdown"``, and
        return-and-clear all results."""
        self._shutting_down = True
        for rep in self._replicas:
            if rep.state != ReplicaState.DEAD:
                try:
                    rep.engine.request_shutdown(grace_s)
                except Exception:  # noqa: BLE001 — best-effort on a zombie
                    pass
        while any(r.state == "dispatched" for r in self._requests.values()):
            self.step()
        for req in self._queued():
            self._finalize(req, "shutdown")
        for lane in self._lanes.values():
            lane.queue = []
        out, self._results = self._results, {}
        for rid in out:
            self._requests.pop(rid, None)
        return out

    # --------------------------------------------------------- internals

    def _shed_expired(self, now: float) -> int:
        """Deadline-aware shedding of the ROUTER queue: queued requests
        past their queue-TTL or total deadline finalize as ``"timeout"``
        (migrated partials kept) instead of occupying queue slots they
        can no longer use."""
        shed = 0
        for lane in self._lanes.values():
            keep = []
            for req in lane.queue:
                waiting = now - req.queued_since   # THIS queue residency
                age = now - req.submit_time        # total lifetime
                if ((req.queue_ttl_s and waiting > req.queue_ttl_s)
                        or (req.deadline_s and age > req.deadline_s)):
                    self._finalize(req, "timeout")
                    obs_emit("request_timeout", request=req.rid,
                             where="router_queue", tenant=req.tenant)
                    self.metrics.record_shed(req.tenant)
                    shed += 1
                else:
                    keep.append(req)
            lane.queue = keep
        return shed

    def _probe(self, rep: _Replica) -> Dict:
        """One health probe: the flap injector may LIE, otherwise the
        replica's ``health()`` report (== its ``/healthz`` body); a
        raising probe reads as dead."""
        lie = faults.on_router_probe(rep.index)
        if lie is not None:
            return lie
        try:
            return rep.engine.health()
        except Exception as e:  # noqa: BLE001 — unreachable replica
            return {"state": "dead", "error": f"{type(e).__name__}: {e}"}

    def _probe_due(self) -> None:
        """Probe replicas whose schedule is due: healthy/draining ones on
        the ``probe_every`` cadence, suspects on their bounded-backoff
        schedule. State transitions per the module docstring."""
        for rep in self._replicas:
            if rep.state == ReplicaState.DEAD:
                continue
            if rep.state == ReplicaState.SUSPECT:
                if self._ticks < rep.next_probe_tick:
                    continue
            elif (self._ticks - 1) % self.probe_every:
                continue
            report = self._probe(rep)
            state = report.get("state", "dead")
            # roles and model families ride the health report so a
            # cross-process router learns placement phases AND grouping
            # from the same /healthz scrape
            rep.role = report.get("role", rep.role)
            rep.model = report.get("model", rep.model)
            if state == "ok":
                if rep.state == ReplicaState.SUSPECT:
                    self._rejoin(rep)
                rep.probe_failures = 0
            elif state == "draining":
                # the replica is finishing its own work: no dispatches,
                # keep ticking, never escalate to dead on this signal.
                # A SUSPECT turning draining must first cancel its
                # hedged-away stale copies — draining replicas ARE
                # ticked, and a stale copy decoding there would
                # double-deliver tokens the migrated copy owns
                if rep.state != ReplicaState.DRAINING:
                    self._cancel_stale(rep)
                    rep.state = ReplicaState.DRAINING
                    obs_emit("replica_out", replica=rep.index,
                             reason="draining")
                    logger.warning(
                        "router: replica %d rotated out (draining)",
                        rep.index)
            else:  # dead / unreachable
                rep.probe_failures += 1
                self.metrics.record_probe_failure()
                if rep.probe_failures >= self.probe_max_failures:
                    self._mark_dead(rep, f"probe escalation "
                                    f"({rep.probe_failures} failures)")
                    continue
                backoff = (self.probe_backoff_ticks
                           * (2 ** (rep.probe_failures - 1)))
                rep.next_probe_tick = self._ticks + min(backoff, 64)
                if rep.state == ReplicaState.OK:
                    rep.state = ReplicaState.SUSPECT
                    obs_emit("replica_out", replica=rep.index,
                             reason=state,
                             probe_failures=rep.probe_failures)
                    logger.warning(
                        "router: replica %d rotated out (probe says %r); "
                        "re-probing with backoff before declaring it dead",
                        rep.index, state)
                    if self.hedge:
                        # hedged re-dispatch: do not wait out the probe
                        # escalation — move its work to survivors now and
                        # cancel the stale copies if it ever rejoins
                        self._migrate_all(rep, why="hedge", stale=True)

    def _cancel_stale(self, rep: _Replica) -> None:
        """Cancel and drop the engine-side copies of requests hedged
        away while ``rep`` was suspect — exactly-one-stream: the
        migrated copy is the live one, so before this engine is ever
        ticked again (rejoin OR drain) its stale copies must die."""
        for erid in rep.stale:
            try:
                rep.engine.cancel(erid)
                rep.engine.take_result(erid)  # drop the cancelled copy
            except Exception:  # noqa: BLE001
                pass
        rep.stale = []

    def _rejoin(self, rep: _Replica) -> None:
        """A suspect proved healthy: cancel the engine-side copies of
        hedged-away requests (exactly-one-result: the migrated copy is
        the live one), then put the replica back in rotation."""
        self._cancel_stale(rep)
        rep.state = ReplicaState.OK
        rep.probe_failures = 0
        obs_emit("replica_back", replica=rep.index)
        logger.warning("router: replica %d back in rotation", rep.index)

    def _mark_dead(self, rep: _Replica, reason: str) -> None:
        """Point of no return for one replica: declare it dead, migrate
        everything it still held, drop its affinity pins."""
        if rep.state == ReplicaState.DEAD:
            return
        rep.state = ReplicaState.DEAD
        try:
            rep.engine.declare_dead()
        except Exception:  # noqa: BLE001 — the process may be gone
            pass
        self.metrics.record_replica_death()
        obs_emit("replica_dead", replica=rep.index, reason=reason)
        logger.error("router: replica %d is DEAD (%s); migrating %d "
                     "in-flight request(s)", rep.index, reason,
                     len(rep.dispatched))
        self._migrate_all(rep, why="replica_dead")
        self._affinity_map = {k: v for k, v in self._affinity_map.items()
                              if v != rep.index}

    def _migrate_all(self, rep: _Replica, *, why: str,
                     stale: bool = False) -> int:
        """Re-queue every request dispatched to ``rep`` at the router
        queue HEAD in submission order, each carrying its durable token
        history for the admit-with-history re-dispatch. ``stale`` tracks
        the engine-side rids for cancel-on-rejoin (hedging)."""
        moved = []
        for erid, rid in sorted(rep.dispatched.items(), key=lambda kv: kv[1]):
            req = self._requests[rid]
            if req.state != "dispatched":
                continue
            req.state = "queued"
            req.replica = None
            req.engine_rid = None
            req.queued_since = self._now()  # fresh TTL clock (re-queue)
            moved.append(req)
            if stale:
                rep.stale.append(erid)
            self.metrics.record_migrated()
            obs_emit("request_migrated", request=rid, replica=rep.index,
                     tokens=len(req.tokens), why=why)
        rep.dispatched = {}
        self._requeue_head(moved)
        return len(moved)

    def _handoff(self) -> int:
        """Disaggregated prefill→decode handoff (docs/SERVING.md): pull
        every finished prefill off the in-rotation PREFILL-role
        replicas, export its KV pages as wire blobs, and re-queue the
        request at the HEAD carrying the payloads — the next dispatch
        lands it on a decode replica that revives the pages instead of
        re-prefilling. Export failure of any kind (fault injector,
        replica error) falls back to the PR 8 replay ladder: the first
        token is already in the durable router history, so the request
        re-queues WITHOUT payloads and replays on a survivor — slower,
        never wrong, zero tokens lost."""
        moved = []
        for rep in self._replicas:
            if (rep.role != "prefill"
                    or rep.state not in (ReplicaState.OK,
                                         ReplicaState.DRAINING)):
                continue
            for erid in rep.engine.prefilled_ready():
                rid = rep.dispatched.get(erid)
                if rid is None:
                    continue  # not ours (direct engine submit)
                req = self._requests[rid]
                try:
                    req.kv_payloads = rep.engine.export_kv(erid)
                except Exception as e:  # noqa: BLE001 — replay fallback
                    obs_emit("kv_ship_failed", request=rid,
                             replica=rep.index, where="export",
                             error=f"{type(e).__name__}: {e}")
                    logger.warning(
                        "router: KV export of request %d failed on "
                        "replica %d (%s); falling back to replay "
                        "re-prefill", rid, rep.index, e)
                    try:
                        rep.engine.cancel(erid)
                    except Exception:  # noqa: BLE001
                        pass
                # drop the engine-side stub result either way: export
                # finalizes the parked copy as "prefilled", cancel as
                # "cancelled" — the router copy is the live one now
                try:
                    rep.engine.take_result(erid)
                except Exception:  # noqa: BLE001 — replica may be gone
                    pass
                rep.dispatched.pop(erid, None)
                req.state = "queued"
                req.replica = None
                req.engine_rid = None
                req.queued_since = self._now()
                moved.append(req)
                obs_emit("request_handoff", request=rid,
                         replica=rep.index,
                         shipped=req.kv_payloads is not None)
        if moved:
            self._requeue_head(moved)
        return len(moved)

    def _load(self, rep: _Replica) -> float:
        """Dispatch load score: what the health report prices. Decode
        and colocated replicas score queued + active work (slot
        pressure); PREFILL-role replicas score queued prompt TOKENS —
        prefill cost scales with tokens, not request count, so two
        8-token prompts are cheaper than one 4096-token prompt even
        though they are "two requests". Units never mix: placement
        filters candidates to one role class before comparing. A
        raising ``health()`` between probes scores infinitely loaded —
        least preferred but never a router-wide crash; the next probe
        rotates the replica out properly."""
        try:
            h = rep.engine.health()
        except Exception:  # noqa: BLE001 — sickness is the probe's call
            return float("inf")
        if rep.role == "prefill":
            return int(h.get("queue_tokens", 0))
        return int(h.get("queue_depth", 0)) + int(h.get("active", 0))

    def _pick_replica(self, req: _RouterRequest, exclude, loads):
        """Placement: ``(replica, via_affinity)`` — prefix affinity
        first (the replica whose warm trie owns this prompt's full-page
        prefix), falling back to least-loaded when the owner is rotated
        out, excluded, or unknown; ``(None, False)`` when no replica is
        in rotation (the queue waits). ``loads`` is this tick's score
        memo (one ``health()`` read per replica per tick, bumped per
        dispatch — the in-process version of scoring from the cached
        probe scrape).

        Phase-aware placement (docs/SERVING.md "Disaggregated
        prefill/decode"): requests carrying token history or shipped KV
        need a replica that DECODES, so prefill-role replicas are only
        used for them as a last resort (no other candidate — they can
        replay-decode, just not divert-park an admit-with-history);
        fresh prompts prefer prefill-role replicas when any are in
        rotation, falling back to the full fleet when the prefill tier
        is gone or saturated — degraded but never stuck."""
        # model group FIRST: cross-family dispatch is never a fallback
        # (an ERNIE replica cannot degrade-serve a GPT prompt) — the
        # exclude/refusal loop above this stays group-local by design
        candidates = [r for r in self._replicas
                      if r.state == ReplicaState.OK
                      and r.model == req.model
                      and r.index not in exclude]
        if not candidates:
            return None, False
        needs_decode = bool(req.tokens) or req.kv_payloads is not None
        tier = [r for r in candidates
                if (r.role != "prefill") == needs_decode]
        if tier:
            candidates = tier
        if req.affinity_key is not None:
            owner = self._affinity_map.get(req.affinity_key)
            for r in candidates:
                if r.index == owner:
                    return r, True
        return min(candidates,
                   key=lambda r: (loads.get(r.index, 0), r.index)), False

    def _dispatch(self) -> int:
        """Dispatch the tenant lanes onto in-rotation replicas —
        deficit round robin by default, the legacy single FIFO under
        ``dispatch="fifo"`` (and byte-equivalently under DRR when only
        the default lane exists)."""
        loads = {r.index: self._load(r) for r in self._replicas
                 if r.state == ReplicaState.OK}
        if self.dispatch_mode == "fifo":
            dispatched = self._dispatch_fifo(loads)
        else:
            dispatched = self._dispatch_drr(loads)
        self._prune_lanes()
        return dispatched

    def _dispatch_fifo(self, loads) -> int:
        """Legacy order: one global FIFO over every lane by submission
        id; a stuck head blocks everything behind it (strict arrival
        fairness, no tenant isolation — the bench's DRR baseline)."""
        dispatched = 0
        for req in self._queued():
            if self._dispatch_one(req, loads):
                dispatched += 1
            elif req.state == "queued":
                break  # preserve FIFO order past the first stuck head
        return dispatched

    def _dispatch_drr(self, loads) -> int:
        """Deficit round robin over the backlogged lanes, strict
        priority tiers first. Each round grants every still-active lane
        ``drr_quantum × weight`` deficit tokens; a lane serves its FIFO
        head while its deficit covers the head's cost (prompt + decode
        budget). A head that cannot place (every candidate full) blocks
        only ITS lane — the other tenants keep draining, which is the
        whole point. Rounds repeat until every lane is empty, blocked,
        or nothing moved."""
        dispatched = 0
        groups: Dict[int, List[_TenantLane]] = {}
        for lane in self._lanes.values():
            if lane.queue:
                groups.setdefault(lane.policy.priority, []).append(lane)
        for prio in sorted(groups, reverse=True):
            lanes = groups[prio]
            active = {lane.name for lane in lanes}
            for _ in range(self._MAX_DRR_ROUNDS):
                progress = False
                for lane in lanes:
                    if lane.name not in active:
                        continue
                    lane.deficit += self.drr_quantum * max(
                        lane.policy.weight, 1e-9)
                    while lane.queue:
                        head = lane.queue[0]
                        if head.state != "queued":  # cancelled elsewhere
                            lane.queue.pop(0)
                            progress = True
                            continue
                        cost = self._cost(head)
                        if cost > lane.deficit:
                            break  # next round adds another quantum
                        if self._dispatch_one(head, loads):
                            lane.deficit -= cost
                            lane.queue.pop(0)
                            dispatched += 1
                            progress = True
                        elif head.state == "queued":
                            # head can't place: lane waits, others go on
                            active.discard(lane.name)
                            break
                        else:  # finalized (timeout/error): drop, go on
                            lane.queue.pop(0)
                            progress = True
                    if not lane.queue:
                        active.discard(lane.name)
                        lane.deficit = 0.0  # empty lane banks nothing
                if not active or not progress:
                    break
        return dispatched

    def _try_preempt(self, req: _RouterRequest, exclude: set,
                     loads) -> bool:
        """Priority preemption (module docstring): a deadline-at-risk
        request of a preempting lane evicts the cheapest-to-replay
        in-flight request of a strictly lower-priority lane in its own
        model group. The victim is cancelled on its replica and
        re-queued at its OWN lane head carrying every delivered token as
        history — exactly the migration path, so zero tokens are lost
        and the exactly-one-result invariant is untouched. Returns True
        when a slot was freed (the caller retries placement)."""
        lane = self._lane(req.tenant)
        if not (self.preempt_enabled and lane.policy.preempts):
            return False
        if not req.deadline_s:
            return False  # no deadline -> never "at risk"
        age = self._now() - req.submit_time
        if age < self.preempt_risk_frac * req.deadline_s:
            return False
        victim = None
        for cand in self._requests.values():
            if cand.state != "dispatched" or cand.model != req.model:
                continue
            if self._lane(cand.tenant).policy.priority >= lane.policy.priority:
                continue
            if self._replicas[cand.replica].state != ReplicaState.OK:
                continue
            if victim is None or len(cand.tokens) < len(victim.tokens):
                victim = cand  # fewest emitted tokens = cheapest replay
        if victim is None:
            return False
        vrep = self._replicas[victim.replica]
        vrep.dispatched.pop(victim.engine_rid, None)
        try:
            vrep.engine.cancel(victim.engine_rid)
            res = vrep.engine.take_result(victim.engine_rid)
        except Exception:  # noqa: BLE001 — fall back to callback history
            res = None
        if res is not None:
            # engine host truth is the durable history (same re-base the
            # migration paths use); the callback stream already saw these
            victim.tokens = [int(t) for t in res.tokens]
        victim.state = "queued"
        victim.replica = None
        victim.engine_rid = None
        victim.queued_since = self._now()
        victim.preemptions += 1
        self._lane(victim.tenant).queue.insert(0, victim)
        if vrep.role != "prefill":
            loads[vrep.index] = max(0, loads.get(vrep.index, 1) - 1)
        exclude.discard(vrep.index)
        self.metrics.record_preempted(victim.tenant)
        self.metrics.record_migrated()
        obs_emit("request_preempted", request=victim.rid,
                 tenant=victim.tenant, by=req.rid,
                 by_tenant=req.tenant, replica=vrep.index,
                 tokens=len(victim.tokens))
        logger.info(
            "router: request %d (tenant %s) preempted off replica %d for "
            "deadline-at-risk request %d (tenant %s); %d tokens carried",
            victim.rid, victim.tenant, vrep.index, req.rid, req.tenant,
            len(victim.tokens))
        return True

    def _dispatch_one(self, req: _RouterRequest, loads) -> bool:
        """Try to place one request; True iff it was dispatched (a
        terminal finalize — dead fleet, bad deadline — returns False but
        leaves ``req.state`` finished, so the caller drops it)."""
        exclude = set()
        refused = None     # last ValueError across candidates
        only_refusals = True  # no candidate was merely full/draining
        while True:
            rep, via_affinity = self._pick_replica(req, exclude, loads)
            if rep is None and not only_refusals:
                # capacity, not validity, is the problem: a preempting
                # lane may evict lower-priority in-flight work to make
                # room (then retry this same placement loop once)
                if self._try_preempt(req, exclude, loads):
                    only_refusals = True
                    refused = None
                    continue
            if rep is None:
                if refused is not None and only_refusals and exclude:
                    # EVERY in-rotation replica judged the request
                    # inadmissible (not full — invalid): exactly one
                    # terminal result, loudly, as an error. If any
                    # candidate was merely full, the request WAITS —
                    # capacity may free up.
                    logger.error(
                        "router: request %d rejected by every replica "
                        "(%s); finalizing as error", req.rid, refused)
                    self._finalize(req, "error")
                return False
            kw = dict(req.kw)
            if req.deadline_s:
                remaining = req.deadline_s - (self._now() - req.submit_time)
                if remaining <= 0:
                    self._finalize(req, "timeout")
                    obs_emit("request_timeout", request=req.rid,
                             where="router_dispatch")
                    self.metrics.record_shed()
                    return False
                # forward the REMAINING budget so the global deadline
                # holds across queue time and migrations
                kw["deadline_s"] = remaining
            try:
                erid = rep.engine.submit(
                    req.prompt, on_token=self._make_cb(req),
                    rng_key=req.rng_key,
                    history=req.tokens if req.tokens else None,
                    kv_payloads=req.kv_payloads, **kw)
            except QueueFull:
                only_refusals = False
                exclude.add(rep.index)
                continue
            except ShuttingDown:
                rep.state = ReplicaState.DRAINING
                obs_emit("replica_out", replica=rep.index,
                         reason="draining")
                only_refusals = False
                exclude.add(rep.index)
                continue
            except ValueError as e:
                if req.kv_payloads is not None:
                    # the shipped pages failed decode-side validation
                    # (wire checksum, page-size mismatch): drop the
                    # blobs and retry THIS SAME candidate set as a
                    # plain replay — the replica is healthy, the
                    # payload was bad, and the history already covers
                    # the prefill
                    req.kv_payloads = None
                    obs_emit("kv_ship_failed", request=req.rid,
                             replica=rep.index, where="admit",
                             error=f"{type(e).__name__}: {e}")
                    logger.warning(
                        "router: replica %d rejected shipped KV for "
                        "request %d (%s); replaying without it",
                        rep.index, req.rid, e)
                    continue
                # THIS replica can't legally admit it (e.g. a smaller
                # survivor whose budget a migrated history exceeds on a
                # heterogeneous fleet) — try the others before giving up
                refused = e
                exclude.add(rep.index)
                continue
            req.kv_payloads = None
            req.state = "dispatched"
            req.replica = rep.index
            req.engine_rid = erid
            req.dispatches += 1
            # bump the memo in the replica's own load units: tokens
            # for a prefill target, requests otherwise (_load docstring)
            loads[rep.index] = loads.get(rep.index, 0) + (
                int(req.prompt.size) if rep.role == "prefill" else 1)
            rep.dispatched[erid] = req.rid
            if req.affinity_key is not None:
                self._affinity_map.setdefault(req.affinity_key, rep.index)
                # bounded pin table: the warm caches the pins point at
                # are themselves LRU, so dropping the OLDEST pin only
                # costs a likely-already-cold locality hint — never
                # correctness — and the router's memory stays constant
                # under millions of distinct prefixes
                while len(self._affinity_map) > self._AFFINITY_CAP:
                    self._affinity_map.pop(next(iter(self._affinity_map)))
            self.metrics.record_dispatch(via_affinity, req.tenant)
            return True

    def _make_cb(self, req: _RouterRequest):
        """Per-dispatch ``on_token`` wrapper: append to the router's
        durable history (the failover replay source), record TTFT, and
        forward to the user's callback under the ROUTER request id."""
        def cb(_engine_rid, tok, finished):
            req.tokens.append(int(tok))
            if req.first_token_time is None:
                req.first_token_time = self._now()
                self.metrics.observe_ttft(
                    req.first_token_time - req.submit_time)
            if req.on_token is not None:
                req.on_token(req.rid, int(tok), bool(finished))
        return cb

    def _tick_replicas(self):
        """Tick every live replica once: the kill injector and
        ``RecoveryExhausted`` feed the dead path; a recovered tick
        re-bases request histories from engine host truth; finished
        engine results finalize their router requests."""
        finished = migrated = 0
        for rep in self._replicas:
            if rep.state in (ReplicaState.DEAD, ReplicaState.SUSPECT):
                continue  # suspects are not ticked (partition semantics)
            try:
                faults.on_router_tick(rep.index, self._ticks)
                with span("router.tick_replica", replica=rep.index):
                    summary = rep.engine.step()
            except ReplicaKilled as e:
                migrated += len(rep.dispatched)
                self._mark_dead(rep, str(e))
                continue
            except RecoveryExhausted as e:
                migrated += len(rep.dispatched)
                self._mark_dead(rep, f"RecoveryExhausted: {e}")
                continue
            if summary.get("recovered"):
                # in-place recovery rolled host truth back: re-base the
                # durable histories on it (stream-offset re-sync)
                for erid, rid in rep.dispatched.items():
                    toks = rep.engine.emitted_tokens(erid)
                    if toks is not None:
                        self._requests[rid].tokens = list(toks)
            finished += self._collect(rep)
        return finished, migrated

    def _collect(self, rep: _Replica) -> int:
        """Pull finished engine results for this replica's dispatches and
        finalize them (exactly once — the dispatched-map entry is the
        single path from engine result to router result)."""
        done = 0
        continued = []
        for erid in list(rep.dispatched):
            res = rep.engine.take_result(erid)
            if res is None:
                continue
            rid = rep.dispatched.pop(erid)
            req = self._requests[rid]
            req.tokens = [int(t) for t in res.tokens]
            if (res.finish_reason == "shutdown" and not self._shutting_down
                    and any(r.state == ReplicaState.OK
                            for r in self._replicas)):
                # an externally-draining replica ran out of grace with
                # this request unfinished: its partial tokens are all
                # delivered, so CONTINUE it on a survivor instead of
                # surfacing a truncated result
                req.state = "queued"
                req.replica = None
                req.engine_rid = None
                req.queued_since = self._now()
                continued.append(req)
                self.metrics.record_migrated()
                obs_emit("request_migrated", request=rid,
                         replica=rep.index, tokens=len(req.tokens),
                         why="drain_expired")
                continue
            self._finalize(req, res.finish_reason)
            done += 1
        if continued:
            # head-of-lane re-queue in submission order — the same
            # fairness _migrate_all gives dead-replica migrations
            self._requeue_head(continued)
        return done

    def _strand_if_no_replicas(self) -> int:
        """Lost-fleet backstop — ``drain()`` must terminate, not hang:

        - every replica dead → everything left finalizes as ``"error"``
          with a ``router_stranded`` event (the operator lost the fleet);
        - every replica dead OR draining → nothing will ever accept a
          dispatch again, so QUEUED requests finalize as ``"shutdown"``
          (dispatched ones keep ticking — their draining replicas retire
          them under the engine grace window).

        A suspect replica blocks both: it may rejoin. On a
        heterogeneous fleet the judgment is PER MODEL GROUP — dispatch
        never crosses families, so a group with no live replicas has
        stranded its requests even while other families keep serving."""
        live = {ReplicaState.OK, ReplicaState.SUSPECT}
        by_model: Dict[str, set] = {}
        for r in self._replicas:
            by_model.setdefault(r.model, set()).add(r.state)
        dead_models, closed_models = set(), set()
        for m, states in by_model.items():
            if states & live:
                continue
            (dead_models if states == {ReplicaState.DEAD}
             else closed_models).add(m)
        if not dead_models and not closed_models:
            return 0
        stranded = 0
        for lane in self._lanes.values():
            keep: List[_RouterRequest] = []
            for req in lane.queue:
                # a family the fleet no longer reports counts as dead
                if req.model in dead_models or req.model not in by_model:
                    self._finalize(req, "error")
                    stranded += 1
                elif req.model in closed_models:
                    self._finalize(req, "shutdown")
                    stranded += 1
                else:
                    keep.append(req)
            lane.queue = keep
        errored = 0
        for req in self._requests.values():
            if (req.state == "dispatched"
                    and req.model in dead_models):  # died with the group
                self._finalize(req, "error")
                stranded += 1
                errored += 1
        if dead_models and (errored or stranded):
            obs_emit("router_stranded", requests=stranded,
                     models=sorted(dead_models),
                     router=self.metrics.router_label)
            logger.error(
                "router: every replica serving %s is dead; %d "
                "request(s) stranded", sorted(dead_models), stranded)
        return stranded

    def _finalize(self, req: _RouterRequest, reason: str) -> None:
        """Record THE terminal result for one request (idempotent — the
        exactly-one-result invariant's last line of defense)."""
        if req.state == "finished":
            return
        req.state = "finished"
        now = self._now()
        self._results[req.rid] = ServingResult(
            id=req.rid, prompt=req.prompt,
            tokens=np.asarray(req.tokens, np.int32),
            finish_reason=reason,
            ttft_s=(req.first_token_time or now) - req.submit_time,
            latency_s=now - req.submit_time,
        )
        self.metrics.record_finished(reason, now - req.submit_time)
        if req.tokens:
            self.metrics.record_tenant_tokens(req.tenant, len(req.tokens))

    # ------------------------------------------------------- fleet membership

    def add_replica(self, engine) -> int:
        """Join a new replica to the rotation (the autoscaler's scale-up
        seam). The engine enters as ``OK`` and is eligible for the very
        next dispatch; per-model submit limits and the affinity page
        granularity tighten to include it. Returns the replica index."""
        rep = _Replica(index=len(self._replicas), engine=engine,
                       role=getattr(engine, "role", "both"),
                       model=getattr(engine, "model_family", "gpt"))
        self._replicas.append(rep)
        lim = getattr(engine, "submit_limit", None)
        if lim is None:
            lim = min(engine.cache_len,
                      engine.model.cfg.max_position_embeddings)
        self._limits[rep.model] = min(
            self._limits.get(rep.model, lim), lim)
        if rep.model == self._default_model:
            self._limit = self._limits[self._default_model]
        if getattr(engine, "paged", False):
            ps = engine.page_size
            self._affinity_page = (min(self._affinity_page, ps)
                                   if self._affinity_page else ps)
        obs_emit("replica_added", replica=rep.index, model=rep.model,
                 role=rep.role, router=self.metrics.router_label)
        logger.info("router: replica %d joined (model=%s role=%s)",
                    rep.index, rep.model, rep.role)
        return rep.index

    def remove_replica(self, index: int) -> bool:
        """Retire a drained replica from the rotation (the autoscaler's
        scale-down seam). Refuses — returns False — while the replica is
        still ``OK`` or holds dispatched work: drain it first
        (``engine.request_shutdown``) so no request is stranded.
        Indices of the surviving replicas are unchanged."""
        if not 0 <= index < len(self._replicas):
            return False
        rep = self._replicas[index]
        if rep.state == ReplicaState.OK or rep.dispatched:
            return False
        rep.state = ReplicaState.DEAD
        self._affinity_map = {k: v for k, v in self._affinity_map.items()
                              if v != index}
        obs_emit("replica_removed", replica=index,
                 router=self.metrics.router_label)
        logger.info("router: replica %d removed from rotation", index)
        return True

    # ---------------------------------------------------------- introspection

    @property
    def replica_states(self) -> List[str]:
        """Per-replica lifecycle state, by index."""
        return [r.state for r in self._replicas]

    def models(self) -> Dict[str, Dict]:
        """Per-family replica-group view — what ``/v1/models`` serves:
        ``{family: {replicas, live, capabilities, limit}}``.
        ``capabilities`` comes from the first replica of the group that
        advertises any (None for pre-protocol engine doubles);
        ``limit`` is the group's smallest rejected input size."""
        out: Dict[str, Dict] = {}
        for rep in self._replicas:
            info = out.setdefault(rep.model, {
                "replicas": [], "live": 0, "capabilities": None,
                "limit": self._limits.get(rep.model, self._limit)})
            info["replicas"].append(rep.index)
            if rep.state in (ReplicaState.OK, ReplicaState.SUSPECT):
                info["live"] += 1
            if info["capabilities"] is None:
                caps = getattr(rep.engine, "capabilities", None)
                if caps is not None:
                    info["capabilities"] = caps.as_dict()
        return out

    @property
    def queue_depth(self) -> int:
        """Requests waiting across every tenant lane."""
        return sum(len(lane.queue) for lane in self._lanes.values())

    @property
    def in_flight(self) -> int:
        """Requests currently dispatched to a replica."""
        return sum(r.state == "dispatched" for r in self._requests.values())
