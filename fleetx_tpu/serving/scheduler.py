"""Continuous-batching scheduler: admission queue + request records.

The policy seam of the serving stack. ``FIFOScheduler`` is deliberately
minimal — arrival order in, arrival order out — because admission policy
is the part operators replace first (priority tiers, per-tenant fairness,
SLA-aware preemption all slot in here without touching the engine): the
engine only asks "how deep is the queue" and "who is next".
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Callable, List, Optional

import jax
import numpy as np

__all__ = ["Request", "FIFOScheduler"]


@dataclasses.dataclass
class Request:
    """One in-flight serving request and its per-request decode knobs.

    ``rng_key`` is this request's OWN sampling stream (derived from the
    engine base key and the request id, or an explicit per-request seed),
    so repeated identical submissions sample independently. ``on_token``
    streams each decoded token as ``on_token(request_id, token, finished)``
    the tick it is produced.

    Lifecycle (``phase``): ``queued`` → [``prefilling``] → ``active`` →
    ``finished``. The ``prefilling`` state exists only under chunked
    prefill (``FLEETX_SERVING_PREFILL_CHUNK`` > 0, docs/SERVING.md): a
    long prompt's KV ingestion is spread over scheduler ticks — one
    chunk per tick, interleaved with the batched decode — with
    ``prefill_pos`` tracking how many prompt tokens (shared prefix
    included) have been written so far and, on the slot path,
    ``chunk_cache`` holding the batch-1 working cache the chunks
    accumulate into before the final scatter."""

    id: int
    prompt: np.ndarray  # [prompt_len] int32, no padding
    max_new_tokens: int
    min_new_tokens: int
    eos_token_id: int  # -1 disables EOS retirement
    greedy: bool
    temperature: float
    top_k: int  # 0 = no filter (engine normalizes >=vocab to 0)
    top_p: float
    rng_key: jax.Array
    on_token: Optional[Callable[[int, int, bool], None]] = None
    submit_time: float = 0.0
    # admission-control limits, resolved by the engine at submit (0 = off):
    # queue_ttl_s bounds time WAITING for a slot, deadline_s bounds the
    # whole submit->finish lifetime; both retire as finish_reason="timeout"
    queue_ttl_s: float = 0.0
    deadline_s: float = 0.0
    # filled in by the engine over the request's lifecycle
    slot: Optional[int] = None
    admit_time: Optional[float] = None
    first_token_time: Optional[float] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    # chunked-prefill lifecycle (class docstring): covered by the
    # engine's transactional-tick snapshot so a rolled-back tick
    # restores chunk progress exactly
    phase: str = "queued"
    prefill_pos: int = 0
    chunk_cache: Any = dataclasses.field(default=None, repr=False)
    # speculative-decoding draft accounting (docs/SERVING.md): lifetime
    # proposed/accepted draft tokens for THIS request — also snapshot-
    # covered, so a tick that faults mid-verify rolls its counts back
    # with its tokens and recovery replay stays byte-identical
    spec_proposed: int = 0
    spec_accepted: int = 0
    # disaggregated serving (docs/SERVING.md): decoded page payloads a
    # PREFILL-role replica shipped for this prompt — consumed (and
    # cleared) by the engine's shipped-KV admission; a request whose
    # shipped admission rolled back re-admits through the replay seam
    kv_payloads: Any = dataclasses.field(default=None, repr=False)

    @property
    def prompt_len(self) -> int:
        """Number of real prompt tokens."""
        return int(self.prompt.shape[0])


class FIFOScheduler:
    """First-in-first-out admission queue over :class:`Request`."""

    def __init__(self):
        self._queue: collections.deque = collections.deque()

    def submit(self, request: Request) -> None:
        """Append a request to the tail of the admission queue."""
        self._queue.append(request)

    def pop_next(self) -> Optional[Request]:
        """Next request to admit (None when the queue is empty)."""
        return self._queue.popleft() if self._queue else None

    def requeue(self, request: Request) -> None:
        """Put a request back at the HEAD of the queue — the recovery
        path for a mid-prefill (chunked) request whose partial KV died
        with the device cache: it was the FIFO head when admitted and no
        token has been emitted, so restarting it from the front preserves
        both arrival order and byte-identity."""
        self._queue.appendleft(request)

    def peek(self) -> Optional[Request]:
        """Next request WITHOUT removing it — the page-granular admission
        path inspects the head's prompt (pages needed vs pages free) and
        only pops once admission is certain, so a too-big head blocks
        FIFO order instead of being silently dropped or reordered."""
        return self._queue[0] if self._queue else None

    def remove(self, request_id: int) -> Optional[Request]:
        """Pull one queued request out by id (None if not queued) — the
        cancel() path for requests that never won a slot."""
        for r in self._queue:
            if r.id == request_id:
                self._queue.remove(r)
                return r
        return None

    def snapshot(self) -> tuple:
        """Immutable view of the queue for the engine's transactional tick
        (crash-safe serving, docs/RESILIENCE.md): captured before device
        work, handed back to :meth:`restore` if the tick fails. Replacement
        schedulers must implement both so a rolled-back tick restores THEIR
        internal order too."""
        return tuple(self._queue)

    def restore(self, snap: tuple) -> None:
        """Reinstate a queue captured by :meth:`snapshot` (the requests
        themselves are restored field-by-field by the engine)."""
        self._queue = collections.deque(snap)

    def drain_all(self) -> List[Request]:
        """Remove and return every queued request (graceful-drain deadline:
        whatever never won a slot is retired with empty tokens)."""
        out = list(self._queue)
        self._queue.clear()
        return out

    def pop_expired(self, now: float) -> List[Request]:
        """Remove and return every queued request whose queue-TTL or total
        deadline has passed at ``now``. Arrival order is preserved for the
        survivors; a queue with no limits configured costs one scan."""
        if not any(r.queue_ttl_s or r.deadline_s for r in self._queue):
            return []
        dead, keep = [], collections.deque()
        for r in self._queue:
            waited = now - r.submit_time
            if ((r.queue_ttl_s and waited > r.queue_ttl_s)
                    or (r.deadline_s and waited > r.deadline_s)):
                dead.append(r)
            else:
                keep.append(r)
        self._queue = keep
        return dead

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def queue_depth(self) -> int:
        """Requests waiting for a slot."""
        return len(self._queue)

    def queued_tokens(self) -> int:
        """Prompt tokens waiting in the queue — the load signal that
        prices a PREFILL-role replica (prefill cost scales with tokens,
        not request count; docs/SERVING.md "Disaggregated
        prefill/decode"). The engine adds in-flight chunked-prefill
        remainders on top."""
        return sum(r.prompt_len for r in self._queue)
