"""HTTP RPC server wrapping one ``ServingEngine`` for a remote router.

One replica process runs one engine behind this server; the router's
:class:`~fleetx_tpu.serving.api.replica_client.ReplicaClient` in the
front-door process drives it through the exact engine surface the
in-process router consumes (docs/SERVING.md "Deployment"):

====================  =====================================================
``GET  /healthz``     The engine's drain-aware ``health()`` dict — the SAME
                      body the obs server serves, so one scrape contract
                      covers both ports.
``GET  /rpc/spec``    Construction-time facts the router reads as replica
                      attributes: ``role``, ``paged``, ``page_size``,
                      ``cache_len``, ``max_position_embeddings``, plus the
                      model's ``vocab_size`` and ``eos_token_id`` for the
                      front door.
``POST /rpc/submit``  ``submit(...)`` with history / kv_payloads / rng-key
                      codecs (wire.py); typed errors cross as
                      ``error_kind`` bodies.
``POST /rpc/step``    One engine tick; returns the summary PLUS the
                      ``on_token`` events the tick emitted (the client
                      replays them into the router's callbacks in order —
                      streaming crosses the boundary batched per tick, in
                      the same order it was emitted).
``POST /rpc/*``       ``take_result`` / ``cancel`` / ``emitted_tokens`` /
                      ``prefilled_ready`` / ``export_kv`` /
                      ``request_shutdown`` / ``declare_dead``.
====================  =====================================================

The engine is single-threaded by design; ``ThreadingHTTPServer``
handlers serialize every engine touch through one lock, so concurrent
router RPCs (or a stray healthz scrape mid-tick) cannot interleave
engine state.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from fleetx_tpu.obs.httpd import HttpDaemon, JsonHandler
from fleetx_tpu.serving.api import wire
from fleetx_tpu.utils.log import logger

__all__ = ["ReplicaServer"]


class _ReplicaHandler(JsonHandler):
    """Routes ``/healthz`` + ``/rpc/*`` onto the wrapped engine."""

    server_version = "fleetx-replica/1"

    def _ctx(self) -> "ReplicaServer":
        return self.server.context["replica"]

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        """Read-only routes: health scrape + replica spec."""
        path = self.path.split("?", 1)[0].rstrip("/")
        ctx = self._ctx()
        if path == "/healthz":
            body = ctx.health()
            self._send_json(200 if body.get("state") == "ok" else 503, body)
        elif path == "/rpc/spec":
            self._send_json(200, ctx.spec())
        else:
            self._send_json(404, {"error": f"unknown path {self.path!r}",
                                  "error_kind": "not_found"})

    def do_POST(self) -> None:  # noqa: N802 — http.server contract
        """Mutating RPC routes (everything engine-state-touching)."""
        path = self.path.split("?", 1)[0].rstrip("/")
        ctx = self._ctx()
        try:
            payload = self._read_json()
        except ValueError as e:
            self._send_json(400, {"error": str(e),
                                  "error_kind": "value_error"})
            return
        method = ctx.rpc_methods.get(path)
        if method is None:
            self._send_json(404, {"error": f"unknown rpc {self.path!r}",
                                  "error_kind": "not_found"})
            return
        try:
            self._send_json(200, method(payload))
        except Exception as e:  # noqa: BLE001 — typed over the wire
            kind = wire.kind_for_exception(e)
            code = {"queue_full": 429, "shutting_down": 503,
                    "value_error": 400, "key_error": 404,
                    "recovery_exhausted": 500}.get(kind, 500)
            if kind == "internal":
                logger.exception("replica rpc %s failed", path)
            self._send_json(code, {"error": f"{type(e).__name__}: {e}",
                                   "error_kind": kind})


class ReplicaServer(HttpDaemon):
    """The per-replica RPC server: one engine, one lock, one port.

    ``ReplicaServer(engine).start()`` and hand ``url`` to the router
    process; ``stop()`` (or process death) makes every client RPC fail
    as ``ConnectionError``, which the router maps to its probe-escalate
    → dead → migrate ladder."""

    def __init__(self, engine, port: int = 0, host: str = "127.0.0.1"):
        super().__init__(_ReplicaHandler, port=port, host=host,
                         context={"replica": self},
                         thread_name="fleetx-replica-rpc")
        self.engine = engine
        self._lock = threading.Lock()
        # on_token events buffered between /rpc/step responses, in
        # emission order: [(engine_rid, token, finished), ...]
        self._events: List[Tuple[int, int, bool]] = []
        self.rpc_methods = {
            "/rpc/submit": self._rpc_submit,
            "/rpc/step": self._rpc_step,
            "/rpc/take_result": self._rpc_take_result,
            "/rpc/cancel": self._rpc_cancel,
            "/rpc/emitted_tokens": self._rpc_emitted_tokens,
            "/rpc/prefilled_ready": self._rpc_prefilled_ready,
            "/rpc/export_kv": self._rpc_export_kv,
            "/rpc/request_shutdown": self._rpc_request_shutdown,
            "/rpc/declare_dead": self._rpc_declare_dead,
        }

    # ------------------------------------------------------------- routes

    def health(self) -> Dict:
        """The engine's ``health()`` dict (the ``/healthz`` contract)."""
        with self._lock:
            return self.engine.health()

    def spec(self) -> Dict:
        """Replica construction facts the client exposes as attributes."""
        eng = self.engine
        return {
            "role": eng.role,
            "paged": bool(eng.paged),
            "page_size": int(eng.page_size) if eng.paged else None,
            "cache_len": int(eng.cache_len),
            "max_position_embeddings":
                int(eng.model.cfg.max_position_embeddings),
            "vocab_size": int(eng.model.cfg.vocab_size),
            "eos_token_id": (None if eng.gen_cfg.eos_token_id is None
                             else int(eng.gen_cfg.eos_token_id)),
            "slots": int(eng.slots),
        }

    def _on_token(self, rid: int, tok: int, finished: bool) -> None:
        """Engine ``on_token`` sink: buffer for the next step response
        (callbacks fire inside the engine tick, under the lock)."""
        self._events.append((int(rid), int(tok), bool(finished)))

    def _rpc_submit(self, p: Dict) -> Dict:
        """``submit`` with the wire codecs; returns the engine rid."""
        kw = dict(p.get("kw") or {})
        with self._lock:
            rid = self.engine.submit(
                p["prompt"],
                on_token=self._on_token,
                rng_key=wire.rng_key_from_wire(p.get("rng_key")),
                history=p.get("history"),
                kv_payloads=wire.b64_blobs_decode(p.get("kv_payloads")),
                **kw)
        return {"id": int(rid)}

    def _rpc_step(self, p: Dict) -> Dict:
        """One tick; the response carries the tick's summary and every
        ``on_token`` event it emitted, in order."""
        with self._lock:
            self._events = []
            summary = self.engine.step()
            events, self._events = self._events, []
        return {"summary": _json_summary(summary), "events": events}

    def _rpc_take_result(self, p: Dict) -> Dict:
        with self._lock:
            res = self.engine.take_result(int(p["id"]))
        return {"result": wire.result_to_wire(res)}

    def _rpc_cancel(self, p: Dict) -> Dict:
        with self._lock:
            return {"cancelled": bool(self.engine.cancel(int(p["id"])))}

    def _rpc_emitted_tokens(self, p: Dict) -> Dict:
        with self._lock:
            toks = self.engine.emitted_tokens(int(p["id"]))
        return {"tokens": None if toks is None else [int(t) for t in toks]}

    def _rpc_prefilled_ready(self, p: Dict) -> Dict:
        with self._lock:
            return {"ids": [int(r) for r in self.engine.prefilled_ready()]}

    def _rpc_export_kv(self, p: Dict) -> Dict:
        with self._lock:
            blobs = self.engine.export_kv(int(p["id"]))
        return {"payloads": wire.b64_blobs_encode(blobs)}

    def _rpc_request_shutdown(self, p: Dict) -> Dict:
        grace = p.get("grace_s")
        with self._lock:
            self.engine.request_shutdown(
                None if grace is None else float(grace))
        return {"ok": True}

    def _rpc_declare_dead(self, p: Dict) -> Dict:
        with self._lock:
            self.engine.declare_dead()
        return {"ok": True}


def _json_summary(summary: Dict) -> Dict:
    """Engine step summaries hold ints/lists/bools; coerce defensively
    so a numpy scalar sneaking in can never break the wire."""
    out = {}
    for k, v in summary.items():
        if isinstance(v, (list, tuple)):
            out[k] = [int(x) for x in v]
        elif isinstance(v, bool) or v is None:
            out[k] = v
        else:
            try:
                out[k] = int(v)
            except (TypeError, ValueError):
                out[k] = str(v)
    return out
