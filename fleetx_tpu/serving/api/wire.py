"""JSON wire codecs + HTTP transport for the cross-process replica RPC.

The router ⇄ replica contract (docs/SERVING.md "Deployment") rides
plain JSON over HTTP so any side can be curl-debugged. Three payload
families need codecs beyond JSON primitives:

- **RNG keys** — the router pins each request's sampling stream to one
  ``jax.random`` key and re-sends the SAME key at every migration
  (RNG-position-exact failover). The key's raw ``uint32`` words
  round-trip losslessly through a JSON int list, so seeded sampling is
  byte-identical across the process boundary.
- **KV page blobs** — ``export_kv`` ships crc32-trailed
  ``HostPageStore.payload_to_bytes`` v2 wire bytes; they cross HTTP
  base64-encoded, UNPARSED — the decode replica's ``submit`` is the one
  place that validates the checksum, same as in-process.
- **Results** — ``ServingResult`` flattens to a dict (arrays →
  lists) and rebuilds on the client, so the router's ``drain()`` hands
  back the same dataclass either way.

Errors cross as ``{"error_kind": ..., "error": ...}`` bodies with a
4xx/5xx status; :func:`raise_for_kind` rebuilds the typed exception
(``QueueFull``, ``ShuttingDown``, ``ValueError``, ...) so the router's
existing except-clauses fire identically for a remote replica.

:func:`rpc_call` is the one transport function: POST/GET with a
timeout, the ``faults.on_rpc`` chaos seam in front, and every network
failure normalized to ``ConnectionError`` — the replica client maps
that onto the router's dead-replica/replay fallbacks.
"""

from __future__ import annotations

import base64
import json
import urllib.error
import urllib.request
from typing import Dict, List, Optional

import numpy as np

from fleetx_tpu.resilience.faults import faults

__all__ = [
    "b64_blobs_decode",
    "b64_blobs_encode",
    "raise_for_kind",
    "result_from_wire",
    "result_to_wire",
    "rng_key_from_wire",
    "rng_key_to_wire",
    "rpc_call",
]


def rng_key_to_wire(rng_key) -> Optional[List[int]]:
    """A jax PRNG key as a JSON-safe list of uint32 words (None passes
    through). Typed (new-style) keys flatten through their raw key
    data; raw ``uint32`` key arrays pass as-is — both reconstruct to
    the RAW layout :func:`rng_key_from_wire` returns."""
    if rng_key is None:
        return None
    import jax

    try:
        arr = np.asarray(rng_key)
        if arr.dtype != np.uint32:
            raise TypeError(f"not a raw key array ({arr.dtype})")
    except TypeError:  # a typed key (opaque dtype): flatten its data
        arr = np.asarray(jax.random.key_data(rng_key))
    return [int(x) for x in arr.reshape(-1)]


def rng_key_from_wire(words) -> Optional[object]:
    """Rebuild the raw ``uint32`` key array a wire list encodes (None
    passes through). The engine's sampling path accepts raw key arrays,
    and uint32 ints round-trip JSON exactly — so the remote stream is
    bit-identical to the in-process one."""
    if words is None:
        return None
    import jax.numpy as jnp

    return jnp.asarray(np.asarray(words, np.uint32))


def b64_blobs_encode(blobs) -> Optional[List[str]]:
    """KV page wire blobs (bytes) → base64 strings (None passes
    through). The crc32 trailer travels inside the blob untouched."""
    if blobs is None:
        return None
    return [base64.b64encode(bytes(b)).decode("ascii") for b in blobs]


def b64_blobs_decode(items) -> Optional[List[bytes]]:
    """Base64 strings → the original wire blobs, still UNVALIDATED —
    ``submit(kv_payloads=...)`` owns the checksum check, so a corrupt
    ship fails exactly where the in-process path fails."""
    if items is None:
        return None
    return [base64.b64decode(s) for s in items]


def result_to_wire(res) -> Optional[Dict]:
    """``ServingResult`` → JSON dict (None while in flight)."""
    if res is None:
        return None
    return {
        "id": int(res.id),
        "prompt": [int(t) for t in np.asarray(res.prompt).reshape(-1)],
        "tokens": [int(t) for t in np.asarray(res.tokens).reshape(-1)],
        "finish_reason": str(res.finish_reason),
        "ttft_s": float(res.ttft_s),
        "latency_s": float(res.latency_s),
    }


def result_from_wire(d: Optional[Dict]):
    """JSON dict → ``ServingResult`` (None passes through)."""
    if d is None:
        return None
    from fleetx_tpu.serving.engine import ServingResult

    return ServingResult(
        id=int(d["id"]),
        prompt=np.asarray(d["prompt"], np.int32),
        tokens=np.asarray(d["tokens"], np.int32),
        finish_reason=str(d["finish_reason"]),
        ttft_s=float(d["ttft_s"]),
        latency_s=float(d["latency_s"]),
    )


# error_kind strings ↔ the exceptions the router's fallbacks key on
_KIND_TO_EXC = None


def _kinds():
    """Lazy error-kind table (serving.engine imports jax — keep the
    wire module importable without pulling the engine first)."""
    global _KIND_TO_EXC
    if _KIND_TO_EXC is None:
        from fleetx_tpu.serving.engine import (
            QueueFull,
            RecoveryExhausted,
            ShuttingDown,
        )

        _KIND_TO_EXC = {
            "queue_full": QueueFull,
            "shutting_down": ShuttingDown,
            "recovery_exhausted": RecoveryExhausted,
            "value_error": ValueError,
            "key_error": KeyError,
        }
    return _KIND_TO_EXC


def kind_for_exception(exc) -> str:
    """The wire ``error_kind`` for an exception the replica raised
    (unknown types cross as ``"internal"`` — the client surfaces them
    as ``RuntimeError``, which the router treats as a sick replica)."""
    for kind, cls in _kinds().items():
        if isinstance(exc, cls):
            return kind
    return "internal"


def raise_for_kind(kind: str, message: str) -> None:
    """Re-raise the typed exception an ``error_kind`` body encodes, so
    the router's except-clauses (``QueueFull`` → try another replica,
    ``ValueError`` → drop shipped KV / exclude, ``RecoveryExhausted``
    → mark dead) behave identically across the process boundary."""
    exc = _kinds().get(kind, RuntimeError)
    raise exc(message)


def rpc_call(url: str, payload: Optional[Dict] = None, *,
             timeout_s: float = 10.0, method: str = "rpc") -> Dict:
    """One RPC: POST ``payload`` as JSON (GET when None) to ``url``,
    return the parsed JSON body. The ``faults.on_rpc`` chaos seam runs
    first (drop/delay injection). An ``error_kind`` body re-raises its
    typed exception regardless of status code; transport-level failures
    (refused, reset, timeout, DNS) normalize to ``ConnectionError`` so
    callers have ONE network-failure type to map onto the router's
    dead-replica fallbacks."""
    faults.on_rpc(method)
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            body = json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        # a structured replica-side error (4xx/5xx with a JSON body)
        try:
            body = json.loads(e.read() or b"{}")
        except (ValueError, OSError):
            raise ConnectionError(
                f"rpc {method} to {url}: HTTP {e.code} with no JSON body")
        if isinstance(body, dict) and "error_kind" in body:
            raise_for_kind(body["error_kind"], body.get("error", ""))
        # a JSON body WITHOUT error_kind on a non-200 is data, not an
        # error: /healthz serves 503 with the draining/dead health dict,
        # and the probe needs that body (draining ≠ dead)
        return body
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        # refused/reset/timeout — the replica process is unreachable
        raise ConnectionError(
            f"rpc {method} to {url} failed: {type(e).__name__}: {e}")
    if isinstance(body, dict) and "error_kind" in body:
        raise_for_kind(body["error_kind"], body.get("error", ""))
    return body
