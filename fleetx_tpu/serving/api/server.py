"""OpenAI-compatible HTTP front door over a ServingEngine or Router.

``ApiServer(target).start()`` puts the serving stack on a port any
stock OpenAI client or curl can talk to (docs/SERVING.md
"Deployment"):

- ``POST /v1/chat/completions`` — chat shape, ``stream: true`` serves
  Server-Sent Events (one ``chat.completion.chunk`` per decoded token,
  closed by ``data: [DONE]``), ``stream: false`` aggregates.
- ``POST /v1/completions`` — classic text-completion shape, same
  streaming contract (``text_completion`` chunks).
- ``POST /v1/embeddings`` — fronts a KV-free embedding family on a
  heterogeneous fleet (float vectors in, float vectors out; 404 when
  no such family is served).
- ``GET /v1/models`` — the served model listing. Plain-engine targets
  report the one configured ``model_id``; a model-aware router derives
  the list from its replica groups (every family + the ``model_id``
  alias for the default group), with replica indices and capability
  flags as extension fields.
- ``GET /healthz`` — engine ``health()`` dict, or the router aggregate.

On a heterogeneous fleet the ``model`` field of a completion request
may name any served family (docs/SERVING.md "Heterogeneous fleet");
it rides ``submit(model=...)`` so dispatch stays group-local. The
configured ``model_id`` keeps addressing the default group, so stock
single-model clients never change.

``target`` is anything with the ``submit / step / take_result /
cancel`` surface — a :class:`~fleetx_tpu.serving.engine.ServingEngine`,
a :class:`~fleetx_tpu.serving.router.ServingRouter` over in-process
engines, or a router over
:class:`~fleetx_tpu.serving.api.replica_client.ReplicaClient` proxies
(the ``tools/serve.py`` fleet shape). A background DRIVER thread ticks
the target while requests are in flight; every target touch — submit,
step, take_result, cancel — serializes through one lock, because
handler threads are many and the engine is single-threaded by design.

Tokens in, tokens out: the default codec treats message/prompt text as
whitespace-separated token ids ("12 7 3") and decodes generated ids to
the same form (each SSE chunk also carries the raw id in an ``token``
extension field, which is what the byte-identity tests compare).
Passing real ``encode``/``decode`` callables at construction swaps in
an actual tokenizer without touching the protocol layer.

Request validation happens BEFORE the engine sees anything: malformed
bodies, empty prompts, bad sampling params and unknown models return
structured 4xx JSON (OpenAI error shape), never an engine exception.
Engine-side refusals map onto HTTP the same way the router maps them
onto fallbacks: ``QueueFull`` → 429, ``ShuttingDown`` → 503,
``ValueError`` → 400.

Sampling params map onto the engine's per-request overrides:
``temperature`` 0/unset → greedy, > 0 → the sampling path with
``top_p``/``top_k``; ``seed`` pins the request's RNG stream (same
seed → byte-identical tokens, across replicas and migrations);
``max_tokens`` → ``max_length``; ``stop_token_id`` (extension) →
``eos_token_id``.

``FLEETX_API_TIMEOUT_S`` bounds how long one request may stay in
flight before the front door cancels it (finish_reason ``timeout``).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from fleetx_tpu.obs.events import emit as obs_emit
from fleetx_tpu.obs.httpd import HttpDaemon, JsonHandler
from fleetx_tpu.obs.registry import get_registry
from fleetx_tpu.utils.log import logger

__all__ = ["ApiServer", "ApiError"]


class ApiError(Exception):
    """A request rejection with an HTTP status + OpenAI error body."""

    def __init__(self, code: int, message: str, kind: str =
                 "invalid_request_error"):
        super().__init__(message)
        self.code = code
        self.kind = kind

    def body(self) -> Dict:
        """The OpenAI-shaped error envelope."""
        return {"error": {"message": str(self), "type": self.kind,
                          "code": self.code}}


def _default_encode(text) -> List[int]:
    """The id codec: text is whitespace-separated token ids (a list of
    ints passes through). Raises :class:`ApiError` 400 on anything the
    codec can't read — the no-tokenizer front door serves token-id
    workloads."""
    if isinstance(text, (list, tuple)):
        try:
            return [int(t) for t in text]
        except (TypeError, ValueError):
            raise ApiError(400, "prompt list must contain token ids")
    if isinstance(text, str):
        try:
            return [int(t) for t in text.split()]
        except ValueError:
            raise ApiError(
                400, "no tokenizer configured: content must be "
                "whitespace-separated token ids (e.g. \"12 7 3\")")
    raise ApiError(400, f"prompt must be a string or token-id list, "
                        f"got {type(text).__name__}")


def _default_decode(tokens: List[int]) -> str:
    """Inverse of :func:`_default_encode`: ids → "12 7 3"."""
    return " ".join(str(int(t)) for t in tokens)


_FINISH_MAP = {"eos": "stop", "max_length": "length"}


class _ApiMetrics:
    """Process-global ``fleetx_api_*`` instruments (docs/OBSERVABILITY.md
    has the table); one set per process, shared across ApiServers."""

    _instance = None

    def __init__(self):
        reg = get_registry()
        self.requests = reg.counter(
            "fleetx_api_requests_total",
            "API requests accepted per route and tenant",
            ("route", "tenant"))
        self.errors = reg.counter(
            "fleetx_api_errors_total",
            "API error responses per HTTP status", ("code",))
        self.tokens = reg.counter(
            "fleetx_api_tokens_total",
            "Completion tokens delivered to API clients per tenant",
            ("tenant",))
        self.active = reg.gauge(
            "fleetx_api_active_requests",
            "API requests currently in flight (streaming or aggregating)")
        self.ttft = reg.histogram(
            "fleetx_api_ttft_seconds",
            "Submit-to-first-SSE-token latency at the API layer")

    @classmethod
    def get(cls) -> "_ApiMetrics":
        """The per-process singleton (registry families are themselves
        process-global; re-instantiating would just re-fetch them)."""
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance


class _ApiHandler(JsonHandler):
    """Routes the OpenAI surface onto the owning :class:`ApiServer`."""

    server_version = "fleetx-api/1"
    protocol_version = "HTTP/1.1"

    def _api(self) -> "ApiServer":
        return self.server.context["api"]

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        """Read-only routes: model listing + health."""
        path = self.path.split("?", 1)[0].rstrip("/")
        api = self._api()
        if path == "/v1/models":
            self._send_json(200, api.models_payload())
        elif path == "/healthz":
            body = api.health()
            self._send_json(200 if body.get("state") == "ok" else 503, body)
        else:
            self._send_json(404, ApiError(
                404, f"unknown path {self.path!r}").body())

    def do_POST(self) -> None:  # noqa: N802 — http.server contract
        """The two completion routes."""
        path = self.path.split("?", 1)[0].rstrip("/")
        api = self._api()
        chat = path == "/v1/chat/completions"
        embeddings = path == "/v1/embeddings"
        if not chat and not embeddings and path != "/v1/completions":
            self._send_json(404, ApiError(
                404, f"unknown path {self.path!r}").body())
            return
        try:
            body = self._read_json()
            if not isinstance(body, dict):
                raise ApiError(400, "request body must be a JSON object")
            if embeddings:
                api.handle_embeddings(self, body)
            else:
                api.handle_completion(self, body, chat=chat)
        except ApiError as e:
            api.metrics.errors.labels(code=str(e.code)).inc()
            self._send_json(e.code, e.body())
        except ValueError as e:
            # malformed JSON from _read_json, or an engine-side
            # validation the pre-checks didn't anticipate
            api.metrics.errors.labels(code="400").inc()
            self._send_json(400, ApiError(400, str(e)).body())
        except BrokenPipeError:
            pass  # client hung up mid-stream; the request was cancelled
        except Exception as e:  # noqa: BLE001 — 500 must stay JSON
            logger.exception("api: unhandled error on %s", path)
            api.metrics.errors.labels(code="500").inc()
            try:
                self._send_json(500, ApiError(
                    500, f"{type(e).__name__}: {e}", "server_error").body())
            except OSError:
                pass


class ApiServer(HttpDaemon):
    """The front door: OpenAI surface + driver thread over one target."""

    def __init__(self, target, *, port: int = 0, host: str = "127.0.0.1",
                 model_id: str = "fleetx",
                 encode: Optional[Callable] = None,
                 decode: Optional[Callable] = None,
                 request_timeout_s: Optional[float] = None):
        super().__init__(_ApiHandler, port=port, host=host,
                         context={"api": self},
                         thread_name="fleetx-api-http")
        self.target = target
        self.model_id = model_id
        self.encode = encode or _default_encode
        self.decode = decode or _default_decode
        self.request_timeout_s = (
            request_timeout_s if request_timeout_s is not None
            else float(os.environ.get("FLEETX_API_TIMEOUT_S", "120")))
        self.metrics = _ApiMetrics.get()
        self._lock = threading.Lock()       # serializes ALL target touches
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._driver: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._created = int(time.time())

    # ------------------------------------------------------------ driver

    def start(self) -> "ApiServer":
        """Start the HTTP listener and the engine driver thread."""
        if self._driver is None:
            self._stop.clear()
            self._driver = threading.Thread(
                target=self._drive, name="fleetx-api-driver", daemon=True)
            self._driver.start()
        super().start()
        return self

    def stop(self) -> None:
        """Stop the listener, then the driver."""
        super().stop()
        self._stop.set()
        if self._driver is not None:
            self._driver.join(timeout=10)
            self._driver = None

    def _drive(self) -> None:
        """Tick the target while requests are in flight; idle cheaply
        otherwise. A tick that raises marks the whole front door sick
        (503 /healthz) rather than silently wedging every stream."""
        self._driver_error = None
        while not self._stop.is_set():
            if self._inflight <= 0:
                time.sleep(0.005)
                continue
            try:
                with self._lock:
                    self.target.step()
            except Exception as e:  # noqa: BLE001 — surfaced via /healthz
                logger.exception("api: driver tick failed")
                self._driver_error = f"{type(e).__name__}: {e}"
                time.sleep(0.1)

    # ------------------------------------------------------------ routes

    def _served_models(self) -> Dict[str, Dict]:
        """The router's per-family replica-group view, ``{}`` for plain
        engine targets (which serve exactly the configured model id)."""
        if hasattr(self.target, "models"):
            with self._lock:
                return self.target.models()
        return {}

    def models_payload(self) -> Dict:
        """The ``/v1/models`` listing: derived from the router's replica
        groups when the target has them (one entry per family, plus the
        configured ``model_id`` as an alias of the default group), else
        the single configured model."""
        served = self._served_models()
        data = [{"id": self.model_id, "object": "model",
                 "created": self._created, "owned_by": "fleetx"}]
        if served:
            default = getattr(self.target, "_default_model", None)
            data[0]["group"] = default
            for family in sorted(served):
                info = served[family]
                data.append({"id": family, "object": "model",
                             "created": self._created,
                             "owned_by": "fleetx",
                             "replicas": info["replicas"],
                             "live": info["live"],
                             "capabilities": info["capabilities"]})
        return {"object": "list", "data": data}

    def health(self) -> Dict:
        """The ``/healthz`` body: the engine's ``health()`` dict, or a
        router aggregate (ok while ANY replica is in rotation)."""
        if getattr(self, "_driver_error", None):
            return {"state": "dead", "error": self._driver_error}
        with self._lock:
            if hasattr(self.target, "health"):
                return self.target.health()
            states = list(self.target.replica_states)
            return {"state": ("ok" if any(s == "ok" for s in states)
                              else "dead"),
                    "replicas": states,
                    "queue_depth": self.target.queue_depth,
                    "in_flight": self.target.in_flight}

    # ------------------------------------------------- request handling

    def _parse(self, body: Dict, chat: bool) -> Tuple[List[int], Dict]:
        """Validate one completion request → (prompt ids, submit kwargs).

        Every rejection is a structured :class:`ApiError` (4xx) raised
        BEFORE the engine is touched — the engine never sees a request
        the validator wouldn't vouch for."""
        model = body.get("model")
        model_kw: Dict = {}
        if model is not None and model != self.model_id:
            served = self._served_models()
            if model in served:
                # family-addressed request on a heterogeneous fleet:
                # dispatch stays inside this model group
                model_kw["model"] = model
            else:
                raise ApiError(
                    404, f"model {model!r} not found (serving "
                    f"{sorted(served) or [self.model_id]})",
                    "model_not_found")
        if body.get("n", 1) != 1:
            raise ApiError(400, "n > 1 is not supported")
        if chat:
            msgs = body.get("messages")
            if not isinstance(msgs, list) or not msgs:
                raise ApiError(400,
                               "messages must be a non-empty array")
            ids: List[int] = []
            for m in msgs:
                if not isinstance(m, dict) or "content" not in m:
                    raise ApiError(400, "each message needs a content")
                ids.extend(self.encode(m["content"]))
        else:
            if "prompt" not in body:
                raise ApiError(400, "prompt is required")
            ids = self.encode(body["prompt"])
        if not ids:
            raise ApiError(400, "prompt is empty after encoding")

        kw: Dict = dict(model_kw)
        max_tokens = body.get("max_tokens", body.get(
            "max_completion_tokens"))
        if max_tokens is not None:
            if not isinstance(max_tokens, int) or max_tokens < 1:
                raise ApiError(400, "max_tokens must be a positive int")
            kw["max_length"] = max_tokens
        temp = body.get("temperature")
        if temp is not None:
            if not isinstance(temp, (int, float)) or temp < 0:
                raise ApiError(400, "temperature must be >= 0")
        top_p = body.get("top_p")
        if top_p is not None:
            if not isinstance(top_p, (int, float)) or not 0 < top_p <= 1:
                raise ApiError(400, "top_p must be in (0, 1]")
        top_k = body.get("top_k")
        if top_k is not None:
            if not isinstance(top_k, int) or top_k < 1:
                raise ApiError(400, "top_k must be a positive int")
        seed = body.get("seed")
        if seed is not None and not isinstance(seed, int):
            raise ApiError(400, "seed must be an int")
        if temp is not None and temp > 0:
            kw["decode_strategy"] = "sampling"
            kw["temperature"] = float(temp)
            if top_p is not None:
                kw["top_p"] = float(top_p)
            if top_k is not None:
                kw["top_k"] = int(top_k)
        elif temp is not None:
            kw["decode_strategy"] = "greedy"  # temperature 0 = greedy
        if seed is not None:
            kw["seed"] = seed
        stop_tok = body.get("stop_token_id")
        if stop_tok is not None:
            if not isinstance(stop_tok, int):
                raise ApiError(400, "stop_token_id must be an int")
            kw["eos_token_id"] = stop_tok
        stream = body.get("stream", False)
        if not isinstance(stream, bool):
            raise ApiError(400, "stream must be a boolean")
        return ids, kw

    def _tenant_of(self, handler: _ApiHandler, body: Dict) -> str:
        """The tenant identity from the auth/header seam: an
        ``X-Fleetx-Tenant`` header (what an authenticating reverse proxy
        stamps after validating the API key) wins; the OpenAI-compatible
        ``user`` body field is the fallback; anonymous traffic shares
        the ``"default"`` lane. The value feeds the per-tenant metric
        labels and — when the target is the QoS router — its dispatch
        lane, budgets, and rate limits (docs/SERVING.md)."""
        t = handler.headers.get("X-Fleetx-Tenant") or body.get("user")
        if not isinstance(t, str):
            return "default"
        return t.strip()[:64] or "default"

    def _submit(self, ids: List[int], kw: Dict, sink) -> int:
        """Submit under the lock, mapping engine refusals onto HTTP."""
        from fleetx_tpu.serving.engine import QueueFull, ShuttingDown

        try:
            with self._lock:
                return self.target.submit(ids, on_token=sink, **kw)
        except QueueFull as e:
            raise ApiError(429, str(e), "rate_limit_exceeded")
        except ShuttingDown as e:
            raise ApiError(503, str(e), "server_shutting_down")
        except ValueError as e:
            raise ApiError(400, str(e))

    def handle_completion(self, handler: _ApiHandler, body: Dict,
                          chat: bool) -> None:
        """One ``/v1/*completions`` request end to end (validate →
        submit → stream or aggregate → respond)."""
        ids, kw = self._parse(body, chat)
        tenant = self._tenant_of(handler, body)
        route = "chat" if chat else "completions"
        self.metrics.requests.labels(route=route, tenant=tenant).inc()
        if getattr(self.target, "supports_tenants", False):
            # the QoS router's per-tenant lane/budget seam; plain
            # engines never see the kwarg
            kw["tenant"] = tenant

        q: "queue.Queue" = queue.Queue()

        def sink(_rid: int, tok: int, finished: bool) -> None:
            q.put((int(tok), bool(finished)))

        with self._inflight_lock:
            self._inflight += 1
        self.metrics.active.inc()
        t0 = time.monotonic()
        try:
            rid = self._submit(ids, kw, sink)
            if body.get("stream", False):
                self._respond_stream(handler, q, rid, ids, chat, t0,
                                     tenant)
            else:
                self._respond_json(handler, q, rid, ids, chat, t0,
                                   tenant)
        finally:
            with self._inflight_lock:
                self._inflight -= 1
            self.metrics.active.inc(-1)

    def handle_embeddings(self, handler: _ApiHandler, body: Dict) -> None:
        """One ``/v1/embeddings`` request: float vectors in, float
        vectors out, through a KV-free embedding family's int32 wire
        encoding (serving/embedding_engine.py). 404 when the fleet
        serves no such family; when it serves several, the request must
        name one."""
        from fleetx_tpu.serving.embedding_engine import (decode_floats,
                                                         encode_floats)

        served = self._served_models()
        float_out = sorted(
            fam for fam, info in served.items()
            if info["capabilities"]
            and info["capabilities"].get("emits") == "floats")
        model = body.get("model")
        if model is None:
            if len(float_out) != 1:
                raise ApiError(
                    404 if not float_out else 400,
                    f"no unambiguous embedding model served (float-out "
                    f"families: {float_out}); name one", "model_not_found")
            model = float_out[0]
        elif model not in float_out:
            raise ApiError(404, f"model {model!r} is not a served "
                                f"embedding family (have {float_out})",
                           "model_not_found")
        inp = body.get("input")
        if isinstance(inp, list) and inp and all(
                isinstance(v, (int, float)) and not isinstance(v, bool)
                for v in inp):
            rows = [inp]
        elif isinstance(inp, list) and inp and all(
                isinstance(r, list) and r and all(
                    isinstance(v, (int, float)) and not isinstance(v, bool)
                    for v in r) for r in inp):
            rows = inp
        else:
            raise ApiError(
                400, "input must be a non-empty array of numbers (one "
                "flattened image/vector) or an array of such arrays")
        tenant = self._tenant_of(handler, body)
        self.metrics.requests.labels(route="embeddings",
                                     tenant=tenant).inc()
        with self._inflight_lock:
            self._inflight += len(rows)
        self.metrics.active.inc()
        t0 = time.monotonic()
        try:
            data = []
            pending = []
            for row in rows:
                q: "queue.Queue" = queue.Queue()

                def sink(_rid, tok, finished, _q=q):
                    _q.put((int(tok), bool(finished)))

                ids = [int(t) for t in encode_floats(row)]
                pending.append(
                    (q, self._submit(ids, dict(model=model), sink)))
            for index, (q, rid) in enumerate(pending):
                result = self._await_result(q, rid, t0, lambda _t: None,
                                            tenant)
                if result.finish_reason != "complete":
                    raise ApiError(
                        503 if result.finish_reason in ("shutdown",
                                                        "timeout")
                        else 500,
                        f"embedding request ended {result.finish_reason!r}",
                        "server_error")
                data.append({
                    "object": "embedding", "index": index,
                    "embedding": [float(v) for v in
                                  decode_floats(result.tokens)]})
            n_in = sum(len(r) for r in rows)
            handler._send_json(200, {
                "object": "list", "data": data, "model": model,
                "usage": {"prompt_tokens": n_in,
                          "total_tokens": n_in}})
        finally:
            with self._inflight_lock:
                self._inflight -= len(rows)
            self.metrics.active.inc(-1)

    def _await_result(self, q: "queue.Queue", rid: int, t0: float,
                      on_token: Callable[[int], None],
                      tenant: str = "default"):
        """Pump the token queue until the request's result is ready.

        Tokens arrive via the queue (the driver thread ticks the target,
        callbacks fire inside the tick); terminal-without-token ends
        (timeout/cancel/shutdown) arrive only as a result appearing, so
        an idle queue polls ``take_result`` too. Returns the
        ``ServingResult``; the front-door deadline cancels the request
        and synthesizes a ``timeout`` result if the target loses it."""
        first = True
        deadline = t0 + self.request_timeout_s
        result = None
        tokens_c = self.metrics.tokens.labels(tenant=tenant)
        while result is None:
            try:
                tok, finished = q.get(timeout=0.05)
                if first:
                    self.metrics.ttft.observe(time.monotonic() - t0)
                    first = False
                tokens_c.inc()
                on_token(tok)
                if not finished:
                    continue
            except queue.Empty:
                pass
            with self._lock:
                result = self.target.take_result(rid)
            if result is None and time.monotonic() > deadline:
                with self._lock:
                    self.target.cancel(rid)
                    result = self.target.take_result(rid)
                obs_emit("api_request_timeout", request=rid,
                         timeout_s=self.request_timeout_s)
                if result is None:
                    from fleetx_tpu.serving.engine import ServingResult

                    result = ServingResult(
                        id=rid, prompt=ids_to_np([]), tokens=ids_to_np([]),
                        finish_reason="timeout", ttft_s=0.0, latency_s=0.0)
                break
        # tokens emitted in the same tick that finished the request may
        # still sit in the queue — flush them before the final chunk
        while True:
            try:
                tok, _fin = q.get_nowait()
            except queue.Empty:
                break
            tokens_c.inc()
            on_token(tok)
        return result

    # ------------------------------------------------------- responders

    def _respond_json(self, handler, q, rid, ids, chat, t0,
                      tenant: str = "default") -> None:
        """Aggregate (non-stream) response."""
        toks: List[int] = []
        result = self._await_result(q, rid, t0, toks.append, tenant)
        text = self.decode([int(t) for t in result.tokens])
        finish = _FINISH_MAP.get(result.finish_reason,
                                 result.finish_reason)
        usage = {"prompt_tokens": len(ids),
                 "completion_tokens": len(result.tokens),
                 "total_tokens": len(ids) + len(result.tokens)}
        if chat:
            payload = {
                "id": f"chatcmpl-{rid}", "object": "chat.completion",
                "created": int(time.time()), "model": self.model_id,
                "choices": [{"index": 0,
                             "message": {"role": "assistant",
                                         "content": text},
                             "finish_reason": finish}],
                "usage": usage,
                "tokens": [int(t) for t in result.tokens]}
        else:
            payload = {
                "id": f"cmpl-{rid}", "object": "text_completion",
                "created": int(time.time()), "model": self.model_id,
                "choices": [{"index": 0, "text": text,
                             "finish_reason": finish}],
                "usage": usage,
                "tokens": [int(t) for t in result.tokens]}
        handler._send_json(200, payload)

    def _respond_stream(self, handler, q, rid, ids, chat, t0,
                        tenant: str = "default") -> None:
        """SSE streaming response: one chunk per decoded token (with the
        raw id in the ``token`` extension field), a final chunk carrying
        ``finish_reason``, then ``data: [DONE]``."""
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.send_header("Connection", "close")
        handler.end_headers()
        obj = "chat.completion.chunk" if chat else "text_completion"
        oid = f"chatcmpl-{rid}" if chat else f"cmpl-{rid}"
        sent = [0]

        def write_event(payload: Dict) -> None:
            handler.wfile.write(
                b"data: " + json.dumps(payload).encode() + b"\n\n")
            handler.wfile.flush()

        def chunk(tok: Optional[int], finish: Optional[str]) -> Dict:
            text = ("" if tok is None
                    else (" " if sent[0] else "") + self.decode([tok]))
            choice: Dict = {"index": 0, "finish_reason": finish}
            if chat:
                choice["delta"] = ({} if tok is None
                                   else {"content": text})
            else:
                choice["text"] = text
            out = {"id": oid, "object": obj, "created": int(time.time()),
                   "model": self.model_id, "choices": [choice]}
            if tok is not None:
                out["token"] = int(tok)
                sent[0] += 1
            return out

        def on_token(tok: int) -> None:
            try:
                write_event(chunk(tok, None))
            except OSError:
                # client went away: cancel so the slot frees, then let
                # the pump finish via the result it produces
                with self._lock:
                    self.target.cancel(rid)
                raise BrokenPipeError("client disconnected mid-stream")

        result = self._await_result(q, rid, t0, on_token, tenant)
        finish = _FINISH_MAP.get(result.finish_reason,
                                 result.finish_reason)
        write_event(chunk(None, finish))
        handler.wfile.write(b"data: [DONE]\n\n")
        handler.wfile.flush()


def ids_to_np(ids: List[int]):
    """Token-id list → the int32 array shape ``ServingResult`` carries."""
    import numpy as np

    return np.asarray(ids, np.int32)
