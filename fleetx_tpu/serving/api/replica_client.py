"""Engine-shaped RPC proxy: drive a remote replica like a local engine.

``ReplicaClient(url)`` presents the exact ``ServingEngine`` surface the
:class:`~fleetx_tpu.serving.router.ServingRouter` consumes — the
attributes (``role``, ``paged``, ``page_size``, ``cache_len``,
``model.cfg.max_position_embeddings``) scraped from ``/rpc/spec`` at
connect, and the ten methods forwarded over
:func:`~fleetx_tpu.serving.api.wire.rpc_call` — so
``ServingRouter(replicas=[ReplicaClient(u) for u in urls])`` just works,
fallbacks included.

The load-bearing part is the NETWORK-FAILURE MAPPING. Every transport
failure surfaces as the exception (or sentinel) the router's existing
resilience ladder already handles for an in-process replica:

==================  ====================  ==============================
method              on ``ConnectionError``  router behavior it triggers
==================  ====================  ==============================
``health``          propagates            probe reads it as ``dead`` →
                                          SUSPECT/backoff escalation
``step``            ``ReplicaKilled``     ``_mark_dead`` → zero-token-
                                          loss ``history=`` migration
``submit``          ``QueueFull``         exclude + retry other
                                          replicas (request waits, never
                                          errors)
``take_result``     returns ``None``      keep polling / migrate
``emitted_tokens``  returns ``None``      re-base from router's record
``prefilled_ready`` returns ``[]``        no handoffs this tick
``cancel``          returns ``False``     a dead replica IS cancelled
``request_shutdown``  swallowed           already down = already drained
``declare_dead``    swallowed             already down = already dead
``export_kv``       propagates            handoff aborts → decode-side
                                          replay fallback
==================  ====================  ==============================

Typed replica-side errors (``error_kind`` bodies) re-raise as the real
exception classes via the wire module, so ``except QueueFull`` /
``except ValueError`` clauses in the router fire identically either way.

Streaming crosses the boundary inside ``/rpc/step`` responses: the
server buffers the tick's ``on_token`` events and the client replays
them — in emission order — into the callbacks registered at
:meth:`submit`. A lost step response therefore delivers NO events, the
router migrates from exactly the tokens it has seen, and the user
stream stays loss- and duplicate-free.
"""

from __future__ import annotations

import time
from types import SimpleNamespace
from typing import Dict, List, Optional

from fleetx_tpu.resilience.faults import ReplicaKilled
from fleetx_tpu.serving.api import wire

__all__ = ["ReplicaClient"]


class ReplicaClient:
    """An engine-shaped handle on one remote replica process."""

    def __init__(self, url: str, *, timeout_s: float = 10.0,
                 connect_wait_s: float = 0.0):
        self.url = url.rstrip("/")
        self.timeout_s = float(timeout_s)
        spec = self._fetch_spec(connect_wait_s)
        self.role = spec.get("role", "both")
        self.paged = bool(spec.get("paged"))
        self.page_size = spec.get("page_size") or 0
        self.cache_len = int(spec.get("cache_len", 0))
        self.slots = int(spec.get("slots", 1))
        self.eos_token_id = spec.get("eos_token_id")
        self.vocab_size = int(spec.get("vocab_size", 0))
        # the nested attribute path the router reads for the shared
        # request-length limit, mirrored from the spec scrape
        self.model = SimpleNamespace(cfg=SimpleNamespace(
            max_position_embeddings=int(spec.get(
                "max_position_embeddings", self.cache_len or 1)),
            vocab_size=self.vocab_size))
        # on_token callbacks by ENGINE rid, fed by step-event replay
        self._cbs: Dict[int, object] = {}

    def _fetch_spec(self, wait_s: float) -> Dict:
        """Scrape ``/rpc/spec``, retrying for up to ``wait_s`` seconds
        (the launcher connects while replica processes are still
        binding their ports)."""
        deadline = time.monotonic() + max(0.0, wait_s)
        while True:
            try:
                return wire.rpc_call(self.url + "/rpc/spec",
                                     timeout_s=self.timeout_s,
                                     method="spec")
            except ConnectionError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.05)

    def _rpc(self, name: str, payload: Dict) -> Dict:
        return wire.rpc_call(f"{self.url}/rpc/{name}", payload,
                             timeout_s=self.timeout_s, method=name)

    # --------------------------------------------- the engine surface

    def submit(self, prompt, *, on_token=None, rng_key=None, history=None,
               kv_payloads=None, **kw) -> int:
        """Forward ``submit`` with the wire codecs. An unreachable
        replica raises :class:`QueueFull` — the router then excludes it
        and retries the others with ``only_refusals=False``, so the
        request waits instead of erroring. Typed replica-side refusals
        (real ``QueueFull``/``ShuttingDown``/``ValueError``) cross
        as themselves."""
        payload = {
            "prompt": [int(t) for t in prompt],
            "rng_key": wire.rng_key_to_wire(rng_key),
            "history": (None if history is None
                        else [int(t) for t in history]),
            "kv_payloads": wire.b64_blobs_encode(kv_payloads),
            "kw": _json_kwargs(kw),
        }
        try:
            rid = int(self._rpc("submit", payload)["id"])
        except ConnectionError as e:
            from fleetx_tpu.serving.engine import QueueFull

            raise QueueFull(f"replica {self.url} unreachable at submit "
                            f"({e})") from e
        if on_token is not None:
            self._cbs[rid] = on_token
        return rid

    def step(self) -> Dict:
        """One remote tick. Replays the tick's ``on_token`` events into
        the registered callbacks (emission order), then returns the
        summary. An unreachable replica raises
        :class:`~fleetx_tpu.resilience.faults.ReplicaKilled` — the
        router's dead-replica migration path."""
        try:
            out = self._rpc("step", {})
        except ConnectionError as e:
            raise ReplicaKilled(
                f"replica {self.url} unreachable at step ({e})") from e
        for erid, tok, finished in out.get("events", ()):
            cb = self._cbs.get(erid)
            if cb is not None:
                cb(erid, tok, bool(finished))
                if finished:
                    self._cbs.pop(erid, None)
        return out.get("summary", {})

    def health(self) -> Dict:
        """The replica's ``/healthz`` body (its engine's ``health()``
        dict). An unreachable replica RAISES — the router probe's
        catch-all already reads a raising health as ``dead``."""
        return wire.rpc_call(self.url + "/healthz",
                             timeout_s=self.timeout_s, method="health")

    def take_result(self, request_id: int):
        """The finished :class:`ServingResult`, or ``None`` while in
        flight — and ``None`` when unreachable (the router keeps
        polling, then migrates when the probe declares death)."""
        try:
            out = self._rpc("take_result", {"id": int(request_id)})
        except ConnectionError:
            return None
        res = wire.result_from_wire(out.get("result"))
        if res is not None:
            self._cbs.pop(int(request_id), None)
        return res

    def emitted_tokens(self, request_id: int) -> Optional[List[int]]:
        """Tokens the replica has emitted for a live request (``None``
        when unknown or unreachable — the router keeps its own record
        as the migration source of truth)."""
        try:
            return self._rpc("emitted_tokens",
                             {"id": int(request_id)}).get("tokens")
        except ConnectionError:
            return None

    def prefilled_ready(self) -> List[int]:
        """Parked prefill-complete request ids (``[]`` when
        unreachable: no handoffs from a dead prefill replica — the
        decode side's replay fallback owns those requests now)."""
        try:
            return list(self._rpc("prefilled_ready", {}).get("ids", []))
        except ConnectionError:
            return []

    def export_kv(self, request_id: int) -> List[bytes]:
        """The crc32-trailed KV page wire blobs for a parked prefill.
        Raises ``KeyError`` (not parked) and ``ConnectionError``
        (unreachable) — both abort this handoff attempt and leave the
        router's decode-side replay fallback in charge."""
        out = self._rpc("export_kv", {"id": int(request_id)})
        return wire.b64_blobs_decode(out["payloads"]) or []

    def cancel(self, request_id: int) -> bool:
        """Cancel remotely; an unreachable replica returns ``False``
        (nothing left to cancel). Drops the local callback first so no
        late events replay for a request the router abandoned."""
        self._cbs.pop(int(request_id), None)
        try:
            return bool(self._rpc("cancel",
                                  {"id": int(request_id)})["cancelled"])
        except ConnectionError:
            return False

    def request_shutdown(self, grace_s: Optional[float] = None) -> None:
        """Flip the remote engine to draining (SIGTERM semantics). An
        unreachable replica is swallowed: already down = already
        drained."""
        try:
            self._rpc("request_shutdown", {"grace_s": grace_s})
        except ConnectionError:
            pass

    def declare_dead(self) -> None:
        """Tell the remote engine it has been failed out (mirror of
        ``ServingEngine.declare_dead``). Swallowed when unreachable."""
        try:
            self._rpc("declare_dead", {})
        except ConnectionError:
            pass


def _json_kwargs(kw: Dict) -> Dict:
    """Per-request override kwargs, coerced to JSON scalars (numpy ints
    from upstream samplers must not poison the wire)."""
    out = {}
    for k, v in kw.items():
        if v is None or isinstance(v, (bool, str)):
            out[k] = v
        elif isinstance(v, float):
            out[k] = float(v)
        else:
            try:
                out[k] = int(v)
            except (TypeError, ValueError):
                out[k] = v
    return out
