"""Deployable serving front door: OpenAI-compatible API + replica RPC.

This package turns the in-process serving stack (``ServingEngine`` +
``ServingRouter``) into something an operator can actually put on a
port (docs/SERVING.md "Deployment"):

- :mod:`~fleetx_tpu.serving.api.server` — ``ApiServer``, a stdlib-only
  OpenAI-compatible HTTP front door (``/v1/chat/completions``,
  ``/v1/completions``, ``/v1/models``) with SSE streaming driven off
  the engine/router ``on_token`` callbacks.
- :mod:`~fleetx_tpu.serving.api.replica_server` /
  :mod:`~fleetx_tpu.serving.api.replica_client` — the cross-process
  replica RPC: each replica process serves its engine over HTTP, the
  router process drives engine-shaped client proxies, and every network
  failure maps onto the router's existing dead-replica / zero-token-loss
  replay fallbacks.
- :mod:`~fleetx_tpu.serving.api.wire` — the JSON codecs (RNG keys, KV
  page blobs, results, typed errors) both sides share.

``tools/serve.py`` is the launcher that composes these into a fleet:
N replica processes behind one router + API process.

Imports here stay lazy: the submodules pull jax/the engine, and the
launcher imports this package before deciding which role a process
plays.
"""

__all__ = ["ApiServer", "ReplicaClient", "ReplicaServer"]


def __getattr__(name):
    """Lazy re-exports (keep ``import fleetx_tpu.serving.api`` cheap)."""
    if name == "ApiServer":
        from fleetx_tpu.serving.api.server import ApiServer
        return ApiServer
    if name == "ReplicaClient":
        from fleetx_tpu.serving.api.replica_client import ReplicaClient
        return ReplicaClient
    if name == "ReplicaServer":
        from fleetx_tpu.serving.api.replica_server import ReplicaServer
        return ReplicaServer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
