"""Closed-loop fleet autoscaling for the multi-replica router.

The router gives the fleet QoS *within* a fixed replica set; this module
closes the loop on the set itself. :class:`FleetAutoscaler` periodically
reads the same per-replica ``health()`` reports the router's prober
already consumes — ``queue_tokens`` (the prefill backlog priced in
tokens), slot occupancy (``active``/``slots``), lifecycle ``state`` —
averages them over the live rotation, and compares against high/low
watermarks:

- **Scale up** — the average queued-token backlog per live replica has
  sat above ``high_queue_tokens`` (or every slot has been busy) for
  ``up_after`` consecutive evaluations: call ``spawn_fn()`` for a fresh
  engine, *pre-warm* its prefix trie (below), then
  ``router.add_replica(engine)`` so it enters the rotation already warm.
- **Scale down** — the backlog has sat below ``low_queue_tokens`` with
  slots mostly idle for ``down_after`` evaluations and the fleet is
  above ``min_replicas``: pick the least-loaded replica, ask it to
  drain (``request_shutdown(grace_s)``) — the router's prober sees
  ``"draining"`` and rotates it out on its own — and once its in-flight
  work has retired, ``router.remove_replica(index)``.

Hysteresis is deliberate on both sides (consecutive-evaluation counters,
distinct watermarks): a bursty trace must not make the fleet breathe on
every spike.

**Pre-warm.** A fresh replica sharing the fleet's ``DiskPageStore``
starts with a cold device trie but a warm persistent tier. Before the
new engine takes traffic the autoscaler replays the router's hottest
observed prompt prefixes (``router.hot_prefixes()``) through
``engine.prewarm()``, which revives the longest persisted prefix of
each into the device trie and parks it zero-ref-warm — so the replica's
first real request prefix-hits instead of re-prefilling from scratch.

Knobs (constructor args override the ``FLEETX_AUTOSCALE_*`` envs):
``min_replicas``/``max_replicas``, ``high_queue_tokens``/
``low_queue_tokens``, ``eval_every`` (router ticks between
evaluations), ``up_after``/``down_after`` (hysteresis), ``prewarm``,
``grace_s`` (drain grace forwarded to ``request_shutdown``).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from fleetx_tpu.obs.events import emit as obs_emit
from fleetx_tpu.serving.engine import _env_float, _env_int
from fleetx_tpu.serving.router import ReplicaState, ServingRouter
from fleetx_tpu.utils.log import logger

__all__ = ["FleetAutoscaler"]


class FleetAutoscaler:
    """Watch a :class:`ServingRouter`'s replica health and grow/shrink
    the fleet through a ``spawn_fn`` seam (module docstring)."""

    def __init__(self, router: ServingRouter,
                 spawn_fn: Callable[[], object], *,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 high_queue_tokens: Optional[float] = None,
                 low_queue_tokens: Optional[float] = None,
                 eval_every: Optional[int] = None,
                 up_after: Optional[int] = None,
                 down_after: Optional[int] = None,
                 prewarm: Optional[bool] = None,
                 grace_s: Optional[float] = None):
        self.router = router
        self.spawn_fn = spawn_fn
        self.min_replicas = max(1, (
            min_replicas if min_replicas is not None
            else _env_int("FLEETX_AUTOSCALE_MIN", 1)))
        self.max_replicas = max(self.min_replicas, (
            max_replicas if max_replicas is not None
            else _env_int("FLEETX_AUTOSCALE_MAX", 8)))
        self.high_queue_tokens = (
            high_queue_tokens if high_queue_tokens is not None
            else _env_float("FLEETX_AUTOSCALE_HIGH_QT", 512.0))
        self.low_queue_tokens = (
            low_queue_tokens if low_queue_tokens is not None
            else _env_float("FLEETX_AUTOSCALE_LOW_QT", 16.0))
        self.eval_every = max(1, (
            eval_every if eval_every is not None
            else _env_int("FLEETX_AUTOSCALE_EVERY", 8)))
        self.up_after = max(1, (
            up_after if up_after is not None
            else _env_int("FLEETX_AUTOSCALE_UP_AFTER", 2)))
        self.down_after = max(1, (
            down_after if down_after is not None
            else _env_int("FLEETX_AUTOSCALE_DOWN_AFTER", 4)))
        self.prewarm = (prewarm if prewarm is not None
                        else _env_int("FLEETX_AUTOSCALE_PREWARM", 1) == 1)
        self.grace_s = (grace_s if grace_s is not None
                        else _env_float("FLEETX_AUTOSCALE_GRACE_S", 30.0))
        self._ticks = 0
        self._high_streak = 0
        self._low_streak = 0
        self._draining: List[int] = []  # replica indices we asked to drain
        self.scale_ups = 0
        self.scale_downs = 0

    # ------------------------------------------------------------- evaluate

    def step(self) -> Optional[str]:
        """Call once per router tick. Every ``eval_every`` ticks the
        fleet is evaluated; returns ``"up"``/``"down"`` when an action
        was taken this call, else None."""
        self._ticks += 1
        self._finish_drains()
        if self._ticks % self.eval_every:
            return None
        live = [r for r in self.router._replicas
                if r.state == ReplicaState.OK
                and r.index not in self._draining]
        if not live:
            return None  # a lost fleet is the operator's page, not ours
        qt = slots = busy = 0
        for rep in live:
            try:
                h = rep.engine.health()
            except Exception:  # noqa: BLE001 — prober owns fault handling
                continue
            qt += float(h.get("queue_tokens", 0) or 0)
            slots += int(h.get("slots", 0) or 0)
            busy += int(h.get("active", 0) or 0)
        backlog = qt / len(live)
        saturated = slots > 0 and busy >= slots
        if backlog > self.high_queue_tokens or saturated:
            self._high_streak += 1
            self._low_streak = 0
        elif backlog < self.low_queue_tokens and (
                slots == 0 or busy * 2 <= slots):
            self._low_streak += 1
            self._high_streak = 0
        else:
            self._high_streak = self._low_streak = 0
        if (self._high_streak >= self.up_after
                and len(live) < self.max_replicas):
            self._high_streak = 0
            return self._scale_up(backlog)
        if (self._low_streak >= self.down_after
                and len(live) > self.min_replicas):
            self._low_streak = 0
            return self._scale_down(live, backlog)
        return None

    # --------------------------------------------------------------- actions

    def _scale_up(self, backlog: float) -> Optional[str]:
        engine = self.spawn_fn()
        if engine is None:
            return None  # launcher could not provide capacity
        warmed = 0
        if self.prewarm and hasattr(engine, "prewarm"):
            for prefix in self.router.hot_prefixes():
                try:
                    warmed += int(engine.prewarm(prefix))
                except Exception as e:  # noqa: BLE001 — warm is best-effort
                    logger.warning("autoscaler: prewarm failed: %s", e)
                    break
        index = self.router.add_replica(engine)
        self.scale_ups += 1
        obs_emit("autoscale_up", replica=index, backlog=round(backlog, 1),
                 prewarmed_tokens=warmed)
        logger.info(
            "autoscaler: scale-up -> replica %d (backlog %.0f tokens/"
            "replica, %d prefix tokens pre-warmed)", index, backlog, warmed)
        return "up"

    def _scale_down(self, live, backlog: float) -> Optional[str]:
        # least-loaded OK replica drains; the router's prober rotates it
        # out the moment health() says "draining"
        victim = min(live, key=lambda r: len(r.dispatched))
        try:
            victim.engine.request_shutdown(self.grace_s)
        except Exception as e:  # noqa: BLE001
            logger.warning("autoscaler: drain request failed: %s", e)
            return None
        self._draining.append(victim.index)
        self.scale_downs += 1
        obs_emit("autoscale_down", replica=victim.index,
                 backlog=round(backlog, 1))
        logger.info(
            "autoscaler: scale-down -> draining replica %d (backlog "
            "%.0f tokens/replica)", victim.index, backlog)
        return "down"

    def _finish_drains(self) -> None:
        """Retire drained replicas: once a replica we asked to drain has
        no dispatched work left and is out of the OK rotation, remove it
        from the router for good."""
        still: List[int] = []
        for idx in self._draining:
            if self.router.remove_replica(idx):
                continue
            still.append(idx)
        self._draining = still

    # --------------------------------------------------------- introspection

    def snapshot(self) -> Dict:
        """Counters + watermarks for bench/debug output."""
        return {
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "draining": list(self._draining),
            "high_queue_tokens": self.high_queue_tokens,
            "low_queue_tokens": self.low_queue_tokens,
        }
