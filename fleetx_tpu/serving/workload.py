"""Trace-driven serving workloads + the SLO goodput scorer.

Fixed-batch benches measure tokens/s; production traffic is Poisson
arrivals, multi-tenant prompt mixes, bursty shared prefixes, and users
who abandon slow requests — and the number that matters under that load
is **SLO goodput**: the fraction of requests that finish normally AND
meet their latency deadlines (TTFT: submit → first token; TPOT: mean
inter-token gap), not bare throughput. An overloaded system earns credit
for degrading gracefully — shedding late requests with ``timeout`` while
the rest keep meeting deadlines — and loses it for collapsing (everyone
slow, nobody shed). This module is that measurement substrate
(ROADMAP item 5): every later serving direction (disaggregated
prefill/decode, heterogeneous fleets) is judged against it, and
``tools/bench_serving.py`` banks its multi-replica record with a
regression gate.

Three pieces, all host-only and engine-agnostic:

- :func:`generate_trace` — a SEEDED, fully deterministic request trace
  from a :class:`WorkloadSpec`: inter-arrivals drawn from the spec's
  named :class:`TraceDistribution` (``"poisson"`` — exponential gaps,
  the classic open-loop model — or ``"azure_llm"`` — Weibull gaps with
  shape < 1 and lognormal-shaped lengths, the heavy-tailed
  burst-and-lull pattern of the Azure LLM inference traces) at the base
  rate, multiplied during periodic burst windows; tenants drawn by
  weight (bursts pin to the shared-prefix-heaviest tenant — the
  "everyone hits the same template at 9am" shape that exercises prefix
  caching and affinity routing); per-tenant prompt/decode length ranges;
  per-tenant deadlines and abandonment patience. :func:`trace_hash`
  fingerprints the result so a banked bench record names exactly the
  workload it measured.
- :func:`run_trace` — replay a trace against anything with the
  submit/step/cancel/take_result surface (``ServingEngine`` or
  ``ServingRouter``), submitting each request at its arrival time,
  cancelling abandoned ones, and recording per-request
  :class:`RequestOutcome` timings from the streaming callbacks.
- :func:`score_goodput` — outcomes → the goodput record: goodput
  fraction, TTFT/TPOT p50/p99, finish-reason mix, per-tenant goodput.

Determinism boundary: the TRACE is bit-deterministic from its seed (the
hash proves it); outcomes depend on wall-clock scheduling like any load
test. Conservation tests therefore drive the router directly with the
trace's requests and tick-counted time, while the bench replays in real
time and scores.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from fleetx_tpu.serving.engine import QueueFull, ShuttingDown

__all__ = [
    "DISTRIBUTIONS",
    "RequestOutcome",
    "TenantSpec",
    "TraceDistribution",
    "TraceRequest",
    "WorkloadSpec",
    "disagg_spec",
    "generate_trace",
    "run_trace",
    "score_goodput",
    "trace_hash",
]


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One traffic class: length mix, shared prefix, SLOs, patience.

    ``shared_prefix_len`` > 0 gives every request of this tenant the
    same leading tokens (a system prompt / template), generated once
    from the workload seed — the shape prefix caching and the router's
    affinity pin exist for. Deadlines are SCORING thresholds (0 = no
    SLO on that axis); ``abandon_s`` is behavioral — the driver cancels
    a request still unfinished that long after submission, the way a
    user closes the tab."""

    name: str
    weight: float = 1.0
    prompt_len: Tuple[int, int] = (8, 64)     # inclusive range, prefix incl.
    gen_len: Tuple[int, int] = (8, 64)        # max_new_tokens range
    shared_prefix_len: int = 0
    ttft_deadline_s: float = 0.0              # 0 = no TTFT SLO
    tpot_deadline_ms: float = 0.0             # 0 = no TPOT SLO
    abandon_s: float = 0.0                    # 0 = infinitely patient


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """One seeded workload: arrival process + tenant mix.

    ``distribution`` names a :data:`DISTRIBUTIONS` entry shaping the
    inter-arrival gaps and the length draws — ``"poisson"`` (default,
    the original synthetic model) or ``"azure_llm"`` (heavy-tailed)."""

    seed: int = 0
    n_requests: int = 64
    arrival_rate: float = 8.0                 # requests/second (base)
    vocab: int = 50304                        # prompt tokens in [1, vocab)
    tenants: Tuple[TenantSpec, ...] = (TenantSpec("default"),)
    burst_every_s: float = 0.0                # 0 = no bursts
    burst_len_s: float = 1.0
    burst_factor: float = 4.0                 # arrival-rate multiplier
    distribution: str = "poisson"             # DISTRIBUTIONS key


class TraceDistribution:
    """The pluggable trace-shape seam: how long until the next arrival,
    and how long prompts/decodes are within each tenant's configured
    range. The base class IS the ``"poisson"`` preset — exponential
    inter-arrivals, uniform lengths — and its rng call pattern is
    frozen: :func:`generate_trace` draws through these exact methods in
    a fixed order, so a given (spec, seed) pair reproduces byte-exact
    traces forever (the banked bench hashes depend on it)."""

    name = "poisson"

    def interarrival(self, rng, rate: float) -> float:
        """Seconds until the next arrival at ``rate`` req/s mean."""
        return float(rng.exponential(1.0 / rate))

    def prompt_len(self, rng, lo: int, hi: int) -> int:
        """Prompt length within the tenant's inclusive range."""
        return int(rng.integers(lo, hi + 1))

    def gen_len(self, rng, lo: int, hi: int) -> int:
        """max_new_tokens within the tenant's inclusive range."""
        return int(rng.integers(lo, hi + 1))


class _AzureLLMDistribution(TraceDistribution):
    """Heavy-tailed preset shaped like the Azure LLM inference traces
    (arXiv 2404.16283): Weibull inter-arrivals with shape < 1 — many
    near-simultaneous arrivals separated by long lulls, far burstier
    than Poisson at the same mean rate — and lognormal-body lengths
    (most requests short, a fat tail of near-range-max ones). The
    Weibull scale is normalized by Γ(1 + 1/k) so the MEAN rate still
    matches ``arrival_rate``: saturation math carries over between
    presets, only the variance (the hard part) changes."""

    name = "azure_llm"
    _SHAPE = 0.45      # Weibull k; < 1 = heavy tail
    _LOGNORM_SPAN = 8.0  # lognormal(0,1) value mapped to range max

    def interarrival(self, rng, rate: float) -> float:
        scale = (1.0 / rate) / math.gamma(1.0 + 1.0 / self._SHAPE)
        return float(rng.weibull(self._SHAPE) * scale)

    def _length(self, rng, lo: int, hi: int) -> int:
        frac = min(float(rng.lognormal(0.0, 1.0)) / self._LOGNORM_SPAN, 1.0)
        return lo + int(round(frac * (hi - lo)))

    def prompt_len(self, rng, lo: int, hi: int) -> int:
        return self._length(rng, lo, hi)

    def gen_len(self, rng, lo: int, hi: int) -> int:
        return self._length(rng, lo, hi)


#: Named trace shapes ``WorkloadSpec.distribution`` selects from.
DISTRIBUTIONS: Dict[str, TraceDistribution] = {
    d.name: d for d in (TraceDistribution(), _AzureLLMDistribution())
}


@dataclasses.dataclass
class TraceRequest:
    """One request of a generated trace (host data only)."""

    index: int
    arrival_s: float
    tenant: str
    prompt: np.ndarray                        # [prompt_len] int32
    max_new_tokens: int
    ttft_deadline_s: float
    tpot_deadline_ms: float
    abandon_s: float


def _in_burst(t: float, spec: WorkloadSpec) -> bool:
    if spec.burst_every_s <= 0:
        return False
    return (t % spec.burst_every_s) < spec.burst_len_s


def generate_trace(spec: WorkloadSpec) -> List[TraceRequest]:
    """Deterministic trace from ``spec.seed`` (module docstring): same
    spec, same bytes — :func:`trace_hash` is the receipt."""
    if spec.n_requests < 1:
        raise ValueError("n_requests must be >= 1")
    if spec.arrival_rate <= 0:
        raise ValueError("arrival_rate must be > 0")
    if not spec.tenants:
        raise ValueError("need at least one tenant")
    if spec.distribution not in DISTRIBUTIONS:
        raise ValueError(
            f"unknown distribution {spec.distribution!r} "
            f"(have {sorted(DISTRIBUTIONS)})")
    dist = DISTRIBUTIONS[spec.distribution]
    rng = np.random.default_rng(spec.seed)
    # per-tenant shared prefixes drawn FIRST, so adding requests to a
    # spec never reshuffles the prefixes earlier requests share
    prefixes = {}
    for t in spec.tenants:
        if t.shared_prefix_len > 0:
            prefixes[t.name] = rng.integers(
                1, spec.vocab, t.shared_prefix_len, dtype=np.int32)
    weights = np.asarray([t.weight for t in spec.tenants], np.float64)
    weights = weights / weights.sum()
    # bursts pin to the shared-prefix-heaviest tenant: the template storm
    burst_tenant = max(
        range(len(spec.tenants)),
        key=lambda i: (spec.tenants[i].shared_prefix_len, -i))
    out: List[TraceRequest] = []
    t = 0.0
    for i in range(spec.n_requests):
        rate = spec.arrival_rate * (
            spec.burst_factor if _in_burst(t, spec) else 1.0)
        t += dist.interarrival(rng, rate)
        ti = (burst_tenant if _in_burst(t, spec)
              else int(rng.choice(len(spec.tenants), p=weights)))
        tenant = spec.tenants[ti]
        prefix = prefixes.get(tenant.name)
        lo, hi = tenant.prompt_len
        plen = dist.prompt_len(rng, lo, hi)
        if prefix is not None:
            plen = max(plen, len(prefix) + 1)  # at least one fresh token
            suffix = rng.integers(1, spec.vocab, plen - len(prefix),
                                  dtype=np.int32)
            prompt = np.concatenate([prefix, suffix])
        else:
            prompt = rng.integers(1, spec.vocab, plen, dtype=np.int32)
        glo, ghi = tenant.gen_len
        out.append(TraceRequest(
            index=i, arrival_s=t, tenant=tenant.name, prompt=prompt,
            max_new_tokens=dist.gen_len(rng, glo, ghi),
            ttft_deadline_s=tenant.ttft_deadline_s,
            tpot_deadline_ms=tenant.tpot_deadline_ms,
            abandon_s=tenant.abandon_s,
        ))
    return out


def disagg_spec(n_requests: int = 32, *,
                vocab: int = 50304,
                prompt_len: Tuple[int, int] = (96, 192),
                gen_len: Tuple[int, int] = (16, 64),
                seed: int = 7) -> WorkloadSpec:
    """The prefill-heavy mix phase disaggregation targets (docs/
    SERVING.md "Disaggregated prefill/decode"): long prompts, short
    decodes — the shape where an arriving prefill steals the most
    decode ticks from in-flight requests on a colocated replica, and
    where shipping KV to a dedicated decode replica pays for itself.
    One tenant, no bursts, no SLOs: ``tools/bench_serving.py`` replays
    the trace through colocated and disaggregated routers and asserts
    byte parity, so the spec stays deliberately minimal (the goodput
    machinery is exercised by the router_slo record instead)."""
    return WorkloadSpec(
        seed=seed, n_requests=n_requests, vocab=vocab,
        arrival_rate=1000.0,  # effectively simultaneous arrivals
        tenants=(TenantSpec("disagg", prompt_len=prompt_len,
                            gen_len=gen_len),))


def trace_hash(trace: List[TraceRequest]) -> str:
    """16-hex-digit fingerprint of a trace — the bench record's workload
    identity (arrivals at microsecond precision, prompts byte-exact,
    and the SLO/abandonment fields: two workloads differing only in
    their deadlines score DIFFERENT goodput, so they must not share a
    fingerprint a regression gate compares against)."""
    h = hashlib.sha256()
    for r in trace:
        h.update(np.int64(round(r.arrival_s * 1e6)).tobytes())
        h.update(r.tenant.encode())
        h.update(np.ascontiguousarray(r.prompt, np.int32).tobytes())
        h.update(np.int64(r.max_new_tokens).tobytes())
        h.update(np.asarray([r.ttft_deadline_s, r.tpot_deadline_ms,
                             r.abandon_s], np.float64).tobytes())
    return h.hexdigest()[:16]


@dataclasses.dataclass
class RequestOutcome:
    """What one trace request actually experienced."""

    index: int
    tenant: str
    finish_reason: str            # engine reasons, plus "rejected"
    n_tokens: int = 0
    ttft_s: Optional[float] = None
    tpot_ms: Optional[float] = None   # mean inter-token gap (>= 2 tokens)
    ttft_deadline_s: float = 0.0
    tpot_deadline_ms: float = 0.0
    tokens: Optional[Tuple[int, ...]] = None  # run_trace(keep_tokens=True)

    @property
    def met_ttft(self) -> bool:
        """TTFT SLO met (vacuously when no deadline is set)."""
        return (not self.ttft_deadline_s
                or (self.ttft_s is not None
                    and self.ttft_s <= self.ttft_deadline_s))

    @property
    def met_tpot(self) -> bool:
        """TPOT SLO met (vacuously with no deadline or < 2 tokens)."""
        return (not self.tpot_deadline_ms or self.tpot_ms is None
                or self.tpot_ms <= self.tpot_deadline_ms)

    @property
    def good(self) -> bool:
        """Counts toward goodput: finished normally AND met every SLO.
        Shed/abandoned/errored requests are the degradation the scorer
        charges for — gracefully if the survivors stayed fast."""
        return (self.finish_reason in ("eos", "max_length")
                and self.met_ttft and self.met_tpot)


def run_trace(target, trace: List[TraceRequest], *,
              now=time.perf_counter, submit_kw: Optional[Dict] = None,
              max_wall_s: float = 300.0,
              keep_tokens: bool = False) -> List[RequestOutcome]:
    """Replay ``trace`` against ``target`` (engine or router: the
    submit/step/cancel/take_result surface) in real time: each request
    submits at its arrival offset, abandoning tenants cancel past their
    patience, and streaming callbacks time every token. Returns one
    :class:`RequestOutcome` per trace request (``"rejected"`` for
    admission-refused submits). ``max_wall_s`` is a loud runaway guard,
    not a scheduling knob.

    A target advertising ``supports_tenants`` (the QoS router, or an
    HTTP shim forwarding the tenant header) receives each request's
    trace tenant as ``submit(tenant=...)`` — the seam that lets one
    trace drive per-tenant dispatch and plain engines alike.
    ``keep_tokens=True`` records each outcome's full token stream
    (``RequestOutcome.tokens``) for byte-parity assertions."""
    submit_kw = dict(submit_kw or {})
    send_tenant = bool(getattr(target, "supports_tenants", False))
    pending = sorted(trace, key=lambda r: (r.arrival_s, r.index))
    live: Dict[int, Dict] = {}  # rid -> record
    outcomes: List[RequestOutcome] = []
    start = now()
    pi = 0
    while pi < len(pending) or live:
        t = now() - start
        if t > max_wall_s:
            raise TimeoutError(
                f"run_trace exceeded max_wall_s={max_wall_s} with "
                f"{len(pending) - pi} unsubmitted + {len(live)} live")
        while pi < len(pending) and pending[pi].arrival_s <= t:
            tr = pending[pi]
            pi += 1
            rec = {"trace": tr, "t_submit": now(), "times": []}

            def cb(_rid, _tok, _fin, rec=rec):
                rec["times"].append(now())

            kw = dict(submit_kw)
            if send_tenant:
                kw["tenant"] = tr.tenant
            try:
                rid = target.submit(tr.prompt,
                                    max_length=tr.max_new_tokens,
                                    on_token=cb, **kw)
            except (QueueFull, ShuttingDown):
                outcomes.append(RequestOutcome(
                    index=tr.index, tenant=tr.tenant,
                    finish_reason="rejected",
                    ttft_deadline_s=tr.ttft_deadline_s,
                    tpot_deadline_ms=tr.tpot_deadline_ms))
                continue
            live[rid] = rec
        # abandonment: the user closed the tab — actively cancel
        for rid, rec in list(live.items()):
            ab = rec["trace"].abandon_s
            if ab and now() - rec["t_submit"] > ab:
                target.cancel(rid)
        target.step()
        for rid in list(live):
            res = target.take_result(rid)
            if res is None:
                continue
            rec = live.pop(rid)
            tr, times = rec["trace"], rec["times"]
            tpot = None
            if len(times) >= 2:
                tpot = (times[-1] - times[0]) / (len(times) - 1) * 1e3
            outcomes.append(RequestOutcome(
                index=tr.index, tenant=tr.tenant,
                finish_reason=res.finish_reason,
                n_tokens=int(len(res.tokens)),
                ttft_s=(times[0] - rec["t_submit"]) if times else None,
                tpot_ms=tpot,
                ttft_deadline_s=tr.ttft_deadline_s,
                tpot_deadline_ms=tr.tpot_deadline_ms,
                tokens=(tuple(int(t) for t in res.tokens)
                        if keep_tokens else None)))
        if pi < len(pending) and not live:
            # idle gap before the next arrival: don't burn a core spinning
            gap = pending[pi].arrival_s - (now() - start)
            if gap > 0:
                time.sleep(min(gap, 0.002))
    outcomes.sort(key=lambda o: o.index)
    return outcomes


def _pct(values, q) -> Optional[float]:
    vals = [v for v in values if v is not None]
    if not vals:
        return None
    return float(np.percentile(np.asarray(vals, np.float64), q))


def score_goodput(outcomes: List[RequestOutcome]) -> Dict:
    """Outcomes → the SLO goodput record (module docstring). Goodput
    divides by ALL submitted requests — a shed or abandoned request is a
    user who got nothing, however graceful the shedding was; the
    ``finish_reasons`` mix shows whether degradation was controlled
    (timeouts/rejects) or chaotic (errors)."""
    n = len(outcomes)
    if n == 0:
        raise ValueError("no outcomes to score")
    reasons: Dict[str, int] = {}
    for o in outcomes:
        reasons[o.finish_reason] = reasons.get(o.finish_reason, 0) + 1
    good = sum(o.good for o in outcomes)
    tenants = sorted({o.tenant for o in outcomes})
    per_tenant = {
        t: round(sum(o.good for o in outcomes if o.tenant == t)
                 / max(sum(o.tenant == t for o in outcomes), 1), 4)
        for t in tenants
    }
    ttfts = [o.ttft_s for o in outcomes]
    tpots = [o.tpot_ms for o in outcomes]
    return {
        "requests": n,
        "goodput": round(good / n, 4),
        "good": good,
        "met_ttft_frac": round(sum(o.met_ttft for o in outcomes) / n, 4),
        "met_tpot_frac": round(sum(o.met_tpot for o in outcomes) / n, 4),
        "completed_frac": round(
            sum(o.finish_reason in ("eos", "max_length")
                for o in outcomes) / n, 4),
        "shed_frac": round(
            (reasons.get("timeout", 0) + reasons.get("rejected", 0)) / n, 4),
        "finish_reasons": reasons,
        "tokens_total": sum(o.n_tokens for o in outcomes),
        "ttft_ms_p50": _pct([t * 1e3 if t is not None else None
                             for t in ttfts], 50),
        "ttft_ms_p99": _pct([t * 1e3 if t is not None else None
                             for t in ttfts], 99),
        "tpot_ms_p50": _pct(tpots, 50),
        "tpot_ms_p99": _pct(tpots, 99),
        "goodput_per_tenant": per_tenant,
    }
