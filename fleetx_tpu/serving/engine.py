"""ServingEngine: continuous-batching decode over the slot kv-cache.

The runtime layer between "a stream of requests" and the single-step
decode functions exposed by ``models/gpt/generation.py``:

- **submit()** queues a request (FIFO) with per-request overrides for
  max/min length, EOS, sampling knobs, and an independent RNG stream.
- **step()** is one scheduler tick: admit queued requests into free slots
  (prefill-on-insert — each prompt is prefilled batch-1 into its slot's
  storage, its first token sampled in the same jitted call), then ONE
  jitted decode step over ALL slots, then per-slot EOS / max-length
  retirement that frees slots for the next tick's admissions.
- **drain()** ticks until queue and slots are empty and returns the
  finished :class:`ServingResult` records.

Cache storage is PAGED by default (``FLEETX_SERVING_PAGED=0`` or
``paged=False`` restores the fixed per-slot cache): K/V live in a shared
``[num_pages, page_size, heads, head_dim]`` pool, each request holds a
block table of page indices, and a refcounted prefix trie lets requests
sharing a token prefix (system prompts) reuse one prefill — admission is
then page-granular (the queue head admits when its PAGES fit, not when a
worst-case slot does), prefill runs only over the non-shared prompt
suffix, and a request's chain grows page-by-page as it decodes
(``finish_reason="cache_full"`` when the pool runs dry mid-flight). See
``cache_manager.py`` for the allocator/trie and the no-zeroing safety
argument; both storage modes emit byte-identical greedy tokens.

Chunked prefill (``FLEETX_SERVING_PREFILL_CHUNK``, default off;
docs/SERVING.md): whole-prompt prefill-on-insert makes decode TPOT
hostage to every long arriving prompt — prefill is MXU-bound, decode is
HBM-bound, and one 4k-token prefill inside a tick stalls every active
stream for its full duration. With a chunk size set, a prompt whose
non-shared suffix exceeds it enters a ``prefilling`` lifecycle state:
the engine runs AT MOST ONE chunk-sized prefill call per tick (a short
prompt's whole-prompt call counts as that tick's chunk), interleaved
with the batched decode, so no decode tick ever stalls more than ~one
chunk of prefill compute. Chunks reuse the bucketed prefill jits at
chunk granularity — long prompts stop minting per-length buckets up to
``cache_len`` — writing through the same per-row ``cache_positions`` /
page-scatter seams decode uses: paged chunks write straight into the
lane's pages at absolute positions, slot chunks accumulate into a
batch-1 working cache scattered into the slot on the final chunk. The
final chunk samples the first token exactly where the one-call path
would (same rng split discipline), so greedy tokens are BYTE-IDENTICAL
to the unchunked engine, and chunk progress rides the transactional-tick
snapshot: a mid-prefill fault rolls back, recovery requeues the request
at the queue head (zero tokens emitted — byte-identity is structural)
and the host-tier prefix cache below makes the re-prefill cheap.
Deadlines are honored BETWEEN chunks: an expired request stops burning
prefill compute and retires ``finish_reason="timeout"`` with its lane
and pages freed (no partial-chunk leak — prefix registration only
happens at completion).

Host-DRAM KV spill tier (``FLEETX_SERVING_HOST_CACHE_BYTES``, default
off; docs/SERVING.md two-level page cache): when the paged pool would
LRU-evict a zero-ref warm trie page, the page (K/V + int8 scales) spills
to a bounded host store instead of being destroyed, keyed by its token-
chunk path; a later prompt with the same prefix revives it into fresh
physical pages via one batched transfer per cache leaf and skips that
prefill entirely — the millions-of-users shared-system-prompt scenario
where the hot prefix set exceeds HBM. The store is content-addressed and
engine-owned, so it SURVIVES replay recovery (the rebuilt pool matches
the same keys) and revived bytes are exactly the spilled bytes: cold vs
spill-revived decoding is byte-identical. A shared-disk tier stacks
under it (``FLEETX_SERVING_DISK_CACHE_DIR``/``_BYTES``): content-
addressed wire-format files every replica in the fleet revives from.

Phase-disaggregated serving (``role=`` kwarg / ``FLEETX_SERVING_ROLE``;
docs/SERVING.md "Disaggregated prefill/decode"): prefill is MXU-bound,
decode is HBM-bound — colocating them makes each the other's noisy
neighbor. A ``role="prefill"`` engine runs admission + (chunked)
prefill to completion, emits the first token, then PARKS the request
(``prefilled_ready()``) instead of decoding; ``export_kv(request_id)``
reads the ``ceil(prompt_len/page_size)`` pages covering the prompt out
of the pool (batched per-leaf gathers, int8 scales included) and
returns them as crc32-trailed wire-format blobs. A decode replica
admits them via ``submit(kv_payloads=..., history=[t0])``: pages are
allocated, shipped payloads written through the revive scatter (no
re-prefill), the prompt registered in its prefix trie, and decoding
resumes from ``t0`` with the RNG carry reconstructed — byte-identical
to colocated decoding. Any handoff failure (export fault, corrupt blob
caught by the crc at submit, replica death mid-ship) falls back to the
replay path: ``t0`` is already in the router's durable history, so
nothing is ever lost, only re-prefilled. ``role="decode"`` is a normal
engine the router labels for placement.

Per-slot progress is carried as explicit ``cache_positions`` into the
model (``SelfAttention._update_cache``), so slots decode at different
depths in one batched forward; each row's attention window is
``[0, lengths[slot]+1)`` — on TPU the flash-decode kernel receives that
window as its per-row ``end`` and streams only the live prefix. Inactive
slots ride the batched step with their writes pinned to the last cache
row and their outputs discarded; a freed slot's stale K/V is never
attended (see ``cache_manager.py``).

Quantized serving (docs/QUANTIZATION.md): ``FLEETX_SERVING_KV_DTYPE=int8``
stores decode K/V (slot cache or paged pool) as int8 with per-vector fp32
scales — quantize-on-write in ``SelfAttention._update_cache``, dequant in
VMEM inside the flash-decode kernels — roughly halving the HBM bytes the
bandwidth-bound decode tick moves (and the pages a cached token pins).
``FLEETX_SERVING_WEIGHT_DTYPE=int8`` serves weight-only-PTQ params: the
tree is quantized once at construction (``ops/quant.quantize_tree_int8``)
and dequantized INSIDE the jitted prefill/decode calls, so XLA fuses the
scale multiply into each matmul consumer and HBM holds int8 + scales.
Replay recovery re-prefills through the same jitted seams, so crash
safety is precision-agnostic. Both knobs default off ("bf16" = the model
compute dtype), and the default path stays byte-identical; quantized
configs trade byte parity for a documented token/logit tolerance.

Speculative decoding (``FLEETX_SERVING_SPEC=1``, default off;
docs/SERVING.md "Speculative decoding"): each tick a proposer
(serving/spec.py — n-gram prompt lookup by default, optionally a small
draft model) guesses up to ``FLEETX_SERVING_SPEC_K`` tokens per active
request, the drafts are written append-only into the request's pages,
and ONE batched prefill-shaped verification call — the same multi-token
``cache_positions`` seam replay/chunked prefill already write through —
scores all k+1 positions at once. Greedy acceptance keeps the longest
draft prefix matching the target argmax plus the correction token, so
greedy streams are BYTE-IDENTICAL to the non-speculative engine by
construction; sampling acceptance runs standard distribution-preserving
speculative rejection (accept d with prob p(d) for the deterministic
proposers, resample the rejection residual otherwise), consuming exactly
one rng split per EMITTED token so replay recovery's stream
reconstruction is unchanged. Rejected tails cost nothing: rollback is a
host-side pointer move (the per-row live length simply doesn't advance
past the accepted prefix — the no-zeroing live-window contract already
leaves stale K/V beyond the window unattended), and the engine clamps
each request's draft length to min(remaining token budget, page/lane
capacity) BEFORE proposing, so a k-token draft can never overrun
``max_length`` or its storage mid-verify. A verify-call fault rides the
same transactional-tick rollback + replay recovery as a plain decode
fault (per-request draft counters are snapshot-covered), and the
proposer's lane state resets with recovery and rebuilds lazily from
host truth.

Mesh-sharded serving (``mesh=`` kwarg; docs/SERVING.md "Mesh-sharded
serving"): the engine runs its device side over a TP/FSDP
``jax.sharding.Mesh`` (arXiv 2105.04663 GSPMD / 2204.06514 pjit are the
blueprint), so a model that does not fit — or does not hit latency
targets — on one chip serves from a mesh. What shards: params (and
quantized weight trees) get TP(mp)/FSDP shardings from the model's own
logical-axis metadata via ``parallel/sharding.serving_param_shardings``,
and BOTH cache layouts (slot and paged pools, int8 scale leaves
included) split their heads axis over ``mp`` — per-device cache bytes
and ``cache_nbytes()`` divide by the mp extent, which is the capacity
math a router prices replicas with. What replicates: the decode-lane
state dict, block tables, and every scalar. Every jitted device call
(bucketed prefill, chunk prefill, decode tick, spec verify, probe,
replay) runs under the mesh, and the flash-decode kernels run per-shard
inside ``shard_map`` over the local head slice (the PR 1 "meshes →
dense fallback" guard is lifted; ops/pallas/decode_attention.py), so
the live-prefix HBM-traffic contract holds per device. Host bookkeeping
— scheduler, lanes, trie, host spill tier, transactional snapshots,
replay recovery — is pure-host and MESH-AGNOSTIC: ``recover()``
rebuilds sharded device state from the same host truth, and greedy
streams are byte-identical to the single-device engine (the per-head
kernel math is unsharded math; the only reduction GSPMD splits is the
row-parallel output projection). pp/cp extents and head counts the mp
extent does not divide raise at construction.

Unsupported request shapes (beam search, repetition penalty, forced
EOS/BOS) raise at construction/submit — they need cross-step state the
slot loop does not carry; use the one-shot ``generate()`` for those.

Admission control & deadlines (docs/RESILIENCE.md): the queue is bounded
(``FLEETX_SERVING_MAX_QUEUE``, 0 = unbounded) and a full queue REJECTS
at submit with :class:`QueueFull` — explicit backpressure the caller can
act on, instead of unbounded growth under overload. Per-request
``queue_ttl_s`` (time waiting for a slot) and ``deadline_s`` (total
submit→finish lifetime) retire requests with ``finish_reason="timeout"``;
``cancel(request_id)`` frees a queued or in-flight request's slot
immediately. A raising ``on_token`` callback retires only ITS request
(``finish_reason="error"``) — neighbors' token streams are untouched.
With no limits configured every knob is inert and token outputs are
byte-identical to the unlimited engine.

Crash safety (docs/RESILIENCE.md serving-recovery):

- **Transactional ticks** — ``step()`` snapshots the pure-host
  bookkeeping (scheduler queue, request table, active map, results)
  before any device work and rolls it back on ANY exception, so a failed
  tick never loses or duplicates a token, a request, or a queue position.
- **Replay recovery** — device caches are pure functions of each
  request's ``prompt + emitted tokens``, so :meth:`ServingEngine.recover`
  rebuilds a fresh cache/pool/lane-table and re-prefills every active
  request's full history (the prefix trie makes shared prompts cheap),
  resuming byte-identically after a rolled-back tick or an external
  device reset. Bounded by ``FLEETX_SERVING_MAX_RECOVERIES`` consecutive
  recoveries without a productive tick → :class:`RecoveryExhausted`.
- **Poison quarantine** — a decode tick that fails again right after a
  recovery triggers bisection probing over the active set (non-donating
  probe ticks whose outputs are discarded) to isolate the request whose
  presence kills the batch; it is retired ``finish_reason="error"`` with
  its partial tokens and every neighbor continues byte-identically. A
  prefill that fails twice for the same request retires that request
  directly — no bisection needed, the culprit is known.
- **Watchdog** — with ``FLEETX_SERVING_TICK_TIMEOUT_S`` > 0 device calls
  run on a monitor-thread executor; a tick exceeding the timeout banks
  diagnostics in ``engine.hang_diagnostics`` and raises
  :class:`TickTimeout` into the same rollback→recovery path (the hung
  call is abandoned; recovery rebuilds fresh buffers).
- **Graceful drain** — :meth:`shutdown` (or SIGTERM via
  :meth:`install_sigterm_handler` → :meth:`request_shutdown`) stops
  admission (:class:`ShuttingDown` rejects at submit), keeps ticking so
  in-flight AND queued work finishes inside the grace window, then
  retires whatever remains with partial tokens and
  ``finish_reason="shutdown"`` — the hook a multi-replica router needs
  to rotate a replica out without dropping a byte.
- **Admit-with-history** — ``submit(history=...)`` aims the replay seam
  at a request ANOTHER replica started: the pre-emitted tokens replay
  through the same one-call prefill recovery uses, the RNG position
  reconstructs, and decoding continues from the last delivered token
  without re-firing its callbacks — the zero-token-loss failover
  primitive of the multi-replica router (serving/router.py). The
  :meth:`health`/:meth:`take_result`/:meth:`emitted_tokens`/
  :meth:`declare_dead` quartet is the rest of the router-facing
  surface.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import os
import time
import weakref
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from fleetx_tpu.obs import http as obs_http
from fleetx_tpu.obs.events import emit as obs_emit
from fleetx_tpu.obs.tracing import span
from fleetx_tpu.models.gpt.generation import (
    GenerationConfig,
    _top_p_cutoff_bisect,
)
from fleetx_tpu.serving.model_protocol import GPTExecutor
from fleetx_tpu.serving.cache_manager import (
    DiskPageStore,
    HostPageStore,
    PagedKVCacheManager,
    SlotKVCacheManager,
    TieredPageStore,
    scatter_slot,
)
from fleetx_tpu.resilience.faults import faults
from fleetx_tpu.serving.metrics import ServingMetrics
from fleetx_tpu.serving.scheduler import FIFOScheduler, Request
from fleetx_tpu.serving.spec import build_proposer
from fleetx_tpu.utils.log import logger

__all__ = [
    "QueueFull",
    "RecoveryExhausted",
    "ServingEngine",
    "ServingResult",
    "ShuttingDown",
    "TickTimeout",
    "filter_logits",
    "sample_tokens",
]

_NEG = -1e9


class QueueFull(RuntimeError):
    """Admission refused: the queue is at ``FLEETX_SERVING_MAX_QUEUE``.
    The explicit backpressure signal — callers shed load or retry later;
    the engine never buffers unboundedly under overload."""


class ShuttingDown(RuntimeError):
    """Admission refused: the engine is draining toward shutdown
    (``QueueFull``-style explicit reject — a router in front of N
    replicas routes around a draining one instead of queueing into it)."""


class TickTimeout(RuntimeError):
    """A device tick exceeded ``FLEETX_SERVING_TICK_TIMEOUT_S``. Raised by
    the watchdog into the transactional-tick rollback, which then runs the
    recovery path; diagnostics are banked in ``engine.hang_diagnostics``."""


class RecoveryExhausted(RuntimeError):
    """More than ``FLEETX_SERVING_MAX_RECOVERIES`` consecutive recoveries
    without a productive tick: the fault is not request-shaped (quarantine
    would have cleared it), so the engine declares itself dead rather than
    spin forever — the caller restarts the process/device."""


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _deactivate(st, slot):
    # clear one slot's active lane; its row still rides the batched decode
    # step (outputs discarded) exactly like any other free slot
    return {**st, "active": st["active"].at[slot].set(False)}


def filter_logits(logits, temperature, top_k, top_p, *, topk_cap: int):
    """THE per-row sampling filter pipeline — temperature scale, top-k
    via ONE static ``lax.top_k(topk_cap)`` partial sort (the per-row
    cutoff is the row's k-th entry; ``top_k`` pre-normalized to
    ``[0, topk_cap]``, 0 = no filter), then the sort-free top-p
    threshold bisection from ``generation.py`` with per-row targets.
    ``logits`` [n, vocab] with per-row knobs [n] → filtered logits
    (removed entries at ``_NEG``). Shared by :func:`sample_tokens` and
    the speculative ``_verify_fn`` so the two sampling paths can never
    drift apart."""
    vocab = logits.shape[-1]
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    cap = max(1, min(topk_cap, vocab))
    vals = jax.lax.top_k(scaled, cap)[0]  # [n, cap] descending
    kth = jnp.take_along_axis(
        vals, jnp.clip(top_k - 1, 0, cap - 1)[:, None], axis=-1
    )
    filtered = jnp.where((top_k > 0)[:, None] & (scaled < kth), _NEG, scaled)
    probs, thresh = _top_p_cutoff_bisect(filtered, top_p[:, None])
    return jnp.where(probs >= thresh, filtered, _NEG)


def sample_tokens(logits, keys, greedy, temperature, top_k, top_p, *,
                  topk_cap: int):
    """Vectorized per-row sampler: each batch row applies ITS OWN decode
    strategy (greedy flag, temperature, top-k, top-p) and draws from its
    own rng key — the per-request-overrides core of the serving engine.
    Filtering is :func:`filter_logits`; greedy rows take the argmax of
    the unfiltered logits (exactly ``_sample``'s greedy branch, so
    greedy parity with ``generate()`` holds per row)."""
    greedy_tok = jnp.argmax(logits, axis=-1)
    filtered = filter_logits(logits, temperature, top_k, top_p,
                             topk_cap=topk_cap)
    sampled = jax.vmap(jax.random.categorical)(keys, filtered)
    return jnp.where(greedy, greedy_tok, sampled).astype(jnp.int32)


@dataclasses.dataclass
class ServingResult:
    """Final outcome of one request: generated tokens + latency stats."""

    id: int
    prompt: np.ndarray
    tokens: np.ndarray  # generated tokens (EOS included when hit)
    # eos | max_length | cache_full | timeout | cancelled | error | shutdown
    # ("error" covers raising callbacks AND quarantined poison requests;
    # "shutdown" = graceful-drain grace window closed, partial tokens kept)
    finish_reason: str
    ttft_s: float
    latency_s: float

    @property
    def sequence(self) -> np.ndarray:
        """prompt + generated tokens, the one-shot ``generate()`` layout
        minus the post-EOS pad fill."""
        return np.concatenate([self.prompt, self.tokens])


class ServingEngine:
    """Slot-based continuous-batching serving loop (module docstring)."""

    def __init__(self, model, variables, *, slots: Optional[int] = None,
                 cache_len: Optional[int] = None,
                 gen_cfg: Optional[GenerationConfig] = None,
                 base_seed: int = 0, topk_cap: Optional[int] = None,
                 prefill_bucket: Optional[int] = None,
                 log_every: Optional[int] = None,
                 metrics: Optional[ServingMetrics] = None,
                 max_queue: Optional[int] = None,
                 queue_ttl_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 paged: Optional[bool] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 max_recoveries: Optional[int] = None,
                 tick_timeout_s: Optional[float] = None,
                 grace_s: Optional[float] = None,
                 kv_dtype: Optional[str] = None,
                 weight_dtype: Optional[str] = None,
                 prefill_chunk: Optional[int] = None,
                 host_cache_bytes: Optional[int] = None,
                 spec: Optional[bool] = None,
                 spec_k: Optional[int] = None,
                 spec_proposer=None,
                 role: Optional[str] = None,
                 disk_cache_dir: Optional[str] = None,
                 disk_cache_bytes: Optional[int] = None,
                 mesh=None, executor=None):
        gen_cfg = gen_cfg or GenerationConfig(decode_strategy="greedy")
        # the model-side serving contract (serving/model_protocol.py):
        # every model compute call below goes through the executor, and
        # the capability flags gate which engine features are legal —
        # the GPT executor is pure delegation to the pre-extraction
        # functions, so this engine's behavior is byte-identical. A
        # default executor is built LATER, over the decode-configured
        # model clone (cache length/pages ride cfg) — here only the
        # capability gates run.
        self.executor = executor
        self.capabilities = (executor.capabilities if executor is not None
                             else GPTExecutor(model).capabilities)
        self.model_family = self.capabilities.family
        if not self.capabilities.has_kv_cache:
            raise ValueError(
                f"model family {self.model_family!r} has no KV cache "
                "(capabilities.has_kv_cache=False); serve it behind a "
                "KV-free engine (serving/batch_engine.py), not "
                "ServingEngine")
        if gen_cfg.repetition_penalty != 1.0:
            raise ValueError("continuous batching does not support "
                             "repetition_penalty (use one-shot generate())")
        if gen_cfg.forced_eos_token_id is not None:
            raise ValueError("continuous batching does not support "
                             "forced_eos_token_id")
        self.gen_cfg = gen_cfg
        # mesh-native serving (module docstring "Mesh-sharded serving"):
        # params shard TP(mp)/FSDP, caches shard heads-over-mp, host
        # bookkeeping stays mesh-agnostic. Validated up front — an
        # unshardable config must fail here with a cause, not deep
        # inside the first traced model.apply.
        self.mesh = mesh
        self._rules = None
        if mesh is not None:
            from fleetx_tpu.parallel.sharding import make_rules

            shape = dict(mesh.shape)
            if shape.get("pp", 1) > 1 or shape.get("cp", 1) > 1:
                raise ValueError(
                    f"serving mesh {shape} has pp/cp extents; the decode "
                    "tick runs the full layer stack per device — use a "
                    "(dp, fsdp, mp) mesh")
            if model.cfg.num_attention_heads % shape.get("mp", 1):
                raise ValueError(
                    f"num_attention_heads {model.cfg.num_attention_heads} "
                    f"does not divide over mp={shape.get('mp', 1)}; the "
                    "kv cache shards over heads (module docstring)")
            if shape.get("dp", 1) > 1:
                # the engine shards nothing over dp (mp splits heads,
                # fsdp splits params): a dp extent just replicates the
                # decode tick on every dp device. Allowed — one engine
                # can own a predict()-shaped mesh — but the hardware
                # would serve more traffic as dp separate REPLICAS.
                logger.warning(
                    "serving: mesh has dp=%d — the decode tick is "
                    "REPLICATED over the dp axis (no throughput gain); "
                    "prefer %d independent engine replicas behind a "
                    "router", shape["dp"], shape["dp"])
            self._rules = make_rules(fsdp_params=shape.get("fsdp", 1) > 1)
        self.slots = slots or _env_int("FLEETX_SERVING_SLOTS", 8)
        self.paged = (paged if paged is not None
                      else _env_int("FLEETX_SERVING_PAGED", 1) == 1)
        self.page_size = page_size or _env_int("FLEETX_SERVING_PAGE_SIZE", 16)
        # phase-disaggregated serving (docs/SERVING.md "Disaggregated
        # prefill/decode"): a PREFILL-role replica runs admission and
        # (chunked) prefill to completion, then PARKS the request for
        # export_kv() instead of decoding; a DECODE-role replica is a
        # normal engine whose router feeds it shipped KV. "both" — the
        # default — is the colocated engine, byte-identical to before.
        self.role = (role or os.environ.get("FLEETX_SERVING_ROLE", "")
                     or "both")
        if self.role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or 'both', got "
                f"{self.role!r}")
        if self.role == "prefill" and not self.paged:
            raise ValueError(
                "role='prefill' requires the paged cache (paged=True): "
                "export_kv() ships whole pages through the block table")
        cache_len = (cache_len
                     or _env_int("FLEETX_SERVING_CACHE_LEN", 0)
                     or model.cfg.max_position_embeddings)
        if self.paged:
            # per-request logical capacity rounds to whole pages (the page
            # is also the flash-decode DMA tile, so this covers the 8-row
            # rounding below)
            cache_len += -cache_len % self.page_size
        elif model.cfg.use_flash_attention:
            # round up to the flash-decode kernel's 8-row KV tile so the
            # fast path engages; the extra rows are never attended
            cache_len += -cache_len % 8
        self.cache_len = cache_len
        # quantized serving (module docstring): kv int8 halves decode HBM
        # traffic + pages per cached token; weight int8 halves/quarters
        # servable-param HBM. "bf16" = the model's native compute dtype.
        from fleetx_tpu.ops.quant import resolve_serving_dtype

        self.kv_dtype = resolve_serving_dtype(
            kv_dtype, "FLEETX_SERVING_KV_DTYPE")
        self.weight_dtype = resolve_serving_dtype(
            weight_dtype, "FLEETX_SERVING_WEIGHT_DTYPE")
        decode_kv = "int8" if self.kv_dtype == "int8" else None
        if self.paged:
            # default pool = the slot cache's capacity in pages + the
            # reserved trash page; short requests then leave pages free
            # for extra concurrent tenants instead of padding dead slots
            self.num_pages = (num_pages
                              or _env_int("FLEETX_SERVING_PAGES", 0)
                              or self.slots * (cache_len // self.page_size)
                              + 1)
            self.prefix_cache = (
                prefix_cache if prefix_cache is not None
                else _env_int("FLEETX_SERVING_PREFIX_CACHE", 1) == 1)
            self.model = model.clone(cfg=dataclasses.replace(
                model.cfg, decode_cache_len=cache_len,
                decode_num_pages=self.num_pages,
                decode_page_size=self.page_size,
                decode_kv_dtype=decode_kv))
        else:
            self.num_pages = 0
            self.prefix_cache = False
            self.model = model.clone(cfg=dataclasses.replace(
                model.cfg, decode_cache_len=cache_len,
                decode_num_pages=None, decode_page_size=None,
                decode_kv_dtype=decode_kv))
        if self.executor is None:
            # wrap the decode-configured clone: init_cache/forward read
            # decode_cache_len/pages off cfg, so the executor must see
            # the same model object every pre-extraction call site saw
            self.executor = GPTExecutor(self.model,
                                        family=self.model_family)
        elif hasattr(self.executor, "bind"):
            self.executor = self.executor.bind(self.model)
        self.params = (variables["params"]
                       if isinstance(variables, dict) and "params" in variables
                       else variables)
        # weight-only PTQ once, up front (no-op at bf16): servable params
        # live in HBM as int8 + per-channel scales; every jitted prefill/
        # decode call dequantizes INSIDE the jit (_dequant_params), so
        # XLA fuses the scale multiply into the matmul consumers.
        # Idempotent for pre-quantized trees (InferenceEngine).
        from fleetx_tpu.ops.quant import serving_weight_params

        self.params = serving_weight_params(self.params, self.weight_dtype)
        if self.mesh is not None:
            # TP(mp)/FSDP-shard the (possibly quantized) servable tree:
            # committed NamedSharding inputs drive GSPMD inside every jit
            # from here on, no per-call annotations needed
            self.params = self._shard_params(self.params)
        self.topk_cap = topk_cap or _env_int("FLEETX_SERVING_TOPK_CAP", 64)
        self.prefill_bucket = (prefill_bucket
                               or _env_int("FLEETX_SERVING_PREFILL_BUCKET", 32))
        # chunked prefill (module docstring): 0/off = today's whole-prompt
        # prefill-on-insert, byte-identical; >0 bounds per-tick prefill
        # work to one chunk-sized call so decode TPOT never stalls longer
        self.prefill_chunk = (prefill_chunk if prefill_chunk is not None
                              else _env_int("FLEETX_SERVING_PREFILL_CHUNK", 0))
        if self.prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0, got {self.prefill_chunk}")
        # host-DRAM KV spill tier (module docstring): 0/off = LRU eviction
        # destroys warm trie pages (today's behavior); >0 bounds the
        # pinned-host store warm pages spill into instead
        host_bytes = (host_cache_bytes if host_cache_bytes is not None
                      else _env_int("FLEETX_SERVING_HOST_CACHE_BYTES", 0))
        # cluster page tier (docs/SERVING.md "Disaggregated prefill/
        # decode"): a shared-directory, byte-bounded, content-addressed
        # disk store every replica points at — the prefix set one
        # replica's DRAM budget would miss stays warm fleet-wide. With
        # both tiers configured, TieredPageStore write-throughs puts and
        # promotes disk hits back into DRAM.
        disk_dir = (disk_cache_dir if disk_cache_dir is not None
                    else os.environ.get("FLEETX_SERVING_DISK_CACHE_DIR", ""))
        disk_bytes = (disk_cache_bytes if disk_cache_bytes is not None
                      else _env_int("FLEETX_SERVING_DISK_CACHE_BYTES", 0))
        tiered = self.paged and self.prefix_cache
        dram = HostPageStore(host_bytes) if host_bytes > 0 and tiered else None
        self._disk_store = (DiskPageStore(disk_dir, disk_bytes)
                            if disk_dir and disk_bytes > 0 and tiered
                            else None)
        self._dram_store = dram
        self._host_store = (
            TieredPageStore(dram, self._disk_store)
            if dram is not None and self._disk_store is not None
            else dram if dram is not None else self._disk_store)
        self.log_every = (log_every if log_every is not None
                          else _env_int("FLEETX_SERVING_LOG_EVERY", 0))
        # admission control (module docstring): all default OFF — an
        # engine with no limits configured behaves byte-identically to the
        # pre-resilience engine
        self.max_queue = (max_queue if max_queue is not None
                          else _env_int("FLEETX_SERVING_MAX_QUEUE", 0))
        self.queue_ttl_s = (queue_ttl_s if queue_ttl_s is not None
                            else _env_float("FLEETX_SERVING_QUEUE_TTL_S", 0.0))
        self.deadline_s = (deadline_s if deadline_s is not None
                           else _env_float("FLEETX_SERVING_DEADLINE_S", 0.0))
        # crash safety (module docstring): recovery budget, hung-tick
        # watchdog, graceful-drain grace window
        self.max_recoveries = (
            max_recoveries if max_recoveries is not None
            else _env_int("FLEETX_SERVING_MAX_RECOVERIES", 8))
        self.tick_timeout_s = (
            tick_timeout_s if tick_timeout_s is not None
            else _env_float("FLEETX_SERVING_TICK_TIMEOUT_S", 0.0))
        self.grace_s = (grace_s if grace_s is not None
                        else _env_float("FLEETX_SERVING_GRACE_S", 30.0))
        self._recoveries_consecutive = 0
        self._tick_strikes = 0              # consecutive failed decode ticks
        self._prefill_strikes: Dict[int, int] = {}  # request id -> failures
        self._fault_ctx = None              # ("prefill", rid) during prefill
        self._fault_ticks = 0               # attempted decode device calls
        self._fault_prefills = 0            # attempted prefill device calls
        self._fault_ships = 0               # attempted KV exports
        self._watchdog = None               # lazy single-thread executor
        self.hang_diagnostics = None        # banked by the watchdog
        self._shutting_down = False
        self._dead = False  # RecoveryExhausted was raised; healthz -> 503
        self._shutdown_deadline = None
        self._shutdown_event_pending = False
        self._prev_sigterm = None
        self._now = time.perf_counter  # swappable clock (chaos tests)
        if self.paged:
            self.cache_manager = PagedKVCacheManager(
                self.model, self.slots, cache_len, self.num_pages,
                self.page_size, prefix_cache=self.prefix_cache,
                host_store=self._host_store)
        else:
            self.cache_manager = SlotKVCacheManager(self.model, self.slots,
                                                    cache_len)
        # mesh: the freshly-built cache tree splits its heads over mp
        # (scale leaves ride the same rule); state/tables replicate
        self.cache_manager.cache = self._shard_cache(self.cache_manager.cache)
        self._tables_dev = None       # device mirror of the block tables,
        self._tables_version = -1     # refreshed when the manager's moves
        self.scheduler = FIFOScheduler()
        self.metrics = metrics or ServingMetrics(self.slots)
        self.metrics.set_role(self.role)
        self._publish_quant_metrics()
        self._base_key = jax.random.PRNGKey(base_seed)
        self._next_id = 0
        self._ticks = 0
        self._active: Dict[int, Request] = {}  # slot -> request
        # chunked prefill: slot -> the request mid-prefill there (at most
        # one by policy — the FIFO head — a dict for snapshot symmetry)
        self._prefilling: Dict[int, Request] = {}
        # disaggregated prefill: slot -> request whose prompt KV is fully
        # written on this PREFILL-role replica, parked (lane + pages held,
        # decode lane inert) until the router calls export_kv()
        self._prefilled: Dict[int, Request] = {}
        self._results: Dict[int, ServingResult] = {}
        self._state = self._replicate(self._init_state())
        # buffer donation halves cache HBM residency on TPU; skipped on
        # CPU/interpret runs where XLA would only warn about it
        donate = jax.default_backend() in ("tpu", "axon")
        # all_greedy is static: an all-greedy tick (the common serving mix
        # for deterministic decode) skips the sampler entirely — at most
        # two cached compilations
        self._decode_jit = jax.jit(
            self._decode_fn, static_argnums=(4,),
            donate_argnums=(1, 2) if donate else ())
        # bisection probes: NO donation — a probe's discarded outputs must
        # leave the committed cache/state buffers untouched
        self._probe_jit = jax.jit(self._decode_fn, static_argnums=(4,))
        self._admit_jit = jax.jit(self._admit_fn, donate_argnums=())
        self._deactivate_jit = jax.jit(_deactivate)
        # chunked slot prefill: fold the finished batch-1 working cache
        # into the big slot cache (both operands are dead afterwards);
        # the pin keeps the folded cache on its mesh layout

        def _scatter_pinned(cache, small, slot):
            return self._pin_cache(scatter_slot(cache, small, slot))

        self._scatter_jit = jax.jit(
            _scatter_pinned, donate_argnums=(0, 1) if donate else ())
        self._prefill_jits = {}  # (kind, bucket_len) -> jitted prefill
        self._donate_cache = donate
        # speculative decoding (module docstring): default OFF — a spec-
        # disabled engine never touches the proposer/verify machinery and
        # stays byte-identical to the pre-spec engine. An explicit
        # spec_proposer IMPLIES speculation (the kwarg wins over the
        # env); handing one to an explicitly spec=False engine is a
        # config contradiction, not something to ignore silently.
        self.spec = (spec if spec is not None
                     else True if spec_proposer is not None
                     else _env_int("FLEETX_SERVING_SPEC", 0) == 1)
        if spec_proposer is not None and not self.spec:
            raise ValueError(
                "spec_proposer was given but speculation is explicitly "
                "disabled (spec=False); drop one or the other")
        self.spec_k = (spec_k if spec_k is not None
                       else _env_int("FLEETX_SERVING_SPEC_K", 4))
        self._proposer = None
        if self.spec and not self.capabilities.supports_spec:
            raise ValueError(
                f"model family {self.model_family!r} does not support "
                "speculative decoding (capabilities.supports_spec=False)")
        if self.spec:
            if self.spec_k < 1:
                raise ValueError(
                    f"spec_k must be >= 1 when speculation is on, got "
                    f"{self.spec_k} (FLEETX_SERVING_SPEC_K)")
            self._proposer = spec_proposer or build_proposer(
                os.environ.get("FLEETX_SERVING_SPEC_DRAFT", ""),
                self.model, {"params": self.params},
                prefill_bucket=self.prefill_bucket)
            self._proposer.bind(self.slots, self.cache_len)
            # one compile per (k, all_greedy) actually seen: k only drops
            # below spec_k when a lane nears cache capacity
            self._verify_jit = jax.jit(
                self._verify_fn, static_argnums=(6, 7),
                donate_argnums=(1, 2) if donate else ())
            obs_emit("spec_enabled", k=self.spec_k,
                     proposer=self._proposer.name)
        # observability (docs/OBSERVABILITY.md): one env var makes this
        # replica scrapeable, and /healthz turns 503 the instant
        # request_shutdown() flips _shutting_down — the rotate-me-out
        # signal the multi-replica router (ROADMAP item 3) consumes.
        # weakref probe: the health registry must never pin a dead engine.
        obs_http.maybe_start_from_env()
        self._health_name = f"serving_engine_{self.metrics.engine_label}"
        ref = weakref.ref(self)

        def _healthy():
            eng = ref()
            if eng is None:
                return True  # owner gone; finalize unregisters shortly
            # the full healthz body (state/queue_depth/active), not a bare
            # bool: the router and external LBs get a rotate-out REASON
            return eng.health()

        obs_http.register_health(self._health_name, _healthy)
        weakref.finalize(self, obs_http.unregister_health, self._health_name)

    # ------------------------------------------------------------ lifecycle

    def submit(self, prompt, *, max_length: Optional[int] = None,
               min_length: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               decode_strategy: Optional[str] = None,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None, top_p: Optional[float] = None,
               seed: Optional[int] = None, rng_key: Optional[jax.Array] = None,
               on_token=None, queue_ttl_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               history=None, kv_payloads=None) -> int:
        """Queue one request; returns its id. Kwargs override the engine's
        ``gen_cfg`` defaults per request; ``seed`` (or a raw ``rng_key``)
        pins this request's private sampling stream, ``on_token`` streams
        ``(request_id, token, finished)`` per decoded token.
        ``queue_ttl_s``/``deadline_s`` override the engine's admission
        limits (0 disables). Raises :class:`QueueFull` when the bounded
        queue is at ``FLEETX_SERVING_MAX_QUEUE`` and :class:`ShuttingDown`
        once :meth:`shutdown`/:meth:`request_shutdown` has been called.

        ``history`` is the ADMIT-WITH-HISTORY seam (the multi-replica
        router's zero-token-loss failover, docs/SERVING.md): tokens this
        request already emitted on another replica before it died. The
        request admits through the replay prefill seam — its
        ``prompt + history[:-1]`` K/V rebuilt in one call, its RNG stream
        advanced to exactly the position ``len(history)`` emitted tokens
        would have consumed (so sampling continues the SAME stream the
        original ``rng_key`` defines — pass the original key) — and
        decoding continues from ``history[-1]``. History tokens count
        against ``max_length`` and ride the final result, but ``on_token``
        fires only for NEWLY decoded tokens (the caller already delivered
        the history). A history that is already terminal (ends in EOS, or
        exhausts ``max_length``) is a caller bug and raises ValueError —
        migrate unfinished requests only.

        ``kv_payloads`` is the DISAGGREGATED-HANDOFF seam (docs/
        SERVING.md "Disaggregated prefill/decode"): the wire-format page
        blobs a PREFILL-role replica's :meth:`export_kv` shipped for
        this prompt, one per page covering the prompt, alongside
        ``history=[t0, ...]`` (the first token that replica emitted).
        The blobs are decoded and validated HERE — a corrupted ship
        raises ValueError at submit, before the request ever queues, so
        the router can fall back to the replay path — and admission
        writes them straight into freshly allocated pages through the
        revive scatter: no prefill forward at all, byte-identical
        decoding to the colocated engine."""
        if self._shutting_down:
            self.metrics.record_drain_reject()
            obs_emit("drain_reject", engine=self.metrics.engine_label)
            raise ShuttingDown(
                "engine is draining toward shutdown; submit to another "
                "replica (in-flight requests are finishing under the "
                "grace window)")
        if self.max_queue and self.scheduler.queue_depth >= self.max_queue:
            # dead entries must not hold live ones out: sweep TTL/deadline
            # expiries before judging the bound (step() normally does this,
            # but a submit burst between ticks sees the stale depth)
            self._expire_queued(self._now())
        if self.max_queue and self.scheduler.queue_depth >= self.max_queue:
            self.metrics.record_reject()
            obs_emit("queue_reject", engine=self.metrics.engine_label,
                     queue_depth=self.scheduler.queue_depth)
            raise QueueFull(
                f"admission queue is full ({self.scheduler.queue_depth}/"
                f"{self.max_queue} waiting, {self.cache_manager.active_count}"
                f"/{self.slots} slots busy); retry later or raise "
                "FLEETX_SERVING_MAX_QUEUE")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        g = self.gen_cfg
        strategy = decode_strategy or g.decode_strategy
        if strategy not in ("greedy", "sampling"):
            raise ValueError(
                f"decode_strategy {strategy!r} not servable by continuous "
                "batching (beam search needs one-shot generate())")
        limit = min(self.cache_len, self.model.cfg.max_position_embeddings)
        if prompt.size >= limit:
            raise ValueError(
                f"prompt_len {prompt.size} leaves no decode room "
                f"(cache/position limit {limit})")
        max_new = int(max_length if max_length is not None else g.max_length)
        if prompt.size + max_new > limit:
            clamped = limit - prompt.size
            logger.warning(
                "serving: request %d max_length %d clamped to %d "
                "(prompt %d + limit %d)", self._next_id, max_new, clamped,
                prompt.size, limit)
            max_new = clamped
        min_new = min(int(min_length if min_length is not None
                          else g.min_length), max_new)
        eos = int(eos_token_id if eos_token_id is not None
                  else (g.eos_token_id if g.eos_token_id is not None else -1))
        vocab = self.model.cfg.vocab_size
        tk = int(top_k if top_k is not None else g.top_k)
        if tk <= 0 or tk >= vocab:
            tk = 0  # no filter (matches _sample's vocab clamp)
        elif tk > self.topk_cap:
            logger.warning(
                "serving: request %d top_k %d clamped to topk_cap %d "
                "(FLEETX_SERVING_TOPK_CAP)", self._next_id, tk, self.topk_cap)
            tk = self.topk_cap
        hist = ([] if history is None
                else [int(t) for t in np.asarray(history,
                                                 np.int64).reshape(-1)])
        if hist:
            if eos >= 0 and hist[-1] == eos:
                raise ValueError(
                    f"history of {len(hist)} tokens already ends in EOS "
                    f"({eos}) — the request is terminal; do not migrate it")
            if max_new <= len(hist):
                raise ValueError(
                    f"history ({len(hist)} tokens) meets or exceeds the "
                    f"max_length budget ({max_new}) — the request is "
                    "terminal; do not migrate it")
        decoded_pages = None
        if kv_payloads is not None:
            if not self.paged:
                raise ValueError(
                    "kv_payloads requires the paged cache (paged=True): "
                    "shipped KV revives into pages")
            if not hist:
                raise ValueError(
                    "kv_payloads without history: the prefill replica "
                    "sampled the first token — pass it as history=[t0]")
            need = -(-prompt.size // self.page_size)
            if len(kv_payloads) != need:
                raise ValueError(
                    f"kv_payloads has {len(kv_payloads)} page blob(s); a "
                    f"{prompt.size}-token prompt at page_size "
                    f"{self.page_size} ships {need}")
            # decode NOW, not at admission: payload_from_bytes verifies
            # the crc32 trailer, so a corrupted ship fails this submit
            # loudly and the request never enters the queue half-armed
            decoded_pages = [
                HostPageStore.payload_from_bytes(b)
                if isinstance(b, (bytes, bytearray, memoryview)) else b
                for b in kv_payloads]
            for leaf in decoded_pages[0]:
                if leaf is not None and leaf.shape[-3] != self.page_size:
                    raise ValueError(
                        f"shipped pages carry {leaf.shape[-3]} rows; this "
                        f"replica's page_size is {self.page_size} — "
                        "disaggregated replicas must agree on page_size")
        rid = self._next_id
        self._next_id += 1
        if rng_key is None:
            rng_key = (jax.random.PRNGKey(int(seed)) if seed is not None
                       else jax.random.fold_in(self._base_key, rid))
        req = Request(
            id=rid, prompt=prompt, max_new_tokens=max(max_new, 1),
            min_new_tokens=min_new, eos_token_id=eos,
            greedy=strategy == "greedy",
            temperature=float(temperature if temperature is not None
                              else g.temperature),
            top_k=tk,
            top_p=float(top_p if top_p is not None else g.top_p),
            rng_key=rng_key, on_token=on_token,
            submit_time=self._now(),
            queue_ttl_s=float(queue_ttl_s if queue_ttl_s is not None
                              else self.queue_ttl_s),
            deadline_s=float(deadline_s if deadline_s is not None
                             else self.deadline_s),
        )
        # admit-with-history: the pre-emitted tokens ARE the request's
        # token list from the start (a queue-expiry or shutdown retirement
        # before admission must still return them — zero token loss), and
        # _admit routes a non-empty list through the replay prefill seam
        req.tokens.extend(hist)
        req.kv_payloads = decoded_pages
        self.scheduler.submit(req)
        self.metrics.record_submit()
        return rid

    def step(self) -> Dict:
        """One TRANSACTIONAL scheduler tick: the pure-host bookkeeping
        (scheduler queue, request table, active map, results) is
        snapshotted before any device work; any exception rolls it back to
        the exact pre-tick state and runs the recovery path (module
        docstring), so the caller's ticking loop just keeps ticking.
        Returns a summary dict (``timed_out`` lists this tick's deadline
        victims; ``recovered`` marks a rolled-back-and-recovered tick).
        Raises only :class:`RecoveryExhausted` (the engine is dead)."""
        t0 = self._now()
        self._flush_shutdown_event()
        if (self._shutting_down and self._shutdown_deadline is not None
                and t0 >= self._shutdown_deadline
                and (len(self.scheduler) or self._active
                     or self._prefilling or self._prefilled)):
            # grace window over: everything still in flight returns NOW
            # with its partial tokens
            retired = self._retire_all("shutdown")
            summary = {"admitted": 0, "decoded": 0, "retired": retired,
                       "timed_out": []}
        else:
            # phase-granular transaction: the snapshot re-commits after
            # every successful admission, so a decode fault rolls back ONLY
            # the decode (admitted requests stay admitted — their prefill
            # device work is real and their first token was emitted), and a
            # prefill fault rolls back only the admission in flight. No
            # phase ever commits partially.
            snap = self._snapshot()

            def commit():
                snap.clear()
                snap.update(self._snapshot())

            try:
                with span("serving.tick", tick=self._ticks):
                    summary = self._step_inner(commit)
                if (summary["decoded"] or summary["admitted"]
                        or summary["chunked"]):
                    # a productive device tick proves the engine is healthy
                    # again — re-arm the recovery budget and strike counts
                    self._recoveries_consecutive = 0
                    if summary["decoded"]:
                        self._tick_strikes = 0
            except RecoveryExhausted:
                raise
            except Exception as exc:  # noqa: BLE001 — THE crash-safety seam
                summary = self._handle_tick_fault(snap, exc)
        self._ticks += 1
        self.metrics.observe_tick(self.scheduler.queue_depth,
                                  len(self._active), self._now() - t0)
        if self.paged:
            self.metrics.observe_pages(self.cache_manager.pages_in_use,
                                       self.cache_manager.usable_pages)
        if self._dram_store is not None:
            self.metrics.observe_host_tier(self._dram_store)
        if self._disk_store is not None:
            self.metrics.observe_disk_tier(self._disk_store)
        self.metrics.observe_queue_tokens(
            self.scheduler.queued_tokens() + sum(
                r.prompt_len - r.prefill_pos
                for r in self._prefilling.values()))
        if self.log_every and self._ticks % self.log_every == 0:
            self.metrics.log_snapshot()
        summary.setdefault("recovered", False)
        summary.setdefault("chunked", 0)
        summary["queue_depth"] = self.scheduler.queue_depth
        summary["active_slots"] = len(self._active)
        summary["prefilling"] = len(self._prefilling)
        summary["prefilled"] = len(self._prefilled)
        return summary

    def _step_inner(self, commit=lambda: None) -> Dict:
        """The actual tick body: queued-expiry sweep, prefill work
        (admissions — or, mid-chunked-prefill, exactly one chunk), one
        batched decode step, retirements, active-deadline sweep.
        ``commit`` re-bases the transactional snapshot after each
        completed phase (see :meth:`step`). With chunking enabled the
        tick's prefill budget is ONE chunk-sized device call — a chunk
        of the in-flight prompt or one short admission — so decode never
        stalls longer (the ``prefill_stall_ms`` histogram measures it)."""
        timed_out = self._expire_queued(self._now())
        admitted = 0
        chunked = 0
        prefill_t0 = self._now()
        if self._prefilling:
            # FIFO holds: the mid-prefill request IS the admission head,
            # so nothing else admits until its chunks finish (or expire)
            n, expired = self._chunk_tick()
            chunked += n
            timed_out += expired
            commit()  # chunk progress (prefill_pos) stays committed
        else:
            while (len(self.scheduler)
                   and self._can_admit(self.scheduler.peek())):
                self._admit(self.scheduler.pop_next())
                admitted += 1
                commit()  # an admission that completed stays admitted
                if self.prefill_chunk:
                    break  # one prefill-shaped device call per tick
        if admitted or chunked:
            self.metrics.observe_prefill_stall(self._now() - prefill_t0)
        decoded = len(self._active)
        retired = []
        if decoded:
            retired = (self._tick_decode_spec() if self._proposer is not None
                       else self._tick_decode())
        # fresh clock: prefill/decode above may have eaten the deadline
        timed_out += self._expire_active(self._now())
        return {"admitted": admitted, "decoded": decoded, "chunked": chunked,
                "retired": retired + timed_out, "timed_out": timed_out}

    def cancel(self, request_id: int) -> bool:
        """Cancel a queued or in-flight request: its slot (if any) is freed
        for the next admission THIS instant and its partial output is
        recorded with ``finish_reason="cancelled"``. Returns False when the
        id is unknown or already finished."""
        now = self._now()
        req = self.scheduler.remove(request_id)
        if req is None:
            for r in (list(self._active.values())
                      + list(self._prefilling.values())
                      + list(self._prefilled.values())):
                if r.id == request_id:
                    req = r
                    break
        if req is None:
            return False
        self._evict(req, "cancelled", now)
        obs_emit("request_cancelled", request=request_id)
        return True

    def prewarm(self, prompt) -> int:
        """Pull ``prompt``'s prefix pages out of the host/disk tiers into
        the device trie BEFORE this engine takes traffic (the
        autoscaler's scale-up pre-warm, docs/SERVING.md "Per-tenant QoS &
        autoscaling"). A fresh replica sharing a :class:`DiskPageStore`
        with the fleet starts with a cold device trie but a warm store;
        this revives the longest already-persisted prefix through the
        normal alloc path (revived pages carry real K/V) and immediately
        frees the lane, parking the pages zero-ref-warm in the trie — so
        the replica's first real request prefix-hits instead of
        re-prefilling. Returns the number of prefix tokens now warm
        (0: not paged / no prefix cache / nothing persisted / pool busy).

        Deliberately NEVER registers fresh pages: only pages revived
        with actual K/V may enter the trie, or later matches would serve
        garbage."""
        if not (self.paged and self.prefix_cache):
            return 0
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0 or prompt.size >= self.cache_len:
            return 0
        pool = self.cache_manager.pool
        chunks = pool._chunks(prompt)
        path = pool._match_path(chunks)
        warm = pool._match_host(chunks, path)
        covered = len(path) + len(warm)
        if covered == 0:
            return 0
        # alloc() shares at most (n-1)//page_size full chunks, so to
        # claim all `covered` warm chunks the probe prompt must span one
        # token PAST them (capped by the real prompt)
        n = min(int(prompt.size), covered * self.page_size + 1)
        if not self.cache_manager.can_admit(prompt[:n]):
            return 0
        got = self.cache_manager.alloc(-1, prompt[:n])
        if got is None:
            return 0
        lane, shared = got
        # free() parks the revived (now zero-ref) pages warm in the trie
        self.cache_manager.free(lane)
        if shared:
            obs_emit("prefix_prewarmed", engine=self.metrics.engine_label,
                     tokens=int(shared))
        return int(shared)

    def _expire_queued(self, now):
        """Retire queued requests whose queue-TTL/deadline passed (they
        never get a slot; ``finish_reason="timeout"``, empty tokens)."""
        out = []
        for req in self.scheduler.pop_expired(now):
            self._finalize(req, "timeout", now)
            obs_emit("request_timeout", request=req.id, where="queue")
            out.append(req.id)
        return out

    def _expire_active(self, now):
        """Retire in-flight requests past their total deadline, freeing
        their slots; partial tokens are kept in the result."""
        out = []
        for req in list(self._active.values()):
            if req.deadline_s and now - req.submit_time > req.deadline_s:
                self._evict(req, "timeout", now)
                obs_emit("request_timeout", request=req.id, where="active")
                out.append(req.id)
        return out

    def _evict(self, req: Request, reason: str, now: float) -> None:
        """THE mid-flight retirement path (cancel / deadline / callback
        error): deactivate the request's decode lane on device if it holds
        one, free the slot, record the partial result."""
        if req.slot is not None:
            self._state = self._deactivate_jit(
                self._state, jnp.asarray(req.slot, jnp.int32))
        self._finalize(req, reason, now)

    # ------------------------------------------------------- crash safety

    def _snapshot(self):
        """Capture the pure-host bookkeeping a tick can mutate. Device
        state is deliberately NOT captured: a failed device call may have
        consumed donated buffers, so rollback restores host truth and
        :meth:`recover` rebuilds the device side from it. Metrics stay
        monotonic (a rolled-back tick's gauge samples are not unwound)."""
        reqs = (list(self.scheduler.snapshot()) + list(self._active.values())
                + list(self._prefilling.values())
                + list(self._prefilled.values()))
        return {
            "queue": self.scheduler.snapshot(),
            "active": dict(self._active),
            "prefilling": dict(self._prefilling),
            "prefilled": dict(self._prefilled),
            "results": dict(self._results),
            # per-request mutable fields the tick touches; tokens rolls
            # back by truncating to its pre-tick length (the list object
            # itself is kept, appends are what a failed tick added).
            # prefill_pos/phase cover chunked-prefill progress, so a
            # mid-chunk fault rolls the request back to its exact
            # pre-tick chunk position (req.chunk_cache is device state —
            # NOT captured; recovery requeues mid-prefill requests and
            # rebuilds it from scratch); spec_proposed/accepted cover the
            # speculative draft counters a mid-verify fault would have
            # advanced
            "reqs": [(r, r.slot, r.admit_time, r.first_token_time,
                      len(r.tokens), r.prefill_pos, r.phase,
                      r.spec_proposed, r.spec_accepted) for r in reqs],
        }

    def _restore(self, snap) -> None:
        self.scheduler.restore(snap["queue"])
        self._active = snap["active"]
        self._prefilling = snap["prefilling"]
        self._prefilled = snap["prefilled"]
        self._results = snap["results"]
        for (r, slot, admit_t, first_t, ntok, ppos, phase, sprop,
             sacc) in snap["reqs"]:
            r.slot = slot
            r.admit_time = admit_t
            r.first_token_time = first_t
            r.prefill_pos = ppos
            r.phase = phase
            r.spec_proposed = sprop
            r.spec_accepted = sacc
            del r.tokens[ntok:]

    def _handle_tick_fault(self, snap, exc: Exception) -> Dict:
        """Rollback + recovery + escalation for one failed tick. Token
        streams are untouched (nothing the failed tick produced was
        committed); the queue and every request are exactly pre-tick."""
        ctx, self._fault_ctx = self._fault_ctx, None
        with span("serving.rollback", tick=self._ticks):
            self._restore(snap)
        victim = ctx[1] if ctx else None
        obs_emit("tick_fault", tick=self._ticks, error=type(exc).__name__,
                 during_prefill=bool(ctx), request=victim)
        logger.error(
            "serving: tick %d failed (%s: %s)%s; host state rolled back, "
            "running replay recovery", self._ticks, type(exc).__name__, exc,
            f" during prefill of request {victim}" if ctx else "")
        if ctx:
            self._prefill_strikes[victim] = (
                self._prefill_strikes.get(victim, 0) + 1)
        else:
            self._tick_strikes += 1
        retired = list(self.recover())
        if ctx and self._prefill_strikes.get(victim, 0) >= 2:
            # a prefill that failed, survived a recovery, and failed again
            # is a poison prompt — and unlike a decode fault, the culprit
            # is already known: the request being admitted
            req = self.scheduler.remove(victim)
            if req is not None:
                logger.error(
                    "serving: quarantining request %d — its prefill failed "
                    "%d times across a recovery; finish_reason='error'",
                    victim, self._prefill_strikes[victim])
                self._finalize(req, "error", self._now())
                self.metrics.record_poison()
                obs_emit("poison_retired", request=victim, via="prefill")
                retired.append(victim)
            self._prefill_strikes.pop(victim, None)
        elif not ctx and self._tick_strikes >= 2:
            # the decode tick failed again right after a recovery: some
            # active request is poison — bisect to find it
            retired += self._bisect_poison()
            self._tick_strikes = 0
        return {"admitted": 0, "decoded": 0, "retired": retired,
                "timed_out": [], "recovered": True}

    def recover(self):
        """Replay recovery: rebuild the device caches, lane table, and
        page pool from host truth, re-prefilling every active request's
        ``prompt + emitted tokens`` (prefix-trie sharing makes common
        prompts one prefill) and reconstructing its decode-lane scalars —
        including the per-request RNG stream position, so sampling
        requests also resume byte-identically. Public: call it after an
        external device reset too. The DEVICE warm prefix cache (retired
        requests' parked pages) is dropped — a correctness-neutral loss —
        but the host spill tier survives: its entries are keyed by token
        content, so the rebuilt pool revives them on the next match.
        Mid-prefill (chunked) requests requeue at the head and restart.
        Returns the ids of requests retired because their own replay
        failed (their fault followed them into recovery — poison)."""
        self._recoveries_consecutive += 1
        self.metrics.record_recovery()
        if self._recoveries_consecutive > self.max_recoveries:
            # the engine is declaring itself dead — flip /healthz to 503
            # BEFORE raising so the router stops sending traffic to a
            # replica whose every further step will fail
            self._dead = True
            raise RecoveryExhausted(
                f"{self._recoveries_consecutive - 1} consecutive recoveries "
                f"without a productive tick (FLEETX_SERVING_MAX_RECOVERIES="
                f"{self.max_recoveries}); the fault is not request-shaped — "
                "restart the engine/device")
        with span("serving.recover",
                  recovery=self.metrics.engine_recoveries):
            old_active = sorted(self._active.items())
            self._active = {}
            # parked (prefilled, awaiting export) requests replay like
            # active ones — their KV died with the device cache — then
            # re-park with the lane deactivated, still export-ready
            old_parked = sorted(self._prefilled.items())
            self._prefilled = {}
            # mid-prefill (chunked) requests: their partial KV died with
            # the device cache and ZERO tokens were emitted, so they go
            # back to the queue HEAD (they were the head when admitted)
            # and restart chunked prefill — byte-identity is structural,
            # and the host tier below keeps their shared prefix cheap
            for _, req in sorted(self._prefilling.items(), reverse=True):
                req.slot = None
                req.prefill_pos = 0
                req.chunk_cache = None
                req.phase = "queued"
                self.scheduler.requeue(req)
            self._prefilling = {}
            self._tables_dev = None
            self._tables_version = -1
            self._state = self._replicate(self._init_state())
            if self.paged:
                # the HOST spill tier survives the rebuild: its entries
                # are keyed by token-chunk path, not trie-node identity,
                # so replayed/requeued prompts revive them from the new
                # pool (only the DEVICE warm cache is a recovery loss)
                self.cache_manager = PagedKVCacheManager(
                    self.model, self.slots, self.cache_len, self.num_pages,
                    self.page_size, prefix_cache=self.prefix_cache,
                    host_store=self._host_store)
            else:
                self.cache_manager = SlotKVCacheManager(
                    self.model, self.slots, self.cache_len)
            # the rebuilt device cache re-commits onto the SAME mesh
            # layout — host truth is mesh-agnostic, the layout is not
            self.cache_manager.cache = self._shard_cache(
                self.cache_manager.cache)
            if self._proposer is not None:
                # draft-lane state is device-adjacent: drop it and let
                # the next propose() rebuild lazily from host truth
                # (deterministic, so post-recovery drafts — and the
                # verified streams — stay byte-identical)
                self._proposer.reset()
            retired = []
            for _, req in old_active:
                req.slot = None
                try:
                    self._replay(req)
                except Exception:  # noqa: BLE001 — isolate, don't cascade
                    logger.exception(
                        "serving: request %d failed its own replay during "
                        "recovery; quarantining it (finish_reason='error', "
                        "%d partial tokens kept)", req.id, len(req.tokens))
                    if req.slot is not None:
                        self.cache_manager.free(req.slot)
                        req.slot = None
                    self._finalize(req, "error", self._now())
                    self.metrics.record_poison()
                    obs_emit("poison_retired", request=req.id, via="replay")
                    retired.append(req.id)
                    continue
                self._active[req.slot] = req
            for _, req in old_parked:
                req.slot = None
                try:
                    self._replay(req)
                except Exception:  # noqa: BLE001 — isolate, don't cascade
                    logger.exception(
                        "serving: parked request %d failed its replay "
                        "during recovery; quarantining it "
                        "(finish_reason='error')", req.id)
                    if req.slot is not None:
                        self.cache_manager.free(req.slot)
                        req.slot = None
                    self._finalize(req, "error", self._now())
                    self.metrics.record_poison()
                    obs_emit("poison_retired", request=req.id, via="replay")
                    retired.append(req.id)
                    continue
                # _replay installs an ACTIVE lane; a parked request must
                # stay off the decode tick until export_kv() ships it
                self._state = self._deactivate_jit(
                    self._state, jnp.asarray(req.slot, jnp.int32))
                req.phase = "prefilled"
                self._prefilled[req.slot] = req
        obs_emit("engine_recovery", number=self.metrics.engine_recoveries,
                 replayed=len(self._active), quarantined=len(retired))
        logger.warning(
            "serving: recovery #%d complete — %d request(s) replayed, %d "
            "quarantined", self.metrics.engine_recoveries,
            len(self._active), len(retired))
        return retired

    def _replay(self, req: Request) -> None:
        """Re-admit one in-flight request into the rebuilt engine: prefill
        its full history (all K/V the decode loop had written: prompt plus
        every emitted token except the last, whose K/V write is the next
        tick's job) and reinstall its lane scalars with ``last_tok`` = the
        last emitted token, ready to decode the next one."""
        n = len(req.tokens)
        history = np.concatenate(
            [req.prompt, np.asarray(req.tokens[:-1], np.int32)])
        if self.paged:
            alloc = self.cache_manager.alloc(req.id, history)
            if alloc is None:
                raise RuntimeError(
                    f"replay alloc failed for request {req.id} "
                    f"({len(history)} history tokens; "
                    f"{self.cache_manager.pool.free_pages} pages free)")
            lane, shared = alloc
            req.slot = lane
            self._paged_prefill_call(req, history[shared:], shared, lane,
                                     replay=True)
            self.cache_manager.register_prefix(lane, req.prompt)
        else:
            slot = self.cache_manager.alloc(req.id, len(history))
            if slot is None:
                raise RuntimeError(
                    f"replay alloc failed for request {req.id}: no free slot")
            req.slot = slot
            self._slot_prefill_call(req, history, slot, replay=True)
        # reconstruct the request's RNG stream position: one split at
        # admit, one per decode tick it was active in (greedy requests
        # never consume their stream, so the value is irrelevant there)
        carry = req.rng_key
        if not req.greedy:
            carry = jax.random.split(carry)[1]
            for _ in range(n - 1):
                carry = jax.random.split(carry)[1]
        self._install_lane(
            req, tok=int(req.tokens[-1]), length=len(history), decoded=n,
            active=True, carry_key=carry)

    def _probe_fails(self, slots) -> bool:
        """Run one NON-COMMITTING decode tick over a subset of the active
        lanes (outputs discarded; ``_probe_jit`` never donates, so the
        committed cache/state buffers are untouched). True iff the device
        call — or the poison injector — raised for this subset."""
        reqs = [self._active[s] for s in slots]
        mask = np.zeros(self.slots, bool)
        mask[list(slots)] = True
        st = dict(self._state)
        st["active"] = self._state["active"] & jnp.asarray(mask)
        all_greedy = all(r.greedy for r in reqs)
        ids = [r.id for r in reqs]
        # operands bound on the main thread (same zombie-safety argument as
        # _tick_decode: an abandoned probe must never see post-recovery
        # objects)
        cache_in, tables_in = self.cache_manager.cache, self._device_tables()

        def run():
            faults.on_serving_batch(ids)
            out = self._probe_jit(self.params, cache_in, st, tables_in,
                                  all_greedy)
            return jax.block_until_ready(out)

        try:
            self._run_device(run)
            return False
        except Exception:  # noqa: BLE001 — a probe exists to catch these
            return True

    def _bisect_poison(self):
        """Binary-search the active set for the request whose presence
        kills the decode step; retire it with its partial tokens. Finds
        one poison per escalation — multiple poisons fall out across
        successive escalations. Returns the retired ids ([] when the
        failure does not reproduce under probing, e.g. a transient)."""
        if not self._active:
            return []
        suspects = sorted(self._active)
        if not self._probe_fails(suspects):
            logger.warning(
                "serving: decode failures did not reproduce under probing "
                "(transient device fault?); no quarantine")
            return []
        while len(suspects) > 1:
            half = suspects[:len(suspects) // 2]
            suspects = (half if self._probe_fails(half)
                        else suspects[len(suspects) // 2:])
        slot = suspects[0]
        req = self._active[slot]
        if not self._probe_fails([slot]):
            logger.warning(
                "serving: bisection could not pin the failure to a single "
                "request (fault needs a specific combination?); no "
                "quarantine this round")
            return []
        logger.error(
            "serving: quarantining poison request %d (lane %d) isolated by "
            "bisection; finish_reason='error', %d partial token(s) kept — "
            "neighbors continue untouched", req.id, slot, len(req.tokens))
        self._evict(req, "error", self._now())
        self.metrics.record_poison()
        obs_emit("poison_retired", request=req.id, via="bisection")
        return [req.id]

    def _run_device(self, fn):
        """Run one device call under the hung-tick watchdog. With
        ``FLEETX_SERVING_TICK_TIMEOUT_S`` unset this is a direct call
        (zero overhead); with a timeout the call runs on a persistent
        monitor-thread executor and exceeding the budget raises
        :class:`TickTimeout` into the transactional-tick rollback. The
        abandoned call's thread is orphaned (a truly hung XLA call cannot
        be interrupted from Python) and its buffers are never reused —
        recovery rebuilds fresh ones."""
        if self.mesh is not None:
            # trace-time mesh context (flash dispatch + logical rules);
            # entered INSIDE the callable so the watchdog's worker thread
            # sees it too (contexts do not cross executor threads)
            inner = fn

            def fn():
                with self._mesh_context():
                    return inner()

        if not self.tick_timeout_s or self.tick_timeout_s <= 0:
            return fn()
        import concurrent.futures

        if self._watchdog is None:
            self._watchdog = concurrent.futures.ThreadPoolExecutor(
                1, thread_name_prefix="fleetx-serving-watchdog")
        fut = self._watchdog.submit(fn)
        try:
            return fut.result(timeout=self.tick_timeout_s)
        except concurrent.futures.TimeoutError:
            self._watchdog.shutdown(wait=False)  # abandon the zombie call
            self._watchdog = None
            self.hang_diagnostics = {
                "tick": self._ticks,
                "timeout_s": self.tick_timeout_s,
                "active_requests": sorted(r.id for r in
                                          self._active.values()),
                "queue_depth": self.scheduler.queue_depth,
                "recoveries": self.metrics.engine_recoveries,
            }
            obs_emit("tick_timeout", tick=self._ticks,
                     timeout_s=self.tick_timeout_s)
            logger.error(
                "serving: device tick exceeded FLEETX_SERVING_TICK_TIMEOUT_S"
                "=%.3fs; diagnostics banked in engine.hang_diagnostics, "
                "abandoning the call and recovering", self.tick_timeout_s)
            raise TickTimeout(
                f"device tick exceeded {self.tick_timeout_s}s "
                "(hung device step; see engine.hang_diagnostics)") from None

    # ----------------------------------------------------- graceful drain

    def request_shutdown(self, grace_s: Optional[float] = None) -> None:
        """Flip the engine into draining mode: new submits reject with
        :class:`ShuttingDown`, ticking continues so in-flight and queued
        requests finish, and once ``grace_s`` (default
        ``FLEETX_SERVING_GRACE_S``) elapses the remainder is retired with
        partial tokens. Idempotent and async-signal-safe (flag writes
        only) — exactly what a SIGTERM handler may do."""
        if self._shutting_down:
            return
        self._shutting_down = True
        grace = self.grace_s if grace_s is None else float(grace_s)
        self._shutdown_deadline = self._now() + max(grace, 0.0)
        # the shutdown event is emitted by the next step(), NOT here: this
        # method is async-signal-safe (flag writes only) and the event
        # log/registry take locks a signal context must never acquire
        self._shutdown_event_pending = True
        logger.warning(
            "serving: shutdown requested — admission stopped, draining %d "
            "active + %d queued request(s) under a %.1fs grace window",
            len(self._active), self.scheduler.queue_depth, max(grace, 0.0))

    def shutdown(self, grace_s: Optional[float] = None
                 ) -> Dict[int, ServingResult]:
        """Graceful drain to completion: :meth:`request_shutdown`, tick
        until every request finished or the grace window closed (then
        retire the rest with ``finish_reason="shutdown"`` and partial
        tokens), and return-and-clear ALL results — every request that was
        in flight or queued gets a terminal result. The checkpoint-safe
        shutdown seam the multi-replica router drains replicas through."""
        self.request_shutdown(grace_s)
        # an idle engine drains without a single tick, so flush the
        # deferred shutdown event here too (step() flushes it otherwise)
        self._flush_shutdown_event()
        while (len(self.scheduler) or self._active or self._prefilling
               or self._prefilled):
            self.step()  # the deadline check inside step() retires leftovers
        out, self._results = self._results, {}
        return out

    def _flush_shutdown_event(self) -> None:
        """Emit the shutdown event request_shutdown deferred (it may run
        in a signal context, where the event log's locks are off-limits).
        Called from step() and shutdown() — always outside signals."""
        if self._shutdown_event_pending:
            self._shutdown_event_pending = False
            obs_emit("shutdown", engine=self.metrics.engine_label,
                     active=len(self._active),
                     queued=self.scheduler.queue_depth)

    def _retire_all(self, reason: str):
        """Retire every queued and in-flight request right now (grace
        window closed): queued requests return empty, in-flight ones their
        partial tokens."""
        now = self._now()
        retired = []
        for req in self.scheduler.drain_all():
            self._finalize(req, reason, now)
            retired.append(req.id)
        for req in (list(self._active.values())
                    + list(self._prefilling.values())
                    + list(self._prefilled.values())):
            self._evict(req, reason, now)
            retired.append(req.id)
        return retired

    def install_sigterm_handler(self, grace_s: Optional[float] = None):
        """Register a SIGTERM handler that calls :meth:`request_shutdown`
        (flags only — the drain itself happens in whatever step()/drain()
        loop is already running, never inside the signal context) and then
        chains any previously-installed handler, mirroring the Trainer's
        preemption plumbing (core/engine.py). Main thread only, per the
        ``signal`` module's rules. Returns the previous handler."""
        import signal

        prev = signal.getsignal(signal.SIGTERM)

        def on_sigterm(signum, frame):
            self.request_shutdown(grace_s)
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)

        self._prev_sigterm = prev
        signal.signal(signal.SIGTERM, on_sigterm)
        return prev

    def uninstall_sigterm_handler(self) -> None:
        """Put back whatever SIGTERM handler install displaced."""
        import signal

        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None

    def drain(self, max_ticks: Optional[int] = None) -> Dict[int, ServingResult]:
        """Tick until queue and slots are empty (or ``max_ticks``), then
        return-and-clear every finished result since the last drain."""
        n = 0
        while len(self.scheduler) or self._active or self._prefilling:
            self.step()
            n += 1
            if max_ticks is not None and n >= max_ticks:
                break
        out, self._results = self._results, {}
        return out

    def generate_batch(self, input_ids, gen_cfg: Optional[GenerationConfig]
                       = None, rng: Optional[jax.Array] = None):
        """One-shot convenience with ``generate()``'s contract: every row
        of ``input_ids`` [b, prompt_len] becomes a request, and the result
        is the [b, prompt_len + max_length] token buffer (pad fill after
        EOS). Greedy rows are byte-identical to one-shot ``generate()``;
        sampling rows draw from per-row streams split off ``rng``."""
        g = gen_cfg or self.gen_cfg
        ids = np.asarray(input_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        b, prompt_len = ids.shape
        limit = min(self.cache_len, self.model.cfg.max_position_embeddings)
        if prompt_len + g.max_length > limit:
            # one-shot generate()'s contract: a decode that cannot fit the
            # position table (or this engine's slot cache) is an error here,
            # not the streaming submit()'s clamp-and-warn
            raise ValueError(
                f"prompt_len({prompt_len}) + max_length({g.max_length}) "
                f"exceeds the engine's decode limit ({limit}: "
                f"min(cache_len, max_position_embeddings))")
        if rng is None:
            rng = self._base_key
        rids = [
            self.submit(
                ids[i], max_length=g.max_length, min_length=g.min_length,
                eos_token_id=g.eos_token_id, decode_strategy=g.decode_strategy,
                temperature=g.temperature, top_k=g.top_k, top_p=g.top_p,
                rng_key=jax.random.fold_in(rng, i),
            )
            for i in range(b)
        ]
        results = self.drain()
        out = np.full((b, prompt_len + g.max_length), g.pad_token_id,
                      np.int32)
        out[:, :prompt_len] = ids
        for i, rid in enumerate(rids):
            res = results.get(rid)
            if res is None:
                # a retired-without-result request (timed out of the queue
                # before this drain, cancelled concurrently, ...) must not
                # crash the whole batch: its row stays pad, loudly
                logger.error(
                    "serving: generate_batch request %d (row %d) produced "
                    "no result; row left as pad", rid, i)
                continue
            if res.finish_reason not in ("eos", "max_length", "cache_full"):
                logger.warning(
                    "serving: generate_batch request %d (row %d) retired "
                    "with finish_reason=%r after %d token(s); rest of row "
                    "is pad", rid, i, res.finish_reason, len(res.tokens))
            toks = res.tokens
            out[i, prompt_len:prompt_len + len(toks)] = toks
        return jnp.asarray(out)

    def result(self, request_id: int) -> Optional[ServingResult]:
        """Finished result for ``request_id`` (None while in flight)."""
        return self._results.get(request_id)

    def take_result(self, request_id: int) -> Optional[ServingResult]:
        """Remove and return one finished result (None while in flight).
        The per-request sibling of :meth:`drain`'s return-and-clear — a
        router collecting results every tick consumes them one at a time
        without resetting the whole table."""
        return self._results.pop(request_id, None)

    def emitted_tokens(self, request_id: int) -> Optional[list]:
        """Host-truth copy of a live request's emitted tokens (None for
        unknown/finished ids). The router's stream-reconciliation seam:
        after a recovered tick it re-bases its durable per-request history
        on the engine's rolled-back-and-replayed token list — the in-
        process analogue of a streaming client re-syncing its offset."""
        for r in (list(self._active.values())
                  + list(self._prefilling.values())
                  + list(self._prefilled.values())
                  + list(self.scheduler.snapshot())):
            if r.id == request_id:
                return list(r.tokens)
        return None

    # ------------------------------------------- disaggregated prefill

    def prefilled_ready(self) -> list:
        """Request ids parked on this PREFILL-role replica with their
        prompt KV fully written, awaiting :meth:`export_kv`
        (docs/SERVING.md "Disaggregated prefill/decode")."""
        return sorted(r.id for r in self._prefilled.values())

    def export_kv(self, request_id: int) -> list:
        """Ship one parked request's prompt KV: walk its block table for
        the ``ceil(prompt_len / page_size)`` pages covering the prompt,
        read them through the same batched per-leaf device gathers the
        host spill tier uses (int8 scale leaves included), and serialize
        each page in the crc32-trailed wire format. On success the
        request finalizes ``finish_reason="prefilled"`` — its lane and
        pages free (the prompt stays warm in THIS replica's prefix trie)
        — and the blobs return in prompt order, ready for
        ``submit(kv_payloads=..., history=[t0])`` on a decode replica.
        Raises KeyError for an id that is not parked; any export fault
        propagates WITHOUT losing the request (it stays parked, its
        emitted first token stays in the router's durable history), so
        the caller falls back to the replay path."""
        req = next((r for r in self._prefilled.values()
                    if r.id == request_id), None)
        if req is None:
            raise KeyError(
                f"request {request_id} is not parked for export "
                f"(parked: {self.prefilled_ready()})")
        attempt = self._fault_ships
        self._fault_ships += 1
        faults.on_kv_ship(attempt, request_id)
        n_pages = -(-req.prompt_len // self.page_size)
        table = self.cache_manager.tables[req.slot]
        pages = [int(table[i]) for i in range(n_pages)]
        with span("serving.export_kv", request=request_id, pages=n_pages):
            payloads = self.cache_manager.read_pages(pages)
        blobs = [HostPageStore.payload_to_bytes(p) for p in payloads]
        if faults.on_kv_ship_corrupt(attempt):
            # chaos seam: flip one byte mid-blob (past the header) — the
            # crc32 trailer must catch it on the decode side's submit
            mid = len(blobs) // 2
            flipped = bytearray(blobs[mid])
            flipped[len(flipped) // 2] ^= 0xFF
            blobs[mid] = bytes(flipped)
        nbytes = sum(len(b) for b in blobs)
        self.metrics.record_kv_shipped(len(blobs), nbytes)
        del self._prefilled[req.slot]
        self._finalize(req, "prefilled", self._now())
        obs_emit("kv_shipped", request=request_id, pages=len(blobs),
                 bytes=nbytes)
        return blobs

    def health(self) -> Dict:
        """The drain-aware health report (the ``/healthz`` JSON body,
        docs/OBSERVABILITY.md): ``state`` is ``"ok"`` while serving,
        ``"draining"`` once :meth:`request_shutdown` flipped admission
        off (rotate out, results still coming), ``"dead"`` after
        :class:`RecoveryExhausted`/:meth:`declare_dead` (rotate out,
        nothing more is coming). ``queue_depth``/``active`` give the
        load-balancing signal next to the rotate-out reason — the
        contract the multi-replica router and any external LB consume."""
        state = ("dead" if self._dead
                 else "draining" if self._shutting_down else "ok")
        out = {"state": state,
               "role": self.role,
               # model-aware routing (docs/SERVING.md "Heterogeneous
               # fleet"): the served family + capability flags ride the
               # same report, so a router groups replicas per model from
               # the scrape it already performs
               "model": self.model_family,
               "capabilities": self.capabilities.as_dict(),
               "queue_depth": self.scheduler.queue_depth,
               # prefill load prices in TOKENS (prefill cost scales with
               # prompt length, not request count): queued prompts plus
               # the unwritten remainder of any in-flight chunked prefill
               "queue_tokens": self.scheduler.queued_tokens() + sum(
                   r.prompt_len - r.prefill_pos
                   for r in self._prefilling.values()),
               "active": (len(self._active) + len(self._prefilling)
                          + len(self._prefilled)),
               "slots": self.slots}
        if self.paged:
            out["pages_in_use"] = self.cache_manager.pages_in_use
            out["usable_pages"] = self.cache_manager.usable_pages
        return out

    def declare_dead(self) -> None:
        """Mark the engine dead (``health()``/``/healthz`` report
        ``"dead"``) without running its shutdown machinery — the seam for
        a supervisor/router that has decided the process or device behind
        this engine is gone (e.g. the replica-kill chaos path). Ticking a
        declared-dead engine is the caller's bug, not prevented here."""
        self._dead = True

    @property
    def submit_limit(self) -> int:
        """The smallest REJECTED per-request prompt size (the engine
        needs at least one token of decode room below it) — the
        per-model admission bound the router validates against at its
        own submit (serving/model_protocol.py ENGINE_SURFACE)."""
        return min(self.cache_len, self.model.cfg.max_position_embeddings)

    # ------------------------------------------------------------- internals

    def _init_state(self):
        s = self.slots
        return {
            "last_tok": jnp.zeros((s,), jnp.int32),
            "lengths": jnp.zeros((s,), jnp.int32),
            "decoded": jnp.zeros((s,), jnp.int32),
            "active": jnp.zeros((s,), bool),
            "eos": jnp.full((s,), -1, jnp.int32),
            "max_new": jnp.ones((s,), jnp.int32),
            "min_new": jnp.zeros((s,), jnp.int32),
            "greedy": jnp.ones((s,), bool),
            "temperature": jnp.ones((s,), jnp.float32),
            "top_k": jnp.zeros((s,), jnp.int32),
            "top_p": jnp.ones((s,), jnp.float32),
            "rng": jnp.zeros((s, 2), jnp.uint32),
        }

    def _dequant_params(self, params):
        """Weight-only-int8 dequant seam, called INSIDE every jitted
        prefill/decode body: a no-op at bf16; at int8 it re-expands the
        {"_q8", "_scale"} leaves so XLA fuses the scale multiply into
        each matmul consumer — HBM holds the int8 tree, the float view
        is a fusion-local temporary."""
        if self.weight_dtype != "int8":
            return params
        from fleetx_tpu.ops.quant import dequantize_tree_int8

        return dequantize_tree_int8(params, dtype=jnp.float32)

    # -------------------------------------------------- mesh sharding seams

    def _mesh_context(self):
        """Trace-time context for meshed device calls: the framework mesh
        registry (so the model's flash-decode dispatch sees the ambient
        mesh and shard_maps the kernels) plus the logical-axis rules (so
        activation constraints resolve). A no-op context unmeshed, and
        free after the first trace per call shape — jit caches skip it."""
        if self.mesh is None:
            return contextlib.nullcontext()
        from flax import linen as nn

        from fleetx_tpu.parallel.mesh import use_mesh

        ctx = contextlib.ExitStack()
        ctx.enter_context(use_mesh(self.mesh))
        ctx.enter_context(nn.logical_axis_rules(list(self._rules)))
        return ctx

    def _shard_params(self, params):
        """device_put the servable tree onto its TP(mp)/FSDP layout. The
        model's own ``nn.Partitioned`` metadata (recovered via an
        eval_shape init) names each param's logical axes; quantized
        ``{"_q8", "_scale"}`` leaves inherit their kernel's spec with
        non-dividing dims dropped (parallel/sharding.py). Boxed trees
        are unboxed first — the committed NamedShardings carry the
        layout from here on."""
        from flax import linen as nn

        from fleetx_tpu.parallel.sharding import serving_param_shardings

        params = jax.tree.map(
            lambda x: x.unbox() if isinstance(x, nn.Partitioned) else x,
            params, is_leaf=lambda x: isinstance(x, nn.Partitioned))
        abstract = jax.eval_shape(lambda: self.model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32)))["params"]
        shardings = serving_param_shardings(abstract, params, self.mesh,
                                            self._rules)
        return jax.tree.map(jax.device_put, params, shardings)

    def _cache_shardings(self, cache):
        """Heads-over-mp NamedShardings for a decode cache tree: every
        rank-≥4 leaf (K/V slots or pages AND their int8 scale leaves —
        all carry heads at axis -2) splits on ``mp``; scalars replicate.
        Head divisibility was validated at construction."""
        mp = dict(self.mesh.shape).get("mp", 1)

        def one(leaf):
            if getattr(leaf, "ndim", 0) >= 4 and mp > 1:
                spec = [None] * leaf.ndim
                spec[leaf.ndim - 2] = "mp"
                return NamedSharding(self.mesh, P(*spec))
            return NamedSharding(self.mesh, P())

        return jax.tree.map(one, cache)

    def _shard_cache(self, cache):
        """Commit a host/eagerly-built cache tree onto the mesh layout
        (construction, recovery, chunk working caches); identity
        unmeshed."""
        if self.mesh is None:
            return cache
        return jax.tree.map(jax.device_put, cache,
                            self._cache_shardings(cache))

    def _pin_cache(self, cache):
        """In-jit sharding constraint pinning a returned cache tree to
        the heads-over-mp layout, so no device call can drift the cache
        into a gathered/replicated layout between ticks (and donation
        keeps matching buffer for buffer); identity unmeshed."""
        if self.mesh is None:
            return cache
        return jax.lax.with_sharding_constraint(
            cache, self._cache_shardings(cache))

    def _replicate(self, tree):
        """Commit small host-built device state (lane scalars, block
        tables) as mesh-replicated; identity unmeshed."""
        if self.mesh is None:
            return tree
        sh = NamedSharding(self.mesh, P())
        return jax.tree.map(lambda x: jax.device_put(x, sh), tree)

    def _publish_quant_metrics(self) -> None:
        """Push the precision + mesh config and bytes gauges into the
        metrics facade (labels kv_dtype/weight_dtype/mesh;
        docs/OBSERVABILITY.md). All byte gauges are PER DEVICE: under a
        mesh the cache splits its heads over mp and the params split
        TP/FSDP, so what one device holds is the capacity number.
        Re-call after swapping ``engine.metrics`` (the bench does)."""
        from fleetx_tpu.serving.cache_manager import leaf_device_nbytes

        cfg = self.model.cfg
        mp = 1 if self.mesh is None else dict(self.mesh.shape).get("mp", 1)
        kv_item = 1 if self.kv_dtype == "int8" else jnp.dtype(cfg.dtype).itemsize
        # K + V bytes one cached token costs across every layer ON ONE
        # DEVICE, scales included (one fp32 scale per head vector at
        # int8); heads divide over mp under a mesh
        kv_bytes = cfg.num_layers * (cfg.num_attention_heads // mp) * 2 * (
            cfg.head_dim * kv_item + (4 if self.kv_dtype == "int8" else 0))
        weight_bytes = sum(
            leaf_device_nbytes(leaf)
            for leaf in jax.tree.leaves(self.params))
        if self.mesh is None:
            self.metrics.set_mesh(1, "-")
        else:
            desc = "x".join(f"{k}{v}" for k, v in self.mesh.shape.items()
                            if v > 1) or "1"
            self.metrics.set_mesh(self.mesh.size, desc)
        self.metrics.set_quant_config(
            self.kv_dtype, self.weight_dtype, kv_bytes, weight_bytes,
            kv_cache_bytes=self.cache_manager.cache_nbytes())

    def _admit_fn(self, st, slot, tok, length, decoded, active, eos, max_new,
                  min_new, greedy, temperature, top_k, top_p, key):
        """Jitted: install one request's scalars into slot ``slot`` of the
        device state — ``decoded=1`` for a fresh admission (first token
        just sampled), ``decoded=n`` when replay recovery reinstalls a
        request that already emitted ``n`` tokens."""
        return {
            "last_tok": st["last_tok"].at[slot].set(tok),
            "lengths": st["lengths"].at[slot].set(length),
            "decoded": st["decoded"].at[slot].set(decoded),
            "active": st["active"].at[slot].set(active),
            "eos": st["eos"].at[slot].set(eos),
            "max_new": st["max_new"].at[slot].set(max_new),
            "min_new": st["min_new"].at[slot].set(min_new),
            "greedy": st["greedy"].at[slot].set(greedy),
            "temperature": st["temperature"].at[slot].set(temperature),
            "top_k": st["top_k"].at[slot].set(top_k),
            "top_p": st["top_p"].at[slot].set(top_p),
            "rng": st["rng"].at[slot].set(key),
        }

    def _admission_tokens(self, req: Request) -> np.ndarray:
        """The tokens admission must find storage for: the prompt alone
        for a fresh request, ``prompt + history[:-1]`` for an admit-with-
        history request (the last history token's K/V write is the next
        decode tick's job, exactly the replay contract)."""
        if req.tokens:
            return np.concatenate(
                [req.prompt, np.asarray(req.tokens[:-1], np.int32)])
        return req.prompt

    def _can_admit(self, req: Request) -> bool:
        """FIFO-head admission judgment: a free decode lane, and — paged —
        enough free pages for the head's prompt plus any migrated history
        (page-granular admission: total live tokens gate entry, not
        worst-case slot capacity). A too-big head BLOCKS, preserving
        arrival order deterministically; it unblocks as retiring requests
        return pages."""
        if self.paged:
            return self.cache_manager.can_admit(self._admission_tokens(req))
        return self.cache_manager.free_count > 0

    def _device_tables(self):
        """Device copy of the block tables, re-uploaded only when the
        manager's version counter moved (None on the slot path)."""
        if not self.paged:
            return None
        version = self.cache_manager.tables_version
        if version != self._tables_version:
            self._tables_dev = self._replicate(
                jnp.asarray(self.cache_manager.tables))
            self._tables_version = version
        return self._tables_dev

    def _make_prefill(self, bucket_len: int):
        """Jitted prefill-on-insert for prompts bucketed to ``bucket_len``:
        batch-1 cached forward into a fresh cache, scatter into the slot,
        sample the first token — one device round-trip per admission."""
        max_pos = self.model.cfg.max_position_embeddings

        def prefill(params, cache, prompt, true_len, slot, eos, min_new,
                    greedy, temperature, top_k, top_p, key):
            params = self._dequant_params(params)
            ids = prompt[None, :]
            # right-pad bucket tail: causal masking keeps the tail out of
            # every position <= true_len-1, and its K/V rows sit beyond the
            # live window until decode overwrites them one by one
            pos = jnp.minimum(jnp.arange(bucket_len, dtype=jnp.int32),
                              max_pos - 1)[None, :]
            logits, small = self.executor.forward(
                params, self.executor.init_cache(1), ids, pos)
            cache = self._pin_cache(scatter_slot(cache, small, slot))
            last = jax.lax.dynamic_slice_in_dim(
                logits[0], true_len - 1, 1, axis=0).astype(jnp.float32)
            vocab = last.shape[-1]
            last = jnp.where(
                (jnp.arange(vocab)[None, :] == eos) & (min_new > 0),
                _NEG, last)
            tok = self.executor.sample(
                last, key[None], greedy[None], temperature[None],
                top_k[None], top_p[None], topk_cap=self.topk_cap)[0]
            return cache, tok

        return jax.jit(
            prefill, donate_argnums=(1,) if self._donate_cache else ())

    def _make_paged_prefill(self, bucket_len: int):
        """Jitted paged prefill-on-insert for prompt SUFFIXES bucketed to
        ``bucket_len``: the non-shared tail of the prompt runs a batch-1
        cached forward writing K/V straight into the lane's pages (no
        fresh cache, no scatter), attending the trie-shared prefix pages
        already in place, then samples the first token — the prefix-cache
        compute saving is exactly the skipped ``wpos`` leading tokens."""
        max_pos = self.model.cfg.max_position_embeddings

        def prefill(params, cache, suffix, true_len, wpos, table, eos,
                    min_new, greedy, temperature, top_k, top_p, key):
            params = self._dequant_params(params)
            ids = suffix[None, :]
            # absolute positions wpos.. for the suffix; the right-pad
            # bucket tail is causally invisible and its writes land beyond
            # the live window (or on the trash page) — cache_manager.py
            pos = jnp.minimum(wpos + jnp.arange(bucket_len, dtype=jnp.int32),
                              max_pos - 1)[None, :]
            logits, cache = self.executor.forward(
                params, cache, ids, pos,
                cache_positions=wpos[None], block_tables=table[None])
            cache = self._pin_cache(cache)
            last = jax.lax.dynamic_slice_in_dim(
                logits[0], true_len - 1, 1, axis=0).astype(jnp.float32)
            vocab = last.shape[-1]
            last = jnp.where(
                (jnp.arange(vocab)[None, :] == eos) & (min_new > 0),
                _NEG, last)
            tok = self.executor.sample(
                last, key[None], greedy[None], temperature[None],
                top_k[None], top_p[None], topk_cap=self.topk_cap)[0]
            return cache, tok

        return jax.jit(
            prefill, donate_argnums=(1,) if self._donate_cache else ())

    def _prefill_scalars(self, req: Request, replay: bool, step_key):
        """Per-request sampler scalars for a prefill call. Replay rebuilds
        K/V only: greedy argmax with inert filters (result discarded, no
        stream consumed)."""
        if replay:
            return (jnp.asarray(-1, jnp.int32), jnp.asarray(0, jnp.int32),
                    jnp.asarray(True), jnp.asarray(1.0, jnp.float32),
                    jnp.asarray(0, jnp.int32), jnp.asarray(1.0, jnp.float32),
                    req.rng_key)
        return (jnp.asarray(req.eos_token_id, jnp.int32),
                jnp.asarray(req.min_new_tokens, jnp.int32),
                jnp.asarray(req.greedy),
                jnp.asarray(req.temperature, jnp.float32),
                jnp.asarray(req.top_k, jnp.int32),
                jnp.asarray(req.top_p, jnp.float32),
                step_key)

    def _guarded_prefill(self, req: Request, fn, args, bucket=None,
                         chunk_cache: bool = False):
        """One prefill device call through the fault-injection hook;
        stores the returned cache (into ``req.chunk_cache`` for chunked
        slot calls, the cache manager otherwise). Deliberately NOT under
        the hung-tick watchdog: prefill calls legitimately include
        fresh-bucket XLA compiles (seconds), and replay recovery
        re-prefills through here — a watchdog here would misread every
        cold compile as a hang and quarantine healthy requests. The
        watchdog budget is calibrated for the steady-state decode tick,
        the loop that actually wedges."""
        attempt = self._fault_prefills
        self._fault_prefills += 1
        with span("serving.prefill", request=req.id, bucket=bucket):
            faults.on_serving_prefill(attempt, req.id)
            with self._mesh_context():
                cache, tok = fn(*args)
        if chunk_cache:
            req.chunk_cache = cache
        else:
            self.cache_manager.cache = cache
        return tok

    def _slot_prefill_call(self, req: Request, tokens, slot,
                           replay: bool = False):
        """Batch-1 prefill of ``tokens`` scattered into ``slot``'s cache
        row. Admission returns ``(first_token, carry_key)``; replay
        (``tokens`` = the request's history) returns None."""
        bucket = -(-len(tokens) // self.prefill_bucket) * self.prefill_bucket
        bucket = min(max(bucket, len(tokens)), self.cache_len)
        fn = self._prefill_jits.get(("slot", bucket))
        if fn is None:
            fn = self._prefill_jits[("slot", bucket)] = \
                self._make_prefill(bucket)
        padded = np.zeros(bucket, np.int32)
        padded[:len(tokens)] = tokens
        step_key = carry_key = None
        if not replay:
            step_key, carry_key = jax.random.split(req.rng_key)
        args = (self.params, self.cache_manager.cache, jnp.asarray(padded),
                jnp.asarray(len(tokens), jnp.int32),
                jnp.asarray(slot, jnp.int32),
                *self._prefill_scalars(req, replay, step_key))
        tok = self._guarded_prefill(req, fn, args, bucket=bucket)
        return None if replay else (tok, carry_key)

    def _paged_prefill_call(self, req: Request, suffix, shared, lane,
                            replay: bool = False):
        """Batch-1 prefill of the non-shared ``suffix`` straight into
        ``lane``'s pages at absolute positions ``shared..``. Admission
        returns ``(first_token, carry_key)``; replay returns None.
        Chunked prefill reuses this call verbatim — an intermediate
        chunk is exactly a ``replay`` call (KV writes only, inert
        sampler, no rng consumed) at its chunk's write offset, and the
        final chunk is exactly an admission call whose ``true_len``
        lands on the last prompt token."""
        bucket = -(-len(suffix) // self.prefill_bucket) * self.prefill_bucket
        bucket = min(max(bucket, len(suffix)), self.cache_len - shared)
        fn = self._prefill_jits.get(("paged", bucket))
        if fn is None:
            fn = self._prefill_jits[("paged", bucket)] = \
                self._make_paged_prefill(bucket)
        padded = np.zeros(bucket, np.int32)
        padded[:len(suffix)] = suffix
        step_key = carry_key = None
        if not replay:
            step_key, carry_key = jax.random.split(req.rng_key)
        args = (self.params, self.cache_manager.cache, jnp.asarray(padded),
                jnp.asarray(len(suffix), jnp.int32),
                jnp.asarray(shared, jnp.int32),
                jnp.asarray(self.cache_manager.tables[lane]),
                *self._prefill_scalars(req, replay, step_key))
        tok = self._guarded_prefill(req, fn, args, bucket=bucket)
        return None if replay else (tok, carry_key)

    def _make_chunk_prefill(self, bucket_len: int):
        """Jitted slot-path CHUNK prefill: write ``bucket_len`` prompt
        tokens into the request's batch-1 working cache at absolute
        positions ``wpos..`` through the per-row ``cache_positions`` seam
        (the paged path needs no sibling — ``_make_paged_prefill`` already
        takes a write offset), and sample from the chunk's last true
        token — the returned token only matters on the FINAL chunk, where
        ``true_len - 1`` is the last prompt position, exactly where the
        one-call path samples."""
        max_pos = self.model.cfg.max_position_embeddings

        def prefill(params, cache, chunk, true_len, wpos, eos, min_new,
                    greedy, temperature, top_k, top_p, key):
            params = self._dequant_params(params)
            ids = chunk[None, :]
            # absolute positions wpos..; the right-pad bucket tail is
            # causally invisible to every real query and its writes are
            # overwritten by the next chunk (or decode) before the live
            # window ever reaches them — same contract as the one-call
            # bucket tail
            pos = jnp.minimum(wpos + jnp.arange(bucket_len, dtype=jnp.int32),
                              max_pos - 1)[None, :]
            logits, cache = self.executor.forward(
                params, cache, ids, pos,
                cache_positions=wpos[None])
            cache = self._pin_cache(cache)
            last = jax.lax.dynamic_slice_in_dim(
                logits[0], true_len - 1, 1, axis=0).astype(jnp.float32)
            vocab = last.shape[-1]
            last = jnp.where(
                (jnp.arange(vocab)[None, :] == eos) & (min_new > 0),
                _NEG, last)
            tok = self.executor.sample(
                last, key[None], greedy[None], temperature[None],
                top_k[None], top_p[None], topk_cap=self.topk_cap)[0]
            return cache, tok

        return jax.jit(
            prefill, donate_argnums=(1,) if self._donate_cache else ())

    def _chunk_prefill_call(self, req: Request, tokens, wpos,
                            replay: bool = False):
        """One slot-path chunk: ``tokens`` into ``req.chunk_cache`` at
        absolute positions ``wpos..``. Intermediate chunks pass
        ``replay=True`` (KV only, rng untouched, returns None); the
        final chunk returns ``(first_token, carry_key)``."""
        bucket = -(-len(tokens) // self.prefill_bucket) * self.prefill_bucket
        # cap at the REMAINING cache span (mirroring the paged call's
        # cache_len - shared): a bucket crossing cache_len would clamp
        # its dynamic_update_slice start and overwrite live prompt KV
        bucket = min(max(bucket, len(tokens)), self.cache_len - wpos)
        fn = self._prefill_jits.get(("chunk", bucket))
        if fn is None:
            fn = self._prefill_jits[("chunk", bucket)] = \
                self._make_chunk_prefill(bucket)
        padded = np.zeros(bucket, np.int32)
        padded[:len(tokens)] = tokens
        step_key = carry_key = None
        if not replay:
            step_key, carry_key = jax.random.split(req.rng_key)
        args = (self.params, req.chunk_cache, jnp.asarray(padded),
                jnp.asarray(len(tokens), jnp.int32),
                jnp.asarray(wpos, jnp.int32),
                *self._prefill_scalars(req, replay, step_key))
        tok = self._guarded_prefill(req, fn, args, bucket=bucket,
                                    chunk_cache=True)
        return None if replay else (tok, carry_key)

    def _claim_storage(self, req: Request) -> int:
        """Claim a decode lane (+ page chain on the paged path) for one
        admission; sets ``req.slot`` and returns the shared-prefix token
        count (trie + host-revived; 0 on the slot path)."""
        if self.paged:
            alloc = self.cache_manager.alloc(req.id, req.prompt)
            if alloc is None:  # _can_admit() passed, so this is an
                raise RuntimeError(  # invariant breach — fail loudly
                    f"paged alloc failed after admission check for request "
                    f"{req.id} (prompt {req.prompt_len} tokens; "
                    f"{self.cache_manager.pool.free_pages} pages free)")
            lane, shared = alloc
            req.slot = lane
            pool = self.cache_manager.pool
            self.metrics.record_prefix(
                shared, req.prompt_len,
                int(pool.alloc_counts[lane] - pool.shared_counts[lane]))
            return shared
        req.slot = self.cache_manager.alloc(req.id, req.prompt_len)
        return 0

    def _install_lane(self, req: Request, *, tok: int, length: int,
                      decoded: int, active: bool, carry_key) -> None:
        """Install one request's decode-lane scalars into the device
        state (shared by fresh admission and replay recovery)."""
        self._state = self._admit_jit(
            self._state, jnp.asarray(req.slot, jnp.int32),
            jnp.asarray(tok, jnp.int32),
            jnp.asarray(length, jnp.int32),
            jnp.asarray(decoded, jnp.int32),
            jnp.asarray(active),
            jnp.asarray(req.eos_token_id, jnp.int32),
            jnp.asarray(req.max_new_tokens, jnp.int32),
            jnp.asarray(req.min_new_tokens, jnp.int32),
            jnp.asarray(req.greedy),
            jnp.asarray(req.temperature, jnp.float32),
            jnp.asarray(req.top_k, jnp.int32),
            jnp.asarray(req.top_p, jnp.float32),
            carry_key,
        )

    def _admit(self, req: Request) -> None:
        """Admit the FIFO head: claim storage, then either the one-call
        whole-suffix prefill (chunking off, or the non-shared suffix fits
        one chunk — today's path, byte-identical) or enter the
        ``prefilling`` state and run the first chunk. A request carrying
        migrated history (``submit(history=...)``) admits through the
        replay seam instead: one whole-history prefill + lane install
        with the RNG position reconstructed, no callbacks re-fired —
        byte-for-byte the recovery replay of PR 8, aimed at a request
        another replica started. A request carrying SHIPPED page
        payloads skips even that prefill: :meth:`_admit_shipped` writes
        them straight into its fresh pages."""
        if req.kv_payloads is not None:
            self._admit_shipped(req)
            return
        if req.tokens:
            self._fault_ctx = ("prefill", req.id)
            with span("serving.admit", request=req.id,
                      prompt_len=req.prompt_len, history=len(req.tokens)):
                self._replay(req)
            self._fault_ctx = None
            self._prefill_strikes.pop(req.id, None)
            now = self._now()
            req.admit_time = now
            self.metrics.record_admit(now - req.submit_time)
            req.phase = "active"
            self._active[req.slot] = req
            return
        self._fault_ctx = ("prefill", req.id)
        with span("serving.admit", request=req.id,
                  prompt_len=req.prompt_len):
            shared = self._claim_storage(req)
            if (self.prefill_chunk
                    and req.prompt_len - shared > self.prefill_chunk):
                req.prefill_pos = shared
                req.phase = "prefilling"
                if not self.paged:
                    req.chunk_cache = self._shard_cache(
                        self.executor.init_cache(1))
                self._prefilling[req.slot] = req
                req.admit_time = self._now()
                self.metrics.record_admit(req.admit_time - req.submit_time)
                self._fault_ctx = None
                self._run_chunk(req)  # this tick's one chunk of budget
                return
            if self.paged:
                tok, carry_key = self._paged_prefill_call(
                    req, req.prompt[shared:], shared, req.slot)
                self.cache_manager.register_prefix(req.slot, req.prompt)
            else:
                tok, carry_key = self._slot_prefill_call(
                    req, req.prompt, req.slot)
        self._fault_ctx = None
        self._prefill_strikes.pop(req.id, None)  # survived its prefill
        now = self._now()
        req.admit_time = now
        self.metrics.record_admit(now - req.submit_time)
        self._finish_first_token(req, int(tok), carry_key)

    def _admit_shipped(self, req: Request) -> None:
        """Admit a request whose prompt KV arrived from a PREFILL-role
        replica (``submit(kv_payloads=...)``): claim a page chain, write
        the shipped pages through the same batched revive scatter the
        host spill tier uses — zero prefill forwards — register the
        prompt in the prefix trie, and install the decode lane resuming
        from ``history[-1]`` with the RNG carry advanced exactly as the
        prefill replica left it. Byte-identical to colocated decoding by
        construction: the pages hold the very K/V bytes that replica's
        prefill wrote. The payloads are consumed UP FRONT, so if this
        admission faults and the transactional tick rolls it back, the
        requeued request re-admits through the replay seam (a re-prefill
        — slower, never wrong)."""
        payloads, req.kv_payloads = req.kv_payloads, None
        self._fault_ctx = ("prefill", req.id)
        with span("serving.admit_shipped", request=req.id,
                  prompt_len=req.prompt_len, pages=len(payloads)):
            alloc = self.cache_manager.alloc(req.id, req.prompt)
            if alloc is None:  # _can_admit() passed, so this is an
                raise RuntimeError(  # invariant breach — fail loudly
                    f"paged alloc failed after admission check for shipped "
                    f"request {req.id} (prompt {req.prompt_len} tokens; "
                    f"{self.cache_manager.pool.free_pages} pages free)")
            lane, shared = alloc
            req.slot = lane
            # trie/host-revived prefix pages are already populated —
            # revive only the shipped pages beyond them
            start = shared // self.page_size
            table = self.cache_manager.tables[lane]
            entries = [(int(table[i]), payloads[i])
                       for i in range(start, len(payloads))]
            if entries:
                self.cache_manager.revive_pages(entries)
            self.cache_manager.register_prefix(lane, req.prompt)
        self._fault_ctx = None
        self._prefill_strikes.pop(req.id, None)
        pool = self.cache_manager.pool
        self.metrics.record_prefix(
            shared, req.prompt_len,
            int(pool.alloc_counts[lane] - pool.shared_counts[lane]))
        self.metrics.record_kv_revived_remote(len(entries))
        now = self._now()
        req.admit_time = now
        self.metrics.record_admit(now - req.submit_time)
        # RNG carry: the prefill replica consumed ONE split sampling t0,
        # plus one per later non-greedy history token — identical to the
        # replay reconstruction (greedy lanes never read the stream)
        n = len(req.tokens)
        carry = req.rng_key
        if not req.greedy:
            for _ in range(n):
                carry = jax.random.split(carry)[1]
        self._install_lane(
            req, tok=int(req.tokens[-1]), length=req.prompt_len + n - 1,
            decoded=n, active=True, carry_key=carry)
        req.phase = "active"
        self._active[req.slot] = req
        obs_emit("kv_revived_remote", request=req.id, pages=len(entries),
                 shared=shared)

    def _run_chunk(self, req: Request) -> None:
        """One prefill chunk for a mid-prefill request. Intermediate
        chunks only write KV (inert sampler, rng untouched); the final
        chunk samples the first token exactly like the one-call path and
        promotes the request to the decode set."""
        start = req.prefill_pos
        end = min(start + self.prefill_chunk, req.prompt_len)
        final = end == req.prompt_len
        tokens = req.prompt[start:end]
        self._fault_ctx = ("prefill", req.id)
        with span("serving.prefill_chunk", request=req.id, start=start,
                  final=final):
            if self.paged:
                out = self._paged_prefill_call(req, tokens, start, req.slot,
                                               replay=not final)
            else:
                out = self._chunk_prefill_call(req, tokens, start,
                                               replay=not final)
        self._fault_ctx = None
        req.prefill_pos = end
        self.metrics.record_prefill_chunk(len(tokens))
        if not final:
            return
        tok, carry_key = out
        if self.paged:
            self.cache_manager.register_prefix(req.slot, req.prompt)
        else:
            # fold the finished batch-1 working cache into the slot row
            self.cache_manager.cache = self._scatter_jit(
                self.cache_manager.cache, req.chunk_cache,
                jnp.asarray(req.slot, jnp.int32))
            req.chunk_cache = None
        del self._prefilling[req.slot]
        self._prefill_strikes.pop(req.id, None)
        self._finish_first_token(req, int(tok), carry_key)

    def _chunk_tick(self):
        """Advance the mid-prefill request by ONE chunk this tick —
        after checking its deadlines, so an expired request stops
        burning prefill compute (retired ``finish_reason="timeout"``
        with lane + pages freed; prefix registration only happens at
        completion, so nothing leaks). A request that has not produced
        its first token is still "waiting" in the queue-TTL sense, so
        BOTH limits apply between chunks. Returns ``(chunks_executed,
        timed_out_ids)``."""
        slot = min(self._prefilling)
        req = self._prefilling[slot]
        now = self._now()
        waited = now - req.submit_time
        if ((req.queue_ttl_s and waited > req.queue_ttl_s)
                or (req.deadline_s and waited > req.deadline_s)):
            self._evict(req, "timeout", now)
            obs_emit("request_timeout", request=req.id, where="prefilling")
            return 0, [req.id]
        self._run_chunk(req)
        return 1, []

    def _finish_first_token(self, req: Request, tok: int,
                            carry_key) -> None:
        """Shared admission tail: the first token is on the host —
        install the decode lane, record TTFT, fire the callback, route
        to the active set or straight to retirement."""
        now = self._now()
        req.first_token_time = now
        req.tokens.append(tok)
        self.metrics.record_first_token(now - req.submit_time)
        self.metrics.record_tokens(1)
        done_eos = req.eos_token_id >= 0 and tok == req.eos_token_id
        done = done_eos or req.max_new_tokens <= 1
        # a PREFILL-role replica never decodes: an unfinished request
        # parks for export_kv() with its lane INERT (active=False keeps
        # any stray decode tick off its pages)
        parked = self.role == "prefill" and not done
        self._install_lane(req, tok=tok, length=req.prompt_len, decoded=1,
                           active=not done and not parked,
                           carry_key=carry_key)
        # callback AFTER the device state is consistent: a raising callback
        # then retires exactly this request and can't leave the slot half-
        # installed (previously it unwound _admit between cache scatter and
        # state install)
        if not self._emit_token(req, tok, done):
            self._retire_error(req, now)
        elif done:
            self._finalize(req, "eos" if done_eos else "max_length", now)
        elif parked:
            req.phase = "prefilled"
            self._prefilled[req.slot] = req
            obs_emit("prefill_parked", request=req.id,
                     prompt_len=req.prompt_len)
        else:
            req.phase = "active"
            self._active[req.slot] = req

    def _decode_fn(self, params, cache, st, tables, all_greedy: bool):
        """Jitted: ONE decode token for every slot (inactive slots ride
        along with writes pinned to the last cache row — which a freed
        lane's zeroed block table re-routes to the trash page — outputs
        ignored). ``tables`` is the device block tables on the paged path
        (None on the slot path). ``all_greedy`` is static — greedy-only
        ticks take a bare argmax and skip the sampler's top-k sort /
        top-p bisection / rng split."""
        params = self._dequant_params(params)
        active = st["active"]
        lengths = st["lengths"]
        max_pos = self.model.cfg.max_position_embeddings
        wpos = jnp.where(active, lengths, self.cache_len - 1)
        posid = jnp.where(active, jnp.minimum(lengths, max_pos - 1), 0)
        logits, cache = self.executor.forward(
            params, cache, st["last_tok"][:, None],
            posid[:, None], None, cache_positions=wpos,
            block_tables=tables)
        step = logits[:, -1, :].astype(jnp.float32)
        vocab = step.shape[-1]
        suppress = ((st["decoded"] < st["min_new"])[:, None]
                    & (jnp.arange(vocab)[None, :] == st["eos"][:, None]))
        step = jnp.where(suppress, _NEG, step)
        if all_greedy:
            tok = jnp.argmax(step, axis=-1).astype(jnp.int32)
            new_rng = st["rng"]  # greedy consumes no randomness
        else:
            keys = jax.vmap(functools.partial(jax.random.split, num=2))(
                st["rng"])
            tok = self.executor.sample(step, keys[:, 0], st["greedy"],
                                       st["temperature"], st["top_k"],
                                       st["top_p"], topk_cap=self.topk_cap)
            new_rng = jnp.where(active[:, None], keys[:, 1], st["rng"])
        new_len = lengths + 1
        decoded = st["decoded"] + 1
        done = active & (
            (tok == st["eos"])
            | (decoded >= st["max_new"])
            | (new_len >= self.cache_len)
        )
        new_st = dict(st)
        new_st["last_tok"] = jnp.where(active, tok, st["last_tok"])
        new_st["lengths"] = jnp.where(active, new_len, lengths)
        new_st["decoded"] = jnp.where(active, decoded, st["decoded"])
        new_st["active"] = active & ~done
        new_st["rng"] = new_rng
        return self._pin_cache(cache), new_st, tok, done

    def _tick_decode(self):
        retired = []
        if self.paged:
            # grow-on-demand BEFORE the write: any active lane whose next
            # position crosses into an unallocated page claims one now; a
            # dry pool retires the request with its partial tokens
            # ("cache_full") — deterministic lowest-lane-first order
            now = self._now()
            for slot in sorted(self._active):
                req = self._active[slot]
                if not self.cache_manager.ensure_page(slot):
                    self._evict(req, "cache_full", now)
                    obs_emit("cache_full", request=req.id,
                             tokens=len(req.tokens))
                    retired.append(req.id)
            if not self._active:
                return retired
        all_greedy = all(r.greedy for r in self._active.values())
        active_ids = [r.id for r in self._active.values()]
        attempt = self._fault_ticks
        self._fault_ticks += 1
        # bind the device operands NOW, on the main thread: if the watchdog
        # abandons this call mid-hang and recovery swaps self.cache_manager/
        # self._state, the zombie thread must wake holding the OLD buffers
        # (safe to donate — they are dead) and never touch the recovered
        # ones; _device_tables() also mutates engine state, so it cannot run
        # on the worker thread
        cache_in, state_in = self.cache_manager.cache, self._state
        tables_in = self._device_tables()

        def run():
            # fault hooks INSIDE the guarded call: an injected hang is what
            # the watchdog times, an injected raise unwinds like a real
            # device error (both inert one-flag checks in production)
            faults.on_serving_tick(attempt)
            faults.on_serving_batch(active_ids)
            out = self._decode_jit(self.params, cache_in, state_in,
                                   tables_in, all_greedy)
            if self.tick_timeout_s > 0:
                # surface async device errors inside the watchdog window
                jax.block_until_ready(out)
            return out

        with span("serving.decode", batch=len(active_ids)):
            cache, st, tok, done = self._run_device(run)
        self.cache_manager.cache = cache
        self._state = st
        tok_np = np.asarray(tok)  # host sync per tick
        done_np = np.asarray(done)
        now = self._now()
        for slot, req in list(self._active.items()):
            t = int(tok_np[slot])
            req.tokens.append(t)
            self.cache_manager.lengths[slot] += 1
            self.metrics.record_tokens(1)
            finished = bool(done_np[slot])
            # firewalled callback: a raising on_token retires THIS request
            # only — every neighbor's host token list was already appended
            # this tick and keeps decoding undisturbed
            if not self._emit_token(req, t, finished):
                self._retire_error(req, now)
                retired.append(req.id)
                continue
            if finished:
                if req.eos_token_id >= 0 and t == req.eos_token_id:
                    reason = "eos"
                elif len(req.tokens) >= req.max_new_tokens:
                    reason = "max_length"
                else:
                    reason = "cache_full"
                self._finalize(req, reason, now)
                retired.append(req.id)
        return retired

    # ------------------------------------------------ speculative decoding

    def _verify_fn(self, params, cache, st, tables, draft, draft_len,
                   k: int, all_greedy: bool):
        """Jitted draft-k-verify-once step (module docstring): ONE
        prefill-shaped forward scores all ``k+1`` positions of every
        lane — ``[last_tok, d1..dk]`` written at the lane's own
        ``cache_positions`` offsets, exactly the multi-token seam
        chunked prefill/replay use — then acceptance runs ON DEVICE so
        the host round-trip stays O(slots·k), not O(vocab).

        Greedy rows keep the longest draft prefix matching the per-
        position argmax (with the per-position ``min_new`` EOS
        suppression the sequential loop would have applied) plus the
        correction/bonus token — byte-identical to k+1 plain ticks by
        construction. Sampling rows run speculative rejection per
        position (accept ``d`` with prob ``p(d)`` — the proposers are
        deterministic, q = 1 — else sample the residual ``(p - q)+``),
        consuming exactly one rng split per EMITTED token so replay's
        stream reconstruction is unchanged. Inactive lanes ride along
        with writes pinned beyond every live window (paged: position
        clamps re-route through zeroed tables to the trash page; slot:
        the tail rows of a dead/mid-prefill lane, which the next
        tenant's full-row scatter overwrites). Returns
        ``(cache, new_state, out_tokens [b,k+1], n_emit [b],
        n_accepted [b], done [b])``."""
        params = self._dequant_params(params)
        s = k + 1
        active = st["active"]
        lengths = st["lengths"]
        max_pos = self.model.cfg.max_position_embeddings
        # pinned write base for inactive rows: the paged path clamps all
        # s positions onto the last logical slot (trash-routed when
        # unallocated); the slot path needs start <= cache_len - s so the
        # per-row dynamic_update_slice cannot clamp-shift backwards
        pin = self.cache_len - 1 if self.paged else self.cache_len - s
        wpos = jnp.where(active, lengths, pin)
        ids = jnp.concatenate([st["last_tok"][:, None], draft], axis=1)
        posid = jnp.minimum(wpos[:, None] + jnp.arange(s, dtype=jnp.int32),
                            max_pos - 1)
        posid = jnp.where(active[:, None], posid, 0)
        logits, cache = self.executor.forward(
            params, cache, ids, posid, None,
            cache_positions=wpos, block_tables=tables)
        logits = logits.astype(jnp.float32)
        vocab = logits.shape[-1]
        # per-position min_new suppression: position j samples generated
        # token number decoded + j + 1, so EOS is banned while
        # decoded + j < min_new — the condition each sequential tick
        # would have applied
        decoded_at = st["decoded"][:, None] + jnp.arange(s)[None, :]
        suppress = ((decoded_at < st["min_new"][:, None])[:, :, None]
                    & (jnp.arange(vocab)[None, None, :]
                       == st["eos"][:, None, None]))
        logits = jnp.where(suppress, _NEG, logits)
        greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [b, s]
        idx = jnp.arange(s, dtype=jnp.int32)[None, :]
        if all_greedy:
            # vectorized acceptance: position j's target IS what tick j
            # would have emitted, so the emitted run is target[:acc+1]
            # cut at the first EOS inside it; no rng is consumed
            match = ((draft == greedy_tok[:, :k])
                     & (jnp.arange(k)[None, :] < draft_len[:, None]))
            acc = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
            m0 = acc + 1
            is_eos = greedy_tok == st["eos"][:, None]
            eos_pos = jnp.min(
                jnp.where(is_eos & (idx < m0[:, None]), idx, s), axis=1)
            m = jnp.minimum(m0, eos_pos + 1)
            acc = jnp.minimum(acc, m)
            out = greedy_tok
            new_rng = st["rng"]  # greedy consumes no randomness
        else:
            # per-position target distributions through THE shared
            # per-row sampler filter pipeline (rows repeated per
            # position: row b*s + j filters position j of lane b)
            b = logits.shape[0]
            filt = self.executor.filter(
                logits.reshape(b * s, vocab),
                jnp.repeat(st["temperature"], s),
                jnp.repeat(st["top_k"], s),
                jnp.repeat(st["top_p"], s),
                topk_cap=self.topk_cap).reshape(b, s, vocab)
            p = jax.nn.softmax(filt, axis=-1)
            split2 = jax.vmap(functools.partial(jax.random.split, num=2))
            alive = active
            carry = st["rng"]
            m = jnp.zeros_like(lengths)
            acc = jnp.zeros_like(lengths)
            cols = []
            for j in range(s):
                pair = split2(carry)
                step_key, next_carry = pair[:, 0], pair[:, 1]
                sub = split2(step_key)
                d = (draft[:, j] if j < k
                     else jnp.zeros_like(st["last_tok"]))
                has_draft = j < draft_len
                pj = p[:, j, :]
                p_d = jnp.take_along_axis(pj, d[:, None], axis=1)[:, 0]
                u = jax.vmap(jax.random.uniform)(sub[:, 0])
                # residual (p - q)+ of a deterministic (one-hot) draft:
                # p with the draft token zeroed; log turns zeros to -inf
                resid = jnp.where(jnp.arange(vocab)[None, :] == d[:, None],
                                  0.0, pj)
                samp_rej = jax.vmap(jax.random.categorical)(
                    sub[:, 1], jnp.log(resid))
                samp_direct = jax.vmap(jax.random.categorical)(
                    sub[:, 1], filt[:, j, :])
                accept_s = has_draft & (u < p_d)
                tok_s = jnp.where(accept_s, d,
                                  jnp.where(has_draft, samp_rej,
                                            samp_direct))
                accept_g = has_draft & (d == greedy_tok[:, j])
                accept_j = jnp.where(st["greedy"], accept_g, accept_s)
                tok_j = jnp.where(st["greedy"], greedy_tok[:, j],
                                  tok_s).astype(jnp.int32)
                cols.append(jnp.where(alive, tok_j, 0))
                m = m + alive
                acc = acc + (alive & accept_j)
                # one split per emitted token, every active row (the
                # mixed-tick baseline advances greedy rows' streams too)
                carry = jnp.where(alive[:, None], next_carry, carry)
                alive = alive & accept_j & (tok_j != st["eos"])
            out = jnp.stack(cols, axis=1)
            new_rng = carry
        m = jnp.where(active, m, 0)
        new_len = lengths + m
        decoded = st["decoded"] + m
        last = jnp.take_along_axis(
            out, jnp.maximum(m - 1, 0)[:, None], axis=1)[:, 0]
        last = jnp.where(active & (m > 0), last, st["last_tok"])
        done = active & (
            (last == st["eos"])
            | (decoded >= st["max_new"])
            | (new_len >= self.cache_len)
        )
        new_st = dict(st)
        new_st["last_tok"] = last
        new_st["lengths"] = jnp.where(active, new_len, lengths)
        new_st["decoded"] = jnp.where(active, decoded, st["decoded"])
        new_st["active"] = active & ~done
        new_st["rng"] = new_rng
        return self._pin_cache(cache), new_st, out, m, acc, done

    def _tick_decode_spec(self):
        """Speculative sibling of :meth:`_tick_decode`: clamp k, grow
        pages for the verify window, draft, verify once, commit the
        accepted run per lane. Falls back to the plain tick when no lane
        has draft headroom (a lane at cache capacity pins the whole
        tick's k — it is about to retire ``cache_full`` anyway)."""
        lens = {s: int(self.cache_manager.lengths[s]) for s in self._active}
        # write-safety clamp: every lane's verify writes land at
        # lengths..lengths+k, all < cache_len (the per-row update must
        # never clamp-shift into live rows) — so k is the min headroom.
        # A lane can only pin k below spec_k while it sits within k
        # tokens of cache capacity (≤ k ticks before it retires
        # cache_full), and the verify jit caches per distinct k, so the
        # throttle is transient and compiles are bounded by spec_k per
        # engine lifetime.
        k = min(self.spec_k,
                min(self.cache_len - 1 - n for n in lens.values()))
        if k <= 0:
            return self._tick_decode()
        retired = []
        now = self._now()
        if self.paged:
            # phase 1: every lane's PENDING-token page first — the exact
            # allocation the plain tick makes, in the same order, so
            # cache_full retirement decisions are identical to the
            # non-speculative engine even under a near-dry pool (draft
            # windows must never starve a neighbor's pending token)
            for slot in sorted(self._active):
                req = self._active[slot]
                if not self.cache_manager.ensure_page(slot):
                    self._evict(req, "cache_full", now)
                    obs_emit("cache_full", request=req.id,
                             tokens=len(req.tokens))
                    retired.append(req.id)
            if not self._active:
                return retired
        cov = {}
        for slot in sorted(self._active):
            req = self._active[slot]
            # the PR 11-style budget clamp (ISSUE small fix): a draft may
            # never overrun the request's remaining token budget or its
            # page coverage — clamp BEFORE proposing
            budget = max(req.max_new_tokens - len(req.tokens) - 1, 0)
            if self.paged:
                # phase 2: draft windows from whatever slack remains
                # (uncovered tail writes trash-route; acceptance clamps
                # to the covered span) — and whatever a draft claims
                # here is RETURNED by trim_span after the verify, so the
                # pool a neighbor sees next tick is the plain engine's
                c = self.cache_manager.ensure_span(
                    slot, min(k, budget) + 1)
            else:
                c = k + 1  # slot lanes are fully allocated
            cov[slot] = min(k, budget, c - 1)
        req_map = {
            slot: (np.concatenate([req.prompt,
                                   np.asarray(req.tokens, np.int32)]),
                   cov[slot])
            for slot, req in self._active.items()
        }
        with span("serving.draft", batch=len(req_map), k=k):
            # mesh context covers draft-model proposers (their device
            # calls run the same sharded params); the n-gram proposer is
            # pure host and the context is a no-op around it
            with self._mesh_context():
                proposals = self._proposer.propose(req_map, k)
        draft = np.zeros((self.slots, k), np.int32)
        dlen = np.zeros(self.slots, np.int32)
        for slot, (_, cap) in req_map.items():
            d = np.asarray(proposals.get(slot, ()),
                           np.int32).reshape(-1)[:cap]
            draft[slot, :len(d)] = d
            dlen[slot] = len(d)
        if not dlen.any():
            # nothing drafted anywhere (no n-gram match / budgets spent):
            # a k+1-wide verify would emit exactly one token per lane at
            # (k+1)x the cost AND skip the flash-decode fast path — take
            # the plain tick instead (byte-identical for greedy; neither
            # proposer holds per-tick state that needs an observe() here).
            # Phase-2 draft pages go back first, so the plain tick and
            # every neighbor see the plain engine's pool state.
            if self.paged:
                for slot in sorted(self._active):
                    self.cache_manager.trim_span(slot)
            return retired + self._tick_decode()
        all_greedy = all(r.greedy for r in self._active.values())
        active_ids = [r.id for r in self._active.values()]
        attempt = self._fault_ticks
        self._fault_ticks += 1
        # operand binding on the main thread — the same zombie-safety
        # argument as _tick_decode (an abandoned verify call must never
        # see post-recovery buffers)
        cache_in, state_in = self.cache_manager.cache, self._state
        tables_in = self._device_tables()
        draft_dev, dlen_dev = jnp.asarray(draft), jnp.asarray(dlen)

        def run():
            faults.on_serving_tick(attempt)
            faults.on_serving_batch(active_ids)
            out = self._verify_jit(self.params, cache_in, state_in,
                                   tables_in, draft_dev, dlen_dev, k,
                                   all_greedy)
            if self.tick_timeout_s > 0:
                jax.block_until_ready(out)
            return out

        with span("serving.verify", batch=len(active_ids), k=k):
            cache, st, out_tok, m, acc, done = self._run_device(run)
        self.cache_manager.cache = cache
        self._state = st
        out_np = np.asarray(out_tok)
        m_np = np.asarray(m)
        acc_np = np.asarray(acc)
        done_np = np.asarray(done)
        now = self._now()
        proposed = accepted = 0
        emitted_rows = []
        for slot, req in list(self._active.items()):
            n = int(m_np[slot])
            toks = [int(t) for t in out_np[slot][:n]]
            row_acc = min(int(acc_np[slot]), n)
            proposed += int(dlen[slot])
            accepted += row_acc
            req.spec_proposed += int(dlen[slot])
            req.spec_accepted += row_acc
            emitted_rows.append(n)
            self.cache_manager.lengths[slot] += n
            if self.paged:
                # return rejected-draft pages to the pool THIS tick:
                # post-trim the chain matches what the plain engine
                # would hold, so draft windows cost neighbors nothing
                self.cache_manager.trim_span(slot)
            self.metrics.record_tokens(n)
            self._proposer.observe(slot, n)
            finished = bool(done_np[slot])
            failed = False
            for i, t in enumerate(toks):
                req.tokens.append(t)
                # firewalled per-token callback, in emission order; a
                # raise retires THIS request with the tokens streamed so
                # far — neighbors keep their whole accepted runs
                if not self._emit_token(req, t, finished and i == n - 1):
                    self._retire_error(req, now)
                    retired.append(req.id)
                    failed = True
                    break
            if failed:
                continue
            if finished:
                if (req.eos_token_id >= 0 and toks
                        and toks[-1] == req.eos_token_id):
                    reason = "eos"
                elif len(req.tokens) >= req.max_new_tokens:
                    reason = "max_length"
                else:
                    reason = "cache_full"
                self._finalize(req, reason, now)
                retired.append(req.id)
        self.metrics.record_spec(proposed, accepted, emitted_rows)
        return retired

    def _emit_token(self, req: Request, tok: int, finished: bool) -> bool:
        """Invoke a request's streaming callback behind a firewall; False
        means the callback raised (the caller retires the request with
        ``finish_reason="error"``)."""
        if not req.on_token:
            return True
        try:
            req.on_token(req.id, tok, finished)
            return True
        except Exception:
            logger.exception(
                "serving: request %d on_token callback raised; retiring it "
                "with finish_reason='error' (other slots unaffected)", req.id)
            return False

    def _retire_error(self, req: Request, now: float) -> None:
        """Retire one request whose callback raised."""
        self._evict(req, "error", now)
        obs_emit("callback_error", request=req.id)

    def _finalize(self, req: Request, reason: str, now: float) -> None:
        if req.slot in self._active and self._active[req.slot] is req:
            del self._active[req.slot]
        if req.slot in self._prefilling and self._prefilling[req.slot] is req:
            del self._prefilling[req.slot]
        if req.slot in self._prefilled and self._prefilled[req.slot] is req:
            del self._prefilled[req.slot]
        req.chunk_cache = None  # a mid-prefill retiree drops its working
        req.phase = "finished"  # cache; pages/lane free below (no leak)
        if req.slot is not None:  # queued-expiry/cancel never held a slot
            if self._proposer is not None:
                self._proposer.on_retire(req.slot)
            self.cache_manager.free(req.slot)
        self.metrics.record_retire(now - req.submit_time, reason)
        self._results[req.id] = ServingResult(
            id=req.id, prompt=req.prompt,
            tokens=np.asarray(req.tokens, np.int32), finish_reason=reason,
            ttft_s=(req.first_token_time or now) - req.submit_time,
            latency_s=now - req.submit_time,
        )
