"""Continuous-batching serving subsystem over the flash-decode fast path.

The runtime layer the reference toolkit never had: instead of one padded
batch per blocking ``generate()`` call, a slot-based scheduler keeps the
decode batch full — requests are admitted into free kv-cache slots the
tick they arrive (prefill-on-insert), every tick runs ONE jitted decode
step over all slots at their own depths, and finished requests free
their slot immediately for the next queued request.

    engine = ServingEngine(model, variables, slots=8)
    rid = engine.submit(prompt_ids, max_length=64)
    results = engine.drain()          # {rid: ServingResult}

Layout: ``cache_manager`` (slot cache + live-window safety argument),
``scheduler`` (FIFO admission policy seam), ``engine`` (submit/step/drain
loop + jitted prefill/decode), ``metrics`` (queue/TTFT/throughput
observability). docs/SERVING.md has the architecture tour.
"""

from fleetx_tpu.serving.cache_manager import SlotKVCacheManager, scatter_slot
from fleetx_tpu.serving.engine import (
    QueueFull,
    ServingEngine,
    ServingResult,
    sample_tokens,
)
from fleetx_tpu.serving.metrics import ServingMetrics
from fleetx_tpu.serving.scheduler import FIFOScheduler, Request

__all__ = [
    "QueueFull",
    "ServingEngine",
    "ServingResult",
    "SlotKVCacheManager",
    "FIFOScheduler",
    "Request",
    "ServingMetrics",
    "sample_tokens",
    "scatter_slot",
]
