"""Continuous-batching serving subsystem over the flash-decode fast path.

The runtime layer the reference toolkit never had: instead of one padded
batch per blocking ``generate()`` call, a slot-based scheduler keeps the
decode batch full — requests are admitted into free decode lanes the
tick they arrive (prefill-on-insert), every tick runs ONE jitted decode
step over all lanes at their own depths, and finished requests free
their lane immediately for the next queued request.

K/V storage is PAGED by default: a shared ``[num_pages, page_size, ...]``
pool with per-request block tables and a refcounted prefix trie, so
cache capacity tracks live tokens (page-granular admission) and requests
sharing a system prompt reuse one prefill (``FLEETX_SERVING_PAGED=0``
restores the fixed per-slot cache).

    engine = ServingEngine(model, variables, slots=8)
    rid = engine.submit(prompt_ids, max_length=64)
    results = engine.drain()          # {rid: ServingResult}

Layout: ``cache_manager`` (page pool + prefix trie + slot-compat cache,
and the no-zeroing live-window safety argument), ``scheduler`` (FIFO
admission policy seam), ``engine`` (submit/step/drain loop + jitted
prefill/decode), ``model_protocol`` (the model-agnostic serving
contract: executor seam + capability flags + the router-facing engine
surface), ``batch_engine`` / ``ernie_engine`` / ``embedding_engine``
(KV-free dynamic-batching engines for encoder-style models), ``metrics``
(queue/TTFT/throughput/prefix-reuse observability), ``router``
(N-replica dispatch with per-model groups, health-based failover,
zero-token-loss migration, and per-tenant QoS: DRR weighted-fair lanes,
admission budgets, priority preemption), ``autoscaler`` (closed-loop
fleet sizing off replica health with prefix pre-warm), ``workload``
(seeded trace generation — Poisson or heavy-tailed Azure-LLM-shaped —
+ the SLO goodput scorer). docs/SERVING.md has the architecture tour.
"""

from fleetx_tpu.serving.autoscaler import FleetAutoscaler

from fleetx_tpu.serving.cache_manager import (
    DiskPageStore,
    HostPageStore,
    PagedKVCacheManager,
    PagePool,
    SlotKVCacheManager,
    TieredPageStore,
    scatter_slot,
)
from fleetx_tpu.serving.embedding_engine import (
    EmbeddingEngine,
    decode_floats,
    encode_floats,
)
from fleetx_tpu.serving.engine import (
    QueueFull,
    RecoveryExhausted,
    ServingEngine,
    ServingResult,
    ShuttingDown,
    TickTimeout,
    sample_tokens,
)
from fleetx_tpu.serving.ernie_engine import ErnieScoringEngine
from fleetx_tpu.serving.batch_engine import BatchingEngine
from fleetx_tpu.serving.metrics import ServingMetrics
from fleetx_tpu.serving.model_protocol import (
    ENGINE_SURFACE,
    GPTExecutor,
    ModelCapabilities,
    ModelExecutor,
    engine_conforms,
)
from fleetx_tpu.serving.router import (
    ReplicaState,
    RouterMetrics,
    ServingRouter,
    TenantPolicy,
)
from fleetx_tpu.serving.scheduler import FIFOScheduler, Request
from fleetx_tpu.serving.spec import (
    DraftModelProposer,
    NgramProposer,
    Proposer,
)
from fleetx_tpu.serving.workload import (
    DISTRIBUTIONS,
    RequestOutcome,
    TenantSpec,
    TraceDistribution,
    TraceRequest,
    WorkloadSpec,
    generate_trace,
    run_trace,
    score_goodput,
    trace_hash,
)

__all__ = [
    "QueueFull",
    "RecoveryExhausted",
    "ServingEngine",
    "ServingResult",
    "ShuttingDown",
    "TickTimeout",
    "BatchingEngine",
    "EmbeddingEngine",
    "ErnieScoringEngine",
    "ENGINE_SURFACE",
    "GPTExecutor",
    "ModelCapabilities",
    "ModelExecutor",
    "engine_conforms",
    "decode_floats",
    "encode_floats",
    "DiskPageStore",
    "HostPageStore",
    "PagePool",
    "PagedKVCacheManager",
    "SlotKVCacheManager",
    "TieredPageStore",
    "FIFOScheduler",
    "Request",
    "DraftModelProposer",
    "NgramProposer",
    "Proposer",
    "DISTRIBUTIONS",
    "FleetAutoscaler",
    "ReplicaState",
    "RequestOutcome",
    "RouterMetrics",
    "ServingMetrics",
    "ServingRouter",
    "TenantPolicy",
    "TenantSpec",
    "TraceDistribution",
    "TraceRequest",
    "WorkloadSpec",
    "generate_trace",
    "run_trace",
    "sample_tokens",
    "scatter_slot",
    "score_goodput",
    "trace_hash",
]
