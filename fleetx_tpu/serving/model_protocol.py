"""The model-agnostic serving protocol: what a model must provide to be
served, and what an engine must provide to be routed.

The serving stack grew GPT-shaped end to end (PRs 2–17): the engine
called ``init_decode_cache`` / ``decode_step`` directly, the router
assumed every replica decodes autoregressively, and the API layer
reported one hardcoded model. The source paper's premise is a ONE-STOP
toolkit — GPT, ERNIE, ViT, MoCo — so this module factors the two
implicit contracts into explicit ones:

**The model-side contract** (:class:`ModelExecutor`): the four seams
``ServingEngine`` actually needs from a model — init cache, the
bucketed prefill / decode forward, and per-row sampling — plus
:class:`ModelCapabilities` flags that say which engine features the
model can legally ride (KV cache, speculative decoding, cache layout).
:class:`GPTExecutor` is the existing GPT path behind that interface:
every method delegates to the exact functions the engine called before
the extraction (``fleetx_tpu/models/gpt/generation.py`` +
``serving/engine.py``'s shared sampler), so the refactor is provably
behavior-free — the byte-parity suites run unchanged.

**The engine-side contract** (:data:`ENGINE_SURFACE`): the
submit/step/healthz surface ``ServingRouter`` and ``ApiServer`` consume.
Three engine kinds implement it today — the autoregressive
``ServingEngine`` (GPT), the encoder-style ``ErnieScoringEngine``
(fill-in-blank / sentence-order scoring; no decode loop), and the
KV-free ``EmbeddingEngine`` (ViT/MoCo dynamic batching; no cache at
all). ``tests/test_protocol.py`` runs one conformance suite against all
three; :func:`engine_conforms` is the structural check it (and the
router, defensively) uses.

Capability flags ride the ``/healthz`` report (``model`` +
``capabilities`` keys), which is how a cross-process router learns what
each replica serves without importing its model code — the same
scrape-don't-import discipline as the ``role`` field
(docs/SERVING.md "Heterogeneous fleet").
"""

from __future__ import annotations

import dataclasses
from typing import Optional

__all__ = [
    "ENGINE_SURFACE",
    "GPTExecutor",
    "ModelCapabilities",
    "ModelExecutor",
    "engine_conforms",
]


@dataclasses.dataclass(frozen=True)
class ModelCapabilities:
    """What engine features a served model family can legally ride.

    The flags gate features at CONSTRUCTION, not mid-request: an engine
    asked to speculate over a model whose executor says
    ``supports_spec=False`` must refuse up front with a cause, the same
    fail-at-the-seam discipline as the mesh validation."""

    #: model family name — the router's grouping key and the id prefix
    #: the API layer lists in ``/v1/models`` ("gpt" | "ernie" | "vit" ...)
    family: str
    #: the model decodes autoregressively against a KV cache; False means
    #: the engine owns no cache pool and every request is one forward
    has_kv_cache: bool
    #: draft-and-verify speculative decoding is legal (requires a decode
    #: loop whose verify call replays multi-token windows — GPT only)
    supports_spec: bool
    #: "slot+paged" (the GPT engine's two cache layouts), "none" (KV-free)
    cache_layout: str
    #: hard per-request input bound (tokens for text, flat elements for
    #: vision) — what the router's per-group submit validation prices
    max_input: int
    #: what the int32 output channel carries: "tokens" (real token /
    #: class ids) or "floats" (a float32 vector bit-cast losslessly —
    #: serving/embedding_engine.py's wire encoding). The API layer keys
    #: ``/v1/embeddings`` eligibility on this, not on KV-freeness —
    #: ERNIE is KV-free but token-out
    emits: str = "tokens"

    def as_dict(self) -> dict:
        """JSON-ready form for the ``/healthz`` report."""
        return dataclasses.asdict(self)


class ModelExecutor:
    """The model-side serving contract (abstract).

    ``ServingEngine`` consumes ONLY this surface for model compute: a
    fresh cache (:meth:`init_cache`), the cached forward that serves
    both bucketed prefill and the decode tick (:meth:`forward`), and
    the shared per-row sampling pipeline (:meth:`sample` /
    :meth:`filter`). Encoder-style engines (ERNIE, ViT) do not run a
    decode loop and need none of this — they call their model directly
    — but still advertise :attr:`capabilities` so the router and
    ``/healthz`` treat every replica uniformly.

    All methods are traced under ``jax.jit``: implementations must be
    pure functions of their arguments (plus the model closed over at
    construction)."""

    capabilities: ModelCapabilities

    def bind(self, model):
        """Rebind to a decode-configured model clone. The engine patches
        cache length / page layout onto ``model.cfg`` before tracing
        anything; executors built over the raw model get this call with
        the clone so :meth:`init_cache` / :meth:`forward` read the
        serving cache config, not the training one."""
        raise NotImplementedError

    def init_cache(self, batch: int):
        """A fresh decode cache for ``batch`` lanes (None when
        ``capabilities.has_kv_cache`` is False)."""
        raise NotImplementedError

    def forward(self, params, cache, ids, positions, mask=None, *,
                cache_positions=None, block_tables=None):
        """One cached forward: ``(logits, new_cache)``. Serves bucketed
        prefill (multi-token ``ids``) and the decode tick (one token per
        lane) through the same seam; ``cache_positions`` are per-lane
        write offsets, ``block_tables`` the paged indirection (None on
        the slot path)."""
        raise NotImplementedError

    def sample(self, logits, keys, greedy, temperature, top_k, top_p, *,
               topk_cap: int):
        """Per-row sampling: each row applies its own strategy knobs and
        draws from its own rng key; returns int32 tokens."""
        raise NotImplementedError

    def filter(self, logits, temperature, top_k, top_p, *, topk_cap: int):
        """The sampling filter pipeline alone (speculative verification
        needs the filtered distribution, not a draw)."""
        raise NotImplementedError


class GPTExecutor(ModelExecutor):
    """The GPT decode path behind the protocol — pure delegation.

    Every method forwards to the exact function the engine called
    before the extraction, with the model closed over; tracing under
    ``jit`` produces identical programs, which is what keeps the
    byte-parity suites green unchanged."""

    def __init__(self, model, family: str = "gpt"):
        self.model = model
        self.capabilities = ModelCapabilities(
            family=family,
            has_kv_cache=True,
            supports_spec=True,
            cache_layout="slot+paged",
            max_input=int(model.cfg.max_position_embeddings),
        )

    def bind(self, model):
        return GPTExecutor(model, family=self.capabilities.family)

    def init_cache(self, batch: int):
        from fleetx_tpu.models.gpt.generation import init_decode_cache

        return init_decode_cache(self.model, batch)

    def forward(self, params, cache, ids, positions, mask=None, *,
                cache_positions=None, block_tables=None):
        from fleetx_tpu.models.gpt.generation import decode_step

        return decode_step(self.model, params, cache, ids, positions, mask,
                           cache_positions=cache_positions,
                           block_tables=block_tables)

    def sample(self, logits, keys, greedy, temperature, top_k, top_p, *,
               topk_cap: int):
        from fleetx_tpu.serving.engine import sample_tokens

        return sample_tokens(logits, keys, greedy, temperature, top_k,
                             top_p, topk_cap=topk_cap)

    def filter(self, logits, temperature, top_k, top_p, *, topk_cap: int):
        from fleetx_tpu.serving.engine import filter_logits

        return filter_logits(logits, temperature, top_k, top_p,
                             topk_cap=topk_cap)


#: The engine-side contract: every serving engine kind — autoregressive
#: or not — exposes this surface, and the router/API layers consume
#: NOTHING else. Methods: the names below; attributes: ``role``
#: ("prefill"/"decode"/"both"), ``paged`` (bool), ``page_size``,
#: ``cache_len``, ``slots``, ``model`` (with ``.cfg``), ``metrics``
#: (``ServingMetrics``-shaped), ``capabilities``
#: (:class:`ModelCapabilities`), ``model_family`` (str), and
#: ``submit_limit`` (the smallest REJECTED per-request input size — the
#: router's per-group admission bound). ``health()`` returns the
#: ``/healthz`` JSON body: ``state`` ok/draining/dead, ``role``,
#: ``model``, ``capabilities``, ``queue_depth``, ``queue_tokens``,
#: ``active``, ``slots``.
ENGINE_SURFACE = (
    "submit", "step", "take_result", "result", "cancel", "emitted_tokens",
    "health", "drain", "shutdown", "request_shutdown", "declare_dead",
)

_ENGINE_ATTRS = ("role", "paged", "page_size", "cache_len", "slots",
                 "model", "metrics", "capabilities", "model_family",
                 "submit_limit")


def engine_conforms(engine, *, require_attrs: bool = True
                    ) -> Optional[str]:
    """Structural conformance check against :data:`ENGINE_SURFACE`:
    returns None when ``engine`` exposes the full router-facing
    contract, else the first missing member's name (the conformance
    tests and the router's construction-time validation both report
    it)."""
    for name in ENGINE_SURFACE:
        if not callable(getattr(engine, name, None)):
            return name
    if require_attrs:
        for name in _ENGINE_ATTRS:
            if not hasattr(engine, name):
                return name
    return None
