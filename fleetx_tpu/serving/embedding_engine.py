"""KV-free embedding / classification engine for vision models.

The simplest engine the protocol admits: a ViT (or MoCo encoder built
on one) maps a batch of images to pooled features or class logits in a
single forward, so serving is pure request coalescing — a
:class:`~fleetx_tpu.serving.batch_engine.BatchingEngine` whose batches
are stacks of fixed-shape images. Two modes, keyed off the model
config exactly like ``fleetx_tpu/models/vision/vit.py`` itself:

- ``cfg.num_classes == 0`` → **embedding**: the pooled hidden vector
  per image, emitted as its float32 bits bit-cast to int32 tokens
  (lossless — :func:`decode_floats` inverts it). Riding the int32
  token channel keeps router migration/history byte-parity semantics
  intact for vectors: the "tokens" ARE the embedding.
- ``cfg.num_classes > 0`` → **classification**: one token, the argmax
  class id.

The wire format for inputs mirrors the outputs: a request "prompt" is
one image, channels-last ``[H, W, C]`` float32, flattened and bit-cast
to int32 (:func:`encode_floats`) — exactly ``H*W*C`` elements, which
is what ``_validate`` enforces (and what makes cross-model dispatch
mistakes fail loudly: a text prompt is never the right size). Batches
need no padding — every image is the same shape — so there is exactly
ONE jitted program per batch bucket. docs/SERVING.md
"Heterogeneous fleet".
"""

from __future__ import annotations

from typing import List

import jax
import numpy as np

from fleetx_tpu.serving.batch_engine import BatchingEngine, _bucket
from fleetx_tpu.serving.model_protocol import ModelCapabilities

__all__ = ["EmbeddingEngine", "decode_floats", "encode_floats"]


def encode_floats(arr) -> np.ndarray:
    """Flatten a float32 array to its int32 bit pattern — the wire
    encoding submits carry (lossless; :func:`decode_floats` inverts)."""
    return np.ascontiguousarray(
        np.asarray(arr, np.float32).reshape(-1)).view(np.int32)


def decode_floats(tokens) -> np.ndarray:
    """Invert :func:`encode_floats`: int32 wire tokens back to the flat
    float32 vector they encode."""
    return np.ascontiguousarray(
        np.asarray(tokens, np.int32).reshape(-1)).view(np.float32)


class EmbeddingEngine(BatchingEngine):
    """Dynamic-batching image embedding / classification over one
    vision model (module docstring)."""

    def __init__(self, model, variables, *, family: str = "vit", **kw):
        cfg = model.cfg
        self.image_shape = (int(cfg.image_size), int(cfg.image_size),
                            int(cfg.in_channels))
        self.image_elems = int(np.prod(self.image_shape))
        self.classify = int(cfg.num_classes) > 0
        self.capabilities = ModelCapabilities(
            family=family,
            has_kv_cache=False,
            supports_spec=False,
            cache_layout="none",
            max_input=self.image_elems,
            emits="tokens" if self.classify else "floats",
        )
        super().__init__(model, variables, **kw)

        def fwd(params, images):
            out = model.apply({"params": params}, images,
                              deterministic=True)
            return jax.numpy.argmax(out, axis=-1) if self.classify else out

        self._fwd = jax.jit(fwd)

    def _validate(self, prompt: np.ndarray) -> None:
        if prompt.size != self.image_elems:
            raise ValueError(
                f"embedding request must be one {self.image_shape} "
                f"float32 image bit-cast to int32 ({self.image_elems} "
                f"elements, see serving.embedding_engine.encode_floats); "
                f"got {prompt.size}")

    def _run_batch(self, requests) -> List[List[int]]:
        b = _bucket(len(requests), self.slots)
        images = np.zeros((b,) + self.image_shape, np.float32)
        for i, r in enumerate(requests):
            images[i] = decode_floats(r.prompt).reshape(self.image_shape)
        out = np.asarray(self._fwd(self.params, images))
        if self.classify:
            return [[int(out[i])] for i in range(len(requests))]
        return [[int(t) for t in encode_floats(out[i])]
                for i in range(len(requests))]

    @property
    def submit_limit(self) -> int:
        """One past the exact image size — images are fixed-shape, so
        any LARGER prompt is rejected (smaller ones fail in
        ``_validate`` with the precise shape message)."""
        return self.image_elems + 1
